"""NHD1xx — JAX tracing / recompile / host-sync hazards.

The solver's throughput rests on two properties the interpreter will not
enforce for us:

* a jitted program must stay traceable — any host coercion of a tracer
  (``int(x)``, ``if x:``, ``np.asarray(x)``) either raises at trace time
  or, worse, silently constant-folds a value that should be data;
* every ``jax.jit`` wrapper owns its own compilation cache — building one
  per call (instead of per bucket shape, under ``lru_cache``) recompiles
  the same program forever and erases the bucketing win.

Scope is computed per module with no imports executed: a function is
*jit-traced* if it is decorated with ``jax.jit`` (directly or through
``functools.partial``), passed to a ``jax.jit(...)`` call anywhere in the
module, or reachable from such a function through module-local calls
(the repo's idiom wraps a closure ``fn`` that forwards to the real
kernel, so one propagation step is load-bearing, not cosmetic).
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set

from nhd_tpu.analysis.core import Finding, _dotted

_COERCIONS = {"int", "float", "bool", "complex"}
# attribute reads that yield static (host) values even on a tracer
_STATIC_ATTRS = {"shape", "ndim", "size", "dtype", "sharding", "weak_type"}
_CACHE_DECORATORS = {"lru_cache", "cache"}
# wall-clock reads inside a traced function execute once, at TRACE time —
# the "timing" they produce is a compile-time constant folded into the
# program, so every later cached call reports the first call's timestamp
_CLOCK_CALLS = {
    "time.time", "time.perf_counter", "time.monotonic",
    "time.process_time", "time.thread_time", "time.perf_counter_ns",
    "time.monotonic_ns", "time.time_ns",
}


def _is_jit_ref(node: ast.AST, jit_names: Set[str]) -> bool:
    d = _dotted(node)
    return d is not None and (d in jit_names or d.endswith(".jit"))


def _jit_call(node: ast.AST, jit_names: Set[str]) -> Optional[ast.Call]:
    """The inner jit Call if *node* is ``jax.jit(...)`` or
    ``partial(jax.jit, ...)``; else None."""
    if not isinstance(node, ast.Call):
        return None
    if _is_jit_ref(node.func, jit_names):
        return node
    d = _dotted(node.func)
    if d in ("partial", "functools.partial") and node.args:
        if _is_jit_ref(node.args[0], jit_names):
            return node
    return None


class _FunctionIndex(ast.NodeVisitor):
    """All function defs (nested included), their call edges, and every
    name passed to a jit call."""

    def __init__(self, jit_names: Set[str]):
        self.jit_names = jit_names
        self.functions: Dict[str, List[ast.FunctionDef]] = {}
        self.calls: Dict[int, Set[str]] = {}    # id(funcdef) -> callee names
        self.jit_roots: Set[str] = set()        # names passed to jax.jit
        self._stack: List[ast.FunctionDef] = []

    def _visit_func(self, node) -> None:
        self.functions.setdefault(node.name, []).append(node)
        for dec in node.decorator_list:
            target = dec.func if isinstance(dec, ast.Call) else dec
            if _is_jit_ref(target, self.jit_names):
                self.jit_roots.add(node.name)
            if isinstance(dec, ast.Call) and _jit_call(dec, self.jit_names):
                self.jit_roots.add(node.name)
        self._stack.append(node)
        self.generic_visit(node)
        self._stack.pop()

    visit_FunctionDef = _visit_func
    visit_AsyncFunctionDef = _visit_func

    def visit_Call(self, node: ast.Call) -> None:
        if self._stack and isinstance(node.func, ast.Name):
            self.calls.setdefault(
                id(self._stack[-1]), set()
            ).add(node.func.id)
        jc = _jit_call(node, self.jit_names)
        if jc is not None:
            for arg in jc.args:
                if isinstance(arg, ast.Name):
                    self.jit_roots.add(arg.id)
        self.generic_visit(node)


def _collect_jit_aliases(tree: ast.Module) -> Set[str]:
    """Local names that mean jax.jit: ``from jax import jit [as j]``."""
    out: Set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom) and node.module == "jax":
            for alias in node.names:
                if alias.name == "jit":
                    out.add(alias.asname or "jit")
    return out


class _TracedChecker:
    """Per-traced-function dataflow: which local names carry tracers."""

    def __init__(self, fn: ast.FunctionDef, findings: List[Finding],
                 path: str):
        self.findings = findings
        self.path = path
        args = fn.args
        params = [a.arg for a in (
            args.posonlyargs + args.args + args.kwonlyargs
        )]
        if args.vararg:
            params.append(args.vararg.arg)
        if args.kwarg:
            params.append(args.kwarg.arg)
        self.traced: Set[str] = set(params) - {"self", "cls"}
        self.fn = fn

    # -- taint judgement -------------------------------------------------

    def is_traced(self, node: ast.AST) -> bool:
        if isinstance(node, ast.Name):
            return node.id in self.traced
        if isinstance(node, ast.Attribute):
            if node.attr in _STATIC_ATTRS:
                return False
            return self.is_traced(node.value)
        if isinstance(node, ast.Subscript):
            return self.is_traced(node.value)
        if isinstance(node, ast.Call):
            d = _dotted(node.func) or ""
            if d == "len" or d.split(".")[-1] in _COERCIONS:
                return False  # result is a concrete host value (the
                #               coercion itself is judged separately)
            if isinstance(node.func, ast.Attribute) and self.is_traced(
                node.func.value
            ):
                return True   # method on a traced object (x.astype(...))
            return any(self.is_traced(a) for a in node.args) or any(
                self.is_traced(k.value) for k in node.keywords
            )
        if isinstance(node, ast.BinOp):
            return self.is_traced(node.left) or self.is_traced(node.right)
        if isinstance(node, ast.UnaryOp):
            return self.is_traced(node.operand)
        if isinstance(node, ast.BoolOp):
            return any(self.is_traced(v) for v in node.values)
        if isinstance(node, ast.Compare):
            return self.is_traced(node.left) or any(
                self.is_traced(c) for c in node.comparators
            )
        if isinstance(node, (ast.Tuple, ast.List, ast.Set)):
            return any(self.is_traced(e) for e in node.elts)
        if isinstance(node, ast.Dict):
            return any(self.is_traced(v) for v in node.values) or any(
                k is not None and self.is_traced(k) for k in node.keys
            )
        if isinstance(node, ast.IfExp):
            return self.is_traced(node.body) or self.is_traced(node.orelse)
        if isinstance(node, ast.Starred):
            return self.is_traced(node.value)
        return False

    # -- propagation + checks -------------------------------------------

    def run(self) -> None:
        # two passes so names assigned late but used early (loops) settle
        for _ in range(2):
            for node in self._own_nodes():
                if isinstance(node, ast.Assign) and self.is_traced(node.value):
                    for tgt in node.targets:
                        self._taint_target(tgt)
                elif isinstance(node, ast.AugAssign) and (
                    self.is_traced(node.value) or self.is_traced(node.target)
                ):
                    self._taint_target(node.target)
        for node in self._own_nodes():
            self._check(node)

    def _own_nodes(self):
        """ast.walk minus nested function bodies: a nested def is judged
        by its own _TracedChecker (it is traced-reachable through the
        call graph), so descending here would double-report and cross
        two scopes' taint sets."""
        stack = list(ast.iter_child_nodes(self.fn))
        while stack:
            node = stack.pop()
            yield node
            if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                stack.extend(ast.iter_child_nodes(node))

    def _taint_target(self, tgt: ast.AST) -> None:
        if isinstance(tgt, ast.Name):
            self.traced.add(tgt.id)
        elif isinstance(tgt, (ast.Tuple, ast.List)):
            for e in tgt.elts:
                self._taint_target(e)

    def _emit(self, rule: str, node: ast.AST, msg: str) -> None:
        self.findings.append(Finding(
            rule, self.path, node.lineno, node.col_offset, msg
        ))

    def _check(self, node: ast.AST) -> None:
        if isinstance(node, ast.Call):
            d = _dotted(node.func)
            if d in _CLOCK_CALLS:
                self._emit(
                    "NHD106", node,
                    f"{d}() inside jit-traced '{self.fn.name}' runs at "
                    "trace time only — the value is a constant folded "
                    "into the compiled program, so the timing is wrong "
                    "on every cached call; time on the host around the "
                    "dispatch (nhd_tpu.utils.tracing.phase)",
                )
            elif (
                d in _COERCIONS
                and node.args
                and self.is_traced(node.args[0])
            ):
                self._emit(
                    "NHD101", node,
                    f"{d}() coerces a traced value inside jit-traced "
                    f"'{self.fn.name}': concretization error or silent "
                    "host sync — keep it a jnp array or hoist to the host",
                )
            elif d and (d.startswith("np.") or d.startswith("numpy.")) and (
                any(self.is_traced(a) for a in node.args)
            ):
                self._emit(
                    "NHD103", node,
                    f"{d}() applies host numpy to a traced value inside "
                    f"jit-traced '{self.fn.name}': use jnp / lax so the op "
                    "stays in the program",
                )
        elif isinstance(node, (ast.If, ast.While)) and self.is_traced(
            node.test
        ):
            kw = "if" if isinstance(node, ast.If) else "while"
            self._emit(
                "NHD102", node,
                f"Python '{kw}' on a traced value inside jit-traced "
                f"'{self.fn.name}': use jnp.where/lax.cond (branch decides "
                "at trace time, not per element)",
            )
        elif isinstance(node, ast.Assert) and self.is_traced(node.test):
            self._emit(
                "NHD102", node,
                f"assert on a traced value inside jit-traced "
                f"'{self.fn.name}': asserts run at trace time only — use "
                "checkify or validate on the host",
            )


def _check_jit_construction(
    tree: ast.Module, jit_names: Set[str], path: str,
    functions: Dict[str, List[ast.FunctionDef]],
) -> List[Finding]:
    """NHD104 (uncached per-call jit wrappers) + NHD105 (unhashable
    static-arg defaults)."""
    findings: List[Finding] = []

    class V(ast.NodeVisitor):
        def __init__(self):
            self.fn_stack: List[ast.FunctionDef] = []
            self.loop_depth = 0

        def _cached(self, fn: ast.FunctionDef) -> bool:
            for dec in fn.decorator_list:
                target = dec.func if isinstance(dec, ast.Call) else dec
                d = _dotted(target) or ""
                if d.split(".")[-1] in _CACHE_DECORATORS:
                    return True
            return False

        def _visit_func(self, node) -> None:
            # decorators evaluate once at def time, in the ENCLOSING
            # scope — '@partial(jax.jit, ...)' on a module-level def is
            # fine, while the same decorator on a def nested in an
            # uncached factory is a per-call construction and flags
            decorators = set(map(id, node.decorator_list))
            for dec in node.decorator_list:
                self.visit(dec)
            self.fn_stack.append(node)
            outer_loops, self.loop_depth = self.loop_depth, 0
            for child in ast.iter_child_nodes(node):
                if id(child) not in decorators:
                    self.visit(child)
            self.loop_depth = outer_loops
            self.fn_stack.pop()

        visit_FunctionDef = _visit_func
        visit_AsyncFunctionDef = _visit_func

        def visit_For(self, node) -> None:
            self._visit_loop(node)

        def visit_While(self, node) -> None:
            self._visit_loop(node)

        def _visit_loop(self, node) -> None:
            self.loop_depth += 1
            self.generic_visit(node)
            self.loop_depth -= 1

        def visit_Call(self, node: ast.Call) -> None:
            jc = _jit_call(node, jit_names)
            if jc is not None:
                self._check_104(node)
                self._check_105(node)
            self.generic_visit(node)

        def _check_104(self, node: ast.Call) -> None:
            if self.loop_depth > 0:
                findings.append(Finding(
                    "NHD104", path, node.lineno, node.col_offset,
                    "jax.jit constructed inside a loop: every iteration "
                    "gets a fresh wrapper with an empty compile cache — "
                    "hoist it out (one wrapper per bucket shape)",
                ))
            elif self.fn_stack and not any(
                self._cached(f) for f in self.fn_stack
            ):
                findings.append(Finding(
                    "NHD104", path, node.lineno, node.col_offset,
                    f"jax.jit constructed per call of "
                    f"'{self.fn_stack[-1].name}': recompiles on every "
                    "invocation — cache the wrapper (functools.lru_cache "
                    "keyed on the bucket shape) or hoist to module scope",
                ))

        def _check_105(self, node: ast.Call) -> None:
            static_nums: List[int] = []
            static_names: List[str] = []
            for kw in node.keywords:
                if kw.arg == "static_argnums":
                    static_nums = _int_list(kw.value)
                elif kw.arg == "static_argnames":
                    static_names = _str_list(kw.value)
            if not static_nums and not static_names:
                return
            target = node.args[0] if node.args else None
            # partial(jax.jit, ...) has the fn elsewhere; only direct
            # jax.jit(fn, static_...) resolves
            if not isinstance(target, ast.Name):
                return
            for fn in functions.get(target.id, []):
                args = fn.args.posonlyargs + fn.args.args
                n_nodefault = len(args) - len(fn.args.defaults)
                for i, a in enumerate(args):
                    if i in static_nums or a.arg in static_names:
                        j = i - n_nodefault
                        if j >= 0 and _is_mutable_literal(
                            fn.args.defaults[j]
                        ):
                            findings.append(Finding(
                                "NHD105", path, node.lineno,
                                node.col_offset,
                                f"static arg '{a.arg}' of '{fn.name}' "
                                "defaults to an unhashable value: the jit "
                                "cache keys statics by hash — use a tuple "
                                "/ frozenset / hashable config object",
                            ))

    V().visit(tree)
    return findings


def _int_list(node: ast.AST) -> List[int]:
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        return [node.value]
    if isinstance(node, (ast.Tuple, ast.List)):
        return [e.value for e in node.elts
                if isinstance(e, ast.Constant) and isinstance(e.value, int)]
    return []


def _str_list(node: ast.AST) -> List[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return [node.value]
    if isinstance(node, (ast.Tuple, ast.List)):
        return [e.value for e in node.elts
                if isinstance(e, ast.Constant) and isinstance(e.value, str)]
    return []


def _is_mutable_literal(node: ast.AST) -> bool:
    if isinstance(node, (ast.List, ast.Dict, ast.Set, ast.ListComp,
                         ast.DictComp, ast.SetComp)):
        return True
    if isinstance(node, ast.Call):
        d = _dotted(node.func) or ""
        return d.split(".")[-1] in ("list", "dict", "set", "bytearray")
    return False


# ---------------------------------------------------------------------------
# NHD107 — host-sync operations in solver hot-path modules
# ---------------------------------------------------------------------------
#
# Every device→host pull costs a full relay flush on the tunnel-attached
# TPU (~65-84 ms regardless of size, docs/TPU_STATUS.md), and a stray
# block_until_ready / device_get / np.asarray in a round loop silently
# serializes the async dispatch pipeline the whole overhead war built.
# Inside nhd_tpu/solver/ the contract is: batch transfers with
# copy_to_host_async and pull at ONE sanctioned flush point per round —
# those sites carry inline suppressions; anything else flags.

import re as _re

_SOLVER_SCOPE_PARTS = ("solver",)
#: call names whose results are (or carry) device arrays — the taint
#: seeds for the np.asarray/np.array judgement
_DEVICE_RESULT = _re.compile(r"(solve|rank|megaround|speculat|fused)")
_SYNC_PULLS = {
    "np.asarray", "np.array", "np.copy",
    "numpy.asarray", "numpy.array", "numpy.copy",
}


def _in_solver_scope(path: str) -> bool:
    parts = path.replace("\\", "/").split("/")
    return any(p in parts for p in _SOLVER_SCOPE_PARTS)


class _HostSyncChecker:
    """Per-function device-taint dataflow: which local names hold (or
    contain) values returned by a solver dispatch.

    Two tiers. STRONG taint flows through plain assignments whose value
    is (or is derived from) a dispatch call — these names definitely
    hold device arrays, so even scalar pulls (``int()``, ``.item()``)
    flag on them. WEAK taint additionally flows through loop targets:
    iterating a dispatch-derived collection often yields HOST tuples
    whose names get reused (flow-insensitive taint cannot un-taint), so
    only the unmistakable array pulls (np.asarray/np.array/np.copy)
    flag at that tier — a deliberate false-negative trade to keep the
    gate quiet on host bookkeeping loops."""

    def __init__(self, fn, findings: List[Finding], path: str,
                 device_get_names: Set[str]):
        self.fn = fn
        self.findings = findings
        self.path = path
        self.device_get_names = device_get_names
        self.dev: Set[str] = set()      # weak OR strong
        self.strong: Set[str] = set()

    def _own_nodes(self):
        stack = list(ast.iter_child_nodes(self.fn))
        while stack:
            node = stack.pop()
            yield node
            if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                stack.extend(ast.iter_child_nodes(node))

    def _is_dispatch_call(self, node: ast.AST) -> bool:
        if not isinstance(node, ast.Call):
            return False
        d = _dotted(node.func) or ""
        if _DEVICE_RESULT.search(d.split(".")[-1]):
            return True
        return isinstance(node.func, ast.Attribute) and bool(
            _DEVICE_RESULT.search(node.func.attr)
        )

    def _tainted(self, node: ast.AST, names: Set[str]) -> bool:
        if isinstance(node, ast.Name):
            return node.id in names
        if isinstance(node, (ast.Attribute, ast.Subscript, ast.Starred)):
            return self._tainted(node.value, names)
        if isinstance(node, (ast.Tuple, ast.List)):
            return any(self._tainted(e, names) for e in node.elts)
        if isinstance(node, ast.Call):
            return self._is_dispatch_call(node)
        return False

    def is_dev(self, node: ast.AST) -> bool:
        return self._tainted(node, self.dev)

    def is_strong(self, node: ast.AST) -> bool:
        return self._tainted(node, self.strong)

    def _taint(self, tgt: ast.AST, strong: bool) -> bool:
        changed = False
        if isinstance(tgt, ast.Name):
            if tgt.id not in self.dev:
                self.dev.add(tgt.id)
                changed = True
            if strong and tgt.id not in self.strong:
                self.strong.add(tgt.id)
                changed = True
        elif isinstance(tgt, (ast.Tuple, ast.List)):
            for e in tgt.elts:
                changed |= self._taint(e, strong)
        elif isinstance(tgt, ast.Starred):
            changed = self._taint(tgt.value, strong)
        return changed

    def run(self) -> None:
        # fixed point: taint chains (dispatch -> name -> name -> loop
        # target) settle regardless of statement order
        for _ in range(8):
            changed = False
            for node in self._own_nodes():
                if isinstance(node, ast.Assign) and self.is_dev(node.value):
                    for tgt in node.targets:
                        changed |= self._taint(
                            tgt, self.is_strong(node.value)
                        )
                elif isinstance(node, ast.AnnAssign) and (
                    node.value is not None and self.is_dev(node.value)
                ):
                    changed |= self._taint(
                        node.target, self.is_strong(node.value)
                    )
                elif isinstance(node, ast.AugAssign) and (
                    self.is_dev(node.value) or self.is_dev(node.target)
                ):
                    changed |= self._taint(node.target, False)
                elif isinstance(node, (ast.For, ast.AsyncFor)) and self.is_dev(
                    node.iter
                ):
                    changed |= self._taint(node.target, False)
            if not changed:
                break
        for node in self._own_nodes():
            if not isinstance(node, ast.Call):
                continue
            d = _dotted(node.func) or ""
            if isinstance(node.func, ast.Attribute) and (
                node.func.attr == "block_until_ready"
            ):
                self._emit(node, "block_until_ready() blocks the host on "
                                 "the device pipeline")
            elif isinstance(node.func, ast.Attribute) and (
                node.func.attr == "item" and self.is_strong(node.func.value)
            ):
                self._emit(node, ".item() on a device array is a "
                                 "synchronous host pull")
            elif d == "jax.device_get" or d in self.device_get_names:
                self._emit(node, "jax.device_get() forces a synchronous "
                                 "device→host transfer")
            elif (
                d in ("int", "float")
                and node.args
                and self.is_strong(node.args[0])
            ):
                self._emit(node, f"{d}() on a device array blocks on the "
                                 "dispatch to concretize the scalar")
            elif d in _SYNC_PULLS and node.args and self.is_dev(node.args[0]):
                self._emit(node, f"{d}() on a device array is a "
                                 "synchronous host pull")

    def _emit(self, node: ast.AST, what: str) -> None:
        self.findings.append(Finding(
            "NHD107", self.path, node.lineno, node.col_offset,
            f"host-sync in solver hot path '{self.fn.name}': {what} — "
            "each pull pays a full relay flush; batch transfers with "
            "copy_to_host_async and pull at the round's ONE sanctioned "
            "flush point (suppress intentional flush sites inline)",
        ))


def _check_host_sync(tree: ast.Module, path: str) -> List[Finding]:
    if not _in_solver_scope(path):
        return []
    device_get_names: Set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom) and node.module == "jax":
            for alias in node.names:
                if alias.name == "device_get":
                    device_get_names.add(alias.asname or "device_get")
    findings: List[Finding] = []
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            _HostSyncChecker(node, findings, path, device_get_names).run()
    return findings


# ---------------------------------------------------------------------------
# NHD108 — full cluster re-encode on a per-event / per-round hot path
# ---------------------------------------------------------------------------
#
# encode_cluster() re-projects EVERY node (O(N) host work); the
# incremental state layer (solver/encode.py ClusterDelta) exists so that
# per-event and per-round paths pay O(changed rows) instead — full
# rebuilds are fallback events that belong to the sanctioned chokepoints.
# Inside nhd_tpu/solver/ and nhd_tpu/scheduler/ any other call flags;
# deliberate one-shot batch sites carry inline suppressions (same
# contract shape as NHD107's sanctioned flush points).

_ENCODE_SCOPE_PARTS = ("solver", "scheduler")
#: enclosing functions allowed to issue the full re-encode: the delta
#: layer's rebuild chokepoint, its parity checker, and the one-shot
#: context builder. A `module:function` entry sanctions the function in
#: that module only — used for surfaces that are chokepoints by design
#: rather than by name (registering one here replaces an inline
#: suppression; the registry is reviewable, the scatter of ignores
#: was not).
_ENCODE_SANCTIONED = {
    "_rebuild", "rebuild", "make_context", "parity_errors",
    # the oracle-parity batch surface: one-shot snapshot evaluation, no
    # rounds and no events, so a delta would have nothing to reuse
    "jax_matcher:find_nodes",
}


def _check_encode_calls(tree: ast.Module, path: str) -> List[Finding]:
    parts = path.replace("\\", "/").split("/")
    if not any(p in parts for p in _ENCODE_SCOPE_PARTS):
        return []
    if parts[-1] == "encode.py":
        return []  # the chokepoint module itself defines the rebuild
    modname = parts[-1][:-3] if parts[-1].endswith(".py") else parts[-1]
    findings: List[Finding] = []

    class V(ast.NodeVisitor):
        def __init__(self):
            self._stack: List[str] = []

        def _visit_func(self, node) -> None:
            self._stack.append(node.name)
            self.generic_visit(node)
            self._stack.pop()

        visit_FunctionDef = _visit_func
        visit_AsyncFunctionDef = _visit_func

        def visit_Call(self, node: ast.Call) -> None:
            d = _dotted(node.func) or ""
            if d == "encode_cluster" or d.endswith(".encode_cluster"):
                fn = self._stack[-1] if self._stack else "<module>"
                if fn not in _ENCODE_SANCTIONED \
                        and f"{modname}:{fn}" not in _ENCODE_SANCTIONED:
                    findings.append(Finding(
                        "NHD108", path, node.lineno, node.col_offset,
                        f"full encode_cluster() in '{fn}' re-projects "
                        "every node (O(N) host work) on a per-event/"
                        "per-round path: get-or-apply row deltas through "
                        "the incremental state (solver/encode.py "
                        "ClusterDelta + refresh_context) instead — full "
                        "rebuilds belong to the sanctioned chokepoints; "
                        "suppress deliberate one-shot batch sites inline",
                    ))
            self.generic_visit(node)

    V().visit(tree)
    return findings


def check_module(tree: ast.Module, src: str, path: str) -> List[Finding]:
    jit_names = _collect_jit_aliases(tree)
    index = _FunctionIndex(jit_names)
    index.visit(tree)

    # propagate tracedness through module-local calls to a fixed point
    traced: Set[str] = set(index.jit_roots)
    changed = True
    while changed:
        changed = False
        for name in list(traced):
            for fn in index.functions.get(name, []):
                for callee in index.calls.get(id(fn), ()):
                    if callee in index.functions and callee not in traced:
                        traced.add(callee)
                        changed = True

    findings: List[Finding] = []
    for name in sorted(traced):
        for fn in index.functions.get(name, []):
            _TracedChecker(fn, findings, path).run()
    findings.extend(
        _check_jit_construction(tree, jit_names, path, index.functions)
    )
    findings.extend(_check_host_sync(tree, path))
    findings.extend(_check_encode_calls(tree, path))
    return findings
