"""NHD4xx — determinism in solver and encode paths.

Two schedulers replaying the same watch stream must produce the same
placements: multihost ranks solve disjoint shards of one cluster and any
rank-local entropy desynchronizes them, and the chaos-soak / oracle-vs-
batch equivalence tests only mean something when a solve is a pure
function of cluster state. So inside ``nhd_tpu/solver/`` (which includes
the encode path):

* NHD401 — global-RNG calls (``random.*``, ``np.random.*``). Simulation
  code (``nhd_tpu/sim/``) seeds its generators explicitly and is out of
  scope; the solver must not roll dice at all.
* NHD402 — wall-clock reads (``time.time``, ``datetime.now``). Busy-decay
  and stats use the caller-passed ``now`` / ``time.monotonic`` /
  ``time.perf_counter``, which stay allowed; calendar time in a solve
  makes placement depend on when you run it.
"""

from __future__ import annotations

import ast
from typing import List

from nhd_tpu.analysis.core import Finding, _dotted

# module-path gate: the pack judges only solver/encode code
_SCOPE_PARTS = ("solver",)

_RANDOM_FUNCS = {
    "random", "randint", "randrange", "uniform", "choice", "choices",
    "shuffle", "sample", "gauss", "normalvariate", "betavariate",
    "expovariate", "triangular", "vonmisesvariate", "getrandbits",
    "rand", "randn", "permutation", "normal", "standard_normal", "bytes",
}
_WALLCLOCK = {
    "time.time", "time.time_ns", "datetime.now", "datetime.utcnow",
    "datetime.today", "date.today",
}


def _in_scope(path: str) -> bool:
    parts = path.replace("\\", "/").split("/")
    return any(p in parts for p in _SCOPE_PARTS)


def check_module(tree: ast.Module, src: str, path: str) -> List[Finding]:
    if not _in_scope(path):
        return []

    # global-RNG names imported from the random modules: `from random
    # import shuffle`. Only names in _RANDOM_FUNCS count — seeded
    # constructors (Random, default_rng, Generator) are the rule's own
    # recommended remedy and must never be flagged.
    from_random: set = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom) and node.module in (
            "random", "numpy.random"
        ):
            for alias in node.names:
                if alias.name in _RANDOM_FUNCS:
                    from_random.add(alias.asname or alias.name)

    findings: List[Finding] = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        d = _dotted(node.func)
        if d is None:
            continue
        head, _, tail = d.rpartition(".")
        if (
            (head in ("random", "np.random", "numpy.random")
             and tail in _RANDOM_FUNCS)
            or (not head and tail in from_random)
        ):
            findings.append(Finding(
                "NHD401", path, node.lineno, node.col_offset,
                f"{d}() draws from a global unseeded RNG inside the "
                "solver path: placement must be a pure function of "
                "cluster state — thread an explicit seeded generator (or "
                "jax.random key) through the caller",
            ))
        elif d in _WALLCLOCK or (
            tail in ("now", "utcnow") and head.endswith("datetime")
        ):
            findings.append(Finding(
                "NHD402", path, node.lineno, node.col_offset,
                f"{d}() reads the wall clock inside the solver path: "
                "placement would depend on when the solve runs — use the "
                "caller-passed 'now' or time.monotonic/perf_counter",
            ))
    return findings
