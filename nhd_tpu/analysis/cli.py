"""``python -m nhd_tpu.analysis`` — the nhdlint command line.

Exit codes: 0 = clean (or everything baselined/suppressed), 1 = new
findings, 2 = usage error. Output formats: human (default, one line per
finding, grep-friendly) and ``--format json`` (stable schema for CI
annotation tooling).
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path
from typing import List, Optional, Sequence

from nhd_tpu.analysis.core import (
    ALL_PACK_NAMES,
    Finding,
    RULES,
    analyze_paths,
    load_baseline,
    subtract_baseline,
    write_baseline,
)

DEFAULT_BASELINE = ".nhdlint-baseline.json"


def _parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="nhdlint",
        description="AST-based static analysis for JAX tracing hazards, "
                    "lock discipline, exception hygiene and scheduler "
                    "determinism (see docs/STATIC_ANALYSIS.md).",
    )
    p.add_argument("paths", nargs="*", default=["nhd_tpu"],
                   help="files or directories to analyze (default: nhd_tpu)")
    p.add_argument("--packs", default=",".join(ALL_PACK_NAMES),
                   help=f"comma-separated packs to run (default: all of "
                        f"{','.join(ALL_PACK_NAMES)})")
    p.add_argument("--exclude", action="append", default=[],
                   metavar="PATTERN",
                   help="fnmatch pattern of paths to skip (repeatable; "
                        "matches whole paths, suffixes, or directory "
                        "segments — e.g. tests/fixtures)")
    p.add_argument("--lock-graph-json", metavar="FILE", default=None,
                   help="write the interprocedural lock graph (locks, "
                        "order edges, inversions) as JSON")
    p.add_argument("--lock-graph-dot", metavar="FILE", default=None,
                   help="write the lock graph as Graphviz DOT (inverted "
                        "pairs highlighted)")
    p.add_argument("-f", "--format", dest="fmt", choices=("human", "json"),
                   default="human")
    p.add_argument("--baseline", default=None,
                   help=f"baseline JSON of grandfathered findings "
                        f"(default: ./{DEFAULT_BASELINE} if present)")
    p.add_argument("--write-baseline", action="store_true",
                   help="write current findings to the baseline file and "
                        "exit 0 (grandfather everything now visible)")
    p.add_argument("--no-baseline", action="store_true",
                   help="ignore any baseline file (report all findings)")
    p.add_argument("--list-rules", action="store_true",
                   help="print the rule catalogue and exit")
    p.add_argument("--diff-base", metavar="REV", default=None,
                   help="differential mode: only findings on lines "
                        "changed since REV (git diff) affect the exit "
                        "code; off-diff findings are reported as "
                        "advisory. The baseline still applies first.")
    p.add_argument("--sarif", metavar="FILE", default=None,
                   help="also write findings as SARIF 2.1.0 (all "
                        "post-baseline findings, independent of "
                        "--diff-base gating)")
    return p


def _changed_lines(rev: str) -> Optional[dict]:
    """{repo-relative path: set of changed line numbers} from
    ``git diff -U0 REV``, or None if git fails (treated as a usage
    error by the caller — a bad REV must not read as 'clean')."""
    import subprocess

    try:
        proc = subprocess.run(
            ["git", "diff", "--no-color", "--unified=0", rev, "--", "*.py"],
            capture_output=True, text=True, timeout=60,
        )
    except (OSError, subprocess.TimeoutExpired):
        return None
    if proc.returncode != 0:
        return None
    changed: dict = {}
    path = None
    for raw in proc.stdout.splitlines():
        if raw.startswith("+++ b/"):
            path = raw[6:].strip()
        elif raw.startswith("+++"):
            path = None  # /dev/null (deletion) or unusual prefix
        elif raw.startswith("@@") and path is not None:
            # @@ -a[,b] +c[,d] @@ — new-file side span is c..c+d-1
            try:
                new_span = raw.split("+", 1)[1].split(" ", 1)[0]
            except IndexError:
                continue
            start, _, count = new_span.partition(",")
            first = int(start)
            n = int(count) if count else 1
            if n > 0:
                changed.setdefault(path, set()).update(
                    range(first, first + n)
                )
    return changed


def _write_sarif(findings: List[Finding], out: Path) -> None:
    """SARIF 2.1.0 — one run, rule metadata from the catalogue, stable
    partialFingerprints so CI viewers track findings across pushes."""
    seen_rules = sorted({f.rule for f in findings})
    doc = {
        "$schema": "https://raw.githubusercontent.com/oasis-tcs/"
                   "sarif-spec/master/Schemata/sarif-schema-2.1.0.json",
        "version": "2.1.0",
        "runs": [{
            "tool": {"driver": {
                "name": "nhdlint",
                "informationUri": "docs/STATIC_ANALYSIS.md",
                "rules": [
                    {
                        "id": rule,
                        "shortDescription": {"text": RULES[rule][1]},
                        "properties": {"pack": RULES[rule][0]},
                    }
                    for rule in seen_rules if rule in RULES
                ],
            }},
            "results": [
                {
                    "ruleId": f.rule,
                    "level": "error",
                    "message": {"text": f.message},
                    "locations": [{
                        "physicalLocation": {
                            "artifactLocation": {"uri": f.path},
                            "region": {
                                "startLine": f.line,
                                "startColumn": f.col + 1,
                            },
                        },
                    }],
                    "partialFingerprints": {
                        "nhdlintFingerprint/v1": f.fingerprint(),
                    },
                }
                for f in findings
            ],
        }],
    }
    out.parent.mkdir(parents=True, exist_ok=True)
    out.write_text(json.dumps(doc, indent=2) + "\n")


def _resolve_packs(arg: str) -> Optional[List[str]]:
    packs = [x.strip() for x in arg.split(",") if x.strip()]
    if not packs:
        # an empty selection (e.g. --packs "$UNSET_VAR") must not read
        # as "clean" with zero rules run — same reasoning as the
        # no-files-found guard below
        print("nhdlint: --packs selected no packs "
              f"(have: {', '.join(ALL_PACK_NAMES)})", file=sys.stderr)
        return None
    unknown = [x for x in packs if x not in ALL_PACK_NAMES]
    if unknown:
        print(f"nhdlint: unknown pack(s): {', '.join(unknown)} "
              f"(have: {', '.join(ALL_PACK_NAMES)})", file=sys.stderr)
        return None
    return packs


def main(argv: Optional[Sequence[str]] = None) -> int:
    args = _parser().parse_args(argv)

    if args.list_rules:
        for rule, (pack, desc) in sorted(RULES.items()):
            print(f"{rule}  [{pack:<11}] {desc}")
        return 0

    packs = _resolve_packs(args.packs)
    if packs is None:
        return 2

    modules: List = []
    reports = analyze_paths(
        args.paths, packs, exclude=args.exclude, modules_out=modules
    )
    if not reports:
        # a path typo must not read as "clean" — that would silently
        # disable the whole lint tier in make lint / CI
        print(f"nhdlint: no Python files found under: "
              f"{', '.join(args.paths)}", file=sys.stderr)
        return 2

    if args.lock_graph_json or args.lock_graph_dot:
        from nhd_tpu.analysis.lockgraph import build_lock_graph, lock_graph_dot

        graph = build_lock_graph(modules)
        if args.lock_graph_json:
            Path(args.lock_graph_json).write_text(
                json.dumps(graph, indent=2) + "\n"
            )
            print(f"nhdlint: lock graph -> {args.lock_graph_json}",
                  file=sys.stderr)
        if args.lock_graph_dot:
            Path(args.lock_graph_dot).write_text(lock_graph_dot(graph))
            print(f"nhdlint: lock graph DOT -> {args.lock_graph_dot}",
                  file=sys.stderr)

    findings: List[Finding] = [f for r in reports for f in r.findings]
    suppressed = sum(r.suppressed for r in reports)
    unused_ignores = [
        (r.path, line) for r in reports for line in r.unused_ignores
    ]

    baseline_path = Path(args.baseline or DEFAULT_BASELINE)
    if args.write_baseline:
        if set(packs) != set(ALL_PACK_NAMES):
            # a subset write would silently drop every other pack's
            # grandfathered entries from the file
            print("nhdlint: --write-baseline requires all packs "
                  "(drop --packs)", file=sys.stderr)
            return 2
        write_baseline(findings, baseline_path)
        print(f"nhdlint: wrote {len(findings)} finding(s) to "
              f"{baseline_path}")
        return 0

    baselined = 0
    if not args.no_baseline and (args.baseline or baseline_path.exists()):
        try:
            baseline = load_baseline(baseline_path)
        except (ValueError, json.JSONDecodeError) as exc:
            print(f"nhdlint: bad baseline {baseline_path}: {exc}",
                  file=sys.stderr)
            return 2
        findings, baselined = subtract_baseline(findings, baseline)

    if args.sarif:
        _write_sarif(findings, Path(args.sarif))
        print(f"nhdlint: SARIF -> {args.sarif}", file=sys.stderr)

    advisory: List[Finding] = []
    if args.diff_base is not None:
        changed = _changed_lines(args.diff_base)
        if changed is None:
            print(f"nhdlint: git diff against {args.diff_base!r} failed",
                  file=sys.stderr)
            return 2
        on_diff = []
        for f in findings:
            (on_diff if f.line in changed.get(f.path, ()) else advisory) \
                .append(f)
        findings = on_diff

    if args.fmt == "json":
        print(json.dumps({
            "findings": [f.to_dict() for f in findings],
            "files": len(reports),
            "suppressed": suppressed,
            "baselined": baselined,
            "unused_ignores": [
                {"path": p, "line": line} for p, line in unused_ignores
            ],
            "packs": packs,
            "advisory": [f.to_dict() for f in advisory],
        }, indent=2))
    else:
        for f in findings:
            print(f"{f.path}:{f.line}:{f.col + 1}: {f.rule} {f.message}")
            if f.snippet:
                print(f"    {f.snippet}")
        for p, line in unused_ignores:
            # advisory, not an exit-code failure: a stale directive can
            # mask a future finding on its line, so keep them visible
            print(f"{p}:{line}: warning: unused 'nhdlint: ignore' directive")
        for f in advisory:
            # off-diff in --diff-base mode: visible, never exit-affecting
            print(f"{f.path}:{f.line}:{f.col + 1}: advisory: "
                  f"{f.rule} {f.message}")
        tail = (f"{len(findings)} finding(s) in {len(reports)} file(s)"
                f" ({suppressed} suppressed, {baselined} baselined, "
                f"{len(unused_ignores)} unused ignore(s))")
        if args.diff_base is not None:
            tail += f"; {len(advisory)} off-diff advisory"
        print(f"nhdlint: {tail}" if findings else f"nhdlint: clean — {tail}")

    return 1 if findings else 0


if __name__ == "__main__":
    raise SystemExit(main())
