"""Cross-module model of the solve-signature contract (NHD7xx pack).

The solver's 25-array solve signature is a *convention* threaded through
eight-plus modules: ``kernel._ARG_ORDER``/``_POD_ARG_ORDER`` name the
arrays, ``encode.DELTA_FIELDS`` mirrors them for the delta layer,
``_MUTABLE``/``_STATIC`` partition them for donation and out-shardings,
``parallel/sharding`` and the kernel's mesh solvers span ``in_shardings``
over them, ``speculate`` strides the flattened pod block by their count,
and ``aot`` hashes the defining modules into the program fingerprint.
PRs that extend the signature must touch every one of those sites; the
one time a site was missed it surfaced only as a runtime parity failure.

This module extracts the *facts* — tuple definitions, ``.index()`` refs,
stride arithmetic, sharding spans, fingerprint sources, env-knob reads,
the knob registry — from a parsed project (``ModuleSource`` set) into a
:class:`ContractModel`. ``rules_contract.py`` judges the facts. Keeping
extraction separate from judgement means a future consumer layer (the
ROADMAP's ragged/autotuner work) adds one extractor + one check, not a
new visitor.

Everything here is stdlib-``ast`` only: the model is built from source
text, never by importing solver modules (the gate must run without jax).
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

from nhd_tpu.analysis.core import ModuleSource, _dotted

#: the contract tuple names the model tracks, wherever they are defined
CONTRACT_TUPLE_NAMES = (
    "_ARG_ORDER", "_POD_ARG_ORDER", "_MUTABLE", "_STATIC", "DELTA_FIELDS",
)

#: flattened-pod-block variables whose stride arithmetic is contract-bound
STRIDE_BASES = ("pod_args",)


def module_basename(path: str) -> str:
    """'kernel' for 'nhd_tpu/solver/kernel.py' — the unit fingerprint
    sources and tuple definitions are matched on."""
    name = path.rsplit("/", 1)[-1]
    return name[:-3] if name.endswith(".py") else name


@dataclass(frozen=True)
class TupleDef:
    """A module-level literal tuple/list-of-strings contract definition."""

    name: str
    path: str
    line: int
    col: int
    fields: Tuple[str, ...]


@dataclass(frozen=True)
class IndexRef:
    """``<tuple>.index("field")`` — a positional consumer of the contract."""

    path: str
    line: int
    col: int
    tuple_name: str
    field_name: str


@dataclass(frozen=True)
class StrideSite:
    """``base[K*b : K*b + K]`` over a flattened pod block."""

    path: str
    line: int
    col: int
    stride: int


@dataclass(frozen=True)
class UnpackSite:
    """Tuple-unpack of a pod-block slice: arity must match the contract."""

    path: str
    line: int
    col: int
    arity: int


@dataclass(frozen=True)
class ShardingSite:
    """``in_shardings=(spec,)*A + (spec2,)*B``: the node/pod spans.

    Each span is either a literal int (judgeable) or the contract tuple
    name whose ``len()`` it takes (symbolic — consistent by construction,
    recorded so the rule can confirm it derives from the *right* tuple).
    A span that is neither (an opaque expression) is ``None``/``None``
    and stays unjudged.
    """

    path: str
    line: int
    col: int
    node_count: Optional[int]
    node_sym: Optional[str]
    pod_count: Optional[int]
    pod_sym: Optional[str]


@dataclass(frozen=True)
class FingerprintSite:
    """``for mod in (a, b): h.update(inspect.getsource(mod)...)`` inside a
    *fingerprint* function — the AOT cache-key source list, resolved to
    module basenames through the import table."""

    path: str
    line: int
    col: int
    hashed: Tuple[str, ...]


@dataclass(frozen=True)
class EnvRead:
    """One ``NHD_*`` environment read (os.environ.get / os.getenv /
    os.environ[...])."""

    path: str
    line: int
    col: int
    name: str


@dataclass(frozen=True)
class KnobRegistry:
    """A module-level ``KNOBS = (Knob(...), ...)`` registry."""

    path: str
    line: int
    names: Tuple[str, ...]


@dataclass
class ContractModel:
    """Everything rules_contract.py judges, extracted in one pass."""

    tuple_defs: Dict[str, List[TupleDef]] = field(default_factory=dict)
    index_refs: List[IndexRef] = field(default_factory=list)
    stride_sites: List[StrideSite] = field(default_factory=list)
    unpack_sites: List[UnpackSite] = field(default_factory=list)
    sharding_sites: List[ShardingSite] = field(default_factory=list)
    fingerprint_sites: List[FingerprintSite] = field(default_factory=list)
    env_reads: List[EnvRead] = field(default_factory=list)
    registries: List[KnobRegistry] = field(default_factory=list)
    #: basenames of modules defining ``get_tables`` (the combo tables —
    #: a required fingerprint source alongside the _ARG_ORDER module)
    table_modules: List[str] = field(default_factory=list)

    def first_def(self, name: str) -> Optional[TupleDef]:
        defs = self.tuple_defs.get(name)
        return defs[0] if defs else None


def _literal_str_tuple(node: ast.AST) -> Optional[Tuple[str, ...]]:
    """('a', 'b') for a Tuple/List of string constants, else None."""
    if not isinstance(node, (ast.Tuple, ast.List)):
        return None
    out: List[str] = []
    for elt in node.elts:
        if not (isinstance(elt, ast.Constant) and isinstance(elt.value, str)):
            return None
        out.append(elt.value)
    return tuple(out)


def _import_table(tree: ast.Module) -> Dict[str, str]:
    """local name -> imported basename, for resolving fingerprint-source
    Names. Function-level imports count: aot imports kernel/combos inside
    program_fingerprint() to dodge an import cycle."""
    table: Dict[str, str] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for alias in node.names:
                if alias.asname:
                    # `import nhd_tpu.solver.kernel as kernel` -> kernel
                    table[alias.asname] = alias.name.rsplit(".", 1)[-1]
                else:
                    # `import os` / `import a.b` binds the top name
                    top = alias.name.split(".")[0]
                    table[top] = top
        elif isinstance(node, ast.ImportFrom):
            for alias in node.names:
                table[alias.asname or alias.name] = alias.name
    return table


def _stride_term(node: ast.AST) -> Optional[int]:
    """K for a ``K*i`` / ``i*K`` product with one int constant, else None."""
    if not (isinstance(node, ast.BinOp) and isinstance(node.op, ast.Mult)):
        return None
    left, right = node.left, node.right
    if isinstance(left, ast.Constant) and isinstance(left.value, int):
        return left.value
    if isinstance(right, ast.Constant) and isinstance(right.value, int):
        return right.value
    return None


def _is_stride_base(node: ast.AST) -> bool:
    if isinstance(node, ast.Name):
        return node.id in STRIDE_BASES
    if isinstance(node, ast.Attribute):
        return node.attr in STRIDE_BASES
    return False


def _span_of(node: ast.AST, len_aliases: Dict[str, str]):
    """(count, sym) for one ``(spec,)*X`` sharding span term: a literal
    int count, or the contract tuple name X takes ``len()`` of (directly
    or through a ``n = len(_ARG_ORDER)`` local alias)."""
    if not (isinstance(node, ast.BinOp) and isinstance(node.op, ast.Mult)):
        return None, None
    for operand in (node.left, node.right):
        if isinstance(operand, ast.Constant) and isinstance(operand.value, int):
            return operand.value, None
        sym = _len_target(operand, len_aliases)
        if sym is not None:
            return None, sym
    return None, None


def _len_target(node: ast.AST, len_aliases: Dict[str, str]) -> Optional[str]:
    """NAME for ``len(NAME)`` or a local alias of it, else None."""
    if (
        isinstance(node, ast.Call)
        and isinstance(node.func, ast.Name)
        and node.func.id == "len"
        and len(node.args) == 1
        and isinstance(node.args[0], ast.Name)
    ):
        return node.args[0].id
    if isinstance(node, ast.Name):
        return len_aliases.get(node.id)
    return None


_ENV_GET_CALLS = {"os.environ.get", "environ.get", "os.getenv", "getenv"}
_ENV_SUBSCRIPTS = {"os.environ", "environ"}


class _ModuleExtractor(ast.NodeVisitor):
    """One pass over one module, appending facts to the shared model."""

    def __init__(self, model: ContractModel, module: ModuleSource):
        self.model = model
        self.path = module.path
        self.imports = _import_table(module.tree)
        # NAME for every `x = len(NAME)` assignment in the module —
        # scoping is flat (module-wide) which is safe: a false alias can
        # only *record* a sharding span as symbolic, never invent a
        # literal mismatch
        self.len_aliases: Dict[str, str] = {}
        # name -> the `(a,)*X + (b,)*Y` expression assigned to it, so an
        # in_shardings kwarg passed by local name is still judgeable
        self.span_assigns: Dict[str, ast.AST] = {}
        for node in ast.walk(module.tree):
            if (
                isinstance(node, ast.Assign)
                and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
            ):
                target = _len_target(node.value, {})
                if target is not None:
                    self.len_aliases[node.targets[0].id] = target
                if isinstance(node.value, ast.BinOp) \
                        and isinstance(node.value.op, ast.Add):
                    self.span_assigns[node.targets[0].id] = node.value

    # -- contract tuple / registry definitions (module level only) ------

    def visit_Module(self, node: ast.Module) -> None:
        for stmt in node.body:
            if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1:
                target = stmt.targets[0]
                if isinstance(target, ast.Name):
                    self._module_assign(target.id, stmt, stmt.value)
            elif isinstance(stmt, ast.AnnAssign) and stmt.value is not None:
                # `KNOBS: Tuple[Knob, ...] = (...)` — annotated form
                if isinstance(stmt.target, ast.Name):
                    self._module_assign(stmt.target.id, stmt, stmt.value)
        self.generic_visit(node)

    def _module_assign(self, name: str, stmt: ast.stmt,
                       value: ast.expr) -> None:
        if name in CONTRACT_TUPLE_NAMES:
            fields = _literal_str_tuple(value)
            if fields is not None:
                self.model.tuple_defs.setdefault(name, []).append(TupleDef(
                    name, self.path, stmt.lineno, stmt.col_offset, fields
                ))
        elif name == "KNOBS" and isinstance(value, (ast.Tuple, ast.List)):
            knobs = []
            for elt in value.elts:
                knob = self._knob_name(elt)
                if knob is None:
                    return  # not a Knob(...) registry after all
                knobs.append(knob)
            self.model.registries.append(
                KnobRegistry(self.path, stmt.lineno, tuple(knobs))
            )

    @staticmethod
    def _knob_name(node: ast.AST) -> Optional[str]:
        if not isinstance(node, ast.Call):
            return None
        callee = _dotted(node.func) or ""
        if callee.rsplit(".", 1)[-1] != "Knob":
            return None
        for kw in node.keywords:
            if (
                kw.arg == "name"
                and isinstance(kw.value, ast.Constant)
                and isinstance(kw.value.value, str)
            ):
                return kw.value.value
        if node.args and isinstance(node.args[0], ast.Constant) \
                and isinstance(node.args[0].value, str):
            return node.args[0].value
        return None

    # -- functions: fingerprint loops + get_tables definers -------------

    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        if node.name == "get_tables":
            base = module_basename(self.path)
            if base not in self.model.table_modules:
                self.model.table_modules.append(base)
        if "fingerprint" in node.name:
            self._fingerprint_sites(node)
        self.generic_visit(node)

    visit_AsyncFunctionDef = visit_FunctionDef  # type: ignore[assignment]

    def _fingerprint_sites(self, func: ast.FunctionDef) -> None:
        for node in ast.walk(func):
            if not (
                isinstance(node, ast.For)
                and isinstance(node.iter, (ast.Tuple, ast.List))
            ):
                continue
            uses_getsource = any(
                isinstance(inner, ast.Call)
                and (_dotted(inner.func) or "").endswith("getsource")
                for body_stmt in node.body
                for inner in ast.walk(body_stmt)
            )
            if not uses_getsource:
                continue
            hashed = []
            for elt in node.iter.elts:
                if isinstance(elt, ast.Name):
                    hashed.append(self.imports.get(elt.id, elt.id))
                else:
                    dotted = _dotted(elt)
                    if dotted:
                        hashed.append(dotted.rsplit(".", 1)[-1])
            self.model.fingerprint_sites.append(FingerprintSite(
                self.path, node.lineno, node.col_offset, tuple(hashed)
            ))

    # -- consumers: .index(), strides, unpacks, shardings, env reads ----

    def _canonical(self, dotted: Optional[str]) -> str:
        """Resolve the leading component of a dotted path through the
        module's import aliases (`import os as _os` → `_os.environ.get`
        matches `os.environ.get`)."""
        if not dotted:
            return ""
        head, sep, rest = dotted.partition(".")
        return self.imports.get(head, head) + sep + rest

    def visit_Call(self, node: ast.Call) -> None:
        func = node.func
        if (
            isinstance(func, ast.Attribute)
            and func.attr == "index"
            and isinstance(func.value, ast.Name)
            and func.value.id in CONTRACT_TUPLE_NAMES
            and len(node.args) == 1
            and isinstance(node.args[0], ast.Constant)
            and isinstance(node.args[0].value, str)
        ):
            self.model.index_refs.append(IndexRef(
                self.path, node.lineno, node.col_offset,
                func.value.id, node.args[0].value,
            ))
        dotted = self._canonical(_dotted(func))
        if dotted in _ENV_GET_CALLS and node.args:
            arg = node.args[0]
            if (
                isinstance(arg, ast.Constant)
                and isinstance(arg.value, str)
                and arg.value.startswith("NHD_")
            ):
                self.model.env_reads.append(EnvRead(
                    self.path, node.lineno, node.col_offset, arg.value
                ))
        for kw in node.keywords:
            if kw.arg == "in_shardings":
                value = kw.value
                if isinstance(value, ast.Name):
                    # `in_shardings = (a,)*X + (b,)*Y` bound to a local
                    # and passed by name (kernel.get_ranked_solver_mesh)
                    value = self.span_assigns.get(value.id, value)
                self._sharding_site(value)
        self.generic_visit(node)

    def _sharding_site(self, value: ast.AST) -> None:
        """Record `(spec,)*A + (spec2,)*B` spans; anything else is opaque
        and stays unrecorded (unjudgeable, never a false positive)."""
        if not (isinstance(value, ast.BinOp) and isinstance(value.op, ast.Add)):
            return
        node_count, node_sym = _span_of(value.left, self.len_aliases)
        pod_count, pod_sym = _span_of(value.right, self.len_aliases)
        if (node_count, node_sym) == (None, None) \
                and (pod_count, pod_sym) == (None, None):
            return
        self.model.sharding_sites.append(ShardingSite(
            self.path, value.lineno, value.col_offset,
            node_count, node_sym, pod_count, pod_sym,
        ))

    def visit_Subscript(self, node: ast.Subscript) -> None:
        if isinstance(node.ctx, ast.Load) and isinstance(node.slice, ast.Slice):
            self._stride_site(node)
        if (
            isinstance(node.ctx, ast.Load)
            and self._canonical(_dotted(node.value)) in _ENV_SUBSCRIPTS
            and isinstance(node.slice, ast.Constant)
            and isinstance(node.slice.value, str)
            and node.slice.value.startswith("NHD_")
        ):
            self.model.env_reads.append(EnvRead(
                self.path, node.lineno, node.col_offset, node.slice.value
            ))
        self.generic_visit(node)

    def _stride_site(self, node: ast.Subscript) -> None:
        if not _is_stride_base(node.value):
            return
        sl = node.slice
        assert isinstance(sl, ast.Slice)
        low_k = _stride_term(sl.lower) if sl.lower is not None else None
        if low_k is None:
            return
        # upper must be `K*b + K2`; both K and K2 are judged by the rule
        up = sl.upper
        up_k: Optional[int] = None
        if isinstance(up, ast.BinOp) and isinstance(up.op, ast.Add):
            for operand in (up.left, up.right):
                if isinstance(operand, ast.Constant) \
                        and isinstance(operand.value, int):
                    up_k = operand.value
        self.model.stride_sites.append(StrideSite(
            self.path, node.lineno, node.col_offset, low_k
        ))
        if up_k is not None and up_k != low_k:
            self.model.stride_sites.append(StrideSite(
                self.path, node.lineno, node.col_offset, up_k
            ))

    def visit_Assign(self, node: ast.Assign) -> None:
        """Tuple-unpack of a pod-block slice: arity is contract-bound."""
        if (
            len(node.targets) == 1
            and isinstance(node.targets[0], ast.Tuple)
            and isinstance(node.value, ast.Subscript)
            and _is_stride_base(node.value.value)
            and isinstance(node.value.slice, ast.Slice)
        ):
            self.model.unpack_sites.append(UnpackSite(
                self.path, node.lineno, node.col_offset,
                len(node.targets[0].elts),
            ))
        self.generic_visit(node)


def build_model(modules: Sequence[ModuleSource]) -> ContractModel:
    """Extract the contract model from every parsed module."""
    model = ContractModel()
    for module in modules:
        _ModuleExtractor(model, module).visit(module.tree)
    return model
