"""NHD6xx — metrics discipline for the Prometheus exposition surface.

The repo's /metrics plane is hand-rendered text exposition
(rpc/metrics.py, obs/histo.py, obs/slo.py), which is exactly where
metric-name typos, unregistered families, and cardinality bombs slip in:
a scraper silently drops a malformed name, a family emitted without a
``# TYPE`` declaration breaks PromQL functions, and one ``corr=`` or
``pod=`` label turns a bounded time series into one-per-pod-ever.

This is a PROJECT pack (like lockgraph): registrations are collected
across every analyzed module first, then each module's exposition
strings are judged against the whole-project registry — histo.py's
constructor table legitimately registers what metrics.py renders.

What counts as a **registration** (any module):

* a ``# TYPE <name> <kind>`` / ``# HELP <name> ...`` string literal with
  a static name;
* a ``Histogram("x", ...)`` / ``LabeledHistogram("x", ...)``
  constructor first argument (family ``nhd_x`` plus its
  ``_bucket``/``_sum``/``_count`` children);
* a tuple literal ``("x", "counter"|"gauge"|..., ...)`` — the
  name/kind/help row idiom rpc/metrics.py and obs/slo.py render from;
* a dict literal ``{"x": ("counter", ...)}`` — the ApiCounters.KNOWN
  idiom;
* a ``*FAMILIES*`` assignment of a tuple/list of plain strings
  (obs/slo.py METRIC_FAMILIES).

What counts as a **sample line**: a string whose static head is a full
metric name followed by ``{`` (labels) or by a value (numeric literal,
or an immediately following f-string interpolation). Dynamic names
(``f"nhd_{name} ..."``) are skipped — those render from a registration
table by construction, which is the sanctioned pattern.

* NHD601 — an exposition name that does not match ``nhd_[a-z0-9_]+``
  (wrong prefix, uppercase, dashes): scrapers and recording rules key on
  the prefix, and invalid chars break the exposition format outright.
* NHD602 — a sample line for a family no analyzed module registers: it
  will scrape TYPE-less (breaking counter semantics) and no registry
  table documents it.
* NHD603 — an unbounded-cardinality label (``corr``/``uid``/``pod``/…)
  on a sample line or as a LabeledHistogram label key: per-pod/per-corr
  series grow without bound and take the scrape DB down; identities
  belong in the flight recorder (/decisions), not in label values.
"""

from __future__ import annotations

import ast
import re
from typing import Iterable, List, Optional, Sequence, Set, Tuple

from nhd_tpu.analysis.core import Finding, ModuleSource

NAME_RE = re.compile(r"^nhd_[a-z0-9_]+$")
# a TYPE declaration is self-identifying (the kind keyword follows); a
# HELP line only counts when the family is nhd-ish — "# HELP me ..."
# prose in a docstring must never register as an exposition line
_TYPE_DECL = re.compile(
    r"#\s*TYPE\s+([A-Za-z_:][A-Za-z0-9_:.\-]*)\s+"
    r"(?:counter|gauge|histogram|summary)\b"
)
_HELP_DECL = re.compile(
    r"#\s*HELP\s+([Nn][Hh][Dd][A-Za-z0-9_:.\-]*)(?=\s|$)"
)
_NAME_HEAD = re.compile(r"^([A-Za-z_][A-Za-z0-9_]*)")
_LABEL = re.compile(r'([A-Za-z_][A-Za-z0-9_]*)\s*=\s*"')
_NUMBERISH = re.compile(r"^(?:[0-9.]|\+Inf)")

EXPOSITION_KINDS = frozenset({"counter", "gauge", "histogram", "summary"})

#: label keys whose value space grows with the pod population — one of
#: these on a metric family is a time-series-per-pod-ever cardinality bomb
UNBOUNDED_LABELS = frozenset({
    "corr", "corr_id", "uid", "pod_uid", "pod", "pod_name", "namespace",
})

#: histogram child suffixes resolve to their parent family registration
_HISTO_SUFFIXES = ("_bucket", "_sum", "_count")


def _static_text(node: ast.AST) -> Optional[str]:
    """The full text of a string literal with every interpolation
    replaced by \\x00 (so label scans see the static skeleton), or None
    for non-strings."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    if isinstance(node, ast.JoinedStr):
        parts = []
        for v in node.values:
            if isinstance(v, ast.Constant) and isinstance(v.value, str):
                parts.append(v.value)
            else:
                parts.append("\x00")
        return "".join(parts)
    return None


def _sample_name(text: str) -> Optional[str]:
    """The metric family a string emits as a sample line, or None.

    Requires an nhd-prefixed, complete static name followed by labels
    (``{``) or a value (numeric, or a \\x00 interpolation placeholder) —
    so prose like ``"nhd_tpu scheduler"``, paths like ``"nhd_tpu/rpc"``
    and bare family references in asserts never register as emissions.
    The prefix gate is case-insensitive so ``NHD_Foo{...}`` still lands
    in NHD601 instead of escaping detection entirely."""
    m = _NAME_HEAD.match(text)
    if not m or not text[len(m.group(1)):]:
        return None  # bare name (a reference, not an emission) or no name
    name, rest = m.group(1), text[len(m.group(1)):]
    if not name.lower().startswith("nhd"):
        return None
    if rest.startswith("{"):
        return name
    if rest.startswith(" "):
        value = rest[1:].lstrip()
        if value.startswith("\x00") or _NUMBERISH.match(value):
            return name
    return None


def _call_name(node: ast.Call) -> Optional[str]:
    f = node.func
    if isinstance(f, ast.Attribute):
        return f.attr
    if isinstance(f, ast.Name):
        return f.id
    return None


def _str_const(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def _registrations(tree: ast.Module) -> Set[str]:
    """Every family this module registers (full names, nhd_-prefixed
    where the idiom stores the unprefixed name)."""
    out: Set[str] = set()
    for node in ast.walk(tree):
        text = _static_text(node) if isinstance(
            node, (ast.Constant, ast.JoinedStr)
        ) else None
        if text is not None:
            for rx in (_TYPE_DECL, _HELP_DECL):
                for m in rx.finditer(text):
                    if "\x00" not in m.group(1):
                        out.add(m.group(1))
        if isinstance(node, ast.Call):
            cname = _call_name(node)
            if cname and cname.endswith("Histogram") and node.args:
                s = _str_const(node.args[0])
                if s:
                    out.add(f"nhd_{s}")
        if isinstance(node, (ast.Tuple, ast.List)) and len(node.elts) >= 2:
            first = _str_const(node.elts[0])
            second = _str_const(node.elts[1])
            if first and second in EXPOSITION_KINDS:
                out.add(f"nhd_{first}")
        if isinstance(node, ast.Dict):
            for k, v in zip(node.keys, node.values):
                key = _str_const(k) if k is not None else None
                if (
                    key
                    and isinstance(v, ast.Tuple)
                    and v.elts
                    and _str_const(v.elts[0]) in EXPOSITION_KINDS
                ):
                    out.add(f"nhd_{key}")
        if isinstance(node, ast.Assign):
            names = [
                t.id for t in node.targets if isinstance(t, ast.Name)
            ]
            if any("FAMILIES" in n for n in names) and isinstance(
                node.value, (ast.Tuple, ast.List)
            ):
                for elt in node.value.elts:
                    s = _str_const(elt)
                    if s:
                        out.add(f"nhd_{s}")
    return out


def _registered(name: str, registry: Set[str]) -> bool:
    if name in registry:
        return True
    for suffix in _HISTO_SUFFIXES:
        if name.endswith(suffix) and name[: -len(suffix)] in registry:
            return True
    return False


class _Visitor(ast.NodeVisitor):
    def __init__(self, path: str, registry: Set[str]):
        self.path = path
        self.registry = registry
        self.findings: List[Finding] = []

    def _check_name(self, name: str, node: ast.AST) -> bool:
        """NHD601; returns whether the name was well-formed (a malformed
        name is not additionally reported unregistered)."""
        if NAME_RE.match(name):
            return True
        self.findings.append(Finding(
            "NHD601", self.path, node.lineno, node.col_offset,
            f"exported metric name {name!r} must match nhd_[a-z0-9_]+ — "
            "scrapers and recording rules key on the prefix, and invalid "
            "characters break the text exposition format",
        ))
        return False

    def _visit_string(self, node: ast.AST) -> None:
        text = _static_text(node)
        if text is None:
            return
        for line in text.split("\n"):
            line = line.strip()
            for rx in (_TYPE_DECL, _HELP_DECL):
                for m in rx.finditer(line):
                    if "\x00" not in m.group(1):
                        self._check_name(m.group(1), node)
            name = _sample_name(line)
            if name is None or "\x00" in name:
                continue
            if self._check_name(name, node) and not _registered(
                name, self.registry
            ):
                self.findings.append(Finding(
                    "NHD602", self.path, node.lineno, node.col_offset,
                    f"metric family {name!r} is emitted but registered "
                    "nowhere (no # TYPE declaration, histogram registry "
                    "entry, name/kind table row, or *FAMILIES* list in "
                    "any analyzed module): it scrapes TYPE-less and no "
                    "registry documents it",
                ))
            for lm in _LABEL.finditer(line):
                if lm.group(1) in UNBOUNDED_LABELS:
                    self.findings.append(Finding(
                        "NHD603", self.path, node.lineno, node.col_offset,
                        f"label {lm.group(1)!r} on metric family "
                        f"{name!r} has unbounded cardinality (one time "
                        "series per pod/correlation ever seen): put "
                        "identities in the flight recorder's /decisions "
                        "view, not in label values",
                    ))

    def visit_Constant(self, node: ast.Constant) -> None:
        self._visit_string(node)

    def visit_JoinedStr(self, node: ast.JoinedStr) -> None:
        self._visit_string(node)
        # don't generic_visit: the inner Constants are fragments of THIS
        # string and must not be re-judged out of context

    def visit_Call(self, node: ast.Call) -> None:
        cname = _call_name(node)
        if cname == "LabeledHistogram":
            # the label key arrives positionally (arg 1) or as label=
            label_node = node.args[1] if len(node.args) >= 2 else next(
                (kw.value for kw in node.keywords if kw.arg == "label"),
                None,
            )
            label = _str_const(label_node) if label_node is not None else None
            if label in UNBOUNDED_LABELS:
                self.findings.append(Finding(
                    "NHD603", self.path, node.lineno, node.col_offset,
                    f"LabeledHistogram label key {label!r} has unbounded "
                    "cardinality (one child histogram per pod/correlation "
                    "ever seen): label sets must be bounded by "
                    "construction",
                ))
        self.generic_visit(node)


def check_project(modules: Sequence[ModuleSource]) -> List[Finding]:
    registry: Set[str] = set()
    for module in modules:
        registry |= _registrations(module.tree)
    findings: List[Finding] = []
    for module in modules:
        visitor = _Visitor(module.path, registry)
        visitor.visit(module.tree)
        findings.extend(visitor.findings)
    return findings
