"""NHD2xx — lock discipline for classes that own a threading lock.

The control plane mutates shared scheduler state from watch threads; the
repo's convention is "a class that owns a Lock/RLock guards its mutable
attributes with it". The pack infers the contract instead of requiring
annotations:

1. lock attributes: ``self.X = threading.Lock()/RLock()/Condition()``
   (or a class-level ``X = threading.Lock()``); a Condition built *on*
   a lock attribute is an alias for it;
2. guarded attributes: every attribute the class mutates anywhere inside
   a ``with self.X:`` block is declared lock-guarded;
3. violations: mutating that attribute outside any such block (except in
   ``__init__``, which runs before the object is published).

Mutation means attribute assignment, subscript store/delete, or calling
a known container mutator (append/pop/update/...). Read-only access is
never flagged — the single-writer pattern (scheduler/core.py) reads
snapshots without the lock by design.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set

from nhd_tpu.analysis.core import Finding

_LOCK_CTORS = {"Lock", "RLock", "Condition"}
_MUTATORS = {
    "append", "extend", "insert", "remove", "pop", "popitem", "clear",
    "update", "add", "discard", "setdefault", "sort", "reverse",
    "appendleft", "extendleft", "popleft",
}


def _terminal_attr(node: ast.AST) -> Optional[str]:
    return node.attr if isinstance(node, ast.Attribute) else None


def _self_attr(node: ast.AST) -> Optional[str]:
    """'X' when node is self.X or cls.X, else None."""
    if isinstance(node, ast.Attribute) and isinstance(node.value, ast.Name):
        if node.value.id in ("self", "cls"):
            return node.attr
    return None


def _is_lock_ctor(node: ast.AST) -> bool:
    return (
        isinstance(node, ast.Call)
        and _terminal_attr(node.func) in _LOCK_CTORS
    )


class _ClassAuditor:
    def __init__(self, cls: ast.ClassDef, path: str):
        self.cls = cls
        self.path = path
        self.lock_attrs: Set[str] = set()
        self.guarded: Set[str] = set()
        # line numbers of guarded-inference sites, for messages
        self.guard_sites: Dict[str, int] = {}

    # -- pass 1: find lock attributes -----------------------------------

    def find_locks(self) -> None:
        for node in ast.walk(self.cls):
            if not isinstance(node, ast.Assign):
                continue
            for tgt in node.targets:
                attr = _self_attr(tgt)
                if attr is None and isinstance(tgt, ast.Name):
                    attr = tgt.id    # class-level: X = threading.Lock()
                if attr is None:
                    continue
                if _is_lock_ctor(node.value):
                    # Condition(self.X) aliases lock X; Condition() owns
                    # its own lock — either way the attr guards state
                    self.lock_attrs.add(attr)

    # -- pass 2/3: guarded inference, then violations -------------------

    def _walk_method(self, fn: ast.FunctionDef, *, collect: bool,
                     findings: Optional[List[Finding]] = None) -> None:
        in_init = fn.name == "__init__"

        def visit(node: ast.AST, held: bool) -> None:
            if isinstance(node, ast.With):
                now_held = held or any(
                    self._is_lock_expr(item.context_expr)
                    for item in node.items
                )
                for child in node.body:
                    visit(child, now_held)
                return
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                # a nested def runs later, possibly unlocked: judge its
                # body as lock-not-held (conservative for inference too)
                for child in ast.iter_child_nodes(node):
                    visit(child, False)
                return
            self._judge(node, held, in_init, collect, findings)
            for child in ast.iter_child_nodes(node):
                visit(child, held)

        for stmt in fn.body:
            visit(stmt, False)

    def _is_lock_expr(self, node: ast.AST) -> bool:
        attr = _self_attr(node)
        return attr is not None and attr in self.lock_attrs

    def _judge(self, node: ast.AST, held: bool, in_init: bool,
               collect: bool, findings: Optional[List[Finding]]) -> None:
        for attr, verb in self._mutations(node):
            if attr in self.lock_attrs:
                continue
            if collect:
                if held:
                    self.guarded.add(attr)
                    self.guard_sites.setdefault(attr, node.lineno)
            else:
                if not held and not in_init and attr in self.guarded:
                    assert findings is not None
                    lock = sorted(self.lock_attrs)[0]
                    findings.append(Finding(
                        "NHD201", self.path, node.lineno, node.col_offset,
                        f"'{verb}' mutates '{attr}' outside 'with "
                        f"{lock}:' — elsewhere (line "
                        f"{self.guard_sites.get(attr, '?')}) this class "
                        f"mutates it under the lock, so this write races "
                        "the guarded readers",
                    ))
        # NHD202: bare acquire()
        if not collect and isinstance(node, ast.Call):
            func = node.func
            if (
                isinstance(func, ast.Attribute)
                and func.attr == "acquire"
                and self._is_lock_expr(func.value)
            ):
                assert findings is not None
                findings.append(Finding(
                    "NHD202", self.path, node.lineno, node.col_offset,
                    "bare .acquire(): an exception before release() "
                    "deadlocks every other thread — use 'with <lock>:'",
                ))

    def _mutations(self, node: ast.AST):
        """Yield (attr, description) for each self/cls-attribute mutation
        this single statement/expression performs."""
        if isinstance(node, ast.Assign):
            for tgt in node.targets:
                yield from self._target_mutation(tgt)
        elif isinstance(node, ast.AugAssign):
            yield from self._target_mutation(node.target)
        elif isinstance(node, ast.AnnAssign):
            if node.value is not None:  # bare 'x: T' declares, not mutates
                yield from self._target_mutation(node.target)
        elif isinstance(node, ast.Delete):
            for tgt in node.targets:
                yield from self._target_mutation(tgt)
        elif isinstance(node, ast.Call):
            func = node.func
            if isinstance(func, ast.Attribute) and func.attr in _MUTATORS:
                attr = _self_attr(func.value)
                if attr is not None:
                    recv = func.value.value.id
                    yield attr, f"{recv}.{attr}.{func.attr}(...)"

    def _target_mutation(self, tgt: ast.AST):
        attr = _self_attr(tgt)
        if attr is not None:
            yield attr, f"{tgt.value.id}.{attr} = ..."
            return
        if isinstance(tgt, ast.Subscript):
            attr = _self_attr(tgt.value)
            if attr is not None:
                yield attr, f"{tgt.value.value.id}.{attr}[...] = ..."

    # -- driver ----------------------------------------------------------

    def audit(self) -> List[Finding]:
        self.find_locks()
        if not self.lock_attrs:
            return []
        methods = [
            n for n in self.cls.body
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
        ]
        for fn in methods:
            self._walk_method(fn, collect=True)
        findings: List[Finding] = []
        for fn in methods:
            self._walk_method(fn, collect=False, findings=findings)
        return findings


def check_module(tree: ast.Module, src: str, path: str) -> List[Finding]:
    findings: List[Finding] = []
    for node in ast.walk(tree):
        if isinstance(node, ast.ClassDef):
            findings.extend(_ClassAuditor(node, path).audit())
    return findings
