"""nhdlint — AST-based static analysis for this codebase's failure modes.

The solver's performance story rests on jit-cache reuse over bucketed
shapes, and the control plane mutates shared state from watch threads.
The bug classes that hurt most at production scale — silent recompiles,
host-sync stalls in the hot batch loop, off-lock state mutation,
nondeterministic placement — are exactly the ones best caught statically.
Per-file rule packs, each a visitor over stdlib ``ast`` (no third-party
dependency, so the gate runs everywhere the tests run):

  tracing      NHD1xx  JAX tracing / recompile / host-sync hazards
  locks        NHD2xx  lock discipline for classes that own a Lock/RLock
  excepts      NHD3xx  exception hygiene (silently swallowed errors)
  determinism  NHD4xx  unseeded randomness / wall-clock in solver paths
  fencing      NHD5xx  commit-fencing discipline in the control plane
  metrics      NHD6xx  observability-surface hygiene

plus *project* packs that see every module at once:

  lockgraph    NHD21x  interprocedural lock-order inversions, blocking
                       calls under locks, re-entrant Lock acquisition —
                       with DOT/JSON export of the whole-program lock
                       graph (--lock-graph-dot / --lock-graph-json)
  contract     NHD7xx  cross-layer solve-signature contract analysis
                       (_ARG_ORDER vs DELTA_FIELDS vs shardings vs
                       stride math vs AOT fingerprints), donation-alias
                       taint tracking into donate_argnums dispatches,
                       and the NHD_* env-knob registry
                       (nhd_tpu/config/knobs.py)

Run ``python -m nhd_tpu.analysis nhd_tpu/`` or see docs/STATIC_ANALYSIS.md
for the rule catalogue, suppression syntax, the baseline workflow, and
the CI modes (``--diff-base REV`` differential lint, ``--sarif``).
"""

from nhd_tpu.analysis.core import (
    ALL_PACK_NAMES,
    Finding,
    ModuleSource,
    PACKS,
    PROJECT_PACKS,
    RULES,
    analyze_file,
    analyze_paths,
    iter_py_files,
    load_baseline,
    subtract_baseline,
    write_baseline,
)

__all__ = [
    "ALL_PACK_NAMES",
    "Finding",
    "ModuleSource",
    "PACKS",
    "PROJECT_PACKS",
    "RULES",
    "analyze_file",
    "analyze_paths",
    "iter_py_files",
    "load_baseline",
    "subtract_baseline",
    "write_baseline",
]
