"""nhdlint engine: findings, suppressions, baseline, file walking.

Per-file rule packs live in sibling ``rules_*`` modules; each exposes
``check_module(tree, src, path) -> List[Finding]``. *Project* packs
(``PROJECT_PACKS``) see every parsed module at once and emit
whole-program findings — the interprocedural lock-graph rules
(``lockgraph.py``) need the cross-module call graph, which no
one-file-at-a-time visitor can build. This module owns everything
rule-independent so a pack is just one visitor (or project function)
plus a rule table entry.
"""

from __future__ import annotations

import ast
import hashlib
import io
import json
import re
import tokenize
from dataclasses import asdict, dataclass, field
from fnmatch import fnmatch
from pathlib import Path
from typing import Callable, Dict, Iterable, List, Optional, Sequence, Tuple


@dataclass(frozen=True)
class Finding:
    """One rule violation at one source location."""

    rule: str          # e.g. "NHD201"
    path: str          # path as given to the analyzer (posix separators)
    line: int          # 1-based line of the offending node
    col: int           # 0-based column
    message: str
    snippet: str = ""  # stripped source line, for output and fingerprints

    def fingerprint(self) -> str:
        """Line-number-independent identity used by the baseline: moving
        a grandfathered finding up or down a file must not resurrect it,
        while editing the offending line (or renaming/moving the file)
        must. Keyed on the last two path components rather than the full
        path so the gate test (absolute paths) and the CLI (relative
        paths) agree on the same entries, while same-named files in
        different directories still get distinct slots."""
        tail = "/".join(self.path.rsplit("/", 2)[-2:])
        raw = f"{self.rule}:{tail}:{self.snippet}"
        return hashlib.sha1(raw.encode()).hexdigest()[:16]

    def to_dict(self) -> dict:
        d = asdict(self)
        d["fingerprint"] = self.fingerprint()
        return d


@dataclass
class FileReport:
    """Per-file outcome: surviving findings plus suppression accounting."""

    path: str
    findings: List[Finding] = field(default_factory=list)
    suppressed: int = 0
    skipped: bool = False          # whole file opted out via skip-file
    unused_ignores: List[int] = field(default_factory=list)  # line numbers


@dataclass
class ModuleSource:
    """One successfully parsed module, as handed to project packs."""

    path: str                      # display path (posix separators)
    src: str
    tree: ast.Module


# ---------------------------------------------------------------------------
# rule registry (packs register lazily to keep import order trivial)
# ---------------------------------------------------------------------------

def _pack_tracing(tree, src, path):
    from nhd_tpu.analysis.rules_tracing import check_module
    return check_module(tree, src, path)


def _pack_locks(tree, src, path):
    from nhd_tpu.analysis.rules_locks import check_module
    return check_module(tree, src, path)


def _pack_excepts(tree, src, path):
    from nhd_tpu.analysis.rules_excepts import check_module
    return check_module(tree, src, path)


def _pack_determinism(tree, src, path):
    from nhd_tpu.analysis.rules_determinism import check_module
    return check_module(tree, src, path)


def _pack_fencing(tree, src, path):
    from nhd_tpu.analysis.rules_fencing import check_module
    return check_module(tree, src, path)


PACKS: Dict[str, Callable] = {
    "tracing": _pack_tracing,
    "locks": _pack_locks,
    "excepts": _pack_excepts,
    "determinism": _pack_determinism,
    "fencing": _pack_fencing,
}


def _pack_lockgraph(modules):
    from nhd_tpu.analysis.lockgraph import check_project
    return check_project(modules)


def _pack_metrics(modules):
    from nhd_tpu.analysis.rules_metrics import check_project
    return check_project(modules)


def _pack_contract(modules):
    from nhd_tpu.analysis.rules_contract import check_project
    return check_project(modules)


def _pack_races(modules):
    from nhd_tpu.analysis.rules_races import check_project
    return check_project(modules)


# project packs: check_project(modules: Sequence[ModuleSource]) -> findings.
# They run over the whole analyzed path set at once (analyze_file hands
# them a one-module project, so EXPECT fixtures keep working unchanged).
PROJECT_PACKS: Dict[str, Callable] = {
    "lockgraph": _pack_lockgraph,
    "metrics": _pack_metrics,
    "contract": _pack_contract,
    "races": _pack_races,
}

ALL_PACK_NAMES: Tuple[str, ...] = (*PACKS, *PROJECT_PACKS)


def _split_packs(
    packs: Optional[Sequence[str]],
) -> Tuple[List[str], List[str]]:
    """(file packs, project packs) in registry order; None = all. Unknown
    names raise KeyError — the CLI validates first, library callers get
    the loud failure."""
    if packs is None:
        return list(PACKS), list(PROJECT_PACKS)
    unknown = [p for p in packs if p not in PACKS and p not in PROJECT_PACKS]
    if unknown:
        raise KeyError(f"unknown pack(s): {', '.join(unknown)}")
    return (
        [p for p in PACKS if p in packs],
        [p for p in PROJECT_PACKS if p in packs],
    )

# rule id -> (pack, one-line description); the single source docs and
# --list-rules render from
RULES: Dict[str, Tuple[str, str]] = {
    "NHD101": ("tracing",
               "int()/float()/bool() coercion of a traced value inside a "
               "jit-traced function (ConcretizationError or silent host sync)"),
    "NHD102": ("tracing",
               "Python control flow (if/while/assert) on a traced value "
               "inside a jit-traced function (TracerBoolConversionError)"),
    "NHD103": ("tracing",
               "numpy host op on a traced value inside a jit-traced "
               "function (breaks tracing or forces a device sync)"),
    "NHD104": ("tracing",
               "jax.jit wrapper constructed per call (not module-scope and "
               "not under lru_cache): a fresh program cache per wrapper "
               "defeats bucketed-shape reuse"),
    "NHD105": ("tracing",
               "static_argnums/static_argnames parameter with an unhashable "
               "(mutable) default: first defaulted call raises, and mutable "
               "statics silently miss the jit cache"),
    "NHD106": ("tracing",
               "raw time.time()/perf_counter() timing inside a jit-traced "
               "function: clock reads execute at trace time and constant-"
               "fold — time on the host around the dispatch "
               "(nhd_tpu.utils.tracing.phase)"),
    "NHD107": ("tracing",
               "host-sync operation (block_until_ready, jax.device_get, "
               "np.asarray/np.array on a device array) in a solver hot-path "
               "module: each pull pays a full relay flush — batch with "
               "copy_to_host_async and pull at the round's one sanctioned "
               "flush point (intentional sites suppressed inline)"),
    "NHD108": ("tracing",
               "full encode_cluster() call on a per-event/per-round hot "
               "path in solver/scheduler code outside the sanctioned "
               "rebuild chokepoint (ClusterDelta._rebuild/make_context): "
               "steady paths must get-or-apply row deltas through the "
               "incremental cluster state"),
    "NHD201": ("locks",
               "write to lock-guarded attribute outside 'with <lock>:' in a "
               "class that owns a threading.Lock/RLock"),
    "NHD202": ("locks",
               "bare <lock>.acquire() call: an exception before release() "
               "deadlocks every other thread; use 'with <lock>:'"),
    "NHD210": ("lockgraph",
               "lock-order inversion: one call path acquires A then B, "
               "another B then A — two threads interleaving them deadlock"),
    "NHD211": ("lockgraph",
               "blocking call (unbounded queue get/join/wait, socket "
               "recv/accept, pjit solve entry) reached while a lock is "
               "held — directly or through the call graph"),
    "NHD212": ("lockgraph",
               "re-entrant acquisition of a non-reentrant Lock through a "
               "call path (callback invoked under the lock it takes)"),
    "NHD301": ("excepts",
               "bare 'except:' catches SystemExit/KeyboardInterrupt and "
               "hides programming errors"),
    "NHD302": ("excepts",
               "broad 'except Exception:' that neither logs, re-raises, nor "
               "returns — watch-loop and RPC errors vanish silently"),
    "NHD401": ("determinism",
               "unseeded global RNG (random.*/np.random.*) in a solver/encode "
               "path: placement must be a pure function of cluster state"),
    "NHD402": ("determinism",
               "wall-clock read (time.time/datetime.now) in a solver/encode "
               "path: use the caller-passed 'now' or time.monotonic"),
    "NHD501": ("fencing",
               "mutating ClusterBackend call in nhd_tpu/scheduler/ outside "
               "its chokepoint: commit-path mutators (bind/annotate/NAD/"
               "spillover) belong in Scheduler._commit_write (the write "
               "must carry the owning shard's fencing epoch), TriadSet "
               "mutators in Controller._coordinator_write (coordinatorship "
               "re-checked at the write, not the pass)"),
    "NHD601": ("metrics",
               "exported metric name does not match nhd_[a-z0-9_]+: "
               "scrapers key on the prefix, and invalid characters break "
               "the text exposition format"),
    "NHD602": ("metrics",
               "metric family emitted but registered nowhere (# TYPE "
               "declaration, histogram registry, name/kind table row or "
               "*FAMILIES* list): it scrapes TYPE-less and undocumented"),
    "NHD603": ("metrics",
               "unbounded-cardinality label (corr/uid/pod/...) on a "
               "metric family: one time series per pod ever seen — "
               "identities belong in /decisions, not label values"),
    "NHD701": ("contract",
               "solve-signature consumer out of step: a field present in "
               "one layer (_ARG_ORDER/_POD_ARG_ORDER) is missing from "
               "another (DELTA_FIELDS, _MUTABLE/_STATIC partition, "
               "in_shardings span, speculate stride/unpack, .index ref) "
               "— the missing consumer layer is named"),
    "NHD702": ("contract",
               "solve-signature order-contract violation: same field set "
               "but different order, duplicated fields, overlapping "
               "_MUTABLE/_STATIC partition, or conflicting definitions — "
               "positional consumers would read the wrong array"),
    "NHD703": ("contract",
               "AOT fingerprint-source omission: program_fingerprint "
               "does not hash a module that defines the compiled program "
               "(the _ARG_ORDER module / the get_tables combo tables) — "
               "cached artifacts would survive semantic edits"),
    "NHD710": ("contract",
               "donation-alias hazard: a host-mirror-tainted value "
               "(getattr on cluster state, zero-copy wrappers, aliasing "
               "pads) reaches a donate_argnums position without an "
               "owning copy — the donated program may mutate the host "
               "mirror in place (the PR 9 _pad_own bug, statically)"),
    "NHD720": ("contract",
               "unregistered env knob: an NHD_* environment read absent "
               "from the nhd_tpu/config/knobs.py KNOBS registry — the "
               "OPERATIONS.md tunables table is generated from the "
               "registry, so the knob is undocumented"),
    "NHD810": ("races",
               "unsynchronized write to a field shared between thread "
               "roots: no single lock is held across every access — "
               "guard all accesses with one lock or declare the owning "
               "thread in the ownership registry"),
    "NHD811": ("races",
               "write to declared single-writer state from a non-owner "
               "thread root: readers tolerate staleness, a second writer "
               "corrupts — route the update through the owner thread"),
    "NHD812": ("races",
               "non-atomic read-modify-write (x += 1, check-then-set) on "
               "a shared field with no lock held: interleaved load/store "
               "drops an update (lost counter, double-initialized cache)"),
    "NHD813": ("races",
               "mutable structure handed raw to a new thread "
               "(Thread/Timer/submit) while the publisher keeps writing "
               "it — pass a copy or guard both sides with one lock"),
}


# ---------------------------------------------------------------------------
# suppression syntax
# ---------------------------------------------------------------------------

# directive forms, all comment-only: "nhdlint:" followed by either
# "ignore[RULE1,RULE2]", a bare "ignore" (all rules), or "skip-file"
_DIRECTIVE = re.compile(
    r"#\s*nhdlint:\s*(?P<kind>ignore|skip-file)"
    r"(?:\[(?P<rules>[A-Z0-9,\s]+)\])?"
)


def _comment_tokens(src: str) -> Dict[int, str]:
    """line -> comment text, via tokenize so directive-looking text inside
    string literals and docstrings can never register as a directive."""
    out: Dict[int, str] = {}
    try:
        for tok in tokenize.generate_tokens(io.StringIO(src).readline):
            if tok.type == tokenize.COMMENT:
                out[tok.start[0]] = tok.string
    except (tokenize.TokenError, IndentationError, SyntaxError):
        # unterminated construct etc. — fall back to raw lines so a file
        # the parser also rejects (reported as NHD000) still honors its
        # directives. Only comment-shaped lines count: a directive inside
        # a string literal must not survive the fallback either.
        for lineno, line in enumerate(src.splitlines(), start=1):
            if line.lstrip().startswith("#"):
                out[lineno] = line
    return out


def _dotted(node: ast.AST) -> Optional[str]:
    """'jax.numpy.asarray' for a nested Attribute/Name chain, else None.
    Shared by the rule packs so they can never disagree on what counts
    as a dotted call."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def parse_suppressions(
    src: str, tree: Optional[ast.Module] = None
) -> Tuple[bool, Dict[int, Optional[frozenset]]]:
    """Scan source *comments* for nhdlint directives.

    Returns (skip_file, {line -> rules-or-None}) where None means "ignore
    every rule on this line". skip-file is honored only above the first
    statement (module docstring/comment block), so it cannot hide inside
    a function body. Pass the already-parsed ``tree`` to avoid a second
    parse; None means the source failed to parse.
    """
    ignores: Dict[int, Optional[frozenset]] = {}
    skip_file = False
    first_code_line = None
    if tree is not None:
        body = [n for n in tree.body
                if not (isinstance(n, ast.Expr)
                        and isinstance(n.value, ast.Constant))]
        if body:
            first_code_line = body[0].lineno
    for lineno, comment in sorted(_comment_tokens(src).items()):
        m = _DIRECTIVE.search(comment)
        if not m:
            continue
        if m.group("kind") == "skip-file":
            if first_code_line is None or lineno <= first_code_line:
                skip_file = True
            continue
        rules = m.group("rules")
        if rules:
            ignores[lineno] = frozenset(
                r.strip() for r in rules.split(",") if r.strip()
            )
        else:
            ignores[lineno] = None
    return skip_file, ignores


# ---------------------------------------------------------------------------
# analysis driver
# ---------------------------------------------------------------------------

def _load_module(
    path: str | Path, src: Optional[str] = None
) -> Tuple[FileReport, Optional[ModuleSource], Dict[int, Optional[frozenset]]]:
    """Read + parse one file. The report comes back terminal (NHD000 /
    skipped) when the module is None; otherwise findings are still to be
    collected and applied via _apply_findings."""
    p = Path(path)
    display = p.as_posix()
    report = FileReport(path=display)
    if src is None:
        try:
            src = p.read_text()
        except (OSError, UnicodeDecodeError) as exc:
            report.findings.append(Finding(
                "NHD000", display, 1, 0, f"unreadable file: {exc}"
            ))
            return report, None, {}
    try:
        tree: Optional[ast.Module] = ast.parse(src, filename=display)
    except SyntaxError as exc:
        tree = None
        syntax_error: Optional[SyntaxError] = exc
    else:
        syntax_error = None
    skip_file, ignores = parse_suppressions(src, tree)
    if skip_file:
        report.skipped = True
        return report, None, {}
    if tree is None:
        assert syntax_error is not None
        report.findings.append(Finding(
            "NHD000", display, syntax_error.lineno or 1, 0,
            f"syntax error: {syntax_error.msg}",
        ))
        return report, None, {}
    return report, ModuleSource(display, src, tree), ignores


def _apply_findings(
    report: FileReport,
    module: ModuleSource,
    ignores: Dict[int, Optional[frozenset]],
    raw: List[Finding],
    ran: set,
) -> None:
    """Attach snippets, apply inline suppressions, account unused
    directives; mutates *report* in place."""
    lines = module.src.splitlines()
    used_ignore_lines = set()
    for f in raw:
        snippet = lines[f.line - 1].strip() if 0 < f.line <= len(lines) else ""
        f = Finding(f.rule, f.path, f.line, f.col, f.message, snippet)
        rules = ignores.get(f.line, "missing")
        if rules != "missing" and (rules is None or f.rule in rules):
            report.suppressed += 1
            used_ignore_lines.add(f.line)
        else:
            report.findings.append(f)
    # a directive is "unused" only when every rule it could suppress was
    # actually checked this run — a --packs subset must not tell people
    # to delete suppressions that are load-bearing for the full run
    ran_rules = {rid for rid, (pack, _) in RULES.items() if pack in ran}
    for line, rules in ignores.items():
        if line in used_ignore_lines:
            continue
        judged = (
            ran == set(ALL_PACK_NAMES) if rules is None else rules <= ran_rules
        )
        if judged:
            report.unused_ignores.append(line)
    report.unused_ignores.sort()
    report.findings.sort(key=lambda f: (f.line, f.col, f.rule))


def analyze_file(
    path: str | Path,
    packs: Optional[Sequence[str]] = None,
    *,
    src: Optional[str] = None,
) -> FileReport:
    """Run the selected packs over one file, applying inline suppressions.
    Project packs see a one-module project — fixture files exercise the
    interprocedural rules within a single module this way."""
    file_packs, proj_packs = _split_packs(packs)
    report, module, ignores = _load_module(path, src)
    if module is None:
        return report
    raw: List[Finding] = []
    for name in file_packs:
        raw.extend(PACKS[name](module.tree, module.src, module.path))
    for name in proj_packs:
        raw.extend(PROJECT_PACKS[name]([module]))
    _apply_findings(report, module, ignores, raw, set(file_packs + proj_packs))
    return report


def _excluded(p: Path, patterns: Sequence[str]) -> bool:
    """fnmatch against the posix path, anchored loosely: a pattern
    matches the whole path, a path suffix, or any directory segment run
    (so ``tests/fixtures`` excludes the fixture tree wherever the repo
    root sits)."""
    s = p.as_posix()
    for pat in patterns:
        if (
            fnmatch(s, pat)
            or fnmatch(s, f"*/{pat}")
            or fnmatch(s, f"{pat}/*")
            or fnmatch(s, f"*/{pat}/*")
        ):
            return True
    return False


def iter_py_files(
    paths: Iterable[str | Path], *, exclude: Sequence[str] = ()
) -> List[Path]:
    """Expand files/directories into a sorted, de-duplicated .py list."""
    out = []
    for p in paths:
        p = Path(p)
        if p.is_dir():
            out.extend(sorted(p.rglob("*.py")))
        elif p.suffix == ".py":
            out.append(p)
    seen, uniq = set(), []
    for p in out:
        if p not in seen and not _excluded(p, exclude):
            seen.add(p)
            uniq.append(p)
    return uniq


def analyze_paths(
    paths: Iterable[str | Path],
    packs: Optional[Sequence[str]] = None,
    *,
    exclude: Sequence[str] = (),
    modules_out: Optional[List[ModuleSource]] = None,
) -> List[FileReport]:
    """Run the selected packs over a path set. Per-file packs run file by
    file; project packs run once over every successfully parsed module,
    their findings distributed back to the owning file's report (so
    inline suppressions and the baseline apply uniformly). Pass a list as
    ``modules_out`` to receive the parsed ModuleSource set — the CLI's
    lock-graph export reuses it instead of re-parsing every file."""
    file_packs, proj_packs = _split_packs(packs)
    ran = set(file_packs + proj_packs)
    loaded = [
        _load_module(p) for p in iter_py_files(paths, exclude=exclude)
    ]
    raw_by_path: Dict[str, List[Finding]] = {}
    modules = [m for _, m, _ in loaded if m is not None]
    if modules_out is not None:
        modules_out.extend(modules)
    for module in modules:
        raw = raw_by_path.setdefault(module.path, [])
        for name in file_packs:
            raw.extend(PACKS[name](module.tree, module.src, module.path))
    for name in proj_packs:
        for f in PROJECT_PACKS[name](modules):
            # a project finding always lands in an analyzed module; guard
            # anyway so a pack bug can't KeyError the whole run
            if f.path in raw_by_path:
                raw_by_path[f.path].append(f)
    reports: List[FileReport] = []
    for report, module, ignores in loaded:
        if module is not None:
            _apply_findings(
                report, module, ignores, raw_by_path[module.path], ran
            )
        reports.append(report)
    return reports


# ---------------------------------------------------------------------------
# baseline: grandfathered findings, matched by fingerprint with multiplicity
# ---------------------------------------------------------------------------

BASELINE_VERSION = 1


def load_baseline(path: str | Path) -> Dict[str, int]:
    """fingerprint -> allowed count. Missing file = empty baseline."""
    p = Path(path)
    if not p.exists():
        return {}
    data = json.loads(p.read_text())
    if data.get("version") != BASELINE_VERSION:
        raise ValueError(
            f"baseline {p}: unsupported version {data.get('version')!r}"
        )
    counts: Dict[str, int] = {}
    for entry in data.get("entries", []):
        counts[entry["fingerprint"]] = (
            counts.get(entry["fingerprint"], 0) + int(entry.get("count", 1))
        )
    return counts


def subtract_baseline(
    findings: Sequence[Finding], baseline: Dict[str, int]
) -> Tuple[List[Finding], int]:
    """Drop findings covered by the baseline; returns (new, baselined)."""
    budget = dict(baseline)
    new: List[Finding] = []
    baselined = 0
    for f in findings:
        fp = f.fingerprint()
        if budget.get(fp, 0) > 0:
            budget[fp] -= 1
            baselined += 1
        else:
            new.append(f)
    return new, baselined


def write_baseline(findings: Sequence[Finding], path: str | Path) -> None:
    """Serialize current findings as the new grandfather set (sorted and
    aggregated so the file diffs cleanly in review)."""
    agg: Dict[Tuple[str, str, str, str], int] = {}
    for f in findings:
        key = (f.rule, f.path, f.snippet, f.fingerprint())
        agg[key] = agg.get(key, 0) + 1
    entries = [
        {"rule": rule, "path": p, "snippet": snip,
         "fingerprint": fp, "count": n}
        for (rule, p, snip, fp), n in sorted(agg.items())
    ]
    Path(path).write_text(json.dumps(
        {"version": BASELINE_VERSION, "entries": entries}, indent=2
    ) + "\n")
