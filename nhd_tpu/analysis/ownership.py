"""nhdrace shared-state model — the static half of the two-layer race
detector (rules_races.py judges it; nhd_tpu/sanitizer/races.py is the
runtime half, keyed on the same field identities).

Built on the lockgraph machinery (same module/function indexing, same
call-graph resolution, same ``with <lock>:`` held-set tracking) so the
two project packs never disagree about what a lock or a call edge is:

1. **thread-root inventory** — every entry point that runs off the main
   thread: ``Thread(target=...)`` / ``Timer(...)`` spawn sites resolved
   through the call-ref machinery, ``pool.submit(fn, ...)`` workers
   (multiplicity > 1 by construction), ``threading.Thread`` subclass
   ``run`` methods, HTTP handler ``do_*`` methods, plus the declared
   :data:`EXTRA_ROOTS` (the scheduler loop, gRPC handler methods) that
   no spawn expression in the analyzed set names;
2. **callable-attribute bindings** — ``CommitPipeline(heartbeat=
   self._beat)`` stores a bound method into ``self._heartbeat``; the
   binding is recovered from the constructor call plus the ``__init__``
   body, so ``self._heartbeat()`` on the worker thread resolves to
   ``Scheduler._beat`` and the heartbeat field is correctly shared;
3. **shared-field registry** — module globals and ``self.X`` attributes
   reachable from >= 2 roots (or from one root spawned with
   multiplicity), keyed ``"mod/label:Class.attr"`` — the exact key the
   lock registry and the runtime race sanitizer use, so a dynamic race
   witness names its static finding;
4. **per-access locksets** — locks held lexically at the access, plus
   the must-hold-on-entry set (intersection over every call path from a
   root, to the same fixed point lockgraph uses for may-acquire).

Ownership (single-writer state) is declared in two places: the central
:data:`OWNERSHIP` table below (live-tree architecture facts: every
``Scheduler`` mirror field is mutated on the scheduler loop only — HTTP
and gRPC views read through the ``ask_scheduler`` RPC queue), and
in-module ``_NHD_RACE_OWNER = {"field": "owner-glob"}`` declarations
(module- or class-level) for state whose owner is a local fact.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field as _dcfield
from fnmatch import fnmatch
from pathlib import Path
from typing import Dict, FrozenSet, List, Optional, Sequence, Set, Tuple

from nhd_tpu.analysis.core import ModuleSource, _dotted
from nhd_tpu.analysis.lockgraph import (
    LockGraphAnalysis,
    _Event,
    _Func,
    _FuncWalker,
    _self_attr,
)

# path scope: production packages only. tools/ and tests/ spawn threads
# freely around fixtures and harnesses; judging them would drown the
# pack in scaffolding noise (the races_out_of_scope fixture pins this).
_SCOPE_PARTS = ("nhd_tpu",)


def in_scope(path: str) -> bool:
    return any(p in _SCOPE_PARTS for p in Path(path).with_suffix("").parts)


# single-writer ownership, field-key glob -> owner-root glob (matched
# against the owning root's entry-function qual). Architecture facts,
# not guesses: keep entries justified.
OWNERSHIP: Tuple[Tuple[str, str], ...] = (
    # every Scheduler mirror/bookkeeping field is mutated on the
    # scheduler loop thread; HTTP/gRPC views go through ask_scheduler
    # (RpcMsgType over mainq) and never touch the object directly
    ("scheduler/core:Scheduler.*", "*scheduler/core:Scheduler.run"),
)

# roots no spawn expression in the analyzed set names: the scheduler
# loop is started by the CLI entry process, gRPC handler methods are
# dispatched by the grpc server's thread pool.
EXTRA_ROOTS: Tuple[str, ...] = (
    "*scheduler/core:Scheduler.run",
    "*rpc/server:NHDControlHandler.Get*",
)

# http.server dispatches these on a per-connection handler thread
_HANDLER_METHODS = {
    "do_GET", "do_POST", "do_PUT", "do_PATCH", "do_DELETE", "do_HEAD",
}

# container methods that mutate the receiver in place
_MUTATORS = {
    "append", "extend", "insert", "add", "update", "pop", "popitem",
    "remove", "discard", "clear", "setdefault", "appendleft", "popleft",
}

# wrapping a field in one of these hands the new thread a copy, not the
# shared structure (judged at spawn sites for NHD813)
_COPY_WRAPPERS = {
    "dict", "list", "set", "tuple", "sorted", "frozenset", "copy",
    "deepcopy",
}

_MUTABLE_CTORS = {
    "dict", "list", "set", "defaultdict", "deque", "OrderedDict",
    "Counter",
}

_INIT_METHODS = {"__init__", "__post_init__", "__new__"}

_WRITE_FLAVORS = ("write", "rmw", "checkset", "mutate")


def _is_mutable_expr(expr: ast.AST) -> bool:
    if isinstance(expr, (ast.List, ast.Dict, ast.Set, ast.ListComp,
                         ast.DictComp, ast.SetComp)):
        return True
    if isinstance(expr, ast.Call):
        d = _dotted(expr.func)
        if d is not None and d.split(".")[-1] in _MUTABLE_CTORS:
            return True
    return False


# ---------------------------------------------------------------------------
# event extraction: lockgraph's walker + field accesses / spawns / bindings
# ---------------------------------------------------------------------------

class _AccessWalker(_FuncWalker):
    """Records, on top of acquire/call/block events (whose consumers
    dispatch on ev.kind and ignore the additions):

    * ``access`` events — target ``(scoped_field, flavor)`` with flavor
      read/write/rmw/checkset/mutate; scoped_field is ``"Cls.attr"`` for
      ``self.X`` or the bare name for a module global;
    * ``spawn`` events — target ``(entry_ref, publish_fields, multiple,
      kind)`` for thread/timer/pool-submit sites;
    * ``ctorbind`` events — target ``(ctor_ref, ((param, value_ref),
      ...))`` wherever a method/function reference is passed as a
      constructor/call argument (callable-attribute resolution).
    """

    def __init__(self, mod, func):
        super().__init__(mod, func)
        self._guards: List[Set[str]] = []   # fields read by enclosing ifs
        self._loop = 0

    # -- field identification ------------------------------------------

    def _field_of(self, expr: ast.AST) -> Optional[str]:
        attr = _self_attr(expr)
        if attr is not None and self.func.cls is not None:
            return f"{self.func.cls}.{attr}"
        if isinstance(expr, ast.Name):
            if expr.id in getattr(self.mod, "race_globals", ()):
                return expr.id
        return None

    def _access(self, scoped: str, flavor: str, node: ast.AST,
                held: FrozenSet[str]) -> None:
        self.func.events.append(_Event(
            "access", (scoped, flavor), held, node.lineno, node.col_offset,
        ))

    # -- traversal ------------------------------------------------------

    def _visit(self, node: ast.AST, held: FrozenSet[str]) -> None:
        if isinstance(node, ast.If):
            # check-then-set: a write in the body of an if whose test
            # read the same field is one non-atomic read-modify-write
            self._visit(node.test, held)
            self._guards.append(self._fields_in(node.test))
            try:
                for child in node.body:
                    self._visit(child, held)
            finally:
                self._guards.pop()
            for child in node.orelse:
                self._visit(child, held)
            return
        if isinstance(node, (ast.For, ast.AsyncFor, ast.While)):
            self._loop += 1
            try:
                super()._visit(node, held)
            finally:
                self._loop -= 1
            return
        if isinstance(node, ast.Assign):
            for tgt in node.targets:
                self._record_store(tgt, node, held)
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            self._record_store(node.target, node, held)
        elif isinstance(node, ast.AugAssign):
            self._record_store(node.target, node, held, aug=True)
        elif isinstance(node, ast.Call):
            self._record_call_extras(node, held)
        elif isinstance(node, ast.Attribute) and isinstance(node.ctx, ast.Load):
            scoped = self._field_of(node)
            if scoped is not None:
                self._access(scoped, "read", node, held)
        elif isinstance(node, ast.Name) and isinstance(node.ctx, ast.Load):
            scoped = self._field_of(node)
            if scoped is not None:
                self._access(scoped, "read", node, held)
        super()._visit(node, held)

    def _fields_in(self, expr: ast.AST) -> Set[str]:
        out: Set[str] = set()
        for node in ast.walk(expr):
            scoped = self._field_of(node)
            if scoped is not None:
                out.add(scoped)
        return out

    def _record_store(self, tgt: ast.AST, stmt: ast.AST,
                      held: FrozenSet[str], aug: bool = False) -> None:
        flavor = "rmw" if aug else "write"
        while isinstance(tgt, ast.Subscript):
            # self.d[k] = v mutates the container self.d holds
            tgt = tgt.value
            if not aug:
                flavor = "mutate"
        if isinstance(tgt, (ast.Tuple, ast.List)):
            for el in tgt.elts:
                self._record_store(el, stmt, held, aug=aug)
            return
        scoped = self._field_of(tgt)
        if scoped is None:
            return
        if not aug and any(scoped in g for g in self._guards):
            flavor = "checkset"
        self._access(scoped, flavor, stmt, held)

    # -- spawns + callable bindings ------------------------------------

    def _value_ref(self, expr: ast.AST):
        """A call-ref for a bare callable expression (mirror of
        _callee_ref, which only looks at Call.func)."""
        attr = _self_attr(expr)
        if attr is not None and self.func.cls is not None:
            return ("method", self.func.cls, attr)
        if isinstance(expr, ast.Name):
            if expr.id in self.mod.import_funcs:
                return ("ext", *self.mod.import_funcs[expr.id])
            return ("local", expr.id)
        d = _dotted(expr)
        if d is not None and "." in d:
            head, _, _rest = d.partition(".")
            mod_part, _, fn_part = d.rpartition(".")
            if head in self.mod.import_mods:
                real = self.mod.import_mods[head]
                if mod_part == head:
                    mod_part = real
                return ("ext", mod_part, fn_part)
        return None

    def _publishes(self, exprs: List[ast.AST]) -> Tuple[str, ...]:
        """Fields handed to the new thread raw (no copy wrapper)."""
        out: List[str] = []
        stack = list(exprs)
        while stack:
            e = stack.pop()
            if isinstance(e, (ast.Tuple, ast.List)):
                stack.extend(e.elts)
                continue
            if isinstance(e, ast.Call):
                d = _dotted(e.func)
                tail = d.split(".")[-1] if d else (
                    e.func.attr if isinstance(e.func, ast.Attribute) else ""
                )
                if tail in _COPY_WRAPPERS or tail == "copy":
                    continue        # dict(self.x) / self.x.copy(): owned
                stack.extend(e.args)
                continue
            scoped = self._field_of(e)
            if scoped is not None:
                out.append(scoped)
        return tuple(sorted(set(out)))

    def _record_call_extras(self, node: ast.Call,
                            held: FrozenSet[str]) -> None:
        if (isinstance(node.func, ast.Attribute)
                and node.func.attr in _MUTATORS):
            scoped = self._field_of(node.func.value)
            if scoped is not None:
                self._access(scoped, "mutate", node, held)
        d = _dotted(node.func)
        tail = d.split(".")[-1] if d else (
            node.func.attr if isinstance(node.func, ast.Attribute) else None
        )
        entry = None
        publish: List[ast.AST] = []
        kind = None
        if tail == "Thread":
            for kw in node.keywords:
                if kw.arg == "target":
                    entry = kw.value
                elif kw.arg in ("args", "kwargs"):
                    publish.append(kw.value)
            kind = "thread"
        elif tail == "Timer":
            if len(node.args) >= 2:
                entry = node.args[1]
                publish.extend(node.args[2:])
            for kw in node.keywords:
                if kw.arg == "function":
                    entry = kw.value
                elif kw.arg in ("args", "kwargs"):
                    publish.append(kw.value)
            kind = "timer"
        elif tail == "submit" and isinstance(node.func, ast.Attribute):
            if node.args:
                entry = node.args[0]
                publish.extend(node.args[1:])
                publish.extend(kw.value for kw in node.keywords)
            kind = "pool"
        elif tail == "start_new_thread":
            if node.args:
                entry = node.args[0]
                publish.extend(node.args[1:])
            kind = "thread"
        if kind is not None and entry is not None:
            ref = self._value_ref(entry)
            multiple = kind == "pool" or self._loop > 0
            self.func.events.append(_Event(
                "spawn", (ref, self._publishes(publish), multiple, kind),
                held, node.lineno, node.col_offset,
            ))
            return
        # callable-attribute bindings: Ctor(..., heartbeat=self._beat)
        bindings: List[Tuple[object, object]] = []
        for i, arg in enumerate(node.args):
            ref = self._method_ref(arg)
            if ref is not None:
                bindings.append((i, ref))
        for kw in node.keywords:
            if kw.arg is None:
                continue
            ref = self._method_ref(kw.value)
            if ref is not None:
                bindings.append((kw.arg, ref))
        if bindings:
            callee = self._callee_ref(node)
            if callee is not None:
                self.func.events.append(_Event(
                    "ctorbind", (callee, tuple(bindings)), held,
                    node.lineno, node.col_offset,
                ))

    def _method_ref(self, expr: ast.AST):
        """Only method/function references qualify as callable bindings
        (a bare Name that is not a known function is just data)."""
        attr = _self_attr(expr)
        if attr is not None and self.func.cls is not None:
            return ("method", self.func.cls, attr)
        if isinstance(expr, ast.Name) and expr.id in self.mod.import_funcs:
            return ("ext", *self.mod.import_funcs[expr.id])
        if isinstance(expr, ast.Name) and (
            expr.id in self.mod.funcs or expr.id in getattr(
                self.func, "nested", {}
            )
        ):
            return ("local", expr.id)
        return None


# ---------------------------------------------------------------------------
# per-class facts for binding + mutability + ownership declarations
# ---------------------------------------------------------------------------

@dataclass
class _ClassInfo:
    name: str
    mod_label: str
    init_params: Tuple[str, ...] = ()       # positional (for index lookup)
    all_params: FrozenSet[str] = frozenset()  # positional + keyword-only
    attr_of_param: Dict[str, str] = _dcfield(default_factory=dict)
    owner_decl: Dict[str, str] = _dcfield(default_factory=dict)
    mutable_attrs: Set[str] = _dcfield(default_factory=set)
    thread_subclass: bool = False


def _const_str_dict(expr: ast.AST) -> Dict[str, str]:
    out: Dict[str, str] = {}
    if isinstance(expr, ast.Dict):
        for k, v in zip(expr.keys, expr.values):
            if (isinstance(k, ast.Constant) and isinstance(k.value, str)
                    and isinstance(v, ast.Constant)
                    and isinstance(v.value, str)):
                out[k.value] = v.value
    return out


# ---------------------------------------------------------------------------
# the model
# ---------------------------------------------------------------------------

@dataclass(frozen=True)
class Root:
    rid: str            # entry-function qual (the stable identity)
    kind: str           # thread | timer | pool | handler | run | declared
    site: str           # where it was inventoried
    multiple: bool      # > 1 concurrent instance possible


@dataclass(frozen=True)
class Access:
    key: str            # "mod/label:Cls.attr" or "mod/label:NAME"
    flavor: str         # read | write | rmw | checkset | mutate
    held: FrozenSet[str]
    path: str
    line: int
    col: int
    fn_qual: str
    roots: FrozenSet[str]
    init: bool          # constructor writing its own instance's field


class _OwnershipAnalysis(LockGraphAnalysis):
    walker_cls = _AccessWalker


class RaceModel:
    """Thread roots + shared-field registry + per-access locksets."""

    def __init__(self, modules: Sequence[ModuleSource]):
        self.analysis = _OwnershipAnalysis(modules)
        # pre-collect module globals so the walkers (which run inside
        # analysis.run) can classify bare-Name accesses
        for mod in self.analysis.modules:
            names: Set[str] = set()
            mutable: Set[str] = set()
            owner: Dict[str, str] = {}
            for node in mod.tree.body:
                tgts: List[ast.AST] = []
                value = None
                if isinstance(node, ast.Assign):
                    tgts, value = node.targets, node.value
                elif isinstance(node, ast.AnnAssign) and node.value is not None:
                    tgts, value = [node.target], node.value
                elif isinstance(node, ast.AugAssign):
                    tgts = [node.target]
                for t in tgts:
                    if isinstance(t, ast.Name):
                        names.add(t.id)
                        if value is not None and _is_mutable_expr(value):
                            mutable.add(t.id)
                        if t.id == "_NHD_RACE_OWNER" and value is not None:
                            owner.update(_const_str_dict(value))
            names.discard("_NHD_RACE_OWNER")
            mod.race_globals = names            # type: ignore[attr-defined]
            mod.race_mutable = mutable          # type: ignore[attr-defined]
            mod.race_owner = owner              # type: ignore[attr-defined]
        self.classes: Dict[Tuple[str, str], _ClassInfo] = {}
        self.roots: Dict[str, Root] = {}
        self.roots_of: Dict[str, Set[str]] = {}
        self.entry_locks: Dict[str, Optional[FrozenSet[str]]] = {}
        self.callable_attrs: Dict[Tuple[str, str, str], Set[str]] = {}
        self.fields: Dict[str, List[Access]] = {}
        self.spawns: List[Tuple[_Func, _Event, Optional[str]]] = []
        self._built = False

    # -- construction ---------------------------------------------------

    def build(self) -> None:
        if self._built:
            return
        self._built = True
        self.analysis.run()
        self._collect_classes()
        self._collect_bindings()
        self._collect_roots()
        self._propagate_reachability()
        self._propagate_entry_locks()
        self._collect_fields()

    def _collect_classes(self) -> None:
        for mod in self.analysis.modules:
            for node in mod.tree.body:
                if not isinstance(node, ast.ClassDef):
                    continue
                info = _ClassInfo(node.name, mod.label)
                for base in node.bases:
                    d = _dotted(base)
                    if d is not None and d.split(".")[-1].endswith("Thread"):
                        info.thread_subclass = True
                for sub in node.body:
                    if isinstance(sub, ast.Assign):
                        for t in sub.targets:
                            if (isinstance(t, ast.Name)
                                    and t.id == "_NHD_RACE_OWNER"):
                                info.owner_decl.update(
                                    _const_str_dict(sub.value)
                                )
                    if (isinstance(sub, (ast.FunctionDef,
                                         ast.AsyncFunctionDef))):
                        if sub.name == "__init__":
                            # positional index lookups use init_params;
                            # keyword bindings resolve by name, so
                            # keyword-only params count too
                            info.init_params = tuple(
                                a.arg for a in sub.args.args[1:]
                            )
                            info.all_params = frozenset(
                                info.init_params
                            ) | {a.arg for a in sub.args.kwonlyargs}
                        for st in ast.walk(sub):
                            if not isinstance(st, ast.Assign):
                                continue
                            for t in st.targets:
                                attr = _self_attr(t)
                                if attr is None:
                                    continue
                                if _is_mutable_expr(st.value):
                                    info.mutable_attrs.add(attr)
                                if (sub.name == "__init__"
                                        and isinstance(st.value, ast.Name)
                                        and st.value.id in info.all_params):
                                    info.attr_of_param[st.value.id] = attr
                self.classes[(mod.label, node.name)] = info

    def _class_of_ref(self, caller: _Func, ref) -> Optional[_ClassInfo]:
        if ref is None:
            return None
        mod = caller.module
        if ref[0] == "local":
            return self.classes.get((mod.label, ref[1]))
        if ref[0] == "ext":
            dotted, name = ref[1], ref[2]
            parts = dotted.split(".")
            for k in range(len(parts), 0, -1):
                cand = self.analysis._by_suffix.get(".".join(parts[-k:]))
                if cand is not None:
                    return self.classes.get((cand.label, name))
        return None

    def _collect_bindings(self) -> None:
        """Ctor(param=self._beat) + 'self.attr = param' in __init__ =>
        calls of self.attr() inside that class resolve to the bound
        method (union over every construction site)."""
        for fn in self.analysis.funcs.values():
            for ev in fn.events:
                if ev.kind != "ctorbind":
                    continue
                ctor_ref, bindings = ev.target
                info = self._class_of_ref(fn, ctor_ref)
                if info is None:
                    continue
                for param, value_ref in bindings:
                    if isinstance(param, int):
                        if param >= len(info.init_params):
                            continue
                        param = info.init_params[param]
                    attr = info.attr_of_param.get(param)
                    if attr is None:
                        continue
                    target = self.analysis._resolve(fn, value_ref)
                    if target is None:
                        continue
                    self.callable_attrs.setdefault(
                        (info.mod_label, info.name, attr), set()
                    ).add(target.qual)

    def _add_root(self, fn: _Func, kind: str, site: str,
                  multiple: bool) -> None:
        cur = self.roots.get(fn.qual)
        if cur is None:
            self.roots[fn.qual] = Root(fn.qual, kind, site, multiple)
        elif multiple and not cur.multiple:
            self.roots[fn.qual] = Root(cur.rid, cur.kind, cur.site, True)

    def _collect_roots(self) -> None:
        for fn in self.analysis.funcs.values():
            for ev in fn.events:
                if ev.kind != "spawn":
                    continue
                ref, _publish, multiple, kind = ev.target
                target = (self.analysis._resolve(fn, ref)
                          if ref is not None else None)
                self.spawns.append(
                    (fn, ev, target.qual if target else None)
                )
                if target is not None:
                    self._add_root(
                        target, kind, f"{fn.path}:{ev.line}", multiple
                    )
        for (mod_label, name), info in self.classes.items():
            if not info.thread_subclass:
                continue
            run = self.analysis.funcs.get(f"{mod_label}:{name}.run")
            if run is not None:
                self._add_root(run, "thread", run.path, False)
        for fn in self.analysis.funcs.values():
            tail = fn.qual.rsplit(".", 1)[-1]
            if tail in _HANDLER_METHODS:
                self._add_root(fn, "handler", fn.path, True)
            elif any(fnmatch(fn.qual, pat) for pat in EXTRA_ROOTS):
                self._add_root(fn, "declared", fn.path, False)

    def _call_targets(self, fn: _Func, ref) -> List[_Func]:
        hit = self.analysis._resolve(fn, ref)
        if hit is not None:
            return [hit]
        if ref is not None and ref[0] == "method" and fn.module is not None:
            quals = self.callable_attrs.get(
                (fn.module.label, ref[1], ref[2]), ()
            )
            return [self.analysis.funcs[q] for q in quals]
        return []

    def _propagate_reachability(self) -> None:
        for rid, root in self.roots.items():
            entry = self.analysis.funcs.get(rid)
            if entry is None:
                continue
            stack, seen = [entry], set()
            while stack:
                fn = stack.pop()
                if fn.qual in seen:
                    continue
                seen.add(fn.qual)
                self.roots_of.setdefault(fn.qual, set()).add(rid)
                for ev in fn.events:
                    if ev.kind == "call":
                        stack.extend(self._call_targets(fn, ev.target))

    def _propagate_entry_locks(self) -> None:
        """Must-hold-on-entry per function: TOP (unconstrained) meets,
        over every call edge, the caller's entry set union the locks
        held at the call site; roots and spawn targets enter with
        nothing held."""
        TOP = None
        entry: Dict[str, Optional[FrozenSet[str]]] = {
            q: TOP for q in self.analysis.funcs
        }

        def meet(qual: str, s: FrozenSet[str]) -> bool:
            cur = entry.get(qual, TOP)
            new = s if cur is TOP else cur & s
            if new != cur:
                entry[qual] = new
                return True
            return False

        for rid in self.roots:
            if rid in entry:
                entry[rid] = frozenset()
        changed, rounds = True, 0
        while changed and rounds < 50:
            changed, rounds = False, rounds + 1
            for fn in self.analysis.funcs.values():
                base = entry.get(fn.qual)
                if base is TOP:
                    continue
                for ev in fn.events:
                    if ev.kind == "call":
                        cs = base | ev.held
                        for callee in self._call_targets(fn, ev.target):
                            changed |= meet(callee.qual, cs)
                    elif ev.kind == "spawn":
                        ref = ev.target[0]
                        target = (self.analysis._resolve(fn, ref)
                                  if ref is not None else None)
                        if target is not None:
                            changed |= meet(target.qual, frozenset())
        self.entry_locks = entry

    def _field_key(self, mod_label: str, scoped: str) -> str:
        return f"{mod_label}:{scoped}"

    def _collect_fields(self) -> None:
        for fn in self.analysis.funcs.values():
            if fn.module is None:
                continue
            roots = frozenset(self.roots_of.get(fn.qual, ()))
            entry = self.entry_locks.get(fn.qual) or frozenset()
            for ev in fn.events:
                if ev.kind != "access":
                    continue
                scoped, flavor = ev.target
                key = self._field_key(fn.module.label, scoped)
                init = (
                    "." in scoped
                    and fn.qual.rsplit(".", 1)[-1] in _INIT_METHODS
                    and fn.cls is not None
                    and scoped.startswith(f"{fn.cls}.")
                )
                self.fields.setdefault(key, []).append(Access(
                    key, flavor, frozenset(ev.held | entry), fn.path,
                    ev.line, ev.col, fn.qual, roots, init,
                ))

    # -- queries --------------------------------------------------------

    def _instance_local(self, key: str, rid: str) -> bool:
        """http.server builds one handler *instance per connection*: a
        do_* root touching its own class's self.X state is thread-local
        by construction, not shared (per-request response flags, etc.)."""
        root = self.roots[rid]
        if root.kind != "handler":
            return False
        label, _, scoped = key.partition(":")
        if "." not in scoped:
            return False
        rlabel, _, rqual = rid.partition(":")
        return rlabel == label and rqual.split(".", 1)[0] == \
            scoped.split(".", 1)[0]

    def shared_fields(self) -> Dict[str, List[Access]]:
        """Fields accessed from >= 2 roots (or one multi-instance root)
        with at least one non-init write — the race candidate registry."""
        out: Dict[str, List[Access]] = {}
        for key, accesses in self.fields.items():
            live = [a for a in accesses if not a.init and a.roots]
            roots: Set[str] = set()
            for a in live:
                roots |= a.roots
            if not any(a.flavor in _WRITE_FLAVORS for a in live):
                continue
            roots = {r for r in roots if not self._instance_local(key, r)}
            multi = len(roots) >= 2 or any(
                self.roots[r].multiple for r in roots
            )
            if multi:
                out[key] = live
        return out

    def owner_of(self, key: str) -> Optional[str]:
        """The declared owner-root glob for a field key, if any."""
        label, _, scoped = key.partition(":")
        for mod in self.analysis.modules:
            if mod.label != label:
                continue
            decl = getattr(mod, "race_owner", {})
            if scoped in decl:
                return decl[scoped]
            if "." in scoped:
                cls, _, attr = scoped.partition(".")
                info = self.classes.get((label, cls))
                if info is not None and attr in info.owner_decl:
                    return info.owner_decl[attr]
        for pat, owner in OWNERSHIP:
            if fnmatch(key, pat):
                return owner
        return None

    def is_mutable(self, key: str) -> bool:
        label, _, scoped = key.partition(":")
        if "." in scoped:
            cls, _, attr = scoped.partition(".")
            info = self.classes.get((label, cls))
            return info is not None and attr in info.mutable_attrs
        for mod in self.analysis.modules:
            if mod.label == label:
                return scoped in getattr(mod, "race_mutable", ())
        return False


def build_model(modules: Sequence[ModuleSource]) -> RaceModel:
    model = RaceModel([m for m in modules if in_scope(m.path)])
    model.build()
    return model
