"""NHD3xx — exception hygiene.

A watch thread that swallows an exception doesn't crash — it silently
stops translating cluster events, and the scheduler keeps running against
a frozen mirror. The reference crashed the whole process instead
(TriadController.py:147-152); this rebuild keeps threads alive, which
makes *visible* handling mandatory:

* NHD301 — bare ``except:`` also catches SystemExit/KeyboardInterrupt
  and turns Ctrl-C / sys.exit into an infinite loop;
* NHD302 — ``except Exception:`` whose handler neither logs, re-raises,
  returns, breaks, nor even reads the caught exception. ``pass`` and
  ``continue`` bodies are the classic watch-loop black hole.

A handler that returns a sentinel (``return False``) is deliberate
control flow, not swallowing — the caller sees the failure. That's why
NHD302 keys on "no observable signal at all" rather than "no logging".
"""

from __future__ import annotations

import ast
from typing import List

from nhd_tpu.analysis.core import Finding

_BROAD = {"Exception", "BaseException"}
_LOGGING_HINTS = {
    "debug", "info", "warning", "warn", "error", "exception", "critical",
    "log", "print",
}


def _is_broad(type_node: ast.AST) -> bool:
    if isinstance(type_node, ast.Name):
        return type_node.id in _BROAD
    if isinstance(type_node, ast.Attribute):
        return type_node.attr in _BROAD
    if isinstance(type_node, ast.Tuple):
        return any(_is_broad(e) for e in type_node.elts)
    return False


def _handler_signals(handler: ast.ExceptHandler) -> bool:
    """True if the handler produces any observable outcome: logs, raises,
    returns/breaks out, or reads the bound exception."""
    exc_name = handler.name
    for node in ast.walk(handler):
        if isinstance(node, (ast.Raise, ast.Return, ast.Break)):
            return True
        if isinstance(node, ast.Call):
            func = node.func
            name = (
                func.attr if isinstance(func, ast.Attribute)
                else func.id if isinstance(func, ast.Name) else None
            )
            if name in _LOGGING_HINTS:
                return True
        if (
            exc_name
            and isinstance(node, ast.Name)
            and node.id == exc_name
            and isinstance(node.ctx, ast.Load)
        ):
            return True
        if isinstance(node, ast.Assign):
            return True  # records state somewhere the caller can observe
    return False


def check_module(tree: ast.Module, src: str, path: str) -> List[Finding]:
    findings: List[Finding] = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Try):
            continue
        for handler in node.handlers:
            if handler.type is None:
                findings.append(Finding(
                    "NHD301", path, handler.lineno, handler.col_offset,
                    "bare 'except:' catches SystemExit/KeyboardInterrupt "
                    "— name the exceptions (at minimum 'except Exception')",
                ))
                continue
            if _is_broad(handler.type) and not _handler_signals(handler):
                findings.append(Finding(
                    "NHD302", path, handler.lineno, handler.col_offset,
                    "broad except swallows the error with no log, raise, "
                    "or return — a dead watch loop looks exactly like a "
                    "quiet one; log it or narrow the exception type",
                ))
    return findings
