"""contract: NHD7xx — cross-layer solve-signature contract analysis.

NHD701  missing-consumer: a field present in one layer of the solve
        signature is absent (or a positional span disagrees) in another —
        DELTA_FIELDS vs _ARG_ORDER, the _MUTABLE/_STATIC partition,
        in_shardings spans, speculate stride math, .index() refs.
NHD702  order-contract violation: same field *set* but a different order
        (positional consumers would read the wrong array), duplicated
        fields, overlapping partition, or conflicting definitions.
NHD703  fingerprint-source omission: the AOT program fingerprint does
        not hash a module whose source defines the compiled program
        (the _ARG_ORDER module and the get_tables combo-table module) —
        a cached artifact would survive an edit that changes placement
        semantics.
NHD710  donation-alias hazard: a value tainted by a host-mirror read
        (``getattr(cluster, field)`` and what flows from it) reaches a
        donated argument position of a ``donate_argnums`` dispatch
        without an owning copy — the compiled program may mutate the
        host array in place through a zero-copy ``jnp.asarray`` (the
        PR 9 ``_pad_own`` double-claim bug, caught here statically).
NHD720  unregistered env knob: an ``NHD_*`` environment read that does
        not appear in the machine-readable knob registry
        (``nhd_tpu/config/knobs.py`` ``KNOBS``) — the OPERATIONS.md
        tunables table is generated from the registry, so an
        unregistered knob is an undocumented knob.

Scope and judgement model (see docs/STATIC_ANALYSIS.md "NHD7xx"):
checks fire only when both sides of a contract are visible in the
analyzed project — analyzing one file alone stays silent unless that
file carries both the definition and the violating consumer, which is
exactly how the EXPECT fixtures exercise each rule. ``test_*``/
``conftest.py`` modules are never part of the contract model.
"""

from __future__ import annotations

import ast
from typing import Dict, FrozenSet, List, Optional, Sequence, Set, Tuple

from nhd_tpu.analysis.core import Finding, ModuleSource, _dotted
from nhd_tpu.analysis.contracts import (
    ContractModel,
    TupleDef,
    build_model,
    module_basename,
)


def _is_test_module(path: str) -> bool:
    name = path.rsplit("/", 1)[-1]
    return name.startswith("test_") or name == "conftest.py"


def check_project(modules: Sequence[ModuleSource]) -> List[Finding]:
    modules = [m for m in modules if not _is_test_module(m.path)]
    model = build_model(modules)
    out: List[Finding] = []
    out.extend(_check_signature(model))
    out.extend(_check_fingerprints(model))
    out.extend(_check_knobs(model))
    out.extend(_check_donation(modules))
    return out


# ---------------------------------------------------------------------------
# NHD701 / NHD702: the signature itself
# ---------------------------------------------------------------------------

def _finding(rule: str, site, message: str) -> Finding:
    return Finding(rule, site.path, site.line, site.col, message)


def _resolve_def(
    model: ContractModel, name: str, out: List[Finding]
) -> Optional[TupleDef]:
    """The project's definition of one contract tuple; conflicting
    re-definitions are themselves an NHD702 (every consumer would pick
    whichever import it happens to see)."""
    defs = model.tuple_defs.get(name, [])
    if not defs:
        return None
    first = defs[0]
    for other in defs[1:]:
        if other.fields != first.fields:
            out.append(_finding(
                "NHD702", other,
                f"conflicting definition of {name}: differs from "
                f"{first.path}:{first.line}",
            ))
    return first


def _check_signature(model: ContractModel) -> List[Finding]:
    out: List[Finding] = []
    arg = _resolve_def(model, "_ARG_ORDER", out)
    pod = _resolve_def(model, "_POD_ARG_ORDER", out)
    mutable = _resolve_def(model, "_MUTABLE", out)
    static = _resolve_def(model, "_STATIC", out)
    delta = _resolve_def(model, "DELTA_FIELDS", out)

    # duplicated fields inside any one tuple break every positional use
    for tdef in (arg, pod, mutable, static, delta):
        if tdef is None:
            continue
        seen: Set[str] = set()
        for f in tdef.fields:
            if f in seen:
                out.append(_finding(
                    "NHD702", tdef,
                    f"{tdef.name} lists '{f}' more than once",
                ))
            seen.add(f)

    # encode's delta layer must mirror the kernel signature exactly:
    # same set (NHD701, the missing consumer is named) and same order
    # (NHD702 — ClusterDelta scatters rows by position)
    if arg is not None and delta is not None:
        for f in arg.fields:
            if f not in delta.fields:
                out.append(_finding(
                    "NHD701", delta,
                    f"'{f}' is in {arg.path.rsplit('/', 1)[-1]} _ARG_ORDER "
                    f"but missing from DELTA_FIELDS — the delta layer "
                    f"(encode.ClusterDelta) would never upload it",
                ))
        for f in delta.fields:
            if f not in arg.fields:
                out.append(_finding(
                    "NHD701", delta,
                    f"DELTA_FIELDS lists '{f}' which is not in _ARG_ORDER "
                    f"— no solver consumer exists for it",
                ))
        if set(arg.fields) == set(delta.fields) and arg.fields != delta.fields:
            i = next(
                i for i, (a, d) in enumerate(zip(arg.fields, delta.fields))
                if a != d
            )
            out.append(_finding(
                "NHD702", delta,
                f"DELTA_FIELDS order diverges from _ARG_ORDER at position "
                f"{i} ('{delta.fields[i]}' vs '{arg.fields[i]}') — "
                f"positional consumers would read the wrong array",
            ))

    # the donation/out-shardings partition must tile _ARG_ORDER exactly
    if arg is not None and mutable is not None and static is not None:
        part = set(mutable.fields) | set(static.fields)
        for f in arg.fields:
            if f not in part:
                out.append(_finding(
                    "NHD701", arg,
                    f"'{f}' is in _ARG_ORDER but neither _MUTABLE nor "
                    f"_STATIC — the megaround out_shardings/donation "
                    f"partition would drop it",
                ))
        for tdef in (mutable, static):
            for f in tdef.fields:
                if f not in arg.fields:
                    out.append(_finding(
                        "NHD701", tdef,
                        f"{tdef.name} lists '{f}' which is not in "
                        f"_ARG_ORDER",
                    ))
        overlap = set(mutable.fields) & set(static.fields)
        for f in sorted(overlap):
            out.append(_finding(
                "NHD702", static,
                f"'{f}' is in both _MUTABLE and _STATIC — the partition "
                f"must be disjoint",
            ))

    # positional .index() consumers
    for ref in model.index_refs:
        tdef = model.first_def(ref.tuple_name)
        if tdef is not None and ref.field_name not in tdef.fields:
            out.append(_finding(
                "NHD701", ref,
                f"{ref.tuple_name}.index('{ref.field_name}'): no such "
                f"field in {tdef.path}:{tdef.line} — this raises "
                f"ValueError at first call",
            ))

    # in_shardings spans: (node_spec,)*len(_ARG_ORDER) +
    # (repl,)*len(_POD_ARG_ORDER); literal counts must match, symbolic
    # spans must derive from the RIGHT tuple
    for site in model.sharding_sites:
        for count, sym, tdef, want in (
            (site.node_count, site.node_sym, arg, "_ARG_ORDER"),
            (site.pod_count, site.pod_sym, pod, "_POD_ARG_ORDER"),
        ):
            if tdef is None:
                continue
            if count is not None and count != len(tdef.fields):
                out.append(_finding(
                    "NHD701", site,
                    f"in_shardings {want.strip('_').lower()} span is a "
                    f"literal {count} but len({want}) == "
                    f"{len(tdef.fields)} — the mesh sharding layer "
                    f"(parallel/sharding) is missing a signature array",
                ))
            elif sym is not None and sym != want \
                    and sym in model.tuple_defs:
                out.append(_finding(
                    "NHD701", site,
                    f"in_shardings span derives from len({sym}); this "
                    f"position spans {want}",
                ))

    # speculate's flattened pod-block stride math
    if pod is not None:
        for stride in model.stride_sites:
            if stride.stride != len(pod.fields):
                out.append(_finding(
                    "NHD701", stride,
                    f"pod_args stride {stride.stride} != "
                    f"len(_POD_ARG_ORDER) == {len(pod.fields)} — the "
                    f"speculate stride layer would misalign every pod "
                    f"block after the first",
                ))
        for unpack in model.unpack_sites:
            if unpack.arity != len(pod.fields):
                out.append(_finding(
                    "NHD701", unpack,
                    f"pod_args slice unpacks {unpack.arity} names but "
                    f"len(_POD_ARG_ORDER) == {len(pod.fields)} — the "
                    f"speculate unpack layer is missing a signature array",
                ))
    return out


# ---------------------------------------------------------------------------
# NHD703: fingerprint sources
# ---------------------------------------------------------------------------

def _check_fingerprints(model: ContractModel) -> List[Finding]:
    out: List[Finding] = []
    if not model.fingerprint_sites:
        return out
    required: Dict[str, str] = {}
    for tdef in model.tuple_defs.get("_ARG_ORDER", []):
        required[module_basename(tdef.path)] = "defines _ARG_ORDER"
    for base in model.table_modules:
        required.setdefault(base, "defines get_tables")
    for site in model.fingerprint_sites:
        hashed = set(site.hashed)
        for base, why in sorted(required.items()):
            if base not in hashed:
                out.append(_finding(
                    "NHD703", site,
                    f"program fingerprint does not hash module '{base}' "
                    f"({why}) — a cached AOT artifact would survive an "
                    f"edit that changes placement semantics",
                ))
    return out


# ---------------------------------------------------------------------------
# NHD720: env-knob registry
# ---------------------------------------------------------------------------

def _check_knobs(model: ContractModel) -> List[Finding]:
    out: List[Finding] = []
    if not model.registries:
        return out  # no registry in this project: out of scope
    registered: Set[str] = set()
    for reg in model.registries:
        registered.update(reg.names)
    reg_path = model.registries[0].path
    for read in model.env_reads:
        if read.name not in registered:
            out.append(_finding(
                "NHD720", read,
                f"env knob '{read.name}' is read here but not registered "
                f"in {reg_path} KNOBS — the OPERATIONS.md tunables table "
                f"is generated from the registry, so this knob is "
                f"undocumented",
            ))
    return out


# ---------------------------------------------------------------------------
# NHD710: donation-alias dataflow
# ---------------------------------------------------------------------------
#
# Model (documented in STATIC_ANALYSIS.md):
#
# * taint SEEDS are ``getattr(obj, name)`` results — the idiom every
#   layer uses to walk the signature over a host-mirror ClusterArrays.
# * taint PROPAGATES through: plain assignment, tuple/list/dict/set
#   displays and comprehensions, subscripts/slices (numpy views),
#   conditional expressions, starred args, zero-copy library wrappers
#   (``jnp.asarray`` / ``np.asarray`` / ``jax.device_put``), user
#   functions classified ALIASING (some return is a bare parameter) or
#   TRANSPARENT (returns a zero-copy wrapper of a parameter), and
#   instance attributes any method of the class assigns a tainted value
#   into (class-wide fixed point).
# * taint is CUT by any other call — ``a.copy()``, ``np.array``,
#   ``np.ascontiguousarray``, ``np.concatenate`` and every function not
#   classified aliasing/transparent produce owned values. A wrapper
#   whose returns are all call results is deliberately judged an
#   ownership boundary (``_pad_own``-style guards): the analysis is
#   one return level deep by design.
# * a DONATING callable is a local bound from a factory whose body
#   builds ``donate_argnums`` into ``jax.jit`` (directly or via a
#   kwargs dict), or from ``jax.jit(f, donate_argnums=...)`` itself.
#   Passing a tainted value in a donated position flags the call.

_ZERO_COPY = {
    "jnp.asarray", "jax.numpy.asarray", "numpy.asarray", "np.asarray",
    "jax.device_put",
}


def _donated_positions(func: ast.AST) -> Optional[FrozenSet[int]]:
    """Donated argument positions for a jit-factory function body, or
    None when the function never donates."""
    positions: Set[int] = set()
    returns_jit = False
    for node in ast.walk(func):
        if isinstance(node, ast.Call):
            dotted = _dotted(node.func) or ""
            if dotted.rsplit(".", 1)[-1] in ("jit", "pjit"):
                returns_jit = True
                for kw in node.keywords:
                    if kw.arg == "donate_argnums":
                        positions.update(_int_elts(kw.value))
        if isinstance(node, ast.Dict):
            for key, value in zip(node.keys, node.values):
                if (
                    isinstance(key, ast.Constant)
                    and key.value == "donate_argnums"
                ):
                    positions.update(_int_elts(value))
    if positions and returns_jit:
        return frozenset(positions)
    return None


def _int_elts(node: ast.AST) -> Set[int]:
    out: Set[int] = set()
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        out.add(node.value)
    elif isinstance(node, (ast.Tuple, ast.List, ast.Set)):
        for elt in node.elts:
            if isinstance(elt, ast.Constant) and isinstance(elt.value, int):
                out.add(elt.value)
    return out


def _classify_functions(modules: Sequence[ModuleSource]) -> Dict[str, str]:
    """name -> 'aliasing' | 'transparent' for every function in the
    project whose returns can pass a parameter through. Names are
    unqualified: the callable travels between modules by from-import."""
    classes: Dict[str, str] = {}
    for module in modules:
        for node in ast.walk(module.tree):
            if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            params = {a.arg for a in node.args.args
                      + node.args.posonlyargs + node.args.kwonlyargs}
            params.discard("self")
            kind = _return_kind(node, params)
            if kind is not None:
                # aliasing dominates transparent if both appear
                if classes.get(node.name) != "aliasing":
                    classes[node.name] = kind
    return classes


def _own_walk(func: ast.AST):
    """ast.walk that does not descend into nested defs/classes — their
    bodies are judged as functions of their own."""
    stack: List[ast.AST] = [func]
    while stack:
        node = stack.pop()
        yield node
        for child in ast.iter_child_nodes(node):
            if isinstance(
                child,
                (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef),
            ):
                continue
            stack.append(child)


def _return_kind(
    func: ast.AST, params: Set[str]
) -> Optional[str]:
    kind: Optional[str] = None
    for node in _own_walk(func):
        if not (isinstance(node, ast.Return) and node.value is not None):
            continue
        if _expr_aliases_param(node.value, params):
            return "aliasing"
        if _is_zero_copy_of_param(node.value, params):
            kind = "transparent"
    return kind


def _expr_aliases_param(node: ast.AST, params: Set[str]) -> bool:
    if isinstance(node, ast.Name):
        return node.id in params
    if isinstance(node, (ast.Tuple, ast.List, ast.Set)):
        return any(_expr_aliases_param(e, params) for e in node.elts)
    if isinstance(node, ast.Dict):
        return any(
            v is not None and _expr_aliases_param(v, params)
            for v in node.values
        )
    if isinstance(node, ast.IfExp):
        return (
            _expr_aliases_param(node.body, params)
            or _expr_aliases_param(node.orelse, params)
        )
    if isinstance(node, ast.Subscript):
        # a slice of a parameter is a numpy view of it
        return _expr_aliases_param(node.value, params)
    if isinstance(node, (ast.ListComp, ast.GeneratorExp, ast.SetComp)):
        return _expr_aliases_param(node.elt, params)
    if isinstance(node, ast.Call) and _is_zero_copy_call(node):
        return bool(node.args) and _expr_aliases_param(node.args[0], params)
    return False


def _is_zero_copy_call(node: ast.Call) -> bool:
    return (_dotted(node.func) or "") in _ZERO_COPY


def _is_zero_copy_of_param(node: ast.AST, params: Set[str]) -> bool:
    return (
        isinstance(node, ast.Call)
        and _is_zero_copy_call(node)
        and bool(node.args)
        and _expr_aliases_param(node.args[0], params)
    )


def _local_alias_table(tree: ast.Module) -> Dict[str, str]:
    """from-import aliases: local name -> original function name."""
    table: Dict[str, str] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom):
            for alias in node.names:
                if alias.asname:
                    table[alias.asname] = alias.name
    return table


class _Taint:
    """Per-function taint evaluation against shared project facts."""

    def __init__(
        self,
        fn_class: Dict[str, str],
        aliases: Dict[str, str],
        attr_taint: Set[str],
    ):
        self.fn_class = fn_class
        self.aliases = aliases
        self.attr_taint = attr_taint
        self.locals: Set[str] = set()

    def _callee_kind(self, call: ast.Call) -> Optional[str]:
        dotted = _dotted(call.func) or ""
        if dotted in _ZERO_COPY:
            return "transparent"
        name = dotted.rsplit(".", 1)[-1]
        name = self.aliases.get(name, name)
        return self.fn_class.get(name)

    def tainted(self, node: ast.AST) -> bool:
        if isinstance(node, ast.Call):
            if isinstance(node.func, ast.Name) and node.func.id == "getattr":
                return True
            kind = self._callee_kind(node)
            if kind == "transparent":
                return bool(node.args) and self.tainted(node.args[0])
            if kind == "aliasing":
                return any(self.tainted(a) for a in node.args)
            return False  # any other call produces an owned value
        if isinstance(node, ast.Name):
            return node.id in self.locals
        if isinstance(node, ast.Attribute):
            return (
                isinstance(node.value, ast.Name)
                and node.value.id == "self"
                and node.attr in self.attr_taint
            )
        if isinstance(node, ast.Subscript):
            return self.tainted(node.value)
        if isinstance(node, (ast.Tuple, ast.List, ast.Set)):
            return any(self.tainted(e) for e in node.elts)
        if isinstance(node, ast.Dict):
            return any(
                v is not None and self.tainted(v) for v in node.values
            )
        if isinstance(node, (ast.ListComp, ast.GeneratorExp, ast.SetComp)):
            return self.tainted(node.elt) or any(
                self.tainted(gen.iter) for gen in node.generators
            )
        if isinstance(node, ast.DictComp):
            return self.tainted(node.value) or any(
                self.tainted(gen.iter) for gen in node.generators
            )
        if isinstance(node, ast.IfExp):
            return self.tainted(node.body) or self.tainted(node.orelse)
        if isinstance(node, ast.Starred):
            return self.tainted(node.value)
        if isinstance(node, ast.NamedExpr):
            return self.tainted(node.value)
        return False


def _function_nodes(tree: ast.Module):
    """(func, owning-class-name-or-None) for every def in the module."""
    out = []

    def walk(node: ast.AST, cls: Optional[str]) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.ClassDef):
                walk(child, child.name)
            elif isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                out.append((child, cls))
                walk(child, cls)
            else:
                walk(child, cls)

    walk(tree, None)
    return out


def _check_donation(modules: Sequence[ModuleSource]) -> List[Finding]:
    fn_class = _classify_functions(modules)
    # donate factories, by unqualified name, project-wide
    factories: Dict[str, FrozenSet[int]] = {}
    for module in modules:
        for node in ast.walk(module.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                pos = _donated_positions(node)
                if pos is not None:
                    factories[node.name] = pos

    out: List[Finding] = []
    for module in modules:
        aliases = _local_alias_table(module.tree)
        funcs = _function_nodes(module.tree)
        # class-wide attribute taint, to a fixed point: self.X = tainted
        # in any method taints reads of self.X in every method
        attr_taint: Dict[Optional[str], Set[str]] = {}
        for _ in range(4):
            changed = False
            for func, cls in funcs:
                taints = attr_taint.setdefault(cls, set())
                eng = _run_function(
                    func, fn_class, aliases, taints, factories, None
                )
                for attr in eng:
                    if attr not in taints:
                        taints.add(attr)
                        changed = True
            if not changed:
                break
        for func, cls in funcs:
            _run_function(
                func, fn_class, aliases, attr_taint.get(cls, set()),
                factories, out, path=module.path,
            )
    return out


def _taint_targets(t: ast.AST) -> List[str]:
    """Names a tainted assignment taints: plain locals, every name of a
    tuple target, the *base* of a subscript store (``d[k] = tainted``
    taints ``d``; ``self._dev[k] = tainted`` taints the attr), and
    ``self.X`` attribute stores (returned as ``self.X``)."""
    if isinstance(t, ast.Name):
        return [t.id]
    if isinstance(t, (ast.Tuple, ast.List)):
        return [n for e in t.elts for n in _taint_targets(e)]
    if isinstance(t, ast.Starred):
        return _taint_targets(t.value)
    if isinstance(t, ast.Subscript):
        return _taint_targets(t.value)
    if (
        isinstance(t, ast.Attribute)
        and isinstance(t.value, ast.Name)
        and t.value.id == "self"
    ):
        return [f"self.{t.attr}"]
    return []


def _run_function(
    func: ast.AST,
    fn_class: Dict[str, str],
    aliases: Dict[str, str],
    attr_taint: Set[str],
    factories: Dict[str, FrozenSet[int]],
    findings: Optional[List[Finding]],
    path: str = "",
) -> Set[str]:
    """One pass over a function body: propagate local taint to a fixed
    point, track donating locals, then (when *findings* is given) flag
    tainted values in donated positions. Returns the attr names this
    function writes tainted values into (for the class fixed point)."""
    eng = _Taint(fn_class, aliases, attr_taint)
    stmts = [
        n for n in _own_walk(func)
        if isinstance(n, (ast.Assign, ast.AnnAssign, ast.AugAssign))
    ]
    stmts.sort(key=lambda n: (n.lineno, n.col_offset))
    donating: Dict[str, FrozenSet[int]] = {}
    attr_writes: Set[str] = set()
    for _ in range(8):
        changed = False
        for stmt in stmts:
            value = stmt.value
            if value is None:
                continue
            targets = (
                stmt.targets if isinstance(stmt, ast.Assign)
                else [stmt.target]
            )
            # donating-callable binding?
            if isinstance(value, ast.Call):
                pos = _factory_positions(value, aliases, factories)
                if pos is not None:
                    for t in targets:
                        if isinstance(t, ast.Name) \
                                and donating.get(t.id) != pos:
                            donating[t.id] = pos
                            changed = True
            is_tainted = eng.tainted(value)
            if not is_tainted:
                continue
            for t in targets:
                for name in _taint_targets(t):
                    if name.startswith("self."):
                        attr = name[5:]
                        if attr not in attr_writes:
                            attr_writes.add(attr)
                            changed = True
                    elif name not in eng.locals:
                        eng.locals.add(name)
                        changed = True
        if not changed:
            break
    if findings is not None:
        for node in _own_walk(func):
            if not isinstance(node, ast.Call):
                continue
            pos = None
            if isinstance(node.func, ast.Name) and node.func.id in donating:
                pos = donating[node.func.id]
            else:
                pos = _factory_positions(node.func, aliases, factories) \
                    if isinstance(node.func, ast.Call) else None
            if not pos:
                continue
            for p in sorted(pos):
                arg: Optional[ast.AST] = None
                if any(isinstance(a, ast.Starred) for a in node.args):
                    starred = [a for a in node.args
                               if isinstance(a, ast.Starred)]
                    arg = starred[0]
                elif p < len(node.args):
                    arg = node.args[p]
                if arg is not None and eng.tainted(arg):
                    findings.append(Finding(
                        "NHD710", path, node.lineno, node.col_offset,
                        f"donated argument {p} may alias a live host "
                        f"array: the value reaches this dispatch from a "
                        f"getattr() host-mirror read without an owning "
                        f"copy, and a zero-copy asarray would let the "
                        f"donated program mutate the host mirror in "
                        f"place — copy first (np.ascontiguousarray / "
                        f".copy())",
                    ))
                    break  # one finding per dispatch site
    return attr_writes


def _factory_positions(
    call: ast.Call,
    aliases: Dict[str, str],
    factories: Dict[str, FrozenSet[int]],
) -> Optional[FrozenSet[int]]:
    """Donated positions when *call* builds a donating callable: either
    a call to a known donate factory, or jax.jit(f, donate_argnums=...)
    inline."""
    dotted = _dotted(call.func) or ""
    name = dotted.rsplit(".", 1)[-1]
    name = aliases.get(name, name)
    if name in factories:
        return factories[name]
    if name in ("jit", "pjit"):
        pos: Set[int] = set()
        for kw in call.keywords:
            if kw.arg == "donate_argnums":
                pos.update(_int_elts(kw.value))
        if pos:
            return frozenset(pos)
    return None
