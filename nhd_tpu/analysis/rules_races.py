"""NHD81x — static data-race rules (project pack 'races').

Judges the shared-state model ``ownership.py`` builds: thread roots,
shared-field registry, per-access effective locksets (lexically held
plus must-hold-on-entry). Field keys are ``"mod/label:Class.attr"`` —
the same identity the runtime race sanitizer (``nhd_tpu/sanitizer/
races.py``) reports, so a dynamic witness names its static finding.

* **NHD810** shared write with an empty consistent lockset: the field is
  written from one thread root and touched from another, and no single
  lock is held across every access. Reported at each unlocked write,
  naming a concurrent access site as the witness.
* **NHD811** write outside the declared owner: the ownership registry
  (``ownership.OWNERSHIP`` + in-module ``_NHD_RACE_OWNER``) declares the
  field single-writer; an unlocked write on a path from any *other* root
  breaks the discipline (readers tolerate staleness, a second writer
  corrupts).
* **NHD812** non-atomic read-modify-write: ``x += 1`` or
  check-then-set (``if self.x is None: self.x = ...``) on a shared field
  with no lock held — two threads interleave load and store and one
  update is lost (the classic dropped counter / double-initialized
  cache).
* **NHD813** mutable publish: a spawn site hands a mutable field
  (list/dict/set-valued) to the new thread raw — no ``copy``/``dict()``
  wrapper, no lock discipline — while the publisher keeps writing it.

A field whose every access shares one common lock is consistent and
skipped entirely; writes that do hold a lock are never reported even
when the overall intersection is empty (the unlocked *other* site is the
bug). Accesses in the owning class's ``__init__`` happen before the
object is published and are exempt. Main-thread-only code (reachable
from no root) neither creates sharing nor weakens locksets — a
documented under-approximation that keeps the pack quiet on
single-threaded modules.
"""

from __future__ import annotations

from fnmatch import fnmatch
from typing import List, Sequence, Set, Tuple

from nhd_tpu.analysis.core import Finding, ModuleSource
from nhd_tpu.analysis.ownership import (
    _WRITE_FLAVORS,
    Access,
    RaceModel,
    build_model,
)

_RMW_FLAVORS = ("rmw", "checkset")


def _fmt_roots(model: RaceModel, roots) -> str:
    return ", ".join(sorted(roots)) or "<main>"


def _witness(accesses: List[Access], mine: Access) -> str:
    """A concurrent access on a different root, for the diagnostic."""
    for a in accesses:
        if a.roots - mine.roots:
            return f"{a.path}:{a.line} ({a.flavor})"
    for a in accesses:
        if a is not mine:
            return f"{a.path}:{a.line} ({a.flavor})"
    return "same site, multiple concurrent instances"


def check_project(modules: Sequence[ModuleSource]) -> List[Finding]:
    model = build_model(modules)
    out: List[Finding] = []
    seen: Set[Tuple[str, str, int]] = set()

    def emit(rule: str, path: str, line: int, col: int, msg: str) -> None:
        k = (rule, path, line)
        if k not in seen:
            seen.add(k)
            out.append(Finding(rule, path, line, col, msg))

    shared = model.shared_fields()
    for key in sorted(shared):
        accesses = shared[key]
        consistent = frozenset.intersection(
            *[a.held for a in accesses]
        )
        if consistent:
            continue                # one lock covers every access: clean
        owner = model.owner_of(key)
        writes = [a for a in accesses if a.flavor in _WRITE_FLAVORS]
        for w in sorted(writes, key=lambda a: (a.path, a.line)):
            if w.held:
                continue            # the unlocked site is the finding
            if owner is not None:
                off_owner = [r for r in w.roots if not fnmatch(r, owner)]
                if off_owner:
                    emit(
                        "NHD811", w.path, w.line, w.col,
                        f"write to single-writer field '{key}' from "
                        f"non-owner thread root(s) "
                        f"{_fmt_roots(model, off_owner)} (declared owner "
                        f"'{owner}'): a second writer corrupts state "
                        "readers only ever expect the owner to advance — "
                        "route the update through the owner thread or "
                        "guard both writers with one lock",
                    )
                continue            # owner's own unlocked writes are the
                                    # single-writer discipline working
            if w.flavor in _RMW_FLAVORS:
                what = ("check-then-set" if w.flavor == "checkset"
                        else "read-modify-write")
                emit(
                    "NHD812", w.path, w.line, w.col,
                    f"non-atomic {what} on shared field '{key}' with no "
                    f"lock held (roots: "
                    f"{_fmt_roots(model, _roots_of(accesses))}): two "
                    "threads interleave the load and the store and one "
                    "update is lost — hold the field's lock across the "
                    "whole operation (or make it owner-thread-only via "
                    "_NHD_RACE_OWNER)",
                )
            else:
                emit(
                    "NHD810", w.path, w.line, w.col,
                    f"unsynchronized write to shared field '{key}' "
                    f"(concurrent access at {_witness(accesses, w)}; "
                    f"roots: {_fmt_roots(model, _roots_of(accesses))}): "
                    "no single lock is held across all accesses — guard "
                    "every access with one lock, or declare the owning "
                    "thread in the ownership registry if it is "
                    "single-writer by design",
                )

    # NHD813: mutable structures handed raw to a new thread
    for fn, ev, target_qual in model.spawns:
        if fn.module is None:
            continue
        _ref, publish, _multiple, kind = ev.target
        for scoped in publish:
            key = f"{fn.module.label}:{scoped}"
            if not model.is_mutable(key):
                continue
            live = [a for a in model.fields.get(key, []) if not a.init]
            writers = [a for a in live if a.flavor in _WRITE_FLAVORS]
            if not writers:
                continue            # effectively frozen after construction
            if all(a.held for a in writers) and ev.held:
                continue            # publisher and spawn share discipline
            emit(
                "NHD813", fn.path, ev.line, ev.col,
                f"mutable field '{key}' passed raw to a {kind} thread "
                f"target (spawned here, still written at "
                f"{writers[0].path}:{writers[0].line}): the new thread "
                "iterates/reads the live structure while the publisher "
                "mutates it — hand it a copy (dict(x)/list(x)/x.copy()) "
                "or guard both sides with one lock",
            )
    return out


def _roots_of(accesses: List[Access]):
    roots: Set[str] = set()
    for a in accesses:
        roots |= a.roots
    return roots
