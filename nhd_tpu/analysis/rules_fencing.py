"""NHD5xx — fenced-commit discipline in the scheduling control plane.

HA mode (k8s/lease.py) is only sound if EVERY mutating backend call on
the commit path carries the current fencing epoch — one raw call is a
hole a deposed leader's in-flight batch can land through. The repo's
contract: inside ``nhd_tpu/scheduler/``, the four commit-path mutators
(``bind_pod_to_node``, ``annotate_pod_config``, ``annotate_pod_gpu_map``,
``add_nad_to_pod``) are invoked ONLY through the fenced-commit helper
``Scheduler._commit_write`` (scheduler/core.py), which stamps the epoch.

* NHD501 — a ``*.backend.<mutator>(...)`` call in scheduler code outside
  the helper. Passing the bound method TO the helper
  (``self._commit_write(self.backend.bind_pod_to_node, ...)``) is the
  sanctioned form and is not a call expression, so it never flags.

The CONTROLLER's cluster mutators (``create_pod_for_triadset``,
``update_triadset_status`` — the TriadSet reconciliation writes) are in
scope too: they must route through ``Controller._coordinator_write``,
which re-checks coordinatorship at the write instead of only at the top
of the reconcile pass (a replica deposed — or whose coordinator shard
handed off under federation — mid-pass must not keep writing).

Reads and ``generate_pod_event`` (idempotent audit trail) are out of
scope — the rule guards exactly the writes whose double application
corrupts cluster state.
"""

from __future__ import annotations

import ast
from typing import List, Optional

from nhd_tpu.analysis.core import Finding, _dotted

# module-path gate: the pack judges only scheduler control-plane code
_SCOPE_PARTS = ("scheduler",)

#: the commit-path mutators that MUST carry a fencing epoch
#: (``evict_pod`` is the policy engine's preemption eviction — an
#: unfenced eviction is the preemption analog of the double-bind hole:
#: a deposed leader could evict a victim the new leader just placed)
FENCED_MUTATORS = frozenset({
    "bind_pod_to_node",
    "annotate_pod_config",
    "annotate_pod_gpu_map",
    "add_nad_to_pod",
    "annotate_pod_meta",
    "claim_spillover_pod",
    "evict_pod",
})

#: the controller's cluster mutators (TriadSet reconciliation) — gated
#: on coordinatorship per write, not per pass
COORDINATOR_MUTATORS = frozenset({
    "create_pod_for_triadset",
    "update_triadset_status",
})

#: mutator → the one function allowed to issue it
FENCE_HELPER = "_commit_write"
COORDINATOR_HELPER = "_coordinator_write"
_HELPER_FOR = {
    **{m: FENCE_HELPER for m in FENCED_MUTATORS},
    **{m: COORDINATOR_HELPER for m in COORDINATOR_MUTATORS},
}


def _in_scope(path: str) -> bool:
    parts = path.replace("\\", "/").split("/")
    return any(p in parts for p in _SCOPE_PARTS)


class _Visitor(ast.NodeVisitor):
    def __init__(self, path: str):
        self.path = path
        self.findings: List[Finding] = []
        self._func_stack: List[str] = []

    def _visit_func(self, node) -> None:
        self._func_stack.append(node.name)
        self.generic_visit(node)
        self._func_stack.pop()

    visit_FunctionDef = _visit_func
    visit_AsyncFunctionDef = _visit_func

    def _enclosing(self) -> Optional[str]:
        return self._func_stack[-1] if self._func_stack else None

    def visit_Call(self, node: ast.Call) -> None:
        d = _dotted(node.func)
        if d is not None:
            parts = d.split(".")
            # any receiver whose terminal name is 'backend': self.backend,
            # sched.backend, AND a bare `backend` parameter — a helper
            # taking the backend directly must not evade the rule
            if (
                len(parts) >= 2
                and parts[-1] in _HELPER_FOR
                and parts[-2] == "backend"
                and self._enclosing() != _HELPER_FOR[parts[-1]]
            ):
                helper = _HELPER_FOR[parts[-1]]
                if helper == FENCE_HELPER:
                    why = (
                        f"{d}() mutates cluster state outside the "
                        f"fenced-commit helper: without the fencing epoch "
                        f"a deposed leader's in-flight write can land "
                        f"after a standby's promotion — route it through "
                        f"Scheduler.{FENCE_HELPER}() "
                        "(docs/RESILIENCE.md 'HA & fencing')"
                    )
                else:
                    why = (
                        f"{d}() mutates cluster state outside the "
                        f"coordinator-write helper: a replica deposed "
                        f"mid-reconcile keeps writing against the new "
                        f"coordinator — route it through "
                        f"Controller.{COORDINATOR_HELPER}() "
                        "(docs/RESILIENCE.md 'Federation')"
                    )
                self.findings.append(Finding(
                    "NHD501", self.path, node.lineno, node.col_offset, why,
                ))
        self.generic_visit(node)


def check_module(tree: ast.Module, src: str, path: str) -> List[Finding]:
    if not _in_scope(path):
        return []
    visitor = _Visitor(path)
    visitor.visit(tree)
    return visitor.findings
