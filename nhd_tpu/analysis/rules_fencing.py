"""NHD5xx — fenced-commit discipline in the scheduling control plane.

HA mode (k8s/lease.py) is only sound if EVERY mutating backend call on
the commit path carries the current fencing epoch — one raw call is a
hole a deposed leader's in-flight batch can land through. The repo's
contract: inside ``nhd_tpu/scheduler/``, the four commit-path mutators
(``bind_pod_to_node``, ``annotate_pod_config``, ``annotate_pod_gpu_map``,
``add_nad_to_pod``) are invoked ONLY through the fenced-commit helper
``Scheduler._commit_write`` (scheduler/core.py), which stamps the epoch.

* NHD501 — a ``*.backend.<mutator>(...)`` call in scheduler code outside
  the helper. Passing the bound method TO the helper
  (``self._commit_write(self.backend.bind_pod_to_node, ...)``) is the
  sanctioned form and is not a call expression, so it never flags.

Reads, ``generate_pod_event`` (idempotent audit trail), and the
controller's TriadSet reconciliation (gated on leadership at the loop
level, and create-idempotent: a double-create answers 409) are out of
scope — the rule guards exactly the writes whose double application
corrupts cluster state.
"""

from __future__ import annotations

import ast
from typing import List, Optional

from nhd_tpu.analysis.core import Finding, _dotted

# module-path gate: the pack judges only scheduler control-plane code
_SCOPE_PARTS = ("scheduler",)

#: the commit-path mutators that MUST carry a fencing epoch
FENCED_MUTATORS = frozenset({
    "bind_pod_to_node",
    "annotate_pod_config",
    "annotate_pod_gpu_map",
    "add_nad_to_pod",
})

#: the one function allowed to issue them
FENCE_HELPER = "_commit_write"


def _in_scope(path: str) -> bool:
    parts = path.replace("\\", "/").split("/")
    return any(p in parts for p in _SCOPE_PARTS)


class _Visitor(ast.NodeVisitor):
    def __init__(self, path: str):
        self.path = path
        self.findings: List[Finding] = []
        self._func_stack: List[str] = []

    def _visit_func(self, node) -> None:
        self._func_stack.append(node.name)
        self.generic_visit(node)
        self._func_stack.pop()

    visit_FunctionDef = _visit_func
    visit_AsyncFunctionDef = _visit_func

    def _enclosing(self) -> Optional[str]:
        return self._func_stack[-1] if self._func_stack else None

    def visit_Call(self, node: ast.Call) -> None:
        d = _dotted(node.func)
        if d is not None:
            parts = d.split(".")
            # any receiver whose terminal name is 'backend': self.backend,
            # sched.backend, AND a bare `backend` parameter — a helper
            # taking the backend directly must not evade the rule
            if (
                len(parts) >= 2
                and parts[-1] in FENCED_MUTATORS
                and parts[-2] == "backend"
                and self._enclosing() != FENCE_HELPER
            ):
                self.findings.append(Finding(
                    "NHD501", self.path, node.lineno, node.col_offset,
                    f"{d}() mutates cluster state outside the fenced-commit "
                    f"helper: without the fencing epoch a deposed leader's "
                    f"in-flight write can land after a standby's promotion "
                    f"— route it through Scheduler.{FENCE_HELPER}() "
                    "(docs/RESILIENCE.md 'HA & fencing')",
                ))
        self.generic_visit(node)


def check_module(tree: ast.Module, src: str, path: str) -> List[Finding]:
    if not _in_scope(path):
        return []
    visitor = _Visitor(path)
    visitor.visit(tree)
    return visitor.findings
