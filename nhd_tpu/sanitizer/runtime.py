"""nhdsan — runtime deadlock sanitizer (the dynamic half of the lock
discipline story; ``nhd_tpu/analysis/lockgraph.py`` is the static half).

ThreadSanitizer-style witness machinery for what the AST cannot prove:
instrumented ``Lock``/``RLock``/``Condition`` wrappers record, per
thread, which locks are held (with acquisition stacks) and which lock is
being waited for. The union is a **wait-for graph**: thread T waits for
lock L, L is owned by thread U, U waits for M, ... — a cycle back to T
is a deadlock *in progress*. The waiter that discovers the cycle records
a witness and raises :class:`DeadlockError`, converting a silent hang
into a diagnosable failure (the streaming-mesh deadlock burned the whole
tier-1 budget precisely because nothing ever failed).

Detection is sound-at-detection-time: the wait-for graph is examined
under the registry lock while every edge in the cycle is current, so a
reported cycle was a real cycle at that instant (no false positives from
stale edges). Hold-while-blocking witnesses — a thread entering an
unbounded ``queue.get``/``Thread.join``/``Event.wait`` while holding an
instrumented lock — are recorded but not fatal: the static analog
(NHD211) flags the pattern; at runtime only the realized cycle kills.

Locks are keyed by their **construction site** (``file:line``), the same
key the static lock graph exports — a runtime witness therefore joins
against static facts by site (docs/OBSERVABILITY.md). Witnesses also
flow into the PR 3 flight recorder (``nhd_tpu/obs``) as ``nhdsan``
category spans when tracing is enabled, so a Chrome trace shows the
witness inline with the scheduling pipeline that produced it.

Opt-in: ``NHD_SAN=1`` makes the tests/conftest.py fixture call
:func:`nhd_tpu.sanitizer.install`, which monkeypatches
``threading.Lock``/``RLock``/``Condition`` (factories for everything
created afterwards) plus the blocking entry points above. Tests can also
instantiate a private :class:`Sanitizer` and build wrappers explicitly —
no global state touched.
"""

from __future__ import annotations

import os
import threading
import time
import traceback
from typing import Dict, List, Optional, Tuple

import _thread

# originals, captured at import so wrappers can never nest even if this
# module loads after install() ran in another process/session
_ALLOCATE = _thread.allocate_lock
_ORIG_RLOCK = _thread.RLock


class DeadlockError(RuntimeError):
    """A wait-for-graph cycle: acquiring would deadlock. The message
    carries the full cycle with per-thread held-lock stacks."""


_SKIP_FILES = (
    os.path.dirname(__file__),
    getattr(threading, "__file__", "<none>"),
)


def _site() -> str:
    """file:line of the nearest stack frame outside this package and the
    stdlib threading/queue modules — the user-code construction (or
    blocking) site, matching the static lock graph's site keys."""
    import queue as _queue

    skip = _SKIP_FILES + (getattr(_queue, "__file__", "<none>"),)
    for frame in reversed(traceback.extract_stack()):
        if not frame.filename.startswith(skip):
            return f"{frame.filename}:{frame.lineno}"
    return "<unknown>"


class _LockInfo:
    __slots__ = ("uid", "site", "kind", "owner", "count", "acquired_at",
                 "n_acquisitions", "n_contended")

    def __init__(self, uid: int, site: str, kind: str):
        self.uid = uid
        self.site = site
        self.kind = kind
        self.owner: Optional[int] = None    # thread ident
        self.count = 0                      # re-entrancy depth (RLock)
        self.acquired_at: Optional[List[str]] = None  # stack summary
        self.n_acquisitions = 0
        self.n_contended = 0


class Sanitizer:
    """One witness registry. ``install()`` publishes a process-global
    instance; tests may build private ones."""

    def __init__(self, *, poll_interval: float = 0.05):
        self.poll_interval = poll_interval
        self._reg = _ALLOCATE()             # raw: never instrumented
        self._locks: Dict[int, _LockInfo] = {}
        self._wants: Dict[int, int] = {}    # thread ident -> lock uid
        self._held: Dict[int, List[int]] = {}  # thread ident -> [lock uid]
        self._witnesses: List[dict] = []
        # hold-while-blocking sites repeat (every queue drain under the
        # same lock): one witness per distinct site, with a count
        self._hwb_counts: Dict[Tuple, dict] = {}
        self._next_uid = 1
        self._t0 = time.monotonic()

    # -- wrapper factories ---------------------------------------------

    def Lock(self) -> "SanLock":
        return SanLock(self, reentrant=False, site=_site())

    def RLock(self) -> "SanLock":
        return SanLock(self, reentrant=True, site=_site())

    def Condition(self, lock=None) -> "threading.Condition":
        # a plain threading.Condition over an instrumented lock: every
        # acquire/release/wait flows through the wrapper, so the
        # wait-for graph sees the condition's lock like any other
        if lock is None:
            lock = self.RLock()
        return _SanCondition(lock)

    # -- registry -------------------------------------------------------

    def _register(self, info: _LockInfo) -> int:
        with self._reg:
            uid = self._next_uid
            self._next_uid += 1
            info.uid = uid
            self._locks[uid] = info
            return uid

    def _holder_stacks(self, idents: List[int]) -> Dict[str, List[str]]:
        out: Dict[str, List[str]] = {}
        for ident in idents:
            held = []
            for uid in self._held.get(ident, ()):
                info = self._locks[uid]
                held.append(f"{info.kind}@{info.site}")
            out[str(ident)] = held
        return out

    def _detect_cycle(self, me: int) -> Optional[List[Tuple[int, int]]]:
        """Follow wants -> owner -> wants ... from *me*; a return to *me*
        is a deadlock. Caller holds the registry lock. Returns the cycle
        as [(thread ident, lock uid waited for), ...] or None."""
        path: List[Tuple[int, int]] = []
        seen = set()
        tid = me
        while True:
            uid = self._wants.get(tid)
            if uid is None:
                return None
            path.append((tid, uid))
            owner = self._locks[uid].owner
            if owner is None or owner == tid:
                return None
            if owner == me:
                return path
            if owner in seen:
                return None     # cycle not through me: its members report
            seen.add(owner)
            tid = owner

    # -- witness recording ---------------------------------------------

    def _record_witness(self, kind: str, detail: dict) -> dict:
        w = {
            "kind": kind,
            "t": time.monotonic() - self._t0,
            "thread": threading.current_thread().name,
            **detail,
        }
        self._witnesses.append(w)   # registry lock held by callers
        return w

    def _emit_span(self, w: dict) -> None:
        """Mirror the witness into the flight recorder (when tracing is
        on) so Chrome traces show it inline with the pipeline."""
        try:
            from nhd_tpu.obs.recorder import get_recorder
            rec = get_recorder()
        except Exception:       # obs depends on nothing, but stay safe
            return
        if rec is None:
            return
        rec.record(
            f"nhdsan.{w['kind']}", time.monotonic(), 0.0, cat="nhdsan",
            attrs={k: v for k, v in w.items() if k not in ("kind",)},
        )

    # -- blocking-entry hook (queue.get / Thread.join / Event.wait) ----

    def note_blocking(self, desc: str) -> None:
        """Called by the installed blocking-entry patches before an
        unbounded wait: a thread holding instrumented locks here is the
        runtime NHD211. Not fatal — only a realized cycle kills."""
        me = threading.get_ident()
        with self._reg:
            held = self._held.get(me)
            if not held:
                return
            held_sites = tuple(
                f"{self._locks[u].kind}@{self._locks[u].site}" for u in held
            )
        at = _site()    # walks the stack: outside the registry lock
        w = None
        with self._reg:
            key = (desc, held_sites, at)
            prior = self._hwb_counts.get(key)
            if prior is not None:
                prior["count"] += 1
            else:
                w = self._record_witness("hold_while_blocking", {
                    "blocking": desc,
                    "held": list(held_sites),
                    "at": at,
                    "count": 1,
                })
                self._hwb_counts[key] = w
        if w is not None:
            self._emit_span(w)

    # -- race-sanitizer hook -------------------------------------------

    def held_snapshot(self, ident: Optional[int] = None) -> Tuple[Tuple[int, str], ...]:
        """(uid, 'kind@site') of every instrumented lock the thread
        holds right now — the per-access lockset the race layer
        (races.py) intersects, Eraser-style."""
        if ident is None:
            ident = threading.get_ident()
        with self._reg:
            return tuple(
                (u, f"{self._locks[u].kind}@{self._locks[u].site}")
                for u in self._held.get(ident, ())
            )

    # -- report ---------------------------------------------------------

    def witnesses(self, kind: Optional[str] = None) -> List[dict]:
        with self._reg:
            out = list(self._witnesses)
        return [w for w in out if kind is None or w["kind"] == kind]

    def report(self) -> dict:
        with self._reg:
            locks = [
                {
                    "site": i.site, "kind": i.kind,
                    "acquisitions": i.n_acquisitions,
                    "contended": i.n_contended,
                }
                for i in self._locks.values()
            ]
            witnesses = list(self._witnesses)
        return {
            "version": 1,
            "cycles": [w for w in witnesses if w["kind"] == "cycle"],
            "hold_while_blocking": [
                w for w in witnesses if w["kind"] == "hold_while_blocking"
            ],
            "locks": sorted(locks, key=lambda l: l["site"]),
        }

    def chrome_trace(self) -> dict:
        """Witnesses as a loadable Chrome trace (obs/chrome.py renders),
        usable even when the flight recorder was off."""
        from nhd_tpu.obs.chrome import chrome_trace_of
        from nhd_tpu.obs.recorder import Span

        spans = [
            Span(
                f"nhdsan.{w['kind']}", w["t"], 0.0, cat="nhdsan",
                thread=w.get("thread", "?"),
                attrs={k: v for k, v in w.items()
                       if k not in ("kind", "t", "thread")},
            )
            for w in self.witnesses()
        ]
        return chrome_trace_of(spans)


class SanLock:
    """Instrumented mutex; reentrant=True gives RLock semantics. Exposes
    the full lock protocol (incl. the ``_release_save`` trio) so
    ``threading.Condition`` composes with it."""

    def __init__(self, san: Sanitizer, *, reentrant: bool, site: str):
        self._san = san
        self._inner = _ALLOCATE()
        self.reentrant = reentrant
        self._info = _LockInfo(0, site, "RLock" if reentrant else "Lock")
        san._register(self._info)

    # -- bookkeeping (registry lock held) ------------------------------

    def _mark_acquired(self, me: int) -> None:
        info = self._info
        info.owner = me
        info.count += 1
        info.n_acquisitions += 1
        self._san._held.setdefault(me, []).append(info.uid)

    def _mark_released(self, me: int) -> None:
        info = self._info
        info.count -= 1
        if info.count == 0:
            info.owner = None
        held = self._san._held.get(me)
        if held and info.uid in held:
            held.reverse()
            held.remove(info.uid)   # innermost occurrence
            held.reverse()

    # -- lock protocol --------------------------------------------------

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        san = self._san
        me = threading.get_ident()
        bounded = timeout is not None and timeout >= 0
        self_deadlock = None
        with san._reg:
            if self.reentrant and self._info.owner == me:
                self._mark_acquired(me)
                return True
            if (not self.reentrant and self._info.owner == me
                    and blocking and not bounded):
                # one-edge self-cycle: re-acquiring a non-reentrant lock
                # this thread already owns can never succeed — the
                # callback-under-lock shape (static NHD212), caught here
                # before the wait-for walk because the thread never gets
                # to register a want against itself
                self_deadlock = san._record_witness("cycle", {
                    "cycle": [{
                        "thread": str(me),
                        "waits_for":
                            f"{self._info.kind}@{self._info.site}",
                        "owner": str(me),
                    }],
                    "held_by_thread": san._holder_stacks([me]),
                })
            elif self._inner.acquire(False):
                self._mark_acquired(me)
                return True
            elif not blocking:
                return False
            else:
                # contended: a bounded waiter cannot deadlock (it times
                # out), so it never enters the wants map — it still
                # appears as an OWNER of whatever it already holds,
                # which is what other threads' cycles need
                if not bounded:
                    san._wants[me] = self._info.uid
                self._info.n_contended += 1
        if self_deadlock is not None:
            # outside the registry lock: the recorder's lock may itself
            # be instrumented
            san._emit_span(self_deadlock)
            raise DeadlockError(
                "nhdsan: re-entrant acquisition of non-reentrant "
                f"{self._info.kind}@{self._info.site} — the owning "
                "thread is re-acquiring its own lock and would deadlock "
                "itself (use RLock or move the call outside the lock)"
            )
        deadline = time.monotonic() + timeout if bounded else None
        try:
            while True:
                w = None
                if not bounded:
                    with san._reg:
                        cycle = san._detect_cycle(me)
                        if cycle is not None:
                            w = san._record_witness("cycle", {
                                "cycle": [
                                    {
                                        "thread": str(tid),
                                        "waits_for":
                                            f"{san._locks[uid].kind}"
                                            f"@{san._locks[uid].site}",
                                        "owner": str(san._locks[uid].owner),
                                    }
                                    for tid, uid in cycle
                                ],
                                "held_by_thread": san._holder_stacks(
                                    [t for t, _ in cycle]
                                ),
                            })
                if w is not None:
                    # outside the registry lock: the recorder's own lock
                    # may itself be instrumented
                    san._emit_span(w)
                    raise DeadlockError(
                        "nhdsan: wait-for-graph cycle — acquiring "
                        f"{self._info.kind}@{self._info.site} would "
                        f"deadlock: {w['cycle']}"
                    )
                slice_ = san.poll_interval
                if deadline is not None:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        return False
                    slice_ = min(slice_, remaining)
                if self._inner.acquire(True, slice_):
                    with san._reg:
                        self._mark_acquired(me)
                    return True
        finally:
            if not bounded:
                with san._reg:
                    san._wants.pop(me, None)

    def release(self) -> None:
        me = threading.get_ident()
        san = self._san
        with san._reg:
            info = self._info
            if info.owner != me or info.count < 1:
                raise RuntimeError(
                    f"release of un-owned {info.kind}@{info.site}"
                )
            self._mark_released(me)
            if info.count == 0:
                self._inner.release()

    def locked(self) -> bool:
        return self._info.owner is not None

    def __enter__(self) -> bool:
        return self.acquire()

    def __exit__(self, *exc) -> None:
        self.release()

    # Condition protocol (lets cond.wait fully release an RLock)
    def _release_save(self):
        me = threading.get_ident()
        san = self._san
        with san._reg:
            info = self._info
            if info.owner != me:
                raise RuntimeError("cannot wait on un-acquired lock")
            count = info.count
            info.count = 0
            info.owner = None
            held = san._held.get(me)
            if held is not None:
                while info.uid in held:
                    held.remove(info.uid)
            self._inner.release()
        return count

    def _acquire_restore(self, count: int) -> None:
        self.acquire()
        if count > 1:
            me = threading.get_ident()
            with self._san._reg:
                for _ in range(count - 1):
                    self._mark_acquired(me)

    def _is_owned(self) -> bool:
        return self._info.owner == threading.get_ident()

    def _at_fork_reinit(self) -> None:
        # stdlib fork handlers (threading, concurrent.futures) reinit
        # locks in the child: fresh inner lock, ownership cleared — the
        # child has exactly one thread
        self._inner = _ALLOCATE()
        self._info.owner = None
        self._info.count = 0

    def __repr__(self) -> str:
        return (
            f"<SanLock {self._info.kind}@{self._info.site} "
            f"owner={self._info.owner}>"
        )


class _SanCondition(threading.Condition):
    """threading.Condition over an instrumented lock. A subclass (not a
    factory function) so ``threading.Condition`` stays a *type* after
    install() swaps the name — isinstance checks keep working."""

    def __init__(self, lock=None):
        if lock is None:
            san = _GLOBAL
            lock = san.RLock() if san is not None else _ORIG_RLOCK()
        super().__init__(lock)


# ---------------------------------------------------------------------------
# global install / uninstall (NHD_SAN=1 path)
# ---------------------------------------------------------------------------

_GLOBAL: Optional[Sanitizer] = None
_PATCHES: List[Tuple[object, str, object]] = []


def get_sanitizer() -> Optional[Sanitizer]:
    return _GLOBAL


def _patch(obj: object, name: str, new: object) -> None:
    _PATCHES.append((obj, name, getattr(obj, name)))
    setattr(obj, name, new)


def install(san: Optional[Sanitizer] = None) -> Sanitizer:
    """Publish *san* (or a fresh Sanitizer) globally and monkeypatch
    ``threading.Lock/RLock/Condition`` plus the unbounded blocking entry
    points. Locks created *before* install stay raw — deliberate for
    jax / interpreter internals, which is why tests/conftest.py installs
    at conftest IMPORT time (after the jax setup, before pytest
    collection imports nhd_tpu modules): module-level locks such as
    streaming's _CPU_MESH_SOLVE_LOCK are then created under
    instrumentation."""
    global _GLOBAL
    if _GLOBAL is not None:
        return _GLOBAL
    san = san or Sanitizer()
    _GLOBAL = san

    import queue

    _patch(threading, "Lock", san.Lock)
    _patch(threading, "RLock", san.RLock)
    _patch(threading, "Condition", _SanCondition)

    orig_get = queue.Queue.get

    def san_get(self, block=True, timeout=None):
        if block and timeout is None:
            san.note_blocking("queue.Queue.get()")
        return orig_get(self, block, timeout)

    _patch(queue.Queue, "get", san_get)

    orig_join = threading.Thread.join

    def san_join(self, timeout=None):
        if timeout is None:
            san.note_blocking("threading.Thread.join()")
        return orig_join(self, timeout)

    _patch(threading.Thread, "join", san_join)

    orig_wait = threading.Event.wait

    def san_wait(self, timeout=None):
        if timeout is None:
            san.note_blocking("threading.Event.wait()")
        return orig_wait(self, timeout)

    _patch(threading.Event, "wait", san_wait)
    return san


def uninstall() -> Optional[Sanitizer]:
    """Restore every patched name; returns the sanitizer that was active
    (its witnesses stay readable after uninstall)."""
    global _GLOBAL
    for obj, name, orig in reversed(_PATCHES):
        setattr(obj, name, orig)
    _PATCHES.clear()
    san, _GLOBAL = _GLOBAL, None
    return san
