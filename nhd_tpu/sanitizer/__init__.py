"""nhdsan — runtime deadlock sanitizer (see runtime.py for the design)
plus nhdrace, the Eraser-style race layer on top (races.py).

Quick use::

    from nhd_tpu.sanitizer import Sanitizer, DeadlockError

    san = Sanitizer()          # private instance: no global patching
    a, b = san.Lock(), san.Lock()
    # threads interleaving a->b and b->a now raise DeadlockError with a
    # wait-for-graph witness instead of hanging forever

or process-wide (the tests/conftest.py NHD_SAN=1 path)::

    from nhd_tpu.sanitizer import install, uninstall
    san = install()            # patches threading.Lock/RLock/Condition
    ...                        # + queue.get / Thread.join / Event.wait
    san.report()               # cycles, hold-while-blocking, lock stats
    uninstall()

Race layer (the NHD_RACE=1 path)::

    from nhd_tpu.sanitizer import install_races, uninstall_races
    rs = install_races()       # wraps __setattr__ of watched classes
    ...                        # product __init__s call maybe_watch(...)
    rs.report()                # races keyed like the static NHD81x pack
    uninstall_races()
"""

from nhd_tpu.sanitizer.races import (
    RaceSanitizer,
    field_key,
    get_race_sanitizer,
    install_races,
    maybe_watch,
    uninstall_races,
)
from nhd_tpu.sanitizer.runtime import (
    DeadlockError,
    SanLock,
    Sanitizer,
    get_sanitizer,
    install,
    uninstall,
)

__all__ = [
    "DeadlockError",
    "RaceSanitizer",
    "SanLock",
    "Sanitizer",
    "field_key",
    "get_race_sanitizer",
    "get_sanitizer",
    "install",
    "install_races",
    "maybe_watch",
    "uninstall",
    "uninstall_races",
]
