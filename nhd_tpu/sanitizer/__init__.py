"""nhdsan — runtime deadlock sanitizer (see runtime.py for the design).

Quick use::

    from nhd_tpu.sanitizer import Sanitizer, DeadlockError

    san = Sanitizer()          # private instance: no global patching
    a, b = san.Lock(), san.Lock()
    # threads interleaving a->b and b->a now raise DeadlockError with a
    # wait-for-graph witness instead of hanging forever

or process-wide (the tests/conftest.py NHD_SAN=1 path)::

    from nhd_tpu.sanitizer import install, uninstall
    san = install()            # patches threading.Lock/RLock/Condition
    ...                        # + queue.get / Thread.join / Event.wait
    san.report()               # cycles, hold-while-blocking, lock stats
    uninstall()
"""

from nhd_tpu.sanitizer.runtime import (
    DeadlockError,
    SanLock,
    Sanitizer,
    get_sanitizer,
    install,
    uninstall,
)

__all__ = [
    "DeadlockError",
    "SanLock",
    "Sanitizer",
    "get_sanitizer",
    "install",
    "uninstall",
]
