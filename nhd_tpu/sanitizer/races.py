"""nhdrace runtime — Eraser-style dynamic race detection (the dynamic
half of NHD81x; ``nhd_tpu/analysis/ownership.py`` is the static half).

Write-focused lockset intersection on *registered* shared objects:
product classes call :func:`maybe_watch` at the end of ``__init__``
(a no-op unless ``install_races()`` ran), after which every write to a
watched field flows through an instrumented class-level ``__setattr__``.
Per (object, field) the detector keeps the classic Eraser state
machine — *exclusive* while a single thread writes (no refinement: the
init/handoff pattern is legal), then on the first write from a second
thread the candidate lockset becomes the intersection of the previous
writer's held locks and the current holder's, refined on every
subsequent write. An empty candidate set in the shared state is a race
witness: two threads write the field and no common lock orders them.

Witness keys are ``"mod/label:Class.attr"`` — exactly the static pack's
shared-field registry keys (:func:`field_key` is the join), so a runtime
witness names its static finding and vice versa. Held locksets come
from nhdsan's registry (``Sanitizer.held_snapshot``), so the two
sanitizers agree on lock identity (construction site) too.

Reports ride the existing NHD_SAN surfaces: ``report()`` merges into
the conftest report dump, witnesses mirror into the flight recorder /
chrome trace as ``nhdsan.race`` spans.

Knobs (all registered in nhd_tpu/config/knobs.py):

* ``NHD_RACE=1`` — conftest/chaos install the race layer (implies
  nhdsan install: locksets need the instrumented locks).
* ``NHD_RACE_INJECT=1`` — negative control: install_races() runs two
  deliberately unsynchronized incrementing threads on a watched dummy;
  the run must FAIL with a race report, proving the detector fires.
* ``NHD_RACE_ALLOW`` — comma-separated fnmatch globs of field keys to
  allowlist (witness recorded as suppressed, run stays green); the
  dynamic mirror of the static pack's written-justification inline
  suppressions.
"""

from __future__ import annotations

import inspect
import os
import threading
import weakref
from fnmatch import fnmatch
from typing import Dict, List, Optional, Set, Tuple

from nhd_tpu.sanitizer.runtime import Sanitizer, _site, get_sanitizer, install

__all__ = [
    "RaceSanitizer", "field_key", "get_race_sanitizer", "install_races",
    "maybe_watch", "uninstall_races",
]


def field_key(cls: type, attr: str) -> str:
    """The shared-state identity: ``mod/label:Class.attr``, where the
    label is the class's defining file's last two path components — the
    same key the static ownership model derives from the AST."""
    from nhd_tpu.analysis.lockgraph import _mod_label
    try:
        path = inspect.getfile(cls)
    except TypeError:           # builtins / C types
        path = cls.__module__.replace(".", "/") + ".py"
    return f"{_mod_label(path)}:{cls.__name__}.{attr}"


class _FieldState:
    __slots__ = ("owner", "lockset", "shared", "first_site")

    def __init__(self, owner: int, lockset: Tuple, site: str):
        self.owner = owner          # sole writer while exclusive
        self.lockset = lockset      # last held (exclusive) / candidates
        self.shared = False
        self.first_site = site


class RaceSanitizer:
    """One registry of watched objects + per-field Eraser states.
    ``install_races()`` publishes a process-global instance."""

    def __init__(self, san: Sanitizer, *, allow: str = ""):
        self._san = san
        # raw lock (never instrumented): same discipline as runtime.py
        import _thread
        self._reg = _thread.allocate_lock()
        self._watched: Dict[int, Set[str]] = {}     # id(obj) -> fields
        self._keys: Dict[int, Dict[str, str]] = {}  # id(obj) -> attr -> key
        self._states: Dict[Tuple[int, str], _FieldState] = {}
        self._patched: Dict[type, Tuple[object, bool]] = {}
        self._races: List[dict] = []
        self._suppressed: List[dict] = []
        self._reported: Set[str] = set()
        self._allow = tuple(
            g.strip() for g in allow.split(",") if g.strip()
        )

    # -- registration ---------------------------------------------------

    def watch(self, obj: object, fields: Tuple[str, ...]) -> None:
        cls = type(obj)
        oid = id(obj)
        with self._reg:
            if cls not in self._patched:
                self._patch_class(cls)
            self._watched.setdefault(oid, set()).update(fields)
            keys = self._keys.setdefault(oid, {})
            for f in fields:
                keys.setdefault(f, field_key(cls, f))
        try:
            weakref.finalize(obj, self._forget, oid)
        except TypeError:
            pass                # not weakref-able: entry lives on

    def _forget(self, oid: int) -> None:
        with self._reg:
            self._watched.pop(oid, None)
            self._keys.pop(oid, None)
            for k in [k for k in self._states if k[0] == oid]:
                del self._states[k]

    def _patch_class(self, cls: type) -> None:
        """Wrap cls.__setattr__ (registry lock held). The wrapper gates
        on the watched-instance registry, so unwatched instances pay one
        dict lookup and nothing else."""
        had_own = "__setattr__" in cls.__dict__
        orig = cls.__setattr__
        rs = self

        def race_setattr(obj, name, value):
            watched = rs._watched.get(id(obj))
            if watched is not None and name in watched:
                rs._on_write(obj, name)
            orig(obj, name, value)

        race_setattr._nhdrace_wrapped = True    # type: ignore[attr-defined]
        cls.__setattr__ = race_setattr          # type: ignore[assignment]
        self._patched[cls] = (orig, had_own)

    # -- the Eraser state machine --------------------------------------

    def _on_write(self, obj: object, name: str) -> None:
        me = threading.get_ident()
        held = self._san.held_snapshot(me)
        uids = frozenset(u for u, _ in held)
        sites = {u: s for u, s in held}
        oid = id(obj)
        race = None
        with self._reg:
            key = self._keys[oid][name]
            sk = (oid, name)
            st = self._states.get(sk)
            if st is None:
                self._states[sk] = _FieldState(me, uids, "<first>")
                st = self._states[sk]
            elif not st.shared:
                if st.owner == me:
                    st.lockset = uids   # still exclusive: refresh, don't
                    #                     refine (single writer is legal)
                else:
                    st.shared = True    # second writer: candidates start
                    st.lockset = frozenset(st.lockset) & uids
            else:
                st.lockset = frozenset(st.lockset) & uids
            if st.shared and not st.lockset and key not in self._reported:
                self._reported.add(key)
                race = {
                    "key": key,
                    "threads": sorted({str(st.owner), str(me)}),
                    "held_now": sorted(sites.values()),
                    "allowed": any(fnmatch(key, g) for g in self._allow),
                }
        if race is not None:
            at = _site()                # stack walk outside the registry
            race["at"] = at
            with self._san._reg:
                w = self._san._record_witness("race", dict(race))
            self._san._emit_span(w)
            with self._reg:
                (self._suppressed if race["allowed"]
                 else self._races).append(race)

    # -- report ---------------------------------------------------------

    def report(self) -> dict:
        with self._reg:
            return {
                "version": 1,
                "races": list(self._races),
                "suppressed": list(self._suppressed),
                "watched_objects": len(self._watched),
                "watched_fields": sorted(
                    {k for m in self._keys.values() for k in m.values()}
                ),
            }

    # -- teardown -------------------------------------------------------

    def unpatch_all(self) -> None:
        with self._reg:
            for cls, (orig, had_own) in self._patched.items():
                if had_own:
                    cls.__setattr__ = orig      # type: ignore[assignment]
                else:
                    try:
                        del cls.__setattr__
                    except AttributeError:
                        pass
            self._patched.clear()


# ---------------------------------------------------------------------------
# global install / uninstall (NHD_RACE=1 path)
# ---------------------------------------------------------------------------

_GLOBAL: Optional[RaceSanitizer] = None


def get_race_sanitizer() -> Optional[RaceSanitizer]:
    return _GLOBAL


def maybe_watch(obj: object, fields: Tuple[str, ...]) -> None:
    """Product-code hook: register *obj*'s shared fields for dynamic
    race checking. No-op (one global read) unless install_races() ran —
    call it at the END of __init__ so construction writes stay exempt,
    mirroring the static pack's init exemption."""
    rs = _GLOBAL
    if rs is not None:
        rs.watch(obj, fields)


def install_races(san: Optional[Sanitizer] = None,
                  *, allow: Optional[str] = None) -> RaceSanitizer:
    """Publish a global RaceSanitizer (installing nhdsan first if
    needed — locksets come from its instrumented locks). When
    NHD_RACE_INJECT=1, immediately run the injected-race negative
    control so the surrounding harness MUST fail: proof the detector and
    the report plumbing fire end to end."""
    global _GLOBAL
    if _GLOBAL is not None:
        return _GLOBAL
    base = san or get_sanitizer() or install()
    if allow is None:
        allow = os.environ.get("NHD_RACE_ALLOW", "")
    _GLOBAL = RaceSanitizer(base, allow=allow)
    if os.environ.get("NHD_RACE_INJECT", "0") == "1":
        inject_race(_GLOBAL)
    return _GLOBAL


def uninstall_races() -> Optional[RaceSanitizer]:
    """Restore every wrapped __setattr__; returns the sanitizer that was
    active (its report stays readable after uninstall)."""
    global _GLOBAL
    rs, _GLOBAL = _GLOBAL, None
    if rs is not None:
        rs.unpatch_all()
    return rs


# ---------------------------------------------------------------------------
# injected-race negative control
# ---------------------------------------------------------------------------

class _InjectedRace:
    """Two threads increment 'counter' with no common lock: the detector
    must produce a race witness for this, or the control fails."""

    def __init__(self):
        self.counter = 0


def inject_race(rs: Optional[RaceSanitizer] = None,
                rounds: int = 200) -> dict:
    """Run the deliberately racy workload on a watched dummy and return
    the race report. Used by NHD_RACE_INJECT=1 and by the tests."""
    rs = rs or _GLOBAL
    assert rs is not None, "install_races() first"
    dummy = _InjectedRace()
    rs.watch(dummy, ("counter",))
    # both threads must be alive at once: a short-lived thread that
    # exits before the second starts can hand its ident to the second
    # (pthread id reuse) and the two writers would look like one
    gate = threading.Barrier(2)

    def spin():
        gate.wait(timeout=10)
        for _ in range(rounds):
            dummy.counter += 1

    threads = [threading.Thread(target=spin, name=f"nhdrace-inject-{i}")
               for i in range(2)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=30)
    return rs.report()
