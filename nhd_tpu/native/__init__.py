"""ctypes loader for the native assignment core (native/nhd_assign.cc).

Builds the shared library on first import when a compiler is available
(`make native` does the same explicitly) and exposes ``assign_pod``; when
neither a prebuilt .so nor g++ exists, ``LIB`` stays None and callers fall
back to the pure-numpy path — same results, ~10× slower per pod.
Disable outright with NHD_TPU_NATIVE=0.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
from pathlib import Path
from typing import Optional

_SRC = Path(__file__).resolve().parents[2] / "native" / "nhd_assign.cc"
_SO = Path(__file__).resolve().parent / "_libnhd.so"


def _build() -> bool:
    """Compile to a temp file and rename into place — atomic for concurrent
    importers (a half-written .so must never be dlopen'd)."""
    tmp = _SO.with_suffix(f".tmp{os.getpid()}.so")
    try:
        subprocess.run(
            ["g++", "-O2", "-shared", "-fPIC", "-o", str(tmp), str(_SRC)],
            check=True, capture_output=True, timeout=60,
        )
        os.replace(tmp, _SO)
        return True
    except Exception:
        tmp.unlink(missing_ok=True)
        return False


def _load() -> Optional[ctypes.CDLL]:
    if os.environ.get("NHD_TPU_NATIVE") == "0":
        return None
    have_src = _SRC.exists()
    stale = (
        have_src
        and _SO.exists()
        and _SO.stat().st_mtime < _SRC.stat().st_mtime
    )
    if not _SO.exists() or stale:
        # rebuild needs the source; a prebuilt .so without source (wheel
        # install) is used as-is
        if not have_src or not _build():
            if not _SO.exists():
                return None
    try:
        lib = ctypes.CDLL(str(_SO))
    except OSError:
        return None
    # all pointers as c_void_p: callers pass raw integer addresses
    # (arr.ctypes.data + row offset) — far cheaper than building typed
    # ctypes pointers per call
    p = ctypes.c_void_p
    i = ctypes.c_int
    if not hasattr(lib, "nhd_assign_pod") or not hasattr(lib, "nhd_assign_round"):
        return None  # stale/foreign library without our symbols
    lib.nhd_assign_pod.restype = ctypes.c_int
    lib.nhd_assign_pod.argtypes = [
        p, p, i, i,          # core overlay, sockets, P, smt
        p, p, p, i,          # gpu overlay, numa, sw, n_gpus
        i,                   # n_groups
        p, p,                # g_numa, g_nic_sw
        p, p, p, p, p,       # proc, proc_smt, helpers, helper_smt, gpus
        i, i, i, i,          # misc numa/count/smt, pci
        p, p, p,             # out cores/counts/gpus
    ]
    lib.nhd_assign_round.restype = ctypes.c_int
    lib.nhd_assign_round.argtypes = (
        [p, p, p, p, i]          # core_used, socket, phys, smt, L
        + [p, p, p, p, p, i]     # gpu used/numa/sw/sw_dense/n_gpus, GM
        + [p, p, p, p, p, p, i, i]  # nic flat/sw/rx/tx/pods/cap, U, K
        + [p]                    # hp_free (int64)
        + [p, p, p, p, p, p, i, i, i]  # cpu_free, gpu_free, gpu_free_sw,
                                       # nic_free, hp_free32, busy, S,
                                       # set_busy, enable_sharing
        + [i, p, p, p, p, p, p, p, p, p, p, p]  # G + 11 type arrays
        + [i, p, p, p, p]        # W, w_node/type/c/m
        + [p, p, p, p, p, p, i, i]  # out status/cores/counts/nic/gpus/pick,
                                    # MAXC, GMX
    )
    return lib


LIB = _load()


def available() -> bool:
    return LIB is not None
