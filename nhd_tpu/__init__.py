"""nhd_tpu — a TPU-native topology-aware scheduling framework.

A brand-new framework with the capabilities of Viasat/NHD (a custom
Kubernetes scheduler for NUMA/PCIe/SMT/NIC-bandwidth/hugepage-aware pod
placement; see /root/reference), re-designed so that the inner
filter→score→bind loop is a batched constraint-satisfaction solve on TPU
via JAX/XLA: all pending pods × all candidate nodes are evaluated at once
as dense boolean masks over topology tensors, with node selection as a
masked-argmax reduction and gang batches resolved in greedy rounds.

Package layout:
  core/      hardware + workload data model (host-side source of truth)
  config/    libconfig parsing and the Triad config round-trip (plugin seam)
  solver/    the matcher: serial oracle + batched JAX solver + sharding
  k8s/       cluster backend interface (fake in-memory + real kube client)
  scheduler/ reconciliation event loop, claim/release, bind orchestration
  rpc/       gRPC stats/introspection plane
  utils/     logging and misc helpers
"""

__version__ = "0.1.0"

NHD_SCHED_NAME = "nhd-scheduler"
