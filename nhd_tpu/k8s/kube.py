"""Real Kubernetes backend: the reference K8SMgr surface on kubernetes-client.

Method-for-method port of the reference's API-server interactions
(K8SMgr.py), behind the ClusterBackend seam. Import is gated: the
kubernetes package is only required when this backend is actually
constructed, so hermetic environments (tests, benchmarks, this dev image)
never need it.

The watch plane differs from the reference by design: instead of kopf's
asyncio operators (TriadController.py:161-171), watches run in daemon
threads that translate raw API events into WatchEvent records drained by
the controller — same information, no framework dependency.
"""

from __future__ import annotations

import os
import queue
import threading
from typing import Dict, Iterable, List, Optional, Tuple

from nhd_tpu.k8s.interface import (
    CFG_ANNOTATION,
    CFG_TYPE_ANNOTATION,
    GPU_MAP_ANNOTATION_PREFIX,
    GROUPS_ANNOTATION,
    LEASE_NAME,
    NAD_ANNOTATION,
    SCHEDULER_TAINT,
    SPILLOVER_ANNOTATION,
    ClusterBackend,
    LeaseView,
    StaleLeaseError,
    TransientBackendError,
    WatchEvent,
    parse_spill_record,
    render_spill_record,
)
from nhd_tpu.k8s.retry import API_COUNTERS, RetryPolicy, RetryingApi, retryable
from nhd_tpu.sanitizer.races import maybe_watch
from nhd_tpu.utils import get_logger

# Periodic full-relist resync cadence (seconds; 0 disables). A dropped
# watch event — queue overflow, proxy hiccup, the etcd compaction window —
# would otherwise leave the backend stale FOREVER; the resync diffs a full
# list against watch-derived state and emits synthetic events for anything
# missed (docs/RESILIENCE.md).
_RESYNC_DEFAULT_SEC = float(os.environ.get("NHD_RESYNC_SEC", "300"))

# last-seen pod snapshot: (uid, annotations, scheduler_name, node,
# created) — what a synthetic delete event must carry after the object is
# gone, plus the creationTimestamp (epoch seconds or None) so the SLO
# engine's per-bind get_pod_created is a dict lookup, not a pod GET
_PodSnap = Tuple[str, Dict[str, str], str, str, Optional[float]]

# namespace holding the election Lease object (the scheduler Deployment's
# own namespace in the 2-replica recipe, docs/OPERATIONS.md)
_LEASE_NS_DEFAULT = os.environ.get("NHD_LEASE_NS", "default")

# fence-check cache window (seconds; 0 = a fresh Lease GET per fenced
# write). A pod commit runs up to 4 fenced mutators — without the cache
# that is 4 serial Lease GETs per pod on the hot bind path. Caching only
# delays noticing a NEWER epoch by at most this window, which is the same
# order as the check-then-write race the kube fence already has (the
# atomic rejection lives in the fake backend / chaos harness); keep it
# well under the lease TTL.
_FENCE_CACHE_SEC = float(os.environ.get("NHD_FENCE_CACHE_SEC", "1.0"))

# K8s MicroTime wire format (Lease spec.acquireTime/renewTime)
_MICRO_TIME_FMT = "%Y-%m-%dT%H:%M:%S.%fZ"


def _micro_time(ts: float) -> str:
    import datetime

    return datetime.datetime.fromtimestamp(
        ts, datetime.timezone.utc
    ).strftime(_MICRO_TIME_FMT)


def _parse_micro_time(raw: Optional[str]) -> Optional[float]:
    if not raw:
        return None
    import datetime

    for fmt in (_MICRO_TIME_FMT, "%Y-%m-%dT%H:%M:%SZ"):
        try:
            return datetime.datetime.strptime(raw, fmt).replace(
                tzinfo=datetime.timezone.utc
            ).timestamp()
        except ValueError:
            continue
    return None


class KubeClusterBackend(ClusterBackend):
    """kubernetes-client implementation (reference: K8SMgr.py)."""

    #: real API round trips per commit: overlap them with the next
    #: batch's admission+solve by default (scheduler/commitpipe.py;
    #: NHD_ASYNC_COMMIT=0 restores the strictly synchronous path)
    ASYNC_COMMIT_DEFAULT = True

    def __init__(
        self,
        start_watches: bool = True,
        *,
        retry_policy: Optional[RetryPolicy] = None,
        resync_interval: Optional[float] = None,
    ):
        using_restclient = False
        try:
            import kubernetes  # noqa: F401
            from kubernetes import client, config, watch
        except ImportError:
            # no kubernetes package: fall back to the in-repo REST client
            # (nhd_tpu/k8s/restclient.py — same surface over real HTTP, the
            # way config/libconfig.py replaces libconf)
            from nhd_tpu.k8s import restclient

            client = restclient.client
            config = restclient.config
            watch = restclient.watch
            using_restclient = True

        self.logger = get_logger(__name__)
        self._client = client
        self._watch_mod = watch
        try:
            config.load_incluster_config()
        except Exception:
            # outside a pod: fall back to kubeconfig (K8SMgr.py:43-46)
            try:
                config.load_kube_config()
            except Exception as exc:
                raise RuntimeError(
                    "no cluster configuration found (neither in-cluster "
                    "env nor a kubeconfig); KubeClusterBackend needs an "
                    "API server to talk to — use FakeClusterBackend for "
                    f"hermetic runs ({exc})"
                ) from exc
        # every non-watch call runs under the retry policy (transient
        # 429/5xx/network faults never surface to the scheduler); watch
        # establishment passes through — the reconnect loop below owns it
        self._retry = retry_policy or RetryPolicy(
            exc_class=client.exceptions.ApiException
        )
        self.v1 = RetryingApi(client.CoreV1Api(), self._retry)
        self.crd = RetryingApi(client.CustomObjectsApi(), self._retry)
        self._events: "queue.Queue[WatchEvent]" = queue.Queue()
        # pause between watch reconnects (the API server ends streams
        # routinely; an immediate retry loop would hammer it)
        self._watch_backoff = 1.0
        self._watch_stop = threading.Event()
        # registered Watch objects, for stop; appended by the watch
        # threads while stop_watches may iterate from another thread, so
        # all access goes through _watch_lock (nhdlint NHD201)
        self._watch_lock = threading.Lock()
        self._watchers: List[object] = []
        # watch-derived state, diffed by resync(); written by the watch
        # threads and read by the resync thread → _state_lock. The touch
        # sequence orders watch updates against resync's relist: anything
        # the watch touched AFTER the relist began is fresher than the
        # listing, and resync must not "repair" it with stale data
        self._state_lock = threading.Lock()
        self._known_pods: Dict[Tuple[str, str], _PodSnap] = {}
        self._node_last: Dict[str, tuple] = {}
        self._watch_seq = 0
        self._pod_touched: Dict[Tuple[str, str], int] = {}
        self._node_touched: Dict[str, int] = {}
        # sequence point of the relist currently in flight (None when
        # none is): delete tombstones older than this are prunable
        self._relist_floor: Optional[int] = None
        self._resync_interval = (
            _RESYNC_DEFAULT_SEC if resync_interval is None else resync_interval
        )
        # HA lease plumbing (k8s/lease.py): the namespace the election
        # Lease lives in, and the DEFAULT lease fenced writes are checked
        # against when the caller names none (shard leases arrive per
        # call via the fence_lease kwarg)
        self._lease_ns = _LEASE_NS_DEFAULT
        self.fence_lease_name = LEASE_NAME
        # fence-check cache, per lease name: (valid-until monotonic
        # stamp, LeaseView or None); written by commit threads under
        # _fence_lock. Only _check_fence reads through it — the election
        # itself (lease_renew/lease_try_acquire) always goes to the
        # server. _lease_epoch_hwm is the per-lease epoch high-water
        # mark: EVERY lease state this process observes (acquire, renew,
        # read) advances it, so a rival acquisition seen through any
        # lease operation fences stale writes immediately — ahead of the
        # cache window (tests/test_kube_faults.py pins this).
        self._fence_lock = threading.Lock()
        self._fence_cached: Dict[str, Tuple[float, Optional[LeaseView]]] = {}
        self._lease_epoch_hwm: Dict[str, int] = {}
        # dead-socket defense on the watch plane: the restclient bakes a
        # finite read timeout into stream requests itself; the real
        # kubernetes client needs it passed per stream() call. Gated on
        # the Watch.stream signature accepting **kwargs so stub Watch
        # implementations (tests) keep working unchanged.
        self._watch_kwargs: Dict[str, object] = {}
        if not using_restclient:
            import inspect

            try:
                params = inspect.signature(watch.Watch.stream).parameters
                accepts_kw = any(
                    p.kind is inspect.Parameter.VAR_KEYWORD
                    for p in params.values()
                )
            except (TypeError, ValueError):
                accepts_kw = False
            if accepts_kw:
                # one parse site for the timeout (restclient owns it) so
                # the two client paths can never drift apart
                from nhd_tpu.k8s.restclient import _WATCH_READ_TIMEOUT

                self._watch_kwargs = {
                    "_request_timeout": (30.0, _WATCH_READ_TIMEOUT)
                }
        # dynamic race layer (NHD_RACE=1): the watch/resync sequence
        # fields are written by three watcher threads, always under
        # _state_lock — registered before the watchers spawn
        maybe_watch(self, ("_watch_seq", "_relist_floor"))
        if start_watches:
            self._start_watches()

    # ------------------------------------------------------------------
    # node reads
    # ------------------------------------------------------------------

    def get_nodes(self) -> List[str]:
        """KubeletReady nodes (K8SMgr.py:55-69)."""
        out = []
        for item in self.v1.list_node().items:
            for cond in item.status.conditions or []:
                if cond.reason == "KubeletReady" and cond.status == "True":
                    out.append(item.metadata.name)
        return out

    def is_node_active(self, node: str) -> bool:
        """Scheduler taint present and node not cordoned (K8SMgr.py:167-192)."""
        obj = self.v1.read_node(node)
        has_taint = any(
            t.key == SCHEDULER_TAINT and t.effect == "NoSchedule"
            for t in (obj.spec.taints or [])
        )
        return has_taint and not bool(obj.spec.unschedulable)

    def get_node_labels(self, node: str) -> Dict[str, str]:
        return dict(self.v1.read_node(node).metadata.labels or {})

    def get_node_addr(self, node: str) -> str:
        """First InternalIP (K8SMgr.py:91-106)."""
        for addr in self.v1.read_node(node).status.addresses or []:
            if addr.type == "InternalIP":
                return addr.address
        return ""

    def get_node_hugepage_resources(self, node: str) -> Tuple[int, int]:
        """1Gi hugepage capacity/allocatable in GiB (K8SMgr.py:71-89)."""
        obj = self.v1.read_node(node)

        def gi(res: Optional[dict]) -> int:
            if not res:
                return 0
            val = res.get("hugepages-1Gi", "0")
            return int(str(val).rstrip("Gi")) if "Gi" in str(val) else int(val)

        return (gi(obj.status.capacity), gi(obj.status.allocatable))

    # ------------------------------------------------------------------
    # pod reads
    # ------------------------------------------------------------------

    def _read_pod(self, pod: str, ns: str):
        try:
            return self.v1.read_namespaced_pod(pod, ns)
        except self._client.exceptions.ApiException as exc:
            if retryable(exc):
                # retry budget spent / circuit open: 'unavailable' must
                # not masquerade as 'pod does not exist' — that would
                # mass-fail healthy pods with FailedCfgParse during an
                # outage. Callers' loop isolation owns the recovery.
                raise TransientBackendError(
                    f"read of {ns}/{pod} failed transiently: {exc}"
                ) from exc
            return None

    def pod_exists(self, pod: str, ns: str) -> bool:
        return self._read_pod(pod, ns) is not None

    def get_pod_node(self, pod: str, ns: str) -> Optional[str]:
        obj = self._read_pod(pod, ns)
        return obj.spec.node_name if obj else None

    def get_pod_annotations(self, pod: str, ns: str) -> Optional[Dict[str, str]]:
        obj = self._read_pod(pod, ns)
        return dict(obj.metadata.annotations or {}) if obj else None

    def get_pod_annotations_cached(
        self, pod: str, ns: str
    ) -> Optional[Dict[str, str]]:
        """Watch-level freshness from the _PodSnap mirror — the
        trace-corr adoption read per pod per batch stays a dict lookup
        instead of a pod GET; live read only for pods the watch has not
        delivered."""
        with self._state_lock:
            snap = self._known_pods.get((ns, pod))
        if snap is not None:
            return dict(snap[1])
        return self.get_pod_annotations(pod, ns)

    def get_pod_created(self, pod: str, ns: str) -> Optional[float]:
        """metadata.creationTimestamp as epoch seconds (the wall-clock
        domain clock_now reports in) — the SLO time-to-bind origin,
        owned by the API server so it survives spills and restarts.
        Served from the watch-derived snapshot (creationTimestamp is
        immutable, and _known_pods tracks delete/re-create) so the
        per-bind SLO observation costs a dict lookup, not a pod GET;
        the GET is only the cold-start fallback for pods the watch has
        not delivered."""
        with self._state_lock:
            snap = self._known_pods.get((ns, pod))
        if snap is not None and snap[4] is not None:
            return snap[4]
        return self._created_ts(self._read_pod(pod, ns))

    def get_cfg_annotations(self, pod: str, ns: str) -> Optional[str]:
        annots = self.get_pod_annotations(pod, ns)
        return annots.get(CFG_ANNOTATION) if annots else None

    def get_cfg_type(self, pod: str, ns: str) -> Optional[str]:
        annots = self.get_pod_annotations(pod, ns)
        return annots.get(CFG_TYPE_ANNOTATION) if annots else None

    def get_pod_node_groups(self, pod: str, ns: str) -> List[str]:
        annots = self.get_pod_annotations(pod, ns) or {}
        if GROUPS_ANNOTATION in annots:
            return annots[GROUPS_ANNOTATION].split(".")
        return ["default"]

    def get_requested_pod_resources(self, pod: str, ns: str) -> Dict[str, str]:
        """First container only, like the reference (K8SMgr.py:215-225)."""
        obj = self._read_pod(pod, ns)
        if not obj or not obj.spec.containers:
            return {}
        res = obj.spec.containers[0].resources
        return dict(res.requests or {}) if res else {}

    def get_scheduled_pods(self, scheduler: str) -> List[Tuple[str, str, str, str]]:
        out = []
        for p in self.v1.list_pod_for_all_namespaces().items:
            if p.spec.scheduler_name == scheduler and p.spec.node_name:
                out.append(
                    (p.metadata.name, p.metadata.namespace, p.metadata.uid,
                     p.status.phase)
                )
        return out

    def service_pods(self, scheduler: str):
        out = {}
        for p in self.v1.list_pod_for_all_namespaces().items:
            if p.spec.scheduler_name == scheduler:
                key = (p.metadata.namespace, p.metadata.name, p.metadata.uid)
                out[key] = (p.status.phase, p.spec.node_name)
        return out

    def get_cfg_map(self, pod: str, ns: str) -> Tuple[Optional[str], Optional[str]]:
        """Find the pod's ConfigMap volume, return its first file
        (K8SMgr.py:328-356)."""
        obj = self._read_pod(pod, ns)
        if obj is None:
            return (None, None)
        for vol in obj.spec.volumes or []:
            if vol.config_map is None:
                continue
            try:
                cm = self.v1.read_namespaced_config_map(vol.config_map.name, ns)
            except self._client.exceptions.ApiException as exc:
                if retryable(exc):
                    raise TransientBackendError(
                        f"configmap {ns}/{vol.config_map.name} read failed "
                        f"transiently: {exc}"
                    ) from exc
                # a pod can reference a ConfigMap that doesn't exist (yet);
                # that fails the pod (FailedCfgParse), never the scheduler
                self.logger.error(
                    f"configmap {ns}/{vol.config_map.name} unreadable: {exc}"
                )
                continue
            if cm.data:
                return (vol.config_map.name, next(iter(cm.data.values())))
        return (None, None)

    # ------------------------------------------------------------------
    # writes
    # ------------------------------------------------------------------

    def _patch_annotation(self, pod: str, ns: str, annots: Dict[str, str]) -> bool:
        try:
            self.v1.patch_namespaced_pod(
                pod, ns, {"metadata": {"annotations": annots}}
            )
            return True
        except self._client.exceptions.ApiException as exc:
            if retryable(exc):
                # retry budget already spent inside the policy: surface as
                # transient so the scheduler requeues instead of failing
                # the pod (scheduler/core.py commit path)
                raise TransientBackendError(
                    f"annotation patch for {ns}/{pod} failed transiently: {exc}"
                ) from exc
            self.logger.error(f"annotation patch failed for {ns}/{pod}: {exc}")
            return False

    def _note_lease_epoch(self, name: str, view: Optional[LeaseView]) -> None:
        """Advance the per-lease epoch high-water mark with an observed
        lease state. Called from every lease-reading path, so any rival
        acquisition this process sees — its own elector's CAS loss, a
        federation peer's shard acquisition through the same backend, a
        fence-check read — immediately fences writes stamped with older
        epochs, without waiting out the fence cache window."""
        if view is None:
            return
        with self._fence_lock:
            if view.epoch > self._lease_epoch_hwm.get(name, 0):
                self._lease_epoch_hwm[name] = view.epoch

    def _check_fence(
        self, epoch: Optional[int], lease_name: Optional[str] = None
    ) -> None:
        """Reject a fenced write whose epoch a newer lease acquisition has
        overtaken. Kubernetes has no conditional bind, so unlike the fake
        backend this is check-then-write, not atomic — the check (a Lease
        GET under the retry policy, cached per lease for
        NHD_FENCE_CACHE_SEC so a pod commit's fenced mutators don't pay
        serial round trips) narrows the deposed-leader window to one
        round trip plus the cache window, and the epoch high-water mark
        (_note_lease_epoch) closes the cache window entirely for any
        rival leadership this process has already observed; the atomic
        form of the rejection is what the split-brain chaos harness
        proves against the fake (docs/RESILIENCE.md)."""
        if epoch is None:
            return
        name = lease_name or self.fence_lease_name
        import time as _time

        now = _time.monotonic()
        with self._fence_lock:
            hwm = self._lease_epoch_hwm.get(name, 0)
        if epoch < hwm:
            API_COUNTERS.inc("ha_stale_writes_rejected_total")
            raise StaleLeaseError(
                f"write fenced off: epoch {epoch} is stale (epoch {hwm} "
                f"already observed for lease {name!r})"
            )
        view = None
        fresh = False
        if _FENCE_CACHE_SEC > 0:
            with self._fence_lock:
                cached = self._fence_cached.get(name)
            if cached is not None and now < cached[0]:
                view, fresh = cached[1], True
        if not fresh:
            view = self.lease_read(name)
            with self._fence_lock:
                self._fence_cached[name] = (now + _FENCE_CACHE_SEC, view)
        if view is not None and epoch < view.epoch:
            API_COUNTERS.inc("ha_stale_writes_rejected_total")
            raise StaleLeaseError(
                f"write fenced off: epoch {epoch} is stale (current lease "
                f"epoch {view.epoch}, holder {view.holder!r})"
            )

    def add_nad_to_pod(
        self, pod: str, ns: str, nad: str, *,
        epoch: Optional[int] = None, fence_lease: Optional[str] = None,
    ) -> bool:
        self._check_fence(epoch, fence_lease)
        return self._patch_annotation(pod, ns, {NAD_ANNOTATION: nad})

    def annotate_pod_config(
        self, ns: str, pod: str, cfg: str, *,
        epoch: Optional[int] = None, fence_lease: Optional[str] = None,
    ) -> bool:
        self._check_fence(epoch, fence_lease)
        return self._patch_annotation(pod, ns, {CFG_ANNOTATION: cfg})

    def annotate_pod_gpu_map(
        self, ns: str, pod: str, gpu_map: Dict[str, int],
        *, epoch: Optional[int] = None, fence_lease: Optional[str] = None,
    ) -> bool:
        self._check_fence(epoch, fence_lease)
        return self._patch_annotation(
            pod, ns,
            {f"{GPU_MAP_ANNOTATION_PREFIX}.{d}": str(i) for d, i in gpu_map.items()},
        )

    def annotate_pod_meta(
        self, ns: str, pod: str, key: str, value: str,
        *, epoch: Optional[int] = None, fence_lease: Optional[str] = None,
    ) -> bool:
        self._check_fence(epoch, fence_lease)
        return self._patch_annotation(pod, ns, {key: value})

    def claim_spillover_pod(
        self, ns: str, pod: str, claim_lease: str, claim_epoch: int,
        *, epoch: Optional[int] = None, fence_lease: Optional[str] = None,
    ) -> bool:
        """Check-then-write like every kube fence (no conditional patch
        on the annotation surface): read the spillover record, honor a
        live foreign claim, else write ours. The window is one RTT — the
        atomic form is what the fake backend provides the chaos proofs."""
        self._check_fence(epoch, fence_lease)
        annots = self.get_pod_annotations(pod, ns)
        if annots is None:
            return False
        rec = parse_spill_record(annots.get(SPILLOVER_ANNOTATION))
        cur = rec.get("claim")
        if cur is not None and cur != (claim_lease, claim_epoch):
            view = self.lease_read(cur[0])
            import time as _time

            if (
                view is not None and view.holder
                and view.expires > _time.time()
                and view.epoch == cur[1]
            ):
                return False  # live foreign claim
        rec["claim"] = (claim_lease, claim_epoch)
        return self._patch_annotation(
            pod, ns, {SPILLOVER_ANNOTATION: render_spill_record(rec)}
        )

    def bind_pod_to_node(
        self, pod: str, node: str, ns: str, *,
        epoch: Optional[int] = None, fence_lease: Optional[str] = None,
    ) -> bool:
        """V1Binding; the known kubernetes-client ValueError on the empty
        response is swallowed like the reference does (K8SMgr.py:487-491)."""
        self._check_fence(epoch, fence_lease)
        client = self._client
        body = client.V1Binding(
            metadata=client.V1ObjectMeta(name=pod),
            target=client.V1ObjectReference(
                api_version="v1", kind="Node", name=node, namespace=ns
            ),
        )
        try:
            self.v1.create_namespaced_pod_binding(pod, ns, body)
        except ValueError:
            pass  # client chokes on the empty 201 body; bind succeeded
        except client.exceptions.ApiException as exc:
            if retryable(exc):
                # the policy's retries are exhausted but the failure is a
                # server-health problem, not a verdict on this bind —
                # requeue the pod rather than failing it (docs/RESILIENCE.md)
                raise TransientBackendError(
                    f"bind for {ns}/{pod} -> {node} failed transiently: {exc}"
                ) from exc
            self.logger.error(f"bind failed for {ns}/{pod} -> {node}: {exc}")
            return False
        return True

    def evict_pod(
        self, pod: str, ns: str, *,
        epoch: Optional[int] = None, fence_lease: Optional[str] = None,
    ) -> bool:
        """Preemption eviction via the Eviction subresource (the API
        server honors PodDisruptionBudgets, which is exactly the extra
        guard an operator wants under policy preemption). Fenced like
        bind; a transient server fault surfaces as
        TransientBackendError so the scheduler's preemption attempt
        aborts cleanly (unevicted victims keep their bindings, the
        preemptor requeues).

        Semantics note (docs/SCHEDULING_POLICIES.md): Kubernetes has no
        unbind — Eviction DELETES the pod, and its owning controller
        (TriadSet) recreates it as a NEW incarnation with a fresh uid.
        The scheduler's same-incarnation victim requeue is therefore a
        best-effort fast path here: the deleted pod fails its
        pod_exists gate at re-admission and the replacement schedules
        through the normal create path instead. The fake backend's
        unbind-to-Pending (same uid, one corr journey) is the
        SIMULATION model the chaos invariants run against."""
        self._check_fence(epoch, fence_lease)
        client = self._client
        body = client.V1Eviction(
            metadata=client.V1ObjectMeta(name=pod, namespace=ns),
        )
        try:
            self.v1.create_namespaced_pod_eviction(pod, ns, body)
        except ValueError:
            pass  # empty-body client quirk, same as bind: evict succeeded
        except client.exceptions.ApiException as exc:
            if retryable(exc):
                raise TransientBackendError(
                    f"evict of {ns}/{pod} failed transiently: {exc}"
                ) from exc
            self.logger.error(f"evict failed for {ns}/{pod}: {exc}")
            return False
        return True

    def generate_pod_event(self, pod, ns, reason, event_type, message) -> None:
        """'NHD:'-prefixed V1Event on the pod (K8SMgr.py:518-559)."""
        import datetime

        client = self._client
        obj = self._read_pod(pod, ns)
        if obj is None:
            return
        now = datetime.datetime.now(datetime.timezone.utc)
        body = client.CoreV1Event(
            metadata=client.V1ObjectMeta(generate_name=f"{pod}.nhd."),
            involved_object=client.V1ObjectReference(
                api_version="v1", kind="Pod", name=pod, namespace=ns,
                uid=obj.metadata.uid,
            ),
            reason=reason, message=f"NHD: {message}",
            type=event_type.value, count=1,
            first_timestamp=now, last_timestamp=now,
            source=client.V1EventSource(component="nhd-scheduler"),
        )
        try:
            self.v1.create_namespaced_event(ns, body)
        except client.exceptions.ApiException as exc:
            self.logger.error(f"event post failed for {ns}/{pod}: {exc}")

    # ------------------------------------------------------------------
    # watch plane
    # ------------------------------------------------------------------

    def _start_watches(self) -> None:
        self._seed_known_state()
        threading.Thread(target=self._watch_pods, daemon=True).start()
        threading.Thread(target=self._watch_nodes, daemon=True).start()
        if self._resync_interval > 0:
            threading.Thread(target=self._resync_loop, daemon=True).start()

    def _seed_known_state(self) -> None:
        """Baseline _known_pods/_node_last from a relist before the
        watches start. A watch established without a resourceVersion does
        NOT replay existing objects, so without this every pre-existing
        pod's first MODIFIED would look like a missed create (one
        synthetic pod_create + warning per pod, cluster-wide, on every
        process start). Consumers don't need those events at startup —
        the scheduler replays deployed state from the cluster itself
        (load_deployed_configs / check_pending_pods)."""
        try:
            with self._state_lock:
                for p in self.v1.list_pod_for_all_namespaces().items:
                    key = (p.metadata.namespace, p.metadata.name)
                    self._known_pods[key] = self._pod_snap(p)
                for n in self.v1.list_node().items:
                    self._node_last[n.metadata.name] = self._node_snap(n)
        except Exception as exc:
            # seeding is an optimization, not a correctness requirement:
            # the watch threads and resync cope with an empty baseline
            self.logger.warning(f"initial state seed failed: {exc}")

    def _register_watcher(self, w: object) -> None:
        with self._watch_lock:
            self._watchers.append(w)
            stopping = self._watch_stop.is_set()
        if stopping:
            # stop_watches already swept the list; a watcher registering
            # after its snapshot would never be stopped (leaked stream) —
            # stop it here instead of racing the sweep
            self._stop_watcher(w)

    @staticmethod
    def _created_ts(obj) -> Optional[float]:
        ts = getattr(obj.metadata, "creation_timestamp", None) if obj else None
        if ts is None:
            return None
        try:
            return ts.timestamp()
        except (AttributeError, ValueError):
            return None

    @staticmethod
    def _pod_snap(obj) -> _PodSnap:
        return (
            obj.metadata.uid,
            dict(obj.metadata.annotations or {}),
            obj.spec.scheduler_name or "",
            obj.spec.node_name or "",
            KubeClusterBackend._created_ts(obj),
        )

    def _note_pod(self, ev_type: str, obj) -> Optional[WatchEvent]:
        """Update watch-derived pod state; return the event to emit (or
        None when the event is state-only).

        After a 410 Gone the fresh full-replay watch re-delivers ADDED for
        every live object — an already-known (ns, name, uid) upserts the
        snapshot quietly instead of double-emitting pod_create (the
        regression test pins this, tests/test_kube_faults.py). MODIFIED
        events for a *known* pod are state-only: the snapshot stays fresh
        so a later delete event carries current annotations/node, but
        nothing is emitted (same information policy as before). A MODIFIED
        for an UNKNOWN pod means its create event was lost — emit the
        pod_create now; recording it silently would mark the pod 'known'
        and stop resync from ever repairing the miss."""
        if ev_type not in ("ADDED", "MODIFIED", "DELETED"):
            # BOOKMARK/ERROR/unknown: the object isn't a Pod (an in-band
            # ERROR carries a Status) — never reach into it
            return None
        key = (obj.metadata.namespace, obj.metadata.name)
        snap = self._pod_snap(obj)
        with self._state_lock:
            self._watch_seq += 1
            if ev_type == "DELETED":
                self._known_pods.pop(key, None)
                self._pod_touched[key] = self._watch_seq
                # opportunistic tombstone prune: delete entries only guard
                # in-flight relists, so anything older than the active
                # relist floor (or everything, when no relist runs — e.g.
                # resync disabled) is dead weight on a churny cluster
                if len(self._pod_touched) > 2 * len(self._known_pods) + 256:
                    floor = self._relist_floor
                    for k in list(self._pod_touched):
                        if k not in self._known_pods and (
                            floor is None or self._pod_touched[k] < floor
                        ):
                            del self._pod_touched[k]
            else:
                prior = self._known_pods.get(key)
                self._known_pods[key] = snap
                self._pod_touched[key] = self._watch_seq
                if ev_type == "ADDED" and prior is not None and prior[0] == snap[0]:
                    API_COUNTERS.inc("watch_dedup_replays_total")
                    return None
        if ev_type == "DELETED":
            kind = "pod_delete"
        elif ev_type == "ADDED":
            kind = "pod_create"
        elif ev_type == "MODIFIED" and prior is None:
            # first sight of this pod: the ADDED was missed upstream
            self.logger.warning(
                f"MODIFIED for unknown pod {key[0]}/{key[1]}; emitting "
                "the missed pod_create"
            )
            kind = "pod_create"
        else:
            return None
        return WatchEvent(
            kind=kind, name=key[1], namespace=key[0],
            annotations=dict(snap[1]), uid=snap[0],
            scheduler_name=snap[2], node=snap[3],
        )

    def _note_watch_exc(self, plane: str, exc: Exception) -> None:
        """Log a watch-stream failure at the right volume. On the real
        kubernetes client the finite read timeout surfaces HERE as an
        exception every quiet 60s (the restclient translates it to a
        silent stream end internally) — that expected recycling must not
        produce an ERROR line per minute on a healthy idle cluster."""
        name = type(exc).__name__
        if isinstance(exc, OSError) or "Timeout" in name:
            API_COUNTERS.inc("watch_read_timeouts_total")
            self.logger.info(f"{plane} watch stream ended ({name}); reconnecting")
        else:
            self.logger.error(f"{plane} watch restarted: {exc}")

    def _watch_error(self, w: object, ev: dict) -> bool:
        """Handle an in-band ERROR watch event (expired resourceVersion
        delivered as a Status object instead of an HTTP 410). Clears the
        tracked resourceVersion so the reconnect starts a fresh watch —
        without this, every reconnect replays the same stale RV and the
        watch degenerates into a permanent error loop."""
        if ev.get("type") != "ERROR":
            return False
        if getattr(w, "resource_version", None) is not None:
            w.resource_version = None
        self.logger.warning(
            "in-band watch ERROR (expired resourceVersion?); "
            "reconnecting with a fresh watch"
        )
        return True

    def _watch_pods(self) -> None:
        w = self._watch_mod.Watch()
        self._register_watcher(w)
        first = True
        while not self._watch_stop.is_set():
            if not first:
                API_COUNTERS.inc("watch_reconnects_total")
            first = False
            try:
                for ev in w.stream(
                    self.v1.list_pod_for_all_namespaces, **self._watch_kwargs
                ):
                    if self._watch_error(w, ev):
                        break  # in-band expiry: reconnect fresh
                    out = self._note_pod(ev["type"], ev["object"])
                    if out is not None:
                        self._events.put(out)
            except Exception as exc:
                self._note_watch_exc("pod", exc)
            # the server ends watch streams routinely; reconnect after a
            # pause rather than spinning
            self._watch_stop.wait(self._watch_backoff)

    @staticmethod
    def _node_snap(obj) -> tuple:
        return (
            dict(obj.metadata.labels or {}),
            bool(obj.spec.unschedulable),
            [t.key for t in (obj.spec.taints or [])],
        )

    def _note_node(
        self, obj, *, emit_unchanged: bool = True,
        if_untouched_since: Optional[int] = None,
    ) -> Optional[WatchEvent]:
        """Update watch-derived node state; return the node_update event.

        With ``emit_unchanged=False`` (resync path) an unchanged node
        produces no event — the controller's handlers are diff-driven, so
        replaying identical state would only churn the queue.
        ``if_untouched_since`` makes the freshness check and the state
        write one atomic step: a node the watch touched after that
        sequence point is left alone entirely (writing the stale relist
        snapshot would revert a cordon the watch just delivered)."""
        name = obj.metadata.name
        cur = self._node_snap(obj)
        with self._state_lock:
            if (if_untouched_since is not None
                    and self._node_touched.get(name, 0) > if_untouched_since):
                return None  # the watch already knows better
            old = self._node_last.get(name)
            self._node_last[name] = cur
            if emit_unchanged:  # watch path: mark fresher than any relist
                self._watch_seq += 1
                self._node_touched[name] = self._watch_seq
        if old is None:
            old = cur
        if not emit_unchanged and old == cur:
            return None
        return WatchEvent(
            kind="node_update", name=name, labels=dict(cur[0]),
            old_labels=dict(old[0]), unschedulable=cur[1],
            was_unschedulable=old[1], taints=list(cur[2]),
            old_taints=list(old[2]),
        )

    def _watch_nodes(self) -> None:
        w = self._watch_mod.Watch()
        self._register_watcher(w)
        first = True
        while not self._watch_stop.is_set():
            if not first:
                API_COUNTERS.inc("watch_reconnects_total")
            first = False
            try:
                for ev in w.stream(self.v1.list_node, **self._watch_kwargs):
                    if self._watch_error(w, ev):
                        break  # in-band expiry: reconnect fresh
                    if ev["type"] not in ("ADDED", "MODIFIED", "DELETED"):
                        continue  # BOOKMARK etc.: not a Node object
                    out = self._note_node(ev["object"])
                    if out is not None:
                        self._events.put(out)
            except Exception as exc:
                self._note_watch_exc("node", exc)
            self._watch_stop.wait(self._watch_backoff)

    # ------------------------------------------------------------------
    # resync: the safety net under the watch plane
    # ------------------------------------------------------------------

    def _resync_loop(self) -> None:
        while not self._watch_stop.wait(self._resync_interval):
            try:
                self.resync()
            except Exception as exc:
                # a transient API failure here costs one cadence, nothing
                # else — the next tick relists from scratch
                self.logger.error(f"resync failed: {exc}")

    def resync(self) -> None:
        """Full relist, diffed against watch-derived state; emits synthetic
        events for anything the watch plane missed.

        Covers the gaps no reconnect can: events dropped while a stream
        was down, a resourceVersion that fell out of the compaction window
        mid-gap, a watch thread wedged long enough for deletes+recreates
        to alias. Synthetic events are indistinguishable from real ones
        downstream (same WatchEvent contract), so the controller and
        scheduler need no resync-awareness at all."""
        from nhd_tpu.obs.recorder import span

        API_COUNTERS.inc("resyncs_total")
        with self._state_lock:
            # everything the watch threads touch after this point is
            # FRESHER than the listing below — resync must not "repair"
            # those keys with stale relist data (spurious deletes for
            # pods created mid-list, reverted node states)
            seq0 = self._watch_seq
            self._relist_floor = seq0  # tombstones >= seq0 must survive
        try:
            # flight-recorder visibility: a resync pass is the API plane's
            # heaviest periodic call (full relist) — it shows in traces as
            # its own interval instead of as unexplained watch latency
            with span("resync", cat="api"):
                self._resync_diff(seq0)
        finally:
            with self._state_lock:
                self._relist_floor = None

    def _resync_diff(self, seq0: int) -> None:
        live: Dict[Tuple[str, str], _PodSnap] = {}
        for p in self.v1.list_pod_for_all_namespaces().items:
            live[(p.metadata.namespace, p.metadata.name)] = self._pod_snap(p)
        synthetic: List[WatchEvent] = []
        with self._state_lock:
            for key, snap in live.items():
                if self._pod_touched.get(key, 0) > seq0:
                    continue  # the watch already knows better
                prior = self._known_pods.get(key)
                if prior is not None and prior[0] == snap[0]:
                    self._known_pods[key] = snap  # refresh annotations/node
                    continue
                if prior is not None:
                    # same name, new uid: the delete was missed too
                    synthetic.append(self._synth_pod_event(
                        "pod_delete", key, prior
                    ))
                synthetic.append(self._synth_pod_event("pod_create", key, snap))
                self._known_pods[key] = snap
            for key in list(self._known_pods):
                if key not in live and self._pod_touched.get(key, 0) <= seq0:
                    synthetic.append(self._synth_pod_event(
                        "pod_delete", key, self._known_pods.pop(key)
                    ))
            # prune touch records for long-gone pods (delete events leave
            # them behind as tombstones guarding in-flight relists)
            for key in list(self._pod_touched):
                if (key not in self._known_pods and key not in live
                        and self._pod_touched[key] <= seq0):
                    del self._pod_touched[key]
        for ev in synthetic:
            key = (ev.namespace, ev.name)
            with self._state_lock:
                if self._pod_touched.get(key, 0) > seq0:
                    # the watch delivered fresher truth for this key while
                    # we were diffing — enqueueing the stale synthetic
                    # AFTER its event would make stale state win downstream
                    continue
                API_COUNTERS.inc("resync_synthetic_events_total")
                self._events.put(ev)
            self.logger.warning(
                f"resync: watch missed {ev.kind} for "
                f"{ev.namespace}/{ev.name}; emitting synthetic event"
            )
        # nodes: emit only real diffs (cordon/label/taint changes missed)
        for n in self.v1.list_node().items:
            out = self._note_node(
                n, emit_unchanged=False, if_untouched_since=seq0
            )
            if out is not None:
                API_COUNTERS.inc("resync_synthetic_events_total")
                self.logger.warning(
                    f"resync: watch missed node_update for {out.name}; "
                    "emitting synthetic event"
                )
                self._events.put(out)

    @staticmethod
    def _synth_pod_event(
        kind: str, key: Tuple[str, str], snap: _PodSnap
    ) -> WatchEvent:
        return WatchEvent(
            kind=kind, name=key[1], namespace=key[0],
            annotations=dict(snap[1]), uid=snap[0],
            scheduler_name=snap[2], node=snap[3],
        )

    def stop_watches(self) -> None:
        """Stop watch threads: interrupt in-flight streams (Watch.stop
        closes the response to unblock the read) and prevent reconnects."""
        self._watch_stop.set()
        with self._watch_lock:
            watchers = list(self._watchers)
        for w in watchers:
            self._stop_watcher(w)

    def _stop_watcher(self, w: object) -> None:
        stop = getattr(w, "stop", None)
        if stop is not None:
            try:
                stop()
            except Exception as exc:
                # keep stopping the rest; a watcher that fails to close
                # is at worst a leaked connection on exit
                self.logger.warning(f"watch stop failed: {exc}")

    def poll_watch_events(self, timeout: float = 0.0) -> Iterable[WatchEvent]:
        out = []
        try:
            while True:
                out.append(self._events.get(block=bool(timeout), timeout=timeout or None))
                timeout = 0.0
        except queue.Empty:
            pass
        return out

    # ------------------------------------------------------------------
    # coordination leases (leader election, k8s/lease.py)
    #
    # Implemented over the generic custom-object surface — both client
    # paths (real kubernetes package and the in-repo restclient) return
    # plain JSON dicts there, and every call runs under the retry policy.
    # The CAS is the API server's own optimistic concurrency: replace()
    # carries metadata.resourceVersion, a stale one answers 409 Conflict.
    # The fencing epoch is spec.leaseTransitions, bumped on EVERY
    # acquisition (a same-holder re-acquire after restart still gets a
    # fresh token).
    # ------------------------------------------------------------------

    _LEASE_GROUP = "coordination.k8s.io"
    _LEASE_VERSION = "v1"
    _LEASE_PLURAL = "leases"

    def _lease_get_raw(self, name: str) -> Optional[dict]:
        try:
            return self.crd.get_namespaced_custom_object(
                self._LEASE_GROUP, self._LEASE_VERSION, self._lease_ns,
                self._LEASE_PLURAL, name,
            )
        except self._client.exceptions.ApiException as exc:
            if getattr(exc, "status", None) == 404:
                return None
            # retry budget spent or a terminal surprise (403, …): either
            # way the election cannot verify the lease right now — the
            # elector's grace logic owns that outcome
            raise TransientBackendError(
                f"lease read for {name} failed: {exc}"
            ) from exc

    @staticmethod
    def _lease_view_of(name: str, obj: dict) -> LeaseView:
        spec = obj.get("spec") or {}
        renewed = _parse_micro_time(
            spec.get("renewTime") or spec.get("acquireTime")
        )
        duration = float(spec.get("leaseDurationSeconds") or 0)
        return LeaseView(
            name=name,
            holder=spec.get("holderIdentity") or "",
            epoch=int(spec.get("leaseTransitions") or 0),
            expires=(renewed + duration) if renewed is not None else 0.0,
        )

    @staticmethod
    def _lease_spec(holder: str, ttl: float, epoch: int, now: float) -> dict:
        stamp = _micro_time(now)
        return {
            "holderIdentity": holder,
            "leaseDurationSeconds": max(int(round(ttl)), 1),
            "acquireTime": stamp,
            "renewTime": stamp,
            "leaseTransitions": epoch,
        }

    def _lease_replace(self, name: str, body: dict) -> Optional[dict]:
        """Conditional replace; None when the CAS lost (409 Conflict)."""
        try:
            return self.crd.replace_namespaced_custom_object(
                self._LEASE_GROUP, self._LEASE_VERSION, self._lease_ns,
                self._LEASE_PLURAL, name, body,
            )
        except self._client.exceptions.ApiException as exc:
            if getattr(exc, "status", None) in (409, 404):
                return None   # lost the race / lease deleted under us
            raise TransientBackendError(
                f"lease replace for {name} failed: {exc}"
            ) from exc

    def _viewed(self, name: str, obj: dict) -> LeaseView:
        """_lease_view_of plus the epoch high-water-mark note — every
        lease state returned to a caller also tightens the fence."""
        view = self._lease_view_of(name, obj)
        self._note_lease_epoch(name, view)
        return view

    def lease_try_acquire(self, name: str, holder: str, ttl: float) -> LeaseView:
        import time as _time

        now = _time.time()
        obj = self._lease_get_raw(name)
        if obj is None:
            body = {
                "apiVersion": f"{self._LEASE_GROUP}/{self._LEASE_VERSION}",
                "kind": "Lease",
                "metadata": {"name": name, "namespace": self._lease_ns},
                "spec": self._lease_spec(holder, ttl, epoch=1, now=now),
            }
            try:
                created = self.crd.create_namespaced_custom_object(
                    self._LEASE_GROUP, self._LEASE_VERSION, self._lease_ns,
                    self._LEASE_PLURAL, body,
                )
                return self._viewed(name, created)
            except self._client.exceptions.ApiException as exc:
                if getattr(exc, "status", None) != 409:
                    raise TransientBackendError(
                        f"lease create for {name} failed: {exc}"
                    ) from exc
                obj = self._lease_get_raw(name)   # lost the create race
                if obj is None:
                    raise TransientBackendError(
                        f"lease {name} vanished mid-acquisition"
                    ) from exc
        view = self._viewed(name, obj)
        if view.holder and view.expires > now and view.holder != holder:
            return view   # held and live: the caller stays a follower
        body = dict(obj)
        body["spec"] = self._lease_spec(
            holder, ttl, epoch=view.epoch + 1, now=now
        )
        replaced = self._lease_replace(name, body)
        if replaced is not None:
            return self._viewed(name, replaced)
        # CAS lost: someone else took it between our read and write —
        # report THEIR state so the caller correctly stays a follower
        obj = self._lease_get_raw(name)
        return (
            self._viewed(name, obj) if obj is not None
            else LeaseView(name=name, holder="", epoch=view.epoch, expires=0.0)
        )

    def lease_renew(self, name: str, holder: str, epoch: int, ttl: float) -> bool:
        import time as _time

        obj = self._lease_get_raw(name)
        if obj is None:
            return False
        view = self._viewed(name, obj)
        if view.holder != holder or view.epoch != epoch:
            return False
        body = dict(obj)
        spec = dict(obj.get("spec") or {})
        spec["renewTime"] = _micro_time(_time.time())
        spec["leaseDurationSeconds"] = max(int(round(ttl)), 1)
        body["spec"] = spec
        if self._lease_replace(name, body) is not None:
            return True
        # CAS lost — but to WHOM? A renew PUT whose response was lost is
        # resent by the retry layer and answers 409 to its own landed
        # first send. If the lease still shows (holder, epoch) == ours,
        # the only writer that can have advanced the resourceVersion
        # while preserving both is ourselves: the renewal landed. Only a
        # rival's acquisition (holder or epoch moved) is a real loss —
        # demoting a healthy leader on every response blip would bounce
        # leadership (and the epoch) once per network hiccup.
        obj = self._lease_get_raw(name)
        if obj is None:
            return False
        cur = self._viewed(name, obj)
        return cur.holder == holder and cur.epoch == epoch

    def lease_release(self, name: str, holder: str, epoch: int) -> bool:
        obj = self._lease_get_raw(name)
        if obj is None:
            return False
        view = self._viewed(name, obj)
        if view.holder != holder or view.epoch != epoch:
            return False
        body = dict(obj)
        spec = dict(obj.get("spec") or {})
        spec["holderIdentity"] = ""   # epoch survives: tokens never rewind
        body["spec"] = spec
        return self._lease_replace(name, body) is not None

    def lease_read(self, name: str) -> Optional[LeaseView]:
        obj = self._lease_get_raw(name)
        return self._viewed(name, obj) if obj is not None else None

    def lease_live(self, name: str) -> str:
        import time as _time

        view = self.lease_read(name)
        if view is None or not view.holder:
            return ""
        return view.holder if view.expires > _time.time() else ""

    # ------------------------------------------------------------------
    # TriadSets (CRD group/version per deploy/triad-crd.1.16.yaml)
    # ------------------------------------------------------------------

    _CRD_GROUP = "sigproc.viasat.io"
    _CRD_VERSION = "v1"
    _CRD_PLURAL = "triadsets"

    def list_triadsets(self) -> List[dict]:
        try:
            objs = self.crd.list_cluster_custom_object(
                self._CRD_GROUP, self._CRD_VERSION, self._CRD_PLURAL
            )
        except self._client.exceptions.ApiException as exc:
            if retryable(exc):
                # the controller's reconcile isolation retries next period
                raise TransientBackendError(
                    f"TriadSet list failed transiently: {exc}"
                ) from exc
            return []  # CRD not installed: a fact, not an outage
        out = []
        for item in objs.get("items", []):
            spec = item.get("spec", {})
            out.append(
                {
                    "name": item["metadata"]["name"],
                    "ns": item["metadata"]["namespace"],
                    "replicas": spec.get("replicas", 0),
                    "service_name": spec.get("serviceName", item["metadata"]["name"]),
                    "template": spec.get("template", {}),
                }
            )
        return out

    def list_pods_of_triadset(self, ts: dict) -> List[str]:
        prefix = ts["service_name"] + "-"
        out = []
        for p in self.v1.list_namespaced_pod(ts["ns"]).items:
            name = p.metadata.name
            if name.startswith(prefix) and name[len(prefix):].isdigit():
                out.append(name)
        return out

    def create_pod_for_triadset(self, ts: dict, ordinal: int) -> bool:
        """Instantiate the template as '{service}-{ordinal}' with hostname
        and subdomain patched (TriadController.py:101-120)."""
        name = f"{ts['service_name']}-{ordinal}"
        template = dict(ts.get("template") or {})
        meta = dict(template.get("metadata", {}))
        spec = dict(template.get("spec", {}))
        meta["name"] = name
        spec["hostname"] = name
        spec["subdomain"] = ts["service_name"]
        body = {"apiVersion": "v1", "kind": "Pod", "metadata": meta, "spec": spec}
        try:
            self.v1.create_namespaced_pod(ts["ns"], body)
            return True
        except self._client.exceptions.ApiException as exc:
            self.logger.error(f"TriadSet pod create failed for {name}: {exc}")
            return False

    def update_triadset_status(self, ts: dict, replicas: int) -> bool:
        """status.replicas for the scale subresource."""
        try:
            self.crd.patch_namespaced_custom_object_status(
                self._CRD_GROUP, self._CRD_VERSION, ts["ns"],
                self._CRD_PLURAL, ts["name"],
                {"status": {"replicas": replicas}},
            )
            return True
        except self._client.exceptions.ApiException as exc:
            self.logger.error(f"TriadSet status update failed: {exc}")
            return False
