"""Real Kubernetes backend: the reference K8SMgr surface on kubernetes-client.

Method-for-method port of the reference's API-server interactions
(K8SMgr.py), behind the ClusterBackend seam. Import is gated: the
kubernetes package is only required when this backend is actually
constructed, so hermetic environments (tests, benchmarks, this dev image)
never need it.

The watch plane differs from the reference by design: instead of kopf's
asyncio operators (TriadController.py:161-171), watches run in daemon
threads that translate raw API events into WatchEvent records drained by
the controller — same information, no framework dependency.
"""

from __future__ import annotations

import queue
import threading
from typing import Dict, Iterable, List, Optional, Tuple

from nhd_tpu.k8s.interface import (
    CFG_ANNOTATION,
    CFG_TYPE_ANNOTATION,
    GPU_MAP_ANNOTATION_PREFIX,
    GROUPS_ANNOTATION,
    NAD_ANNOTATION,
    SCHEDULER_TAINT,
    ClusterBackend,
    WatchEvent,
)
from nhd_tpu.utils import get_logger


class KubeClusterBackend(ClusterBackend):
    """kubernetes-client implementation (reference: K8SMgr.py)."""

    def __init__(self, start_watches: bool = True):
        try:
            import kubernetes  # noqa: F401
            from kubernetes import client, config, watch
        except ImportError:
            # no kubernetes package: fall back to the in-repo REST client
            # (nhd_tpu/k8s/restclient.py — same surface over real HTTP, the
            # way config/libconfig.py replaces libconf)
            from nhd_tpu.k8s import restclient

            client = restclient.client
            config = restclient.config
            watch = restclient.watch

        self.logger = get_logger(__name__)
        self._client = client
        self._watch_mod = watch
        try:
            config.load_incluster_config()
        except Exception:
            # outside a pod: fall back to kubeconfig (K8SMgr.py:43-46)
            try:
                config.load_kube_config()
            except Exception as exc:
                raise RuntimeError(
                    "no cluster configuration found (neither in-cluster "
                    "env nor a kubeconfig); KubeClusterBackend needs an "
                    "API server to talk to — use FakeClusterBackend for "
                    f"hermetic runs ({exc})"
                ) from exc
        self.v1 = client.CoreV1Api()
        self.crd = client.CustomObjectsApi()
        self._events: "queue.Queue[WatchEvent]" = queue.Queue()
        # pause between watch reconnects (the API server ends streams
        # routinely; an immediate retry loop would hammer it)
        self._watch_backoff = 1.0
        self._watch_stop = threading.Event()
        # registered Watch objects, for stop; appended by the watch
        # threads while stop_watches may iterate from another thread, so
        # all access goes through _watch_lock (nhdlint NHD201)
        self._watch_lock = threading.Lock()
        self._watchers: List[object] = []
        if start_watches:
            self._start_watches()

    # ------------------------------------------------------------------
    # node reads
    # ------------------------------------------------------------------

    def get_nodes(self) -> List[str]:
        """KubeletReady nodes (K8SMgr.py:55-69)."""
        out = []
        for item in self.v1.list_node().items:
            for cond in item.status.conditions or []:
                if cond.reason == "KubeletReady" and cond.status == "True":
                    out.append(item.metadata.name)
        return out

    def is_node_active(self, node: str) -> bool:
        """Scheduler taint present and node not cordoned (K8SMgr.py:167-192)."""
        obj = self.v1.read_node(node)
        has_taint = any(
            t.key == SCHEDULER_TAINT and t.effect == "NoSchedule"
            for t in (obj.spec.taints or [])
        )
        return has_taint and not bool(obj.spec.unschedulable)

    def get_node_labels(self, node: str) -> Dict[str, str]:
        return dict(self.v1.read_node(node).metadata.labels or {})

    def get_node_addr(self, node: str) -> str:
        """First InternalIP (K8SMgr.py:91-106)."""
        for addr in self.v1.read_node(node).status.addresses or []:
            if addr.type == "InternalIP":
                return addr.address
        return ""

    def get_node_hugepage_resources(self, node: str) -> Tuple[int, int]:
        """1Gi hugepage capacity/allocatable in GiB (K8SMgr.py:71-89)."""
        obj = self.v1.read_node(node)

        def gi(res: Optional[dict]) -> int:
            if not res:
                return 0
            val = res.get("hugepages-1Gi", "0")
            return int(str(val).rstrip("Gi")) if "Gi" in str(val) else int(val)

        return (gi(obj.status.capacity), gi(obj.status.allocatable))

    # ------------------------------------------------------------------
    # pod reads
    # ------------------------------------------------------------------

    def _read_pod(self, pod: str, ns: str):
        try:
            return self.v1.read_namespaced_pod(pod, ns)
        except self._client.exceptions.ApiException:
            return None

    def pod_exists(self, pod: str, ns: str) -> bool:
        return self._read_pod(pod, ns) is not None

    def get_pod_node(self, pod: str, ns: str) -> Optional[str]:
        obj = self._read_pod(pod, ns)
        return obj.spec.node_name if obj else None

    def get_pod_annotations(self, pod: str, ns: str) -> Optional[Dict[str, str]]:
        obj = self._read_pod(pod, ns)
        return dict(obj.metadata.annotations or {}) if obj else None

    def get_cfg_annotations(self, pod: str, ns: str) -> Optional[str]:
        annots = self.get_pod_annotations(pod, ns)
        return annots.get(CFG_ANNOTATION) if annots else None

    def get_cfg_type(self, pod: str, ns: str) -> Optional[str]:
        annots = self.get_pod_annotations(pod, ns)
        return annots.get(CFG_TYPE_ANNOTATION) if annots else None

    def get_pod_node_groups(self, pod: str, ns: str) -> List[str]:
        annots = self.get_pod_annotations(pod, ns) or {}
        if GROUPS_ANNOTATION in annots:
            return annots[GROUPS_ANNOTATION].split(".")
        return ["default"]

    def get_requested_pod_resources(self, pod: str, ns: str) -> Dict[str, str]:
        """First container only, like the reference (K8SMgr.py:215-225)."""
        obj = self._read_pod(pod, ns)
        if not obj or not obj.spec.containers:
            return {}
        res = obj.spec.containers[0].resources
        return dict(res.requests or {}) if res else {}

    def get_scheduled_pods(self, scheduler: str) -> List[Tuple[str, str, str, str]]:
        out = []
        for p in self.v1.list_pod_for_all_namespaces().items:
            if p.spec.scheduler_name == scheduler and p.spec.node_name:
                out.append(
                    (p.metadata.name, p.metadata.namespace, p.metadata.uid,
                     p.status.phase)
                )
        return out

    def service_pods(self, scheduler: str):
        out = {}
        for p in self.v1.list_pod_for_all_namespaces().items:
            if p.spec.scheduler_name == scheduler:
                key = (p.metadata.namespace, p.metadata.name, p.metadata.uid)
                out[key] = (p.status.phase, p.spec.node_name)
        return out

    def get_cfg_map(self, pod: str, ns: str) -> Tuple[Optional[str], Optional[str]]:
        """Find the pod's ConfigMap volume, return its first file
        (K8SMgr.py:328-356)."""
        obj = self._read_pod(pod, ns)
        if obj is None:
            return (None, None)
        for vol in obj.spec.volumes or []:
            if vol.config_map is None:
                continue
            try:
                cm = self.v1.read_namespaced_config_map(vol.config_map.name, ns)
            except self._client.exceptions.ApiException as exc:
                # a pod can reference a ConfigMap that doesn't exist (yet);
                # that fails the pod (FailedCfgParse), never the scheduler
                self.logger.error(
                    f"configmap {ns}/{vol.config_map.name} unreadable: {exc}"
                )
                continue
            if cm.data:
                return (vol.config_map.name, next(iter(cm.data.values())))
        return (None, None)

    # ------------------------------------------------------------------
    # writes
    # ------------------------------------------------------------------

    def _patch_annotation(self, pod: str, ns: str, annots: Dict[str, str]) -> bool:
        try:
            self.v1.patch_namespaced_pod(
                pod, ns, {"metadata": {"annotations": annots}}
            )
            return True
        except self._client.exceptions.ApiException as exc:
            self.logger.error(f"annotation patch failed for {ns}/{pod}: {exc}")
            return False

    def add_nad_to_pod(self, pod: str, ns: str, nad: str) -> bool:
        return self._patch_annotation(pod, ns, {NAD_ANNOTATION: nad})

    def annotate_pod_config(self, ns: str, pod: str, cfg: str) -> bool:
        return self._patch_annotation(pod, ns, {CFG_ANNOTATION: cfg})

    def annotate_pod_gpu_map(self, ns: str, pod: str, gpu_map: Dict[str, int]) -> bool:
        return self._patch_annotation(
            pod, ns,
            {f"{GPU_MAP_ANNOTATION_PREFIX}.{d}": str(i) for d, i in gpu_map.items()},
        )

    def bind_pod_to_node(self, pod: str, node: str, ns: str) -> bool:
        """V1Binding; the known kubernetes-client ValueError on the empty
        response is swallowed like the reference does (K8SMgr.py:487-491)."""
        client = self._client
        body = client.V1Binding(
            metadata=client.V1ObjectMeta(name=pod),
            target=client.V1ObjectReference(
                api_version="v1", kind="Node", name=node, namespace=ns
            ),
        )
        try:
            self.v1.create_namespaced_pod_binding(pod, ns, body)
        except ValueError:
            pass  # client chokes on the empty 201 body; bind succeeded
        except client.exceptions.ApiException as exc:
            self.logger.error(f"bind failed for {ns}/{pod} -> {node}: {exc}")
            return False
        return True

    def generate_pod_event(self, pod, ns, reason, event_type, message) -> None:
        """'NHD:'-prefixed V1Event on the pod (K8SMgr.py:518-559)."""
        import datetime

        client = self._client
        obj = self._read_pod(pod, ns)
        if obj is None:
            return
        now = datetime.datetime.now(datetime.timezone.utc)
        body = client.CoreV1Event(
            metadata=client.V1ObjectMeta(generate_name=f"{pod}.nhd."),
            involved_object=client.V1ObjectReference(
                api_version="v1", kind="Pod", name=pod, namespace=ns,
                uid=obj.metadata.uid,
            ),
            reason=reason, message=f"NHD: {message}",
            type=event_type.value, count=1,
            first_timestamp=now, last_timestamp=now,
            source=client.V1EventSource(component="nhd-scheduler"),
        )
        try:
            self.v1.create_namespaced_event(ns, body)
        except client.exceptions.ApiException as exc:
            self.logger.error(f"event post failed for {ns}/{pod}: {exc}")

    # ------------------------------------------------------------------
    # watch plane
    # ------------------------------------------------------------------

    def _start_watches(self) -> None:
        threading.Thread(target=self._watch_pods, daemon=True).start()
        threading.Thread(target=self._watch_nodes, daemon=True).start()

    def _register_watcher(self, w: object) -> None:
        with self._watch_lock:
            self._watchers.append(w)
            stopping = self._watch_stop.is_set()
        if stopping:
            # stop_watches already swept the list; a watcher registering
            # after its snapshot would never be stopped (leaked stream) —
            # stop it here instead of racing the sweep
            self._stop_watcher(w)

    def _watch_pods(self) -> None:
        w = self._watch_mod.Watch()
        self._register_watcher(w)
        while not self._watch_stop.is_set():
            try:
                for ev in w.stream(self.v1.list_pod_for_all_namespaces):
                    obj = ev["object"]
                    kind = {"ADDED": "pod_create", "DELETED": "pod_delete"}.get(
                        ev["type"]
                    )
                    if kind is None:
                        continue
                    self._events.put(
                        WatchEvent(
                            kind=kind, name=obj.metadata.name,
                            namespace=obj.metadata.namespace,
                            annotations=dict(obj.metadata.annotations or {}),
                            uid=obj.metadata.uid,
                            scheduler_name=obj.spec.scheduler_name or "",
                            node=obj.spec.node_name or "",
                        )
                    )
            except Exception as exc:
                self.logger.error(f"pod watch restarted: {exc}")
            # the server ends watch streams routinely; reconnect after a
            # pause rather than spinning
            self._watch_stop.wait(self._watch_backoff)

    def _watch_nodes(self) -> None:
        last: Dict[str, tuple] = {}
        w = self._watch_mod.Watch()
        self._register_watcher(w)
        while not self._watch_stop.is_set():
            try:
                for ev in w.stream(self.v1.list_node):
                    obj = ev["object"]
                    name = obj.metadata.name
                    labels = dict(obj.metadata.labels or {})
                    unsched = bool(obj.spec.unschedulable)
                    taints = [t.key for t in (obj.spec.taints or [])]
                    old_labels, old_unsched, old_taints = last.get(
                        name, (labels, unsched, taints)
                    )
                    self._events.put(
                        WatchEvent(
                            kind="node_update", name=name, labels=labels,
                            old_labels=old_labels, unschedulable=unsched,
                            was_unschedulable=old_unsched, taints=taints,
                            old_taints=old_taints,
                        )
                    )
                    last[name] = (labels, unsched, taints)
            except Exception as exc:
                self.logger.error(f"node watch restarted: {exc}")
            self._watch_stop.wait(self._watch_backoff)

    def stop_watches(self) -> None:
        """Stop watch threads: interrupt in-flight streams (Watch.stop
        closes the response to unblock the read) and prevent reconnects."""
        self._watch_stop.set()
        with self._watch_lock:
            watchers = list(self._watchers)
        for w in watchers:
            self._stop_watcher(w)

    def _stop_watcher(self, w: object) -> None:
        stop = getattr(w, "stop", None)
        if stop is not None:
            try:
                stop()
            except Exception as exc:
                # keep stopping the rest; a watcher that fails to close
                # is at worst a leaked connection on exit
                self.logger.warning(f"watch stop failed: {exc}")

    def poll_watch_events(self, timeout: float = 0.0) -> Iterable[WatchEvent]:
        out = []
        try:
            while True:
                out.append(self._events.get(block=bool(timeout), timeout=timeout or None))
                timeout = 0.0
        except queue.Empty:
            pass
        return out

    # ------------------------------------------------------------------
    # TriadSets (CRD group/version per deploy/triad-crd.1.16.yaml)
    # ------------------------------------------------------------------

    _CRD_GROUP = "sigproc.viasat.io"
    _CRD_VERSION = "v1"
    _CRD_PLURAL = "triadsets"

    def list_triadsets(self) -> List[dict]:
        try:
            objs = self.crd.list_cluster_custom_object(
                self._CRD_GROUP, self._CRD_VERSION, self._CRD_PLURAL
            )
        except self._client.exceptions.ApiException:
            return []
        out = []
        for item in objs.get("items", []):
            spec = item.get("spec", {})
            out.append(
                {
                    "name": item["metadata"]["name"],
                    "ns": item["metadata"]["namespace"],
                    "replicas": spec.get("replicas", 0),
                    "service_name": spec.get("serviceName", item["metadata"]["name"]),
                    "template": spec.get("template", {}),
                }
            )
        return out

    def list_pods_of_triadset(self, ts: dict) -> List[str]:
        prefix = ts["service_name"] + "-"
        out = []
        for p in self.v1.list_namespaced_pod(ts["ns"]).items:
            name = p.metadata.name
            if name.startswith(prefix) and name[len(prefix):].isdigit():
                out.append(name)
        return out

    def create_pod_for_triadset(self, ts: dict, ordinal: int) -> bool:
        """Instantiate the template as '{service}-{ordinal}' with hostname
        and subdomain patched (TriadController.py:101-120)."""
        name = f"{ts['service_name']}-{ordinal}"
        template = dict(ts.get("template") or {})
        meta = dict(template.get("metadata", {}))
        spec = dict(template.get("spec", {}))
        meta["name"] = name
        spec["hostname"] = name
        spec["subdomain"] = ts["service_name"]
        body = {"apiVersion": "v1", "kind": "Pod", "metadata": meta, "spec": spec}
        try:
            self.v1.create_namespaced_pod(ts["ns"], body)
            return True
        except self._client.exceptions.ApiException as exc:
            self.logger.error(f"TriadSet pod create failed for {name}: {exc}")
            return False

    def update_triadset_status(self, ts: dict, replicas: int) -> bool:
        """status.replicas for the scale subresource."""
        try:
            self.crd.patch_namespaced_custom_object_status(
                self._CRD_GROUP, self._CRD_VERSION, ts["ns"],
                self._CRD_PLURAL, ts["name"],
                {"status": {"replicas": replicas}},
            )
            return True
        except self._client.exceptions.ApiException as exc:
            self.logger.error(f"TriadSet status update failed: {exc}")
            return False
