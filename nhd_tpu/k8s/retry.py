"""Unified retry/backoff policy for API-server calls.

The reference treats the API server as always-available: every K8SMgr call
is one-shot, and a transient 503 surfaces straight into the control loop
(SURVEY §5.3 — resilience rests on crash-only restarts, not on absorbing
faults). Gandiva/Gavel-style cluster schedulers instead treat the API
server as an unreliable dependency. This module is that defense layer:

* :func:`classify` — splits failures into *retryable* (429, 5xx, status-0
  network errors) and *terminal* (any other 4xx, plus the V1Binding
  ValueError quirk the bind path depends on);
* :class:`RetryPolicy` — exponential backoff with decorrelated jitter, a
  per-call deadline, Retry-After honoring, and a circuit breaker that
  trips after consecutive retryable failures and half-opens on a timer;
* :class:`RetryingApi` — wraps a CoreV1Api/CustomObjectsApi-shaped object
  so every non-watch method call runs under the policy (watch calls pass
  through: the watch plane has its own reconnect loop in k8s/kube.py);
* :data:`API_COUNTERS` — process-wide observability for the layer itself,
  exported through rpc/metrics.py.

Everything is injectable (clock, sleep, RNG) so the policy is unit-tested
without a single real sleep (tests/test_retry.py).
"""

from __future__ import annotations

import functools
import http.client as _httplib
import random
import threading
import time
from typing import Any, Callable, Dict, Optional, Tuple

from nhd_tpu.obs import histo as _histo

# exceptions that mean "the network/transport failed" when no HTTP status
# is attached. Statusless exceptions OUTSIDE this set are client-side bugs
# (TypeError, KeyError, …) — retrying them burns backoff sleeps on a
# deterministic failure and can open the breaker against a healthy server.
_NETWORK_ERRORS: tuple = (OSError, _httplib.HTTPException)
try:  # the real kubernetes client surfaces transport faults as urllib3's
    import urllib3.exceptions as _u3

    _NETWORK_ERRORS = _NETWORK_ERRORS + (_u3.HTTPError,)
except Exception:  # nhdlint: ignore[NHD302]
    pass  # urllib3 absent (restclient fallback): stdlib set suffices

# circuit-breaker states (exported as the nhd_api_circuit_state gauge)
CIRCUIT_CLOSED = 0
CIRCUIT_OPEN = 1
CIRCUIT_HALF_OPEN = 2


class ApiCounters:
    """Thread-safe counter/gauge registry for the fault-tolerance layer.

    KNOWN is the single source of truth — name → (prometheus kind, help
    text) — iterated by rpc/metrics.py, so adding a counter here is all
    it takes to surface it on /metrics. Names are pre-seeded to 0 so
    every metric is visible from process start, not only after its first
    event.
    """

    KNOWN: Dict[str, Tuple[str, str]] = {
        "api_calls_total":
            ("counter", "API calls issued under the retry policy"),
        "api_retries_total":
            ("counter", "API call retries (backoff slept)"),
        "api_giveups_total":
            ("counter", "API calls abandoned after the retry budget"),
        "api_failures_total":
            ("counter", "Retryable API call failures observed"),
        "api_circuit_open_total":
            ("counter", "Circuit breaker open transitions"),
        "api_circuit_rejections_total":
            ("counter", "Calls rejected while the circuit was open"),
        "api_circuit_state":
            ("gauge", "Circuit state (0 closed, 1 open, 2 half-open)"),
        "watch_reconnects_total":
            ("counter", "Watch stream reconnects"),
        "watch_dedup_replays_total":
            ("counter", "Replayed watch ADDED events deduplicated"),
        "watch_malformed_lines_total":
            ("counter", "Malformed watch lines dropped"),
        "watch_read_timeouts_total":
            ("counter", "Watch streams ended by read timeout/error"),
        "resyncs_total":
            ("counter", "Full-relist resync passes"),
        "resync_synthetic_events_total":
            ("counter", "Synthetic events emitted by resync"),
        "controller_event_errors_total":
            ("counter", "Poisoned watch events isolated"),
        "controller_reconcile_errors_total":
            ("counter", "TriadSet reconcile passes failed"),
        "scheduler_loop_errors_total":
            ("counter",
             "Scheduler run-loop passes isolated (mirror rebuilt after)"),
        "bind_requeues_total":
            ("counter", "Pods requeued after a transient commit failure"),
        # incremental device-resident cluster state (solver/encode.py
        # ClusterDelta + solver/device_state.py row scatters,
        # docs/PERFORMANCE.md "Incremental device-resident state"). The
        # labeled complement nhd_device_state_rebuilds_total{reason=...}
        # is rendered from encode.rebuild_reasons_snapshot() in
        # rpc/metrics.py (bounded reason vocabulary, NHD603).
        "device_state_events_total":
            ("counter", "Watch/claim events folded into the incremental "
                        "cluster state as deltas"),
        "device_state_deltas_total":
            ("counter", "Row patches applied to the host-resident packed "
                        "cluster arrays"),
        "device_state_rows_uploaded_total":
            ("counter", "Node rows scattered/uploaded to the "
                        "device-resident arrays"),
        "device_state_full_rebuilds_total":
            ("counter", "Incremental-state fallbacks to a full "
                        "encode_cluster rebuild"),
        "device_state_resident_age_seconds":
            ("gauge", "Seconds since the resident cluster state was "
                      "last fully rebuilt"),
        # SPMD mesh plane (kernel.get_ranked_solver_mesh +
        # device_state._scatter_mesh, docs/PERFORMANCE.md "SPMD
        # megaround"): the sharded-solve posture and its upload economy
        "mesh_devices":
            ("gauge", "Devices in the scheduler's solve mesh "
                      "(0 = single-device posture)"),
        "mesh_shard_rows":
            ("gauge", "Padded node rows resident per mesh shard"),
        "mesh_solves_total":
            ("counter", "Fused ranked megarounds dispatched SPMD over "
                        "the mesh"),
        "mesh_rows_uploaded_total":
            ("counter", "Node rows scattered into mesh-sharded resident "
                        "arrays via per-shard delta scatters"),
        "mesh_wholesale_uploads_total":
            ("counter", "Mesh resident-state uploads that fell back to "
                        "a wholesale re-shard (storm-sized delta or "
                        "NHD_DEVICE_DELTA=0)"),
        # solver data-plane guard (solver/guard.py, docs/RESILIENCE.md
        # "Layer 8"): the detect -> degrade -> repair ladder's ledger.
        # guard_rung is the current degradation floor (0 = full
        # fidelity/mesh, 1 = single-device, 2 = host solve path).
        "guard_rung":
            ("gauge", "Solver guard degradation floor (0 mesh/full, "
                      "1 single-device, 2 host)"),
        "guard_faults_total":
            ("counter", "Device-plane faults the solver guard observed"),
        "guard_retries_total":
            ("counter", "Solver rounds re-dispatched after a transient "
                        "device-plane fault"),
        "guard_giveups_total":
            ("counter", "Device-plane faults surfaced past the guard "
                        "(terminal, or the rung ladder exhausted)"),
        "guard_degradations_total":
            ("counter", "Rung drops down the mesh -> single-device -> "
                        "host ladder"),
        "guard_promotions_total":
            ("counter", "Rung re-promotions after clean probe rounds"),
        "guard_audits_total":
            ("counter", "Resident-state audit passes run"),
        "guard_audit_rows_total":
            ("counter", "Device rows bit-exact spot-checked against the "
                        "host mirror"),
        "guard_corruptions_total":
            ("counter", "Resident-state corruptions detected (audit "
                        "mismatches + rank-tensor screen failures)"),
        "guard_repairs_total":
            ("counter", "Resident states rebuilt from host truth by the "
                        "guard"),
        "guard_quarantined_shapes":
            ("gauge", "Shape keys quarantined for repeated program "
                      "faults (AOT artifact retired, live re-trace)"),
        # scheduling-policy engine (nhd_tpu/policy/,
        # docs/SCHEDULING_POLICIES.md): heterogeneity scoring posture +
        # the bounded-preemption ledger. The labeled complement
        # nhd_policy_preemptions_total{tier=...} is rendered from
        # policy.preempt_tier_snapshot() in rpc/metrics.py (tier labels
        # clamp to a bounded vocabulary, NHD603 stance).
        "policy_preemptions_total":
            ("counter", "Pods evicted by bounded policy preemption"),
        "policy_preempt_budget_exhausted_total":
            ("counter", "Preemption plans refused by the round/tenant "
                        "budgets"),
        "policy_score_mode":
            ("gauge", "Heterogeneity scoring mode (0 off, 1 uniform, "
                      "2 matrix)"),
        # AOT export worker (solver/aot.py): background-thread failures
        # were invisible before this counter
        "aot_export_failures_total":
            ("counter", "AOT StableHLO background exports that failed "
                        "(serving unaffected; cache not written)"),
        # HA plane (k8s/lease.py, docs/RESILIENCE.md "HA & fencing").
        # Under the sharded federation the single-leader gauges
        # generalize: ha_is_leader means "holds at least one shard" and
        # ha_epoch reports the highest held shard token — the per-shard
        # truth lives on the nhd_shard_* families below.
        "ha_is_leader":
            ("gauge", "This replica holds the scheduler lease "
                      "(federation: at least one shard lease)"),
        "ha_epoch":
            ("gauge", "Fencing epoch of this replica's last leadership "
                      "(federation: highest held shard epoch)"),
        "ha_transitions_total":
            ("counter", "Leadership transitions (promotions + demotions)"),
        "ha_renewals_total":
            ("counter", "Successful lease renewals"),
        "ha_renewal_failures_total":
            ("counter", "Lease renewals that errored or lost the CAS"),
        "ha_promotions_total":
            ("counter", "Standby -> leader promotion replays completed"),
        "ha_stale_writes_rejected_total":
            ("counter", "Fenced writes rejected for a stale epoch"),
        "ha_watchdog_stalls_total":
            ("counter", "Stall-watchdog firings (lease released, exiting)"),
        "ha_watchdog_loop_age_seconds":
            ("gauge", "Age of the scheduling loop's last heartbeat"),
        # shard federation plane (k8s/lease.py ShardedElector +
        # scheduler/core.py spillover, docs/RESILIENCE.md "Federation");
        # the per-shard epoch gauge nhd_shard_epoch{shard=...} is
        # rendered from lease.shard_status_snapshot() in rpc/metrics.py
        "shard_owned_count":
            ("gauge", "Shard leases this replica currently holds"),
        "shard_acquisitions_total":
            ("counter", "Shard lease acquisitions (rendezvous-preferred "
                        "or patience-expired takeovers)"),
        "shard_handoffs_total":
            ("counter", "Shards voluntarily handed to a better-ranked "
                        "live member (bounded rebalance releases)"),
        "shard_spillover_claims_total":
            ("counter", "Cross-shard spillover pods claimed for a local "
                        "placement attempt"),
        "shard_spillover_spilled_total":
            ("counter", "Pods spilled to the untried shards after no "
                        "owned shard could place them"),
        "shard_spillover_exhausted_total":
            ("counter", "Spilled pods declared explicitly unschedulable "
                        "(every shard tried, or the record aged out)"),
        "shard_spillover_depth":
            ("gauge", "Pending pods carrying a live spillover record"),
        "shard_spillover_oldest_age_seconds":
            ("gauge", "Age of the oldest live spillover record"),
        "shard_spillover_orphan_age_max_seconds":
            ("gauge", "High-water mark of spillover record age (the "
                      "bounded-orphan-window observable)"),
        # ingress admission plane (nhd_tpu/ingress/admission.py,
        # docs/RESILIENCE.md "Layer 9 — Overload & admission")
        "admission_admitted_total":
            ("counter", "Pod creates admitted into a tenant lane"),
        "admission_deferred_total":
            ("counter", "Over-rate creates parked at the defer rung"),
        "admission_readmitted_total":
            ("counter", "Deferred creates re-admitted after recovery"),
        "admission_shed_total":
            ("counter", "Creates refused by the shed ladder (every one "
                        "gets a decision record + journal event)"),
        "admission_requeue_refusals_total":
            ("counter", "Scheduler requeues refused at the hard lane cap"),
    }

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._vals: Dict[str, float] = {name: 0 for name in self.KNOWN}

    def inc(self, name: str, by: float = 1) -> None:
        with self._lock:
            self._vals[name] = self._vals.get(name, 0) + by

    def set(self, name: str, value: float) -> None:
        with self._lock:
            self._vals[name] = value

    def get(self, name: str) -> float:
        with self._lock:
            return self._vals.get(name, 0)

    def snapshot(self) -> Dict[str, float]:
        with self._lock:
            return dict(self._vals)

    def reset(self) -> None:
        """Back to all-zero (test isolation)."""
        with self._lock:
            self._vals = {name: 0 for name in self.KNOWN}


#: process-wide registry: the scheduler owns one API-server relationship,
#: so one counter set mirrors what an operator sees on the wire
API_COUNTERS = ApiCounters()


def classify(exc: BaseException) -> Tuple[bool, Optional[float]]:
    """(retryable?, Retry-After seconds or None) for an API-call failure.

    Retryable: HTTP 429 and 5xx, plus status-0/status-less failures (the
    restclient maps URLError to ApiException(status=0); the real client
    raises bare network exceptions with no status at all). Terminal: every
    other 4xx — a 404/409/410 will not improve with repetition — and
    ValueError, which the bind path REQUIRES to propagate untouched (the
    V1Binding deserialization quirk signals success, K8SMgr.py:487-491).
    """
    if isinstance(exc, ValueError):
        return (False, None)
    status = getattr(exc, "status", None)
    if status is None:
        # no HTTP status: retry only genuine transport failures — a
        # TypeError from a bad call is deterministic and must surface
        return (isinstance(exc, _NETWORK_ERRORS), None)
    try:
        status = int(status)
    except (TypeError, ValueError):
        return (isinstance(exc, _NETWORK_ERRORS), None)
    if status == 429 or status >= 500 or status == 0:
        return (status != 501, _retry_after(exc))  # 501 never improves
    return (False, None)


def _retry_after(exc: BaseException) -> Optional[float]:
    headers = getattr(exc, "headers", None)
    if headers is None:
        return None
    try:
        raw = headers.get("Retry-After")
        if raw is None:
            # plain-dict headers (restclient path) preserve wire casing,
            # and HTTP/2 hops lowercase header names — match insensitively
            for k in headers:
                if str(k).lower() == "retry-after":
                    raw = headers[k]
                    break
    except (AttributeError, TypeError):
        return None
    try:
        return float(raw) if raw is not None else None
    except (TypeError, ValueError):
        return None  # HTTP-date form: rare enough to fall back to jitter


def retryable(exc: BaseException) -> bool:
    """Would the policy have retried this failure? (Used by backends to
    translate an exhausted-retry error into TransientBackendError.)"""
    return classify(exc)[0]


class RetryPolicy:
    """Retry + backoff + circuit breaker for one API-server relationship.

    ``call(fn, *args, **kwargs)`` runs ``fn`` until success, a terminal
    failure, the attempt budget, or the per-call deadline — whichever
    comes first. Backoff is decorrelated jitter (AWS architecture-blog
    form): ``delay = min(cap, uniform(base, prev * 3))``, floored by a
    server-sent Retry-After when present.

    The breaker counts *consecutive* retryable failures across calls;
    at ``breaker_threshold`` it opens and rejects calls instantly (the
    scheduler keeps its loop latency instead of stacking timeouts), then
    half-opens after ``breaker_cooldown`` to let one probe through.
    """

    def __init__(
        self,
        *,
        attempts: int = 4,
        base_delay: float = 0.05,
        max_delay: float = 2.0,
        deadline: float = 15.0,
        breaker_threshold: int = 10,
        breaker_cooldown: float = 30.0,
        clock: Callable[[], float] = time.monotonic,
        sleep: Callable[[float], None] = time.sleep,
        rng: Optional[random.Random] = None,
        exc_class: Optional[type] = None,
        counters: ApiCounters = API_COUNTERS,
    ):
        if attempts < 1:
            raise ValueError(f"attempts must be >= 1, got {attempts}")
        self.attempts = attempts
        self.base_delay = base_delay
        self.max_delay = max_delay
        self.deadline = deadline
        self.breaker_threshold = breaker_threshold
        self.breaker_cooldown = breaker_cooldown
        self._clock = clock
        self._sleep = sleep
        self._rng = rng or random.Random()
        # exceptions the breaker raises while open; kube.py passes the
        # active client's ApiException so existing handlers catch it
        self._exc_class = exc_class
        self._counters = counters
        self._lock = threading.Lock()
        self._consecutive_failures = 0
        self._state = CIRCUIT_CLOSED
        self._open_until = 0.0
        self._half_open_since = 0.0

    # ------------------------------------------------------------------
    # circuit breaker
    # ------------------------------------------------------------------

    @property
    def circuit_state(self) -> int:
        with self._lock:
            return self._state

    def _set_state(self, state: int) -> None:
        # caller holds self._lock
        self._state = state
        self._counters.set("api_circuit_state", state)

    def _admit(self) -> bool:
        """May a call proceed right now? (False = breaker rejects it.)"""
        with self._lock:
            if self._state == CIRCUIT_CLOSED:
                return True
            if self._state == CIRCUIT_OPEN:
                if self._clock() < self._open_until:
                    return False
                # cooldown lapsed: half-open, admit exactly this probe
                self._set_state(CIRCUIT_HALF_OPEN)
                self._half_open_since = self._clock()
                return True
            # HALF_OPEN: one probe is already in flight; reject the rest
            # so a burst doesn't re-storm a recovering server. But the
            # probe may never report back (hung socket with no client
            # timeout, thread unwound by a BaseException) — after a full
            # cooldown of silence, assume it died and admit a new probe,
            # or the breaker would convert one stuck thread into a
            # permanent process-wide rejection
            if self._clock() - self._half_open_since >= self.breaker_cooldown:
                self._half_open_since = self._clock()
                return True
            return False

    def _record_success(self) -> None:
        with self._lock:
            self._consecutive_failures = 0
            if self._state != CIRCUIT_CLOSED:
                self._set_state(CIRCUIT_CLOSED)

    def _record_failure(self) -> None:
        with self._lock:
            self._consecutive_failures += 1
            if self._state == CIRCUIT_HALF_OPEN or (
                self._state == CIRCUIT_CLOSED
                and self._consecutive_failures >= self.breaker_threshold
            ):
                self._set_state(CIRCUIT_OPEN)
                self._open_until = self._clock() + self.breaker_cooldown
                self._counters.inc("api_circuit_open_total")

    def _reject(self) -> BaseException:
        self._counters.inc("api_circuit_rejections_total")
        if self._exc_class is not None:
            return self._exc_class(
                status=0, reason="circuit breaker open (API server failing)"
            )
        return CircuitOpenError("circuit breaker open (API server failing)")

    # ------------------------------------------------------------------
    # the call loop
    # ------------------------------------------------------------------

    def call(self, fn: Callable[..., Any], *args: Any, **kwargs: Any) -> Any:
        if not self._admit():
            raise self._reject()  # rejected calls never hit the wire —
            #                       they stay out of the latency histogram
        t0 = time.perf_counter()
        try:
            return self._call_under_policy(fn, *args, **kwargs)
        finally:
            # whole-call latency incl. backoff sleeps — the figure a
            # caller (the scheduler's commit path) actually waited
            _histo.observe("api_call_seconds", time.perf_counter() - t0)

    def _call_under_policy(
        self, fn: Callable[..., Any], *args: Any, **kwargs: Any
    ) -> Any:
        self._counters.inc("api_calls_total")
        deadline_at = self._clock() + self.deadline
        prev_delay = self.base_delay
        attempt = 0
        while True:
            try:
                result = fn(*args, **kwargs)
            except ValueError:
                # the V1Binding quirk: a 2xx the client can't deserialize.
                # The call SUCCEEDED on the wire — callers depend on seeing
                # this exact exception (k8s/kube.py bind_pod_to_node)
                self._record_success()
                raise
            except Exception as exc:
                is_retryable, retry_after = classify(exc)
                if not is_retryable:
                    # terminal 4xx: a fact about the request, not about
                    # server health. The server RESPONDED, so this also
                    # counts as proof of health — without it a half-open
                    # probe answered 404 would wedge the breaker in
                    # HALF_OPEN and reject every later call forever
                    self._record_success()
                    raise
                self._counters.inc("api_failures_total")
                self._record_failure()
                attempt += 1
                delay = min(
                    self.max_delay,
                    self._rng.uniform(self.base_delay, prev_delay * 3),
                )
                if retry_after is not None:
                    # honor the server's directive up to the remaining
                    # deadline — capping it at max_delay would re-hit a
                    # throttling server well inside the window it asked
                    # us to stay away
                    remaining = max(0.0, deadline_at - self._clock())
                    delay = max(delay, min(retry_after, remaining))
                prev_delay = delay
                if (
                    attempt >= self.attempts
                    or not self._admit_retry()
                    or self._clock() + delay > deadline_at
                ):
                    self._counters.inc("api_giveups_total")
                    raise
                self._counters.inc("api_retries_total")
                self._sleep(delay)
                continue
            self._record_success()
            return result

    def _admit_retry(self) -> bool:
        """Retries stop immediately once the breaker opens mid-call."""
        with self._lock:
            return self._state != CIRCUIT_OPEN


class CircuitOpenError(Exception):
    """Raised for rejected calls when no client exception class is wired."""

    status = 0
    reason = "circuit breaker open"


class RetryingApi:
    """Proxy that runs every non-watch method of a kubernetes-client-shaped
    API object under a RetryPolicy.

    Watch establishment (``watch=True`` kwarg, as both the real client's
    ``Watch.stream`` and the restclient fallback issue it) passes through
    untouched: the watch plane's reconnect loop (k8s/kube.py) owns that
    backoff, and stacking the two would double-delay stream recovery.
    """

    def __init__(self, api: Any, policy: RetryPolicy):
        self._api = api
        self._policy = policy
        self._wrapped: Dict[str, Callable[..., Any]] = {}

    def __getattr__(self, name: str) -> Any:
        attr = getattr(self._api, name)
        if not callable(attr):
            return attr
        cached = self._wrapped.get(name)
        if cached is not None:
            return cached

        def wrapped(*args: Any, __attr=attr, **kwargs: Any) -> Any:
            if kwargs.get("watch"):
                return __attr(*args, **kwargs)
            return self._policy.call(__attr, *args, **kwargs)

        # full metadata, not just __name__: the real kubernetes client's
        # Watch.stream picks its deserialization return type by scanning
        # func.__doc__ for ':return:' — losing the docstring would leave
        # every watch event a raw dict and silently kill the watch plane
        functools.update_wrapper(wrapped, attr)
        self._wrapped[name] = wrapped
        return wrapped
