from nhd_tpu.k8s.interface import ClusterBackend, EventType, PodEvent, WatchEvent

__all__ = ["ClusterBackend", "EventType", "PodEvent", "WatchEvent"]
