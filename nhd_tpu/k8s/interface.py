"""Cluster backend interface: every API-server interaction behind one seam.

The reference funnels all Kubernetes I/O through the K8SMgr singleton
(K8SMgr.py:21-53) — mockable but never mocked (SURVEY §4). Here the seam is
an explicit ABC with two implementations:

* k8s.fake.FakeClusterBackend — in-memory cluster for tests, simulation and
  benchmarks (the "multi-node without a real cluster" story the reference
  lacks);
* k8s.kube.KubeClusterBackend — the real kubernetes-client backend, method
  for method the reference's K8SMgr surface.

Annotation keys and taints match the reference so both systems can coexist
on one cluster.
"""

from __future__ import annotations

from abc import ABC, abstractmethod
from dataclasses import dataclass, field
from enum import Enum
from typing import Dict, Iterable, List, Optional, Tuple

# Reference annotation/label/taint vocabulary (K8SMgr.py:139,160,182,496;
# Node.py:108; TriadController.py:19-23)
from nhd_tpu.core.node import MAINTENANCE_LABEL  # noqa: F401 — re-export seam

DOMAIN = "sigproc.viasat.io"
CFG_ANNOTATION = f"{DOMAIN}/nhd_config"
CFG_TYPE_ANNOTATION = f"{DOMAIN}/cfg_type"
GROUPS_ANNOTATION = f"{DOMAIN}/nhd_groups"
GPU_MAP_ANNOTATION_PREFIX = f"{DOMAIN}/nhd_gpu_devices"
SCHEDULER_TAINT = f"{DOMAIN}/nhd_scheduler"
NAD_ANNOTATION = "k8s.v1.cni.cncf.io/networks"

#: the election lease every replica competes for, and the lease fenced
#: writes are checked against (k8s/lease.py, docs/RESILIENCE.md "HA").
#: Under a sharded federation (k8s/lease.py shard_lease_name) each shard
#: gets its own lease derived from this name; S=1 degenerates to exactly
#: this single lease.
LEASE_NAME = "nhd-scheduler-leader"

#: cross-shard spillover record (docs/RESILIENCE.md "Federation"): one
#: JSON annotation carrying which shards already failed to place the pod
#: ("tried"), the current claim ([lease, epoch] of the shard attempting
#: it now — claims go stale the moment that lease's epoch advances), and
#: the first-spill stamp ("since", for the orphan-age metrics)
SPILLOVER_ANNOTATION = f"{DOMAIN}/nhd_spillover"

#: scheduling priority tier (policy engine, nhd_tpu/policy/ +
#: docs/SCHEDULING_POLICIES.md): integer annotation, 0/absent =
#: best-effort; higher tiers may trigger bounded preemption of strictly
#: lower tiers when unplaceable
TIER_ANNOTATION = f"{DOMAIN}/nhd_tier"

#: cross-replica trace context (docs/OBSERVABILITY.md "Federation"): one
#: JSON annotation stamped at a pod's FIRST receipt by any replica —
#: the correlation ID, the origin replica, and the receipt wall stamp.
#: Every later replica that drives the pod (spillover claim, shard
#: handoff, post-restart retry) ADOPTS the recorded corr ID instead of
#: minting its own, so N processes' flight-recorder dumps merge into one
#: journey per pod (obs/chrome.py merge_chrome_traces).
TRACE_ANNOTATION = f"{DOMAIN}/nhd_trace"


def parse_trace_record(raw: Optional[str]) -> Optional[dict]:
    """Decode a trace-context annotation; None for absence or garbage
    (a malformed record just means the next replica re-stamps — trace
    continuity is best-effort, never load-bearing for scheduling)."""
    if not raw:
        return None
    import json

    try:
        data = json.loads(raw)
        corr = str(data["corr"])
        if not corr:
            return None
        return {
            "corr": corr,
            "origin": str(data.get("origin", "")),
            "t0": float(data["t0"]) if data.get("t0") is not None else None,
        }
    except (ValueError, TypeError, KeyError):
        return None


def render_trace_record(rec: dict) -> str:
    import json

    return json.dumps({
        "corr": rec["corr"],
        "origin": rec.get("origin", ""),
        "t0": rec.get("t0"),
    }, sort_keys=True)


def parse_spill_record(raw: Optional[str]) -> dict:
    """Decode a spillover annotation; tolerant of absence and garbage
    (a malformed record reads as 'never spilled' — the pod just re-enters
    the cycle at its home shard)."""
    out: dict = {"tried": set(), "claim": None, "since": None}
    if not raw:
        return out
    import json

    try:
        data = json.loads(raw)
        out["tried"] = {int(s) for s in data.get("tried", [])}
        claim = data.get("claim")
        if claim:
            out["claim"] = (str(claim[0]), int(claim[1]))
        if data.get("since") is not None:
            out["since"] = float(data["since"])
    except (ValueError, TypeError, KeyError, IndexError):
        return {"tried": set(), "claim": None, "since": None}
    return out


def render_spill_record(rec: dict) -> str:
    import json

    return json.dumps({
        "tried": sorted(int(s) for s in rec.get("tried", ())),
        "claim": list(rec["claim"]) if rec.get("claim") else None,
        "since": rec.get("since"),
    }, sort_keys=True)


class EventType(Enum):
    NORMAL = "Normal"
    WARNING = "Warning"


class TransientBackendError(Exception):
    """A backend write failed for a *retryable* reason (429/5xx/network)
    after the in-call retry budget was spent.

    Distinct from a ``False`` return (terminal failure: the request is
    wrong, e.g. 409 on a bind) so the scheduler can requeue the pod for
    another pass instead of marking it failed (scheduler/core.py commit
    path; docs/RESILIENCE.md). Raised by KubeClusterBackend when the
    retry policy gives up on a retryable error, and by the fault-injection
    shim (sim/faults.py) to simulate exactly that."""


class StaleLeaseError(TransientBackendError):
    """A fenced write carried an epoch older than the backend's current
    lease epoch: the caller was deposed mid-commit and a newer leader has
    already taken over. Subclasses TransientBackendError so the deposed
    leader's commit path takes the existing unwind+requeue route — the
    claim rolls back locally and the NEW leader owns the pod's next
    attempt (docs/RESILIENCE.md "HA & fencing")."""


@dataclass(frozen=True)
class LeaseView:
    """Point-in-time state of a coordination lease.

    ``epoch`` is the monotonic fencing token: bumped on EVERY acquisition
    (even a same-holder re-acquisition after expiry), never reused, so a
    write stamped with epoch N can be rejected the instant any lease
    acquisition advances past N. ``expires`` is in the backend's own
    clock domain — callers compare holders and epochs, not clocks."""

    name: str
    holder: str        # "" = unheld
    epoch: int
    expires: float


@dataclass
class PodEvent:
    """A recorded scheduling event (reference: K8SMgr.py:518-559)."""

    pod: str
    namespace: str
    reason: str
    event_type: EventType
    message: str


@dataclass
class WatchEvent:
    """Backend→controller change notification (what kopf watches deliver
    in the reference, TriadController.py:41-144)."""

    kind: str                    # 'pod_create' | 'pod_delete' | 'node_update'
    name: str
    namespace: str = ""
    annotations: Dict[str, str] = field(default_factory=dict)
    labels: Dict[str, str] = field(default_factory=dict)
    old_labels: Dict[str, str] = field(default_factory=dict)
    uid: str = ""
    scheduler_name: str = ""     # pod events: spec.schedulerName
    node: str = ""               # pod events: spec.nodeName at event time
    unschedulable: bool = False
    was_unschedulable: bool = False
    taints: List[str] = field(default_factory=list)
    old_taints: List[str] = field(default_factory=list)


class ClusterBackend(ABC):
    """The K8SMgr surface (reference file:line cited per method)."""

    #: default posture for the overlapped commit pipeline
    #: (scheduler/commitpipe.py, NHD_ASYNC_COMMIT): off unless the
    #: backend's commits are real API round trips worth hiding — the
    #: kube backend flips this to True; the fake backend (tests, chaos,
    #: bench) stays synchronous so direct drives see their outcomes
    #: before attempt_scheduling_batch returns
    ASYNC_COMMIT_DEFAULT = False

    # ---- node reads ----

    @abstractmethod
    def get_nodes(self) -> List[str]:
        """Names of KubeletReady nodes (K8SMgr.py:55-69)."""

    @abstractmethod
    def is_node_active(self, node: str) -> bool:
        """Has the scheduler taint and is not unschedulable (K8SMgr.py:167-192)."""

    @abstractmethod
    def get_node_labels(self, node: str) -> Dict[str, str]:
        """(K8SMgr.py:108-110)"""

    @abstractmethod
    def get_node_addr(self, node: str) -> str:
        """First InternalIP (K8SMgr.py:91-106)."""

    @abstractmethod
    def get_node_hugepage_resources(self, node: str) -> Tuple[int, int]:
        """(capacity GiB, allocatable GiB) of 1Gi hugepages (K8SMgr.py:71-89)."""

    # ---- pod reads ----

    @abstractmethod
    def pod_exists(self, pod: str, ns: str) -> bool:
        """(K8SMgr.py:128-135)"""

    @abstractmethod
    def get_pod_node(self, pod: str, ns: str) -> Optional[str]:
        """(K8SMgr.py:112-126)"""

    @abstractmethod
    def get_pod_annotations(self, pod: str, ns: str) -> Optional[Dict[str, str]]:
        """(K8SMgr.py:194-202)"""

    def get_pod_annotations_cached(
        self, pod: str, ns: str
    ) -> Optional[Dict[str, str]]:
        """Annotations at watch-level freshness: backends with a
        watch-derived pod mirror may serve this without an API read.
        For consumers where slightly-stale is acceptable (trace-corr
        adoption) — NEVER for fenced CAS paths, which must read live.
        Default: the live read."""
        return self.get_pod_annotations(pod, ns)

    @abstractmethod
    def get_cfg_annotations(self, pod: str, ns: str) -> Optional[str]:
        """The solved-config annotation, if present (K8SMgr.py:137-150)."""

    @abstractmethod
    def get_cfg_type(self, pod: str, ns: str) -> Optional[str]:
        """(K8SMgr.py:494-506)"""

    @abstractmethod
    def get_pod_node_groups(self, pod: str, ns: str) -> List[str]:
        """Requested node groups, defaulting to ['default'] (K8SMgr.py:152-165)."""

    @abstractmethod
    def get_requested_pod_resources(self, pod: str, ns: str) -> Dict[str, str]:
        """First container's resource requests (K8SMgr.py:215-225)."""

    def get_pod_created(self, pod: str, ns: str) -> Optional[float]:
        """The pod's creationTimestamp in THIS backend's clock domain
        (``clock_now``), or None when unknown. This is the SLO engine's
        time-to-bind origin (obs/slo.py): unlike the local enqueue
        stamp, it survives spillover hops, shard handoffs, and replica
        restarts — the cluster, not any one process, owns it. Default
        None keeps duck-typed test backends working (SLO observation is
        simply skipped)."""
        return None

    def clock_now(self) -> float:
        """Now, in the same clock domain ``get_pod_created`` reports in
        (wall time against a real API server; the injectable sim clock
        on the fake). Callers compute time-to-bind as
        ``clock_now() - get_pod_created(...)`` — never by mixing in a
        local monotonic stamp."""
        import time

        return time.time()

    @abstractmethod
    def get_scheduled_pods(self, scheduler: str) -> List[Tuple[str, str, str, str]]:
        """(pod, ns, uid, phase) for pods already bound by this scheduler
        (K8SMgr.py:204-213)."""

    @abstractmethod
    def service_pods(self, scheduler: str) -> Dict[Tuple[str, str, str], Tuple[str, Optional[str]]]:
        """{(ns, pod, uid): (phase, node)} for pods requesting this
        scheduler (K8SMgr.py:227-242)."""

    @abstractmethod
    def get_cfg_map(self, pod: str, ns: str) -> Tuple[Optional[str], Optional[str]]:
        """(configmap name, first file's text) for the pod's config volume
        (K8SMgr.py:328-356)."""

    # ---- writes ----
    #
    # Every mutating call on the scheduling commit path takes an optional
    # ``epoch`` fencing token (k8s/lease.py). ``None`` means unfenced —
    # the single-replica stance, exactly the pre-HA behavior. With an
    # epoch, the backend MUST reject the write with StaleLeaseError when
    # a newer lease epoch exists, atomically with the write itself, so a
    # deposed leader's in-flight commit can never land after a standby's
    # promotion (docs/RESILIENCE.md "HA & fencing").
    #
    # ``fence_lease`` names WHICH lease the epoch is checked against —
    # under the sharded federation every write is fenced by the lease of
    # the shard owning the target node, not one global lease. ``None``
    # keeps the PR 5 single-lease behavior (the backend's default fence
    # lease).

    @abstractmethod
    def add_nad_to_pod(
        self, pod: str, ns: str, nad: str, *,
        epoch: Optional[int] = None, fence_lease: Optional[str] = None,
    ) -> bool:
        """CNI NetworkAttachmentDefinition annotation (K8SMgr.py:284-298)."""

    @abstractmethod
    def annotate_pod_config(
        self, ns: str, pod: str, cfg: str, *,
        epoch: Optional[int] = None, fence_lease: Optional[str] = None,
    ) -> bool:
        """Persist the solved config (K8SMgr.py:379-393)."""

    @abstractmethod
    def annotate_pod_gpu_map(
        self, ns: str, pod: str, gpu_map: Dict[str, int],
        *, epoch: Optional[int] = None, fence_lease: Optional[str] = None,
    ) -> bool:
        """Per-device GPU annotations (K8SMgr.py:359-376)."""

    @abstractmethod
    def annotate_pod_meta(
        self, ns: str, pod: str, key: str, value: str,
        *, epoch: Optional[int] = None, fence_lease: Optional[str] = None,
    ) -> bool:
        """One arbitrary pod annotation (rebuild addition: the spillover
        record SPILLOVER_ANNOTATION rides this). Fenced like every other
        commit-path mutator."""

    @abstractmethod
    def claim_spillover_pod(
        self, ns: str, pod: str, claim_lease: str, claim_epoch: int,
        *, epoch: Optional[int] = None, fence_lease: Optional[str] = None,
    ) -> bool:
        """Atomically claim a spilled pod for one shard's attempt: write
        ``claim = (claim_lease, claim_epoch)`` into the spillover record
        UNLESS a live foreign claim exists (a claim is live while its
        lease's current epoch still equals the claim's — a crashed or
        deposed claimant's claim goes stale the moment its shard lease
        is re-acquired, which bounds the orphan window). Returns False
        when another shard's live claim blocks us, True when the claim
        is ours (re-claiming our own claim is idempotent). Two shards
        racing the same spilled pod is the cross-shard double-bind hole;
        this is the gate that closes it."""

    @abstractmethod
    def bind_pod_to_node(
        self, pod: str, node: str, ns: str, *,
        epoch: Optional[int] = None, fence_lease: Optional[str] = None,
    ) -> bool:
        """THE schedule commit point — V1Binding (K8SMgr.py:468-492)."""

    def evict_pod(
        self, pod: str, ns: str, *,
        epoch: Optional[int] = None, fence_lease: Optional[str] = None,
    ) -> bool:
        """Preemption eviction (policy engine, nhd_tpu/policy/preempt):
        unbind the pod so it returns to Pending and can requeue — the
        solved-config annotations survive so the scheduler's unwind path
        can release the victim's claims exactly like a transient-commit
        unwind. Fenced like every other commit-path mutator (nhdlint
        NHD501: callable only through Scheduler._commit_write). Default:
        unsupported — a backend that can't evict disables preemption
        rather than faking it."""
        return False

    def get_pod_tier(self, pod: str, ns: str) -> int:
        """The pod's scheduling priority tier (TIER_ANNOTATION; 0 =
        best-effort / absent / unparseable — a malformed tier must never
        unschedule a pod, only deprioritize it)."""
        try:
            annots = self.get_pod_annotations(pod, ns)
            return max(0, int((annots or {}).get(TIER_ANNOTATION, "0")))
        except (TransientBackendError, ValueError, TypeError):
            return 0

    @abstractmethod
    def generate_pod_event(
        self, pod: str, ns: str, reason: str, event_type: EventType, message: str
    ) -> None:
        """Operator-facing audit trail, 'NHD:'-prefixed (K8SMgr.py:518-559)."""

    # ---- coordination leases (leader election, k8s/lease.py) ----
    #
    # Lease times live in the BACKEND's clock domain (the fake's
    # injectable clock for tests/chaos, wall time against a real API
    # server); callers reason about holders and epochs only.

    @abstractmethod
    def lease_try_acquire(self, name: str, holder: str, ttl: float) -> LeaseView:
        """Atomically acquire the lease if it is unheld or expired,
        bumping the fencing epoch; returns the RESULTING lease state
        either way (``view.holder == holder`` tells the caller it won).
        Losing an acquisition race is a normal outcome, not an error."""

    @abstractmethod
    def lease_renew(self, name: str, holder: str, epoch: int, ttl: float) -> bool:
        """Extend the lease iff (holder, epoch) still match the current
        record — a compare-and-swap. False means the lease was lost
        (expired and re-acquired, or force-taken): step down NOW."""

    @abstractmethod
    def lease_release(self, name: str, holder: str, epoch: int) -> bool:
        """Voluntary step-down: clear the holder iff (holder, epoch)
        still match, so a standby can acquire without waiting out the
        TTL. The epoch is NOT reset — fencing tokens never go back."""

    @abstractmethod
    def lease_read(self, name: str) -> Optional[LeaseView]:
        """Current lease state, or None when no such lease exists."""

    @abstractmethod
    def lease_live(self, name: str) -> str:
        """The holder iff the lease exists AND is unexpired, else "".
        Expiry is evaluated in the BACKEND's own clock domain — this is
        the one liveness question callers cannot answer from a LeaseView
        alone (federation membership + shard-orphan patience need it,
        k8s/lease.py ShardedElector)."""

    # ---- watch plane (consumed by the controller) ----

    @abstractmethod
    def poll_watch_events(self, timeout: float = 0.0) -> Iterable[WatchEvent]:
        """Drain pending cluster-change notifications (the kopf watch
        equivalent, TriadController.py:41-144)."""

    # ---- TriadSet support ----

    @abstractmethod
    def list_triadsets(self) -> List[dict]:
        """TriadSet CRD objects: {name, ns, replicas, service_name, template}
        (TriadController.py:87-120, deploy/triad-crd.1.16.yaml)."""

    @abstractmethod
    def list_pods_of_triadset(self, ts: dict) -> List[str]:
        """Existing pod names for a TriadSet."""

    @abstractmethod
    def create_pod_for_triadset(self, ts: dict, ordinal: int) -> bool:
        """Create the missing '{service}-{ordinal}' pod with hostname/
        subdomain patched in (TriadController.py:101-120)."""

    @abstractmethod
    def update_triadset_status(self, ts: dict, replicas: int) -> bool:
        """Write status.replicas — backs the CRD's scale subresource
        (deploy/triadset-crd.yaml; the reference declares the subresource,
        triad-crd.1.16.yaml:57-62, but never updates it). Returns success
        so callers only cache acknowledged writes."""
