"""In-process stub Kubernetes API server for contract-testing the real
HTTP path of KubeClusterBackend.

The reference's K8SMgr was hardened against a live API server (it even
codes around a kubernetes-client V1Binding deserialization quirk,
K8SMgr.py:468-492); a mocked client module can't catch payload or
serialization bugs. This stub speaks the actual REST endpoints kube.py
uses — list/read nodes and pods, ConfigMaps, strategic-merge pod
patches, pod bindings, events, pod creation, the TriadSet custom
resource, coordination.k8s.io Leases (with real resourceVersion
optimistic concurrency: a stale replace answers 409, and the
``fail_lease_puts`` hook forces conflicts for renewal-fault testing and
``fail_lease_gets`` fails reads for election/federation-liveness fault
testing),
and line-delimited watch streams — over a real HTTP socket,
records every request (method, path, content type, raw body bytes) for
byte-level assertions, and answers with faithful camelCase JSON shapes
(a binding POST returns a Status object, exactly the response that trips
the client quirk).

Watch behavior: each GET …?watch=true drains the currently queued events
as JSON lines and then closes the stream, so client reconnect loops are
exercised for real (reconnects are counted per path).

Test-facing surface: ``StubApiServer`` (start/stop, ``requests`` log,
``watch_connects``, seed helpers ``add_node``/``add_pod``/
``add_configmap``/``add_triadset``, ``queue_watch_event``) and the
``make_node``/``make_pod`` JSON builders.
"""

from __future__ import annotations

import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, List, Optional, Tuple
from urllib.parse import urlparse


def make_node(
    name: str,
    *,
    ready: bool = True,
    taint: bool = True,
    unschedulable: bool = False,
    labels: Optional[Dict[str, str]] = None,
    internal_ip: str = "10.0.0.1",
    hugepages_capacity: str = "64Gi",
    hugepages_allocatable: str = "60Gi",
) -> dict:
    """Node JSON the way an API server serves it (camelCase)."""
    taints = (
        [{"key": "sigproc.viasat.io/nhd_scheduler", "effect": "NoSchedule"}]
        if taint
        else []
    )
    return {
        "apiVersion": "v1",
        "kind": "Node",
        "metadata": {"name": name, "labels": labels or {}},
        "spec": {"taints": taints, "unschedulable": unschedulable},
        "status": {
            "conditions": [
                {
                    "type": "Ready",
                    "reason": "KubeletReady",
                    "status": "True" if ready else "False",
                }
            ],
            "addresses": [
                {"type": "Hostname", "address": name},
                {"type": "InternalIP", "address": internal_ip},
            ],
            "capacity": {"hugepages-1Gi": hugepages_capacity},
            "allocatable": {"hugepages-1Gi": hugepages_allocatable},
        },
    }


def make_pod(
    name: str,
    namespace: str = "default",
    *,
    scheduler: str = "nhd-scheduler",
    node: Optional[str] = None,
    phase: str = "Pending",
    uid: str = "uid-1",
    annotations: Optional[Dict[str, str]] = None,
    configmap: Optional[str] = None,
    requests: Optional[Dict[str, str]] = None,
) -> dict:
    volumes = (
        [{"name": "cfg", "configMap": {"name": configmap}}] if configmap else []
    )
    return {
        "apiVersion": "v1",
        "kind": "Pod",
        "metadata": {
            "name": name,
            "namespace": namespace,
            "uid": uid,
            "annotations": annotations or {},
        },
        "spec": {
            "schedulerName": scheduler,
            "nodeName": node,
            "volumes": volumes,
            "containers": [
                {"name": "main", "resources": {"requests": requests or {}}}
            ],
        },
        "status": {"phase": phase},
    }


class _Handler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"
    server: "StubApiServer"

    # quiet the default stderr access log
    def log_message(self, fmt: str, *args: Any) -> None:
        pass

    # ------------------------------------------------------------------

    def _reject_auth(self) -> bool:
        token = self.server.stub.token
        if token is None:
            return False
        if self.headers.get("Authorization") == f"Bearer {token}":
            return False
        self._send_json(401, _status(401, "Unauthorized"))
        return True

    def _send_json(self, code: int, obj: Any) -> None:
        data = json.dumps(obj).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        self.wfile.write(data)

    def _body(self) -> bytes:
        length = int(self.headers.get("Content-Length") or 0)
        return self.rfile.read(length) if length else b""

    def _record(self, body: bytes) -> None:
        stub = self.server.stub
        with stub.lock:
            stub.requests.append(
                (
                    self.command,
                    self.path,
                    self.headers.get("Content-Type", ""),
                    body,
                )
            )

    # ------------------------------------------------------------------

    def do_GET(self) -> None:  # noqa: N802 (http.server API)
        self._record(b"")
        if self._reject_auth():
            return
        url = urlparse(self.path)
        parts = [p for p in url.path.split("/") if p]
        srv = self.server.stub
        if "watch=true" in (url.query or ""):
            return self._stream_watch(url.path)
        with srv.lock:
            if srv.fail_gets > 0:
                # transient-fault injection: the next N non-watch GETs
                # answer 5xx (exercises the retry policy over the wire).
                # Checked AFTER the watch dispatch so a concurrent watch
                # reconnect can't silently eat the injected budget
                srv.fail_gets -= 1
                return self._send_json(503, _status(503, "ServiceUnavailable"))
        with srv.lock:
            # /api/v1/nodes[/name]
            if parts[:3] == ["api", "v1", "nodes"]:
                if len(parts) == 3:
                    return self._send_json(
                        200, _list("NodeList", list(srv.nodes.values()))
                    )
                node = srv.nodes.get(parts[3])
                return self._send_json(
                    200 if node else 404, node or _status(404, "NotFound")
                )
            # /api/v1/pods
            if parts[:3] == ["api", "v1", "pods"]:
                return self._send_json(
                    200, _list("PodList", list(srv.pods.values()))
                )
            # /api/v1/namespaces/{ns}/...
            if parts[:3] == ["api", "v1", "namespaces"] and len(parts) >= 5:
                ns, kind = parts[3], parts[4]
                if kind == "pods" and len(parts) == 5:
                    pods = [
                        p
                        for (pns, _), p in srv.pods.items()
                        if pns == ns
                    ]
                    return self._send_json(200, _list("PodList", pods))
                if kind == "pods":
                    pod = srv.pods.get((ns, parts[5]))
                    return self._send_json(
                        200 if pod else 404, pod or _status(404, "NotFound")
                    )
                if kind == "configmaps":
                    cm = srv.configmaps.get((ns, parts[5]))
                    return self._send_json(
                        200 if cm else 404, cm or _status(404, "NotFound")
                    )
            # /apis/{group}/{version}/{plural}
            if parts[:1] == ["apis"] and len(parts) == 4:
                return self._send_json(
                    200,
                    {
                        "apiVersion": f"{parts[1]}/{parts[2]}",
                        "kind": "TriadSetList",
                        "items": list(srv.triadsets.values()),
                    },
                )
            # GET /apis/coordination.k8s.io/v1/namespaces/{ns}/leases/{name}
            if (
                parts[:1] == ["apis"] and len(parts) == 7
                and parts[3] == "namespaces" and parts[5] == "leases"
            ):
                with srv.lock:
                    if srv.fail_lease_gets > 0:
                        srv.fail_lease_gets -= 1
                        # lease reads feed the election AND federation
                        # liveness (lease_live): a 500 here exercises the
                        # unverifiable-peer / unverifiable-shard paths
                        return self._send_json(
                            500, _status(500, "InternalError")
                        )
                lease = srv.leases.get((parts[4], parts[6]))
                return self._send_json(
                    200 if lease else 404, lease or _status(404, "NotFound")
                )
        self._send_json(404, _status(404, "NotFound"))

    def _stream_watch(self, path: str) -> None:
        srv = self.server.stub
        with srv.lock:
            srv.watch_connects[path] = srv.watch_connects.get(path, 0) + 1
            pending = srv.watch_events.get(path, [])
            srv.watch_events[path] = []
            hang = srv.watch_hang
        self.send_response(200)
        self.send_header("Content-Type", "application/json")
        self.send_header("Transfer-Encoding", "chunked")
        self.end_headers()
        try:
            for ev in pending:
                # raw entries (queue_watch_raw) go on the wire verbatim —
                # malformed-line fault injection
                line = (
                    ev if isinstance(ev, bytes)
                    else json.dumps(ev).encode() + b"\n"
                )
                self.wfile.write(f"{len(line):x}\r\n".encode() + line + b"\r\n")
            if hang:
                # dead-socket simulation: stream stays open, silent — the
                # client's finite read timeout must end it (in slices so
                # stop() doesn't wait the full hang out)
                deadline = time.monotonic() + hang
                while time.monotonic() < deadline and not srv.closing:
                    time.sleep(0.05)
            # terminal chunk: server closes the stream, client reconnects
            self.wfile.write(b"0\r\n\r\n")
        except OSError:
            pass  # client gave up mid-stream (e.g. its read timed out)
        self.close_connection = True

    def do_PATCH(self) -> None:  # noqa: N802
        body = self._body()
        self._record(body)
        if self._reject_auth():
            return
        parts = [p for p in urlparse(self.path).path.split("/") if p]
        srv = self.server.stub
        patch = json.loads(body or b"{}")
        with srv.lock:
            # strategic-merge patch on a pod: merge metadata.annotations
            if parts[:3] == ["api", "v1", "namespaces"] and len(parts) == 6 \
                    and parts[4] == "pods":
                pod = srv.pods.get((parts[3], parts[5]))
                if pod is None:
                    return self._send_json(404, _status(404, "NotFound"))
                if srv.fail_patches:
                    return self._send_json(
                        500, _status(500, "InternalError")
                    )
                annots = (patch.get("metadata") or {}).get("annotations") or {}
                pod["metadata"].setdefault("annotations", {}).update(annots)
                return self._send_json(200, pod)
            # merge patch on a TriadSet status subresource
            if parts[:1] == ["apis"] and len(parts) == 8 and parts[7] == "status":
                ts = srv.triadsets.get((parts[4], parts[6]))
                if ts is None:
                    return self._send_json(404, _status(404, "NotFound"))
                ts.setdefault("status", {}).update(patch.get("status") or {})
                return self._send_json(200, ts)
        self._send_json(404, _status(404, "NotFound"))

    def do_PUT(self) -> None:  # noqa: N802
        """Lease replace with the API server's optimistic concurrency:
        a body whose metadata.resourceVersion is stale answers 409, and
        the ``fail_lease_puts`` fault hook forces the next N replaces to
        409 regardless — the conflict-on-renew injection
        (tests/test_kube_faults.py)."""
        body = self._body()
        self._record(body)
        if self._reject_auth():
            return
        parts = [p for p in urlparse(self.path).path.split("/") if p]
        srv = self.server.stub
        payload = json.loads(body or b"{}")
        with srv.lock:
            if not (
                parts[:1] == ["apis"] and len(parts) == 7
                and parts[3] == "namespaces" and parts[5] == "leases"
            ):
                return self._send_json(404, _status(404, "NotFound"))
            key = (parts[4], parts[6])
            lease = srv.leases.get(key)
            if lease is None:
                return self._send_json(404, _status(404, "NotFound"))
            if srv.fail_lease_puts > 0:
                srv.fail_lease_puts -= 1
                return self._send_json(409, _status(409, "Conflict"))
            sent_rv = (payload.get("metadata") or {}).get("resourceVersion")
            cur_rv = lease["metadata"].get("resourceVersion")
            if sent_rv != cur_rv:
                return self._send_json(409, _status(409, "Conflict"))
            payload.setdefault("metadata", {})["resourceVersion"] = str(
                int(cur_rv) + 1
            )
            srv.leases[key] = payload
            return self._send_json(200, payload)

    def do_POST(self) -> None:  # noqa: N802
        body = self._body()
        self._record(body)
        if self._reject_auth():
            return
        parts = [p for p in urlparse(self.path).path.split("/") if p]
        srv = self.server.stub
        payload = json.loads(body or b"{}")
        with srv.lock:
            # POST /apis/coordination.k8s.io/v1/namespaces/{ns}/leases
            if (
                parts[:1] == ["apis"] and len(parts) == 6
                and parts[3] == "namespaces" and parts[5] == "leases"
            ):
                name = (payload.get("metadata") or {}).get("name")
                if not name:
                    return self._send_json(400, _status(400, "BadRequest"))
                key = (parts[4], name)
                if key in srv.leases:
                    return self._send_json(409, _status(409, "Conflict"))
                payload["metadata"]["resourceVersion"] = "1"
                payload["metadata"].setdefault("namespace", parts[4])
                srv.leases[key] = payload
                return self._send_json(201, payload)
            if parts[:3] != ["api", "v1", "namespaces"]:
                return self._send_json(404, _status(404, "NotFound"))
            ns = parts[3]
            # POST …/pods/{name}/binding
            if len(parts) == 7 and parts[4] == "pods" and parts[6] == "binding":
                pod = srv.pods.get((ns, parts[5]))
                if pod is None:
                    return self._send_json(404, _status(404, "NotFound"))
                if srv.fail_bindings:
                    return self._send_json(409, _status(409, "Conflict"))
                srv.bindings.append((ns, parts[5], payload))
                pod["spec"]["nodeName"] = (payload.get("target") or {}).get(
                    "name"
                )
                # a real API server answers a binding create with Status —
                # the response that trips the client's V1Binding quirk
                return self._send_json(201, _status(201, "Created"))
            # POST …/events
            if len(parts) == 5 and parts[4] == "events":
                srv.events.append(payload)
                return self._send_json(201, payload)
            # POST …/pods (TriadSet pod creation)
            if len(parts) == 5 and parts[4] == "pods":
                name = (payload.get("metadata") or {}).get("name")
                if not name:
                    return self._send_json(400, _status(400, "BadRequest"))
                payload["metadata"].setdefault("namespace", ns)
                payload.setdefault("status", {"phase": "Pending"})
                srv.pods[(ns, name)] = payload
                return self._send_json(201, payload)
        self._send_json(404, _status(404, "NotFound"))


def _status(code: int, reason: str) -> dict:
    return {
        "apiVersion": "v1",
        "kind": "Status",
        "status": "Failure" if code >= 400 else "Success",
        "code": code,
        "reason": reason,
    }


def _list(kind: str, items: List[dict]) -> dict:
    return {"apiVersion": "v1", "kind": kind, "items": items}


class StubApiServer:
    """Threaded stub API server bound to 127.0.0.1:<ephemeral>."""

    def __init__(self, token: Optional[str] = None):
        self.nodes: Dict[str, dict] = {}
        self.pods: Dict[Tuple[str, str], dict] = {}
        self.configmaps: Dict[Tuple[str, str], dict] = {}
        self.triadsets: Dict[Tuple[str, str], dict] = {}
        self.events: List[dict] = []
        self.bindings: List[Tuple[str, str, dict]] = []
        self.requests: List[Tuple[str, str, str, bytes]] = []
        self.watch_events: Dict[str, List[dict]] = {}
        self.watch_connects: Dict[str, int] = {}
        self.leases: Dict[Tuple[str, str], dict] = {}
        self.fail_patches = False
        self.fail_bindings = False
        self.fail_gets = 0      # next N GETs answer 503 (retry testing)
        self.fail_lease_puts = 0  # next N lease replaces answer 409
        #                          (conflict-on-renew fault injection)
        self.fail_lease_gets = 0  # next N lease GETs answer 500 (election
        #                          + federation-liveness fault injection)
        self.watch_hang = 0.0   # seconds a watch stream stays open, silent
        self.closing = False
        self.token = token
        self.lock = threading.RLock()
        self._httpd = ThreadingHTTPServer(("127.0.0.1", 0), _Handler)
        # the handler reads ALL state through this one reference, so
        # post-construction mutation of any stub attribute just works
        self._httpd.stub = self
        # short poll so stop() returns promptly (the default 0.5 s poll
        # costs every stub-based test its teardown)
        self._thread = threading.Thread(
            target=lambda: self._httpd.serve_forever(poll_interval=0.05),
            daemon=True,
        )

    @property
    def port(self) -> int:
        return self._httpd.server_address[1]

    def start(self) -> "StubApiServer":
        self._thread.start()
        return self

    def stop(self) -> None:
        self.closing = True  # unblocks any hanging watch handler
        self._httpd.shutdown()
        self._httpd.server_close()
        self._thread.join(timeout=5)

    # ---- seed helpers ----

    def add_node(self, name: str, **kw: Any) -> dict:
        node = make_node(name, **kw)
        with self.lock:
            self.nodes[name] = node
        return node

    def add_pod(self, name: str, namespace: str = "default", **kw: Any) -> dict:
        pod = make_pod(name, namespace, **kw)
        with self.lock:
            self.pods[(namespace, name)] = pod
        return pod

    def add_configmap(
        self, name: str, namespace: str, data: Dict[str, str]
    ) -> None:
        with self.lock:
            self.configmaps[(namespace, name)] = {
                "apiVersion": "v1",
                "kind": "ConfigMap",
                "metadata": {"name": name, "namespace": namespace},
                "data": data,
            }

    def add_triadset(
        self,
        name: str,
        namespace: str,
        *,
        replicas: int,
        service_name: Optional[str] = None,
        template: Optional[dict] = None,
    ) -> None:
        with self.lock:
            self.triadsets[(namespace, name)] = {
                "apiVersion": "sigproc.viasat.io/v1",
                "kind": "TriadSet",
                "metadata": {"name": name, "namespace": namespace},
                "spec": {
                    "replicas": replicas,
                    "serviceName": service_name or name,
                    "template": template or {},
                },
            }

    def queue_watch_event(self, path: str, ev_type: str, obj: dict) -> None:
        """Queue one watch event; the next GET <path>?watch=true drains it."""
        with self.lock:
            self.watch_events.setdefault(path, []).append(
                {"type": ev_type, "object": obj}
            )

    def queue_watch_raw(self, path: str, raw: bytes) -> None:
        """Queue raw bytes as one watch line — malformed-line injection
        (a garbled chunk as the client would see it after a mid-cut)."""
        with self.lock:
            self.watch_events.setdefault(path, []).append(raw)
