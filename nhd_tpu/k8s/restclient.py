"""Minimal kubernetes-client-compatible REST client over real HTTP.

The reference talks to the API server through the `kubernetes` package
(K8SMgr.py:9,44-48). That package isn't a baked-in dependency here, so —
exactly like config/libconfig.py replaces the libconf dependency — this
module implements the *subset of the kubernetes-client surface that
k8s/kube.py actually uses*, speaking genuine HTTP+JSON to an API server:

* ``client``: CoreV1Api / CustomObjectsApi, the request models
  (V1Binding, V1ObjectMeta, V1ObjectReference, CoreV1Event,
  V1EventSource), and ``client.exceptions.ApiException``;
* ``config``: load_incluster_config / load_kube_config;
* ``watch``: Watch with a reconnectable ``stream()``.

k8s/kube.py prefers the real ``kubernetes`` package when importable and
falls back to this module otherwise, so the backend works (and is
contract-tested over real HTTP, tests/test_kube_http.py) in hermetic
environments.

Wire-format notes (all mirroring the real client):

* response JSON is exposed as objects whose snake_case attributes map to
  camelCase JSON fields (``pod.spec.scheduler_name`` ⇒
  ``spec.schedulerName``), with dict-style access for map-valued fields
  (labels/annotations/capacity/data);
* pod patches are ``application/strategic-merge-patch+json``, custom
  object status patches ``application/merge-patch+json``
  (the real client's defaults for these calls);
* POST …/binding deliberately reproduces the kubernetes-client quirk the
  reference codes around (K8SMgr.py:487-491): the API server answers a
  binding create with a Status object, the client tries to deserialize
  it into the request model and raises ValueError — callers must treat
  ValueError after a 2xx as success, which k8s/kube.py does.
"""

from __future__ import annotations

import datetime as _dt
import http.client as _httplib
import json as _json
import logging as _logging
import os
import re
import ssl
import types
import urllib.error
import urllib.request
from typing import Any, Dict, Iterator, Optional

from nhd_tpu.k8s.retry import API_COUNTERS

_logger = _logging.getLogger(__name__)


# ---------------------------------------------------------------------------
# exceptions
# ---------------------------------------------------------------------------


class ApiException(Exception):
    """Mirror of kubernetes.client.exceptions.ApiException."""

    def __init__(self, status: int = 0, reason: str = "", body: str = "",
                 headers: Optional[Dict[str, str]] = None):
        super().__init__(f"({status}) Reason: {reason}")
        self.status = status
        self.reason = reason
        self.body = body
        # response headers (Retry-After drives the retry policy's backoff
        # floor, k8s/retry.py)
        self.headers = dict(headers) if headers else {}


class ConfigException(Exception):
    """Mirror of kubernetes.config.ConfigException."""


# ---------------------------------------------------------------------------
# response objects: snake_case attributes over camelCase JSON
# ---------------------------------------------------------------------------

_SNAKE_RE = re.compile(r"_([a-z])")


def _snake_to_camel(name: str) -> str:
    return _SNAKE_RE.sub(lambda m: m.group(1).upper(), name)


def _wrap(value: Any) -> Any:
    if isinstance(value, dict):
        return K8sObj(value)
    if isinstance(value, list):
        return [_wrap(v) for v in value]
    return value


class K8sObj:
    """JSON response wrapper.

    Attribute access converts snake_case to camelCase and wraps nested
    structures (``obj.spec.node_name``); mapping access (get/keys/values/
    iteration/``dict()``) returns *raw* values, which is what callers do
    with labels/annotations/capacity/ConfigMap data. ``items`` is a JSON
    field (list responses), not the dict method, so no ``items()`` method
    is defined.
    """

    __slots__ = ("_data",)

    def __init__(self, data: Dict[str, Any]):
        object.__setattr__(self, "_data", data)

    # --- attribute access (model-object style) ---

    def __getattr__(self, name: str) -> Any:
        data = object.__getattribute__(self, "_data")
        for key in (_snake_to_camel(name), name):
            if key in data:
                return _wrap(data[key])
        return None

    # --- mapping access (dict-valued fields) ---

    def __getitem__(self, key: str) -> Any:
        return object.__getattribute__(self, "_data")[key]

    def get(self, key: str, default: Any = None) -> Any:
        return object.__getattribute__(self, "_data").get(key, default)

    def keys(self):
        return object.__getattribute__(self, "_data").keys()

    def values(self):
        return object.__getattribute__(self, "_data").values()

    def __iter__(self):
        return iter(object.__getattribute__(self, "_data"))

    def __contains__(self, key: str) -> bool:
        return key in object.__getattribute__(self, "_data")

    def __len__(self) -> int:
        return len(object.__getattribute__(self, "_data"))

    def __bool__(self) -> bool:
        return bool(object.__getattribute__(self, "_data"))

    def __repr__(self) -> str:
        return f"K8sObj({object.__getattribute__(self, '_data')!r})"

    def to_dict(self) -> Dict[str, Any]:
        return dict(object.__getattribute__(self, "_data"))


# ---------------------------------------------------------------------------
# request models (the ones k8s/kube.py constructs)
# ---------------------------------------------------------------------------


def _serialize(value: Any) -> Any:
    """Model/python value → JSON value (camelCase keys, RFC3339 times,
    None fields dropped) — the real client's sanitize_for_serialization."""
    if isinstance(value, _Model):
        out = {}
        for k, v in value.__dict__.items():
            if v is None:
                continue
            out[_snake_to_camel(k)] = _serialize(v)
        return out
    if isinstance(value, _dt.datetime):
        return value.isoformat().replace("+00:00", "Z")
    if isinstance(value, dict):
        # None values in plain dicts are kept: an explicit null in a
        # merge patch deletes the key (only unset *model* attributes are
        # dropped, matching the real client's sanitize_for_serialization)
        return {k: _serialize(v) for k, v in value.items()}
    if isinstance(value, (list, tuple)):
        return [_serialize(v) for v in value]
    return value


class _Model:
    """kwargs-bag base for request models; snake_case kwargs serialize to
    camelCase JSON via _serialize."""

    _required: tuple = ()

    def __init__(self, **kwargs: Any):
        self.__dict__.update(kwargs)


class V1ObjectMeta(_Model):
    pass


class V1ObjectReference(_Model):
    pass


class V1Binding(_Model):
    pass


class V1EventSource(_Model):
    pass


class CoreV1Event(_Model):
    pass


# ---------------------------------------------------------------------------
# configuration
# ---------------------------------------------------------------------------


class Configuration:
    def __init__(self, host: str, token: Optional[str] = None,
                 ca_file: Optional[str] = None, verify_ssl: bool = True,
                 token_file: Optional[str] = None):
        self.host = host.rstrip("/")
        self.token = token
        # bound SA tokens rotate on disk (k8s 1.21+); when a file is known,
        # the HTTP layer re-reads it per request so credentials never go
        # stale in a long-lived scheduler process
        self.token_file = token_file
        self.ca_file = ca_file
        self.verify_ssl = verify_ssl

    def current_token(self) -> Optional[str]:
        if self.token_file:
            try:
                with open(self.token_file) as f:
                    fresh = f.read().strip()
                if fresh:
                    self.token = fresh
            except OSError:
                pass  # keep the last good token
        return self.token


_active_config: Optional[Configuration] = None


def _set_config(cfg: Configuration) -> None:
    global _active_config
    _active_config = cfg


_SA_DIR = "/var/run/secrets/kubernetes.io/serviceaccount"


def load_incluster_config() -> None:
    """Env + mounted-serviceaccount config (in-pod). Raises ConfigException
    outside a cluster so callers can fall back to kubeconfig, matching the
    reference's pattern (K8SMgr.py:43-46)."""
    host = os.environ.get("KUBERNETES_SERVICE_HOST")
    port = os.environ.get("KUBERNETES_SERVICE_PORT")
    if not host or not port:
        raise ConfigException(
            "Service host/port is not set (not running in a cluster)"
        )
    scheme = os.environ.get("KUBERNETES_SERVICE_SCHEME", "https")
    if ":" in host and not host.startswith("["):  # bare IPv6
        host = f"[{host}]"
    token = None
    token_file = os.environ.get("NHD_K8S_TOKEN_FILE", f"{_SA_DIR}/token")
    if os.path.exists(token_file):
        with open(token_file) as f:
            token = f.read().strip()
    else:
        token_file = None
    ca = f"{_SA_DIR}/ca.crt"
    _set_config(Configuration(
        f"{scheme}://{host}:{port}", token=token, token_file=token_file,
        ca_file=ca if os.path.exists(ca) else None,
    ))


def load_kube_config(config_file: Optional[str] = None) -> None:
    """Minimal kubeconfig loader: current-context cluster server + user
    token; TLS verification honors insecure-skip-tls-verify."""
    import yaml

    path = config_file or os.environ.get(
        "KUBECONFIG", os.path.expanduser("~/.kube/config")
    )
    if not os.path.exists(path):
        raise ConfigException(f"kubeconfig not found: {path}")
    with open(path) as f:
        doc = yaml.safe_load(f) or {}

    def by_name(section: str, name: str) -> dict:
        for entry in doc.get(section, []) or []:
            if entry.get("name") == name:
                return entry
        return {}

    ctx_name = doc.get("current-context", "")
    ctx = by_name("contexts", ctx_name).get("context", {})
    cluster = by_name("clusters", ctx.get("cluster", "")).get("cluster", {})
    user = by_name("users", ctx.get("user", "")).get("user", {})
    server = cluster.get("server")
    if not server:
        raise ConfigException(f"no cluster server in {path}")
    token = user.get("token")
    if not token and (
        user.get("client-certificate-data") or user.get("client-certificate")
    ):
        # cert-auth kubeconfigs (kubeadm default) aren't supported by this
        # minimal loader — fail loudly rather than send unauthenticated
        # requests that 401/403 confusingly later
        raise ConfigException(
            "kubeconfig uses client-certificate auth, which the minimal "
            "restclient does not support; use a token-based user or the "
            "real kubernetes package"
        )
    _set_config(Configuration(
        server, token=token,
        ca_file=cluster.get("certificate-authority"),
        verify_ssl=not cluster.get("insecure-skip-tls-verify", False),
    ))


# ---------------------------------------------------------------------------
# HTTP core
# ---------------------------------------------------------------------------

_DEFAULT_TIMEOUT = 30.0

# Finite socket timeout for watch streams. The old behavior (timeout=None)
# meant a silently dead socket — NAT reset with no FIN, crashed LB — blocked
# the watch thread FOREVER with no events and no error. A quiet-but-alive
# watch simply times out too: Watch.stream translates the read timeout into
# a normal stream end, and the reconnect loop in k8s/kube.py resumes from
# the tracked resourceVersion (no replay). 60s matches the order of the API
# server's own --min-request-timeout stream recycling.
_WATCH_READ_TIMEOUT = float(os.environ.get("NHD_WATCH_READ_TIMEOUT", "60"))


class _HttpClient:
    def __init__(self, cfg: Configuration):
        self.cfg = cfg

    def _context(self) -> Optional[ssl.SSLContext]:
        if not self.cfg.host.startswith("https"):
            return None
        if not self.cfg.verify_ssl:
            return ssl._create_unverified_context()
        ctx = ssl.create_default_context()
        if self.cfg.ca_file:
            ctx.load_verify_locations(self.cfg.ca_file)
        return ctx

    def request(
        self,
        method: str,
        path: str,
        *,
        body: Optional[Any] = None,
        content_type: str = "application/json",
        stream: bool = False,
        timeout: Optional[float] = _DEFAULT_TIMEOUT,
    ) -> Any:
        """One API call. Non-stream: parsed JSON (or None on an empty
        body). Stream: the raw response object (chunked decoding handled
        by http.client; iterate lines, close when done). Non-2xx raises
        ApiException."""
        data = None
        headers = {"Accept": "application/json"}
        if body is not None:
            data = _json.dumps(body).encode()
            headers["Content-Type"] = content_type
        token = self.cfg.current_token()
        if token:
            headers["Authorization"] = f"Bearer {token}"
        req = urllib.request.Request(
            self.cfg.host + path, data=data, headers=headers, method=method
        )
        try:
            resp = urllib.request.urlopen(
                req, timeout=_WATCH_READ_TIMEOUT if stream else timeout,
                context=self._context(),
            )
        except urllib.error.HTTPError as exc:
            raise ApiException(
                status=exc.code, reason=exc.reason,
                body=exc.read().decode(errors="replace"),
                headers=dict(exc.headers or {}),
            ) from None
        except urllib.error.URLError as exc:
            raise ApiException(status=0, reason=str(exc.reason)) from None
        if stream:
            return resp
        with resp:
            raw = resp.read()
        return _json.loads(raw) if raw else None


def _api_http() -> _HttpClient:
    if _active_config is None:
        raise ConfigException(
            "no configuration loaded: call config.load_incluster_config() "
            "or config.load_kube_config() first"
        )
    return _HttpClient(_active_config)


# ---------------------------------------------------------------------------
# CoreV1Api — exactly the calls k8s/kube.py makes
# ---------------------------------------------------------------------------


class CoreV1Api:
    def __init__(self) -> None:
        self._http = _api_http()

    # -- reads --

    def list_node(
        self, *, watch: bool = False, resource_version: Optional[str] = None
    ):
        if watch:
            path = "/api/v1/nodes?watch=true"
            if resource_version:
                path += f"&resourceVersion={resource_version}"
            return self._http.request("GET", path, stream=True)
        return K8sObj(self._http.request("GET", "/api/v1/nodes"))

    def read_node(self, name: str) -> K8sObj:
        return K8sObj(self._http.request("GET", f"/api/v1/nodes/{name}"))

    def read_namespaced_pod(self, name: str, namespace: str) -> K8sObj:
        return K8sObj(self._http.request(
            "GET", f"/api/v1/namespaces/{namespace}/pods/{name}"
        ))

    def list_pod_for_all_namespaces(
        self, *, watch: bool = False, resource_version: Optional[str] = None
    ):
        if watch:
            path = "/api/v1/pods?watch=true"
            if resource_version:
                path += f"&resourceVersion={resource_version}"
            return self._http.request("GET", path, stream=True)
        return K8sObj(self._http.request("GET", "/api/v1/pods"))

    def list_namespaced_pod(self, namespace: str) -> K8sObj:
        return K8sObj(self._http.request(
            "GET", f"/api/v1/namespaces/{namespace}/pods"
        ))

    def read_namespaced_config_map(self, name: str, namespace: str) -> K8sObj:
        return K8sObj(self._http.request(
            "GET", f"/api/v1/namespaces/{namespace}/configmaps/{name}"
        ))

    # -- writes --

    def patch_namespaced_pod(self, name: str, namespace: str, body: Any) -> K8sObj:
        return K8sObj(self._http.request(
            "PATCH", f"/api/v1/namespaces/{namespace}/pods/{name}",
            body=_serialize(body),
            content_type="application/strategic-merge-patch+json",
        ))

    def create_namespaced_pod_binding(
        self, name: str, namespace: str, body: V1Binding
    ) -> Any:
        resp = self._http.request(
            "POST", f"/api/v1/namespaces/{namespace}/pods/{name}/binding",
            body=_serialize(body),
        )
        # Faithful reproduction of the kubernetes-client quirk the
        # reference codes around (K8SMgr.py:487-491): the API server
        # answers with a Status object; deserializing it into the V1Binding
        # response model trips on the missing required 'target'.
        if not isinstance(resp, dict) or "target" not in resp:
            raise ValueError(
                "Invalid value for `target`, must not be `None`"
            )
        return K8sObj(resp)

    def create_namespaced_event(self, namespace: str, body: CoreV1Event) -> K8sObj:
        return K8sObj(self._http.request(
            "POST", f"/api/v1/namespaces/{namespace}/events",
            body=_serialize(body),
        ))

    def create_namespaced_pod(self, namespace: str, body: Any) -> K8sObj:
        return K8sObj(self._http.request(
            "POST", f"/api/v1/namespaces/{namespace}/pods",
            body=_serialize(body),
        ))


class CustomObjectsApi:
    def __init__(self) -> None:
        self._http = _api_http()

    def list_cluster_custom_object(
        self, group: str, version: str, plural: str
    ) -> dict:
        # the real client returns plain JSON for custom objects
        return self._http.request("GET", f"/apis/{group}/{version}/{plural}")

    def patch_namespaced_custom_object_status(
        self, group: str, version: str, namespace: str, plural: str,
        name: str, body: Any,
    ) -> dict:
        return self._http.request(
            "PATCH",
            f"/apis/{group}/{version}/namespaces/{namespace}/{plural}/"
            f"{name}/status",
            body=_serialize(body),
            content_type="application/merge-patch+json",
        )

    # namespaced get/create/replace — the real client's generic custom-
    # object surface, which kube.py also uses for coordination.k8s.io
    # Lease objects (leader election, k8s/lease.py): plain-JSON shapes on
    # both client paths, and replace() carries metadata.resourceVersion so
    # the API server's optimistic concurrency (409 Conflict) is the CAS.

    def get_namespaced_custom_object(
        self, group: str, version: str, namespace: str, plural: str,
        name: str,
    ) -> dict:
        return self._http.request(
            "GET",
            f"/apis/{group}/{version}/namespaces/{namespace}/{plural}/{name}",
        )

    def create_namespaced_custom_object(
        self, group: str, version: str, namespace: str, plural: str,
        body: Any,
    ) -> dict:
        return self._http.request(
            "POST",
            f"/apis/{group}/{version}/namespaces/{namespace}/{plural}",
            body=_serialize(body),
        )

    def replace_namespaced_custom_object(
        self, group: str, version: str, namespace: str, plural: str,
        name: str, body: Any,
    ) -> dict:
        return self._http.request(
            "PUT",
            f"/apis/{group}/{version}/namespaces/{namespace}/{plural}/{name}",
            body=_serialize(body),
        )


# ---------------------------------------------------------------------------
# watch
# ---------------------------------------------------------------------------


class Watch:
    """Line-delimited JSON watch stream. The generator ends when the server
    closes the connection; callers reconnect by looping (k8s/kube.py wraps
    stream() in ``while True``, like kopf's own reconnect loop).

    ``resource_version`` is tracked across stream() calls on the same Watch
    — reconnects resume from the last seen event instead of replaying
    synthetic ADDED events for every live object (the real client's
    behavior)."""

    def __init__(self) -> None:
        self._stopped = False
        self._resp = None
        self.resource_version: Optional[str] = None

    def stream(self, func, **kwargs) -> Iterator[dict]:
        if self.resource_version and "resource_version" not in kwargs:
            kwargs["resource_version"] = self.resource_version
        try:
            resp = func(watch=True, **kwargs)
        except ApiException as exc:
            if exc.status == 410:
                # 410 Gone: our resourceVersion fell out of the etcd
                # compaction window — forget it so the next reconnect
                # starts a fresh (full-replay) watch instead of retrying
                # the stale version forever
                self.resource_version = None
            raise
        self._resp = resp
        try:
            for line in resp:
                if self._stopped:
                    break
                line = line.strip()
                if not line:
                    continue
                try:
                    ev = _json.loads(line)
                except ValueError:
                    # one garbled chunk (routine on a mid-stream cut) must
                    # not raise JSONDecodeError out of the generator and
                    # kill the watch thread: drop the line, end the stream,
                    # let the caller's reconnect loop start a fresh watch
                    API_COUNTERS.inc("watch_malformed_lines_total")
                    _logger.warning(
                        "malformed watch line (%d bytes); dropping and "
                        "ending stream for reconnect", len(line)
                    )
                    break
                obj = ev.get("object", {})
                rv = (obj.get("metadata") or {}).get("resourceVersion")
                if rv:
                    self.resource_version = rv
                yield {"type": ev.get("type"), "object": _wrap(obj)}
        except (OSError, _httplib.HTTPException) as exc:
            # the finite socket timeout (silently dead peer) or a torn
            # chunked read surfaces here mid-iteration — translate into a
            # normal stream end so the reconnect loop takes over instead
            # of the error escaping the generator. A plain timeout is
            # routine stream recycling on a quiet cluster: INFO, not a
            # warning per idle minute
            API_COUNTERS.inc("watch_read_timeouts_total")
            log = (_logger.info if isinstance(exc, TimeoutError)
                   else _logger.warning)
            log(f"watch stream read ended ({exc!r}); reconnecting")
        finally:
            try:
                resp.close()
            except Exception:  # nhdlint: ignore[NHD302]
                pass  # best-effort close of an already-broken stream
            self._resp = None

    def stop(self) -> None:
        self._stopped = True
        if self._resp is not None:
            try:
                self._resp.close()
            except Exception:  # nhdlint: ignore[NHD302]
                pass  # racing the reader's own close; either one wins


# ---------------------------------------------------------------------------
# package-shaped namespaces so `from ... import client, config, watch` works
# ---------------------------------------------------------------------------

client = types.SimpleNamespace(
    CoreV1Api=CoreV1Api,
    CustomObjectsApi=CustomObjectsApi,
    V1ObjectMeta=V1ObjectMeta,
    V1ObjectReference=V1ObjectReference,
    V1Binding=V1Binding,
    V1EventSource=V1EventSource,
    CoreV1Event=CoreV1Event,
    Configuration=Configuration,
    exceptions=types.SimpleNamespace(ApiException=ApiException),
)

config = types.SimpleNamespace(
    load_incluster_config=load_incluster_config,
    load_kube_config=load_kube_config,
    ConfigException=ConfigException,
)

watch = types.SimpleNamespace(Watch=Watch)
