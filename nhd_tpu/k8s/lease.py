"""Lease-based leader election with monotonic fencing epochs.

The reference NHD is a single replica whose whole availability story is
"crash-only + Deployment restart" (bin/nhd:43-56): a wedged or restarting
scheduler means NO scheduler until the kubelet notices. This module lets
two or more replicas run safely:

* :class:`LeaderElector` — acquire/renew/step-down over the
  ``ClusterBackend`` lease seam (interface.py). Backed by
  ``FakeClusterBackend`` state for tests and chaos, by
  coordination.k8s.io/v1 Lease objects through ``kube.py`` (under the
  retry layer) on a real cluster. Every acquisition bumps a monotonic
  **fencing epoch**; the scheduler stamps it onto every mutating commit
  (scheduler/core.py ``_commit_write``) and backends reject stale epochs
  atomically, so a deposed leader's in-flight batch cannot land.
* :class:`LeaseKeeper` — the daemon thread that ticks an elector at the
  renew cadence (the production driver; tests tick by hand).
* :class:`StallWatchdog` — observes the scheduling loop's heartbeat
  (``Scheduler.last_heartbeat``, the same loop the flight-recorder spans
  are emitted from). A loop wedged past the stall budget voluntarily
  releases the lease and exits crash-only, so a standby replica takes
  over in one renew interval instead of a liveness-probe eternity.

Renewal semantics (the client-go shape): a renewal that *errors*
(TransientBackendError — the API server is unreachable) is tolerated
while the last successful renewal is younger than the TTL — the lease
can't have expired yet, so leadership is still provably ours. Past the
TTL the elector demotes itself WITHOUT waiting for proof: it can no
longer distinguish "server down" from "deposed", and acting without a
live lease is exactly the split-brain this module exists to prevent. A
renewal that *returns False* (the compare-and-swap lost: someone else
holds the lease, or the epoch moved) demotes immediately.

Everything is injectable (clock, counters) so election is unit-tested
without a single real sleep (tests/test_ha.py, same pattern as
tests/test_retry.py).
"""

from __future__ import annotations

import os
import threading
import time
from typing import Callable, Optional

from nhd_tpu.k8s.interface import LEASE_NAME, ClusterBackend, TransientBackendError
from nhd_tpu.k8s.retry import API_COUNTERS, ApiCounters
from nhd_tpu.utils import get_logger

# production cadence knobs (docs/OPERATIONS.md "High availability"):
# renew several times per TTL so one flaky renewal never costs leadership
LEASE_TTL_SEC = float(os.environ.get("NHD_LEASE_TTL", "15"))
LEASE_RENEW_SEC = float(os.environ.get("NHD_LEASE_RENEW_SEC", "4"))
# the stall budget: how long the scheduling loop may go without a
# heartbeat before the watchdog releases the lease and exits crash-only.
# The loop beats at least every Q_BLOCK_TIME_SEC (0.5 s) when healthy,
# plus however long one batch solve+commit legitimately takes — size the
# budget for the worst legitimate batch, not the idle cadence.
WATCHDOG_STALL_SEC = float(os.environ.get("NHD_WATCHDOG_STALL_SEC", "120"))
WATCHDOG_POLL_SEC = float(os.environ.get("NHD_WATCHDOG_POLL_SEC", "5"))


class LeaderElector:
    """One replica's view of the election: FOLLOWER until an acquisition
    wins, LEADER until a renewal proves otherwise.

    ``tick()`` is the whole protocol — call it every ``renew_interval``
    (LeaseKeeper does, chaos/tests do it by hand). ``is_leader`` /
    ``fencing_epoch()`` are thread-safe snapshots for the scheduler and
    its commit-pool threads; state only CHANGES inside ``tick()`` and
    ``step_down()``, so a replica that believes it leads keeps believing
    so between ticks — which is precisely the split-brain window the
    fencing epochs exist to make harmless.
    """

    def __init__(
        self,
        backend: ClusterBackend,
        *,
        identity: str,
        lease_name: str = LEASE_NAME,
        ttl: float = LEASE_TTL_SEC,
        clock: Callable[[], float] = time.monotonic,
        counters: ApiCounters = API_COUNTERS,
    ):
        if ttl <= 0:
            raise ValueError(f"lease ttl must be > 0, got {ttl}")
        self.backend = backend
        self.identity = identity
        self.lease_name = lease_name
        self.ttl = ttl
        self.logger = get_logger(__name__)
        self._clock = clock
        self._counters = counters
        self._lock = threading.Lock()
        self._leader = False
        self._epoch = 0           # last epoch we led under (never rewinds)
        self._last_renew_ok = 0.0

    # -- thread-safe snapshots -----------------------------------------

    @property
    def is_leader(self) -> bool:
        with self._lock:
            return self._leader

    @property
    def epoch(self) -> int:
        """The last epoch this replica led under (0 = never led)."""
        with self._lock:
            return self._epoch

    def fencing_epoch(self) -> Optional[int]:
        """The epoch to stamp on a fenced write, or None when this
        replica is not (or no longer) the leader."""
        with self._lock:
            return self._epoch if self._leader else None

    # -- the protocol ---------------------------------------------------

    def tick(self) -> bool:
        """One election step: leaders renew, followers try to acquire.
        Returns the post-tick leadership. Backend faults never escape —
        an unreachable API server is an election outcome (grace, then
        demotion), not an exception for the caller."""
        if self.is_leader:
            self._tick_leader()
        else:
            self._tick_follower()
        return self.is_leader

    def _tick_leader(self) -> None:
        now = self._clock()
        try:
            ok = self.backend.lease_renew(
                self.lease_name, self.identity, self._epoch, self.ttl
            )
        except TransientBackendError as exc:
            # server health, not a verdict: leadership is provably ours
            # while the lease we last renewed cannot have expired yet
            self._counters.inc("ha_renewal_failures_total")
            with self._lock:
                grace_spent = now - self._last_renew_ok > self.ttl
            if grace_spent:
                self._demote(f"renew grace expired ({exc})")
            else:
                self.logger.warning(
                    f"lease renew errored (within grace): {exc}"
                )
            return
        if ok:
            self._counters.inc("ha_renewals_total")
            with self._lock:
                self._last_renew_ok = now
        else:
            # CAS lost: the lease is no longer ours — no grace applies
            self._counters.inc("ha_renewal_failures_total")
            self._demote("lease lost (renew CAS failed)")

    def _tick_follower(self) -> None:
        try:
            view = self.backend.lease_try_acquire(
                self.lease_name, self.identity, self.ttl
            )
        except TransientBackendError as exc:
            self.logger.warning(f"lease acquire errored: {exc}")
            return
        if view.holder == self.identity:
            self._promote(view.epoch)

    def step_down(self) -> None:
        """Voluntary release (watchdog demotion, clean shutdown): clears
        the holder so a standby acquires on its next tick instead of
        waiting out the TTL."""
        with self._lock:
            if not self._leader:
                return
            epoch = self._epoch
        try:
            self.backend.lease_release(self.lease_name, self.identity, epoch)
        except TransientBackendError as exc:
            # the release is an optimization (faster handover); expiry
            # still bounds the gap if it never lands
            self.logger.warning(f"lease release failed: {exc}")
        self._demote("voluntary step-down")

    # -- transitions ----------------------------------------------------

    def _promote(self, epoch: int) -> None:
        with self._lock:
            self._leader = True
            self._epoch = epoch
            self._last_renew_ok = self._clock()
        self._counters.inc("ha_transitions_total")
        self._counters.set("ha_is_leader", 1)
        self._counters.set("ha_epoch", epoch)
        self.logger.warning(
            f"{self.identity}: elected leader (epoch {epoch})"
        )

    def _demote(self, why: str) -> None:
        with self._lock:
            if not self._leader:
                return
            self._leader = False
        self._counters.inc("ha_transitions_total")
        self._counters.set("ha_is_leader", 0)
        self.logger.warning(f"{self.identity}: stepping down — {why}")


class LeaseKeeper(threading.Thread):
    """Daemon thread ticking an elector at the renew cadence (the
    production driver behind ``nhd-tpu --ha``)."""

    def __init__(
        self, elector: LeaderElector, *, interval: float = LEASE_RENEW_SEC
    ):
        super().__init__(name="nhd-lease-keeper", daemon=True)
        self.elector = elector
        self.interval = interval
        self.logger = get_logger(__name__)
        self._stop_event = threading.Event()

    def run(self) -> None:
        while not self._stop_event.is_set():
            try:
                self.elector.tick()
            except Exception:
                # tick() absorbs backend faults itself; anything else is
                # a bug worth logging, but the keeper dying would freeze
                # the election at whatever state it last reached
                self.logger.exception("election tick failed")
            self._stop_event.wait(self.interval)

    def stop(self) -> None:
        self._stop_event.set()


class StallWatchdog(threading.Thread):
    """Crash-only stall detection for the scheduling loop.

    ``beat`` returns the loop's last-heartbeat stamp (monotonic; the
    scheduler refreshes it at the top of every ``run_once``, the same
    turn of the loop the flight-recorder spans and histograms are fed
    from). When the heartbeat goes stale past ``stall_after``, the
    watchdog releases the lease (so a standby promotes within one renew
    interval) and invokes ``exit_fn`` — ``os._exit`` by default, the
    same crash-only exit the cli liveness loop uses for a *dead* thread.
    This covers the case that loop cannot: a thread that is alive but
    wedged (stuck solve, hung uninstrumented call) still holds the lease
    and silently stalls the queue.
    """

    def __init__(
        self,
        beat: Callable[[], float],
        *,
        stall_after: float = WATCHDOG_STALL_SEC,
        interval: float = WATCHDOG_POLL_SEC,
        elector: Optional[LeaderElector] = None,
        exit_fn: Callable[[int], None] = os._exit,
        clock: Callable[[], float] = time.monotonic,
        counters: ApiCounters = API_COUNTERS,
    ):
        super().__init__(name="nhd-stall-watchdog", daemon=True)
        if stall_after <= 0:
            raise ValueError(f"stall_after must be > 0, got {stall_after}")
        self.logger = get_logger(__name__)
        self._beat = beat
        self.stall_after = stall_after
        self.interval = interval
        self.elector = elector
        self._exit_fn = exit_fn
        self._clock = clock
        self._counters = counters
        self._stop_event = threading.Event()
        self.fired = False

    def check(self, now: Optional[float] = None) -> bool:
        """One watchdog pass; returns True when the stall tripped.
        Public so tests drive it with an injected clock, no thread."""
        now = self._clock() if now is None else now
        age = max(now - self._beat(), 0.0)
        self._counters.set("ha_watchdog_loop_age_seconds", age)
        if age <= self.stall_after or self.fired:
            return self.fired
        self.fired = True
        self._counters.inc("ha_watchdog_stalls_total")
        self.logger.error(
            f"scheduling loop stalled ({age:.1f}s since last heartbeat, "
            f"budget {self.stall_after:.1f}s); releasing lease and "
            "exiting crash-only"
        )
        if self.elector is not None:
            self.elector.step_down()
        self._exit_fn(2)
        return True

    def run(self) -> None:
        while not self._stop_event.is_set():
            try:
                self.check()
            except Exception:
                # a broken beat source must not kill the watchdog quietly
                self.logger.exception("watchdog check failed")
            self._stop_event.wait(self.interval)

    def stop(self) -> None:
        self._stop_event.set()
