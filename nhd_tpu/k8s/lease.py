"""Lease-based leader election with monotonic fencing epochs.

The reference NHD is a single replica whose whole availability story is
"crash-only + Deployment restart" (bin/nhd:43-56): a wedged or restarting
scheduler means NO scheduler until the kubelet notices. This module lets
two or more replicas run safely:

* :class:`LeaderElector` — acquire/renew/step-down over the
  ``ClusterBackend`` lease seam (interface.py). Backed by
  ``FakeClusterBackend`` state for tests and chaos, by
  coordination.k8s.io/v1 Lease objects through ``kube.py`` (under the
  retry layer) on a real cluster. Every acquisition bumps a monotonic
  **fencing epoch**; the scheduler stamps it onto every mutating commit
  (scheduler/core.py ``_commit_write``) and backends reject stale epochs
  atomically, so a deposed leader's in-flight batch cannot land.
* :class:`LeaseKeeper` — the daemon thread that ticks an elector at the
  renew cadence (the production driver; tests tick by hand).
* :class:`StallWatchdog` — observes the scheduling loop's heartbeat
  (``Scheduler.last_heartbeat``, the same loop the flight-recorder spans
  are emitted from). A loop wedged past the stall budget voluntarily
  releases the lease and exits crash-only, so a standby replica takes
  over in one renew interval instead of a liveness-probe eternity.

Renewal semantics (the client-go shape): a renewal that *errors*
(TransientBackendError — the API server is unreachable) is tolerated
while the last successful renewal is younger than the TTL — the lease
can't have expired yet, so leadership is still provably ours. Past the
TTL the elector demotes itself WITHOUT waiting for proof: it can no
longer distinguish "server down" from "deposed", and acting without a
live lease is exactly the split-brain this module exists to prevent. A
renewal that *returns False* (the compare-and-swap lost: someone else
holds the lease, or the epoch moved) demotes immediately.

Everything is injectable (clock, counters) so election is unit-tested
without a single real sleep (tests/test_ha.py, same pattern as
tests/test_retry.py).
"""

from __future__ import annotations

import hashlib
import os
import threading
import time
from typing import Callable, Dict, Iterable, List, Optional, Set

from nhd_tpu.k8s.interface import LEASE_NAME, ClusterBackend, TransientBackendError
from nhd_tpu.k8s.retry import API_COUNTERS, ApiCounters
from nhd_tpu.utils import get_logger

# production cadence knobs (docs/OPERATIONS.md "High availability"):
# renew several times per TTL so one flaky renewal never costs leadership
LEASE_TTL_SEC = float(os.environ.get("NHD_LEASE_TTL", "15"))
LEASE_RENEW_SEC = float(os.environ.get("NHD_LEASE_RENEW_SEC", "4"))
# the stall budget: how long the scheduling loop may go without a
# heartbeat before the watchdog releases the lease and exits crash-only.
# The loop beats at least every Q_BLOCK_TIME_SEC (0.5 s) when healthy,
# plus however long one batch solve+commit legitimately takes — size the
# budget for the worst legitimate batch, not the idle cadence.
WATCHDOG_STALL_SEC = float(os.environ.get("NHD_WATCHDOG_STALL_SEC", "120"))
WATCHDOG_POLL_SEC = float(os.environ.get("NHD_WATCHDOG_POLL_SEC", "5"))


class LeaderElector:
    """One replica's view of the election: FOLLOWER until an acquisition
    wins, LEADER until a renewal proves otherwise.

    ``tick()`` is the whole protocol — call it every ``renew_interval``
    (LeaseKeeper does, chaos/tests do it by hand). ``is_leader`` /
    ``fencing_epoch()`` are thread-safe snapshots for the scheduler and
    its commit-pool threads; state only CHANGES inside ``tick()`` and
    ``step_down()``, so a replica that believes it leads keeps believing
    so between ticks — which is precisely the split-brain window the
    fencing epochs exist to make harmless.
    """

    def __init__(
        self,
        backend: ClusterBackend,
        *,
        identity: str,
        lease_name: str = LEASE_NAME,
        ttl: float = LEASE_TTL_SEC,
        clock: Callable[[], float] = time.monotonic,
        counters: ApiCounters = API_COUNTERS,
        on_demote: Optional[Callable[[str], None]] = None,
    ):
        if ttl <= 0:
            raise ValueError(f"lease ttl must be > 0, got {ttl}")
        self.backend = backend
        self.identity = identity
        self.lease_name = lease_name
        self.ttl = ttl
        self.logger = get_logger(__name__)
        self._clock = clock
        self._counters = counters
        # fires once per leader→follower transition (with the reason),
        # AFTER the state flip — the flight-recorder demotion dump rides
        # this (cli.py): a deposed leader's final batch stays
        # investigable instead of only surviving clean exits
        self._on_demote = on_demote
        self._lock = threading.Lock()
        self._leader = False
        self._epoch = 0           # last epoch we led under (never rewinds)
        self._last_renew_ok = 0.0

    # -- thread-safe snapshots -----------------------------------------

    @property
    def is_leader(self) -> bool:
        with self._lock:
            return self._leader

    @property
    def epoch(self) -> int:
        """The last epoch this replica led under (0 = never led)."""
        with self._lock:
            return self._epoch

    def fencing_epoch(self) -> Optional[int]:
        """The epoch to stamp on a fenced write, or None when this
        replica is not (or no longer) the leader."""
        with self._lock:
            return self._epoch if self._leader else None

    # -- the protocol ---------------------------------------------------

    def tick(self) -> bool:
        """One election step: leaders renew, followers try to acquire.
        Returns the post-tick leadership. Backend faults never escape —
        an unreachable API server is an election outcome (grace, then
        demotion), not an exception for the caller."""
        if self.is_leader:
            self._tick_leader()
        else:
            self._tick_follower()
        return self.is_leader

    def _tick_leader(self) -> None:
        now = self._clock()
        try:
            ok = self.backend.lease_renew(
                self.lease_name, self.identity, self._epoch, self.ttl
            )
        except TransientBackendError as exc:
            # server health, not a verdict: leadership is provably ours
            # while the lease we last renewed cannot have expired yet
            self._counters.inc("ha_renewal_failures_total")
            with self._lock:
                grace_spent = now - self._last_renew_ok > self.ttl
            if grace_spent:
                self._demote(f"renew grace expired ({exc})")
            else:
                self.logger.warning(
                    f"lease renew errored (within grace): {exc}"
                )
            return
        if ok:
            self._counters.inc("ha_renewals_total")
            with self._lock:
                self._last_renew_ok = now
        else:
            # CAS lost: the lease is no longer ours — no grace applies
            self._counters.inc("ha_renewal_failures_total")
            self._demote("lease lost (renew CAS failed)")

    def _tick_follower(self) -> None:
        try:
            view = self.backend.lease_try_acquire(
                self.lease_name, self.identity, self.ttl
            )
        except TransientBackendError as exc:
            self.logger.warning(f"lease acquire errored: {exc}")
            return
        if view.holder == self.identity:
            self._promote(view.epoch)

    def step_down(self) -> None:
        """Voluntary release (watchdog demotion, clean shutdown): clears
        the holder so a standby acquires on its next tick instead of
        waiting out the TTL."""
        with self._lock:
            if not self._leader:
                return
            epoch = self._epoch
        try:
            self.backend.lease_release(self.lease_name, self.identity, epoch)
        except TransientBackendError as exc:
            # the release is an optimization (faster handover); expiry
            # still bounds the gap if it never lands
            self.logger.warning(f"lease release failed: {exc}")
        self._demote("voluntary step-down")

    # -- transitions ----------------------------------------------------

    def _promote(self, epoch: int) -> None:
        with self._lock:
            self._leader = True
            self._epoch = epoch
            self._last_renew_ok = self._clock()
        self._counters.inc("ha_transitions_total")
        self._counters.set("ha_is_leader", 1)
        self._counters.set("ha_epoch", epoch)
        self.logger.warning(
            f"{self.identity}: elected leader (epoch {epoch})"
        )

    def _demote(self, why: str) -> None:
        with self._lock:
            if not self._leader:
                return
            self._leader = False
        self._counters.inc("ha_transitions_total")
        self._counters.set("ha_is_leader", 0)
        self.logger.warning(f"{self.identity}: stepping down — {why}")
        if self._on_demote is not None:
            # outside the lock: the callback may do I/O (trace dump) and
            # must never wedge the election; its failure is loggable, not
            # demotable — the state flip above already happened
            try:
                self._on_demote(why)
            except Exception:
                self.logger.exception("on_demote callback failed")


class LeaseKeeper(threading.Thread):
    """Daemon thread ticking an elector at the renew cadence (the
    production driver behind ``nhd-tpu --ha``)."""

    def __init__(
        self, elector: LeaderElector, *, interval: float = LEASE_RENEW_SEC
    ):
        super().__init__(name="nhd-lease-keeper", daemon=True)
        self.elector = elector
        self.interval = interval
        self.logger = get_logger(__name__)
        self._stop_event = threading.Event()

    def run(self) -> None:
        while not self._stop_event.is_set():
            try:
                self.elector.tick()
            except Exception:
                # tick() absorbs backend faults itself; anything else is
                # a bug worth logging, but the keeper dying would freeze
                # the election at whatever state it last reached
                self.logger.exception("election tick failed")
            self._stop_event.wait(self.interval)

    def stop(self) -> None:
        self._stop_event.set()


class StallWatchdog(threading.Thread):
    """Crash-only stall detection for the scheduling loop.

    ``beat`` returns the loop's last-heartbeat stamp (monotonic; the
    scheduler refreshes it at the top of every ``run_once``, the same
    turn of the loop the flight-recorder spans and histograms are fed
    from). When the heartbeat goes stale past ``stall_after``, the
    watchdog releases the lease (so a standby promotes within one renew
    interval) and invokes ``exit_fn`` — ``os._exit`` by default, the
    same crash-only exit the cli liveness loop uses for a *dead* thread.
    This covers the case that loop cannot: a thread that is alive but
    wedged (stuck solve, hung uninstrumented call) still holds the lease
    and silently stalls the queue.
    """

    def __init__(
        self,
        beat: Callable[[], float],
        *,
        stall_after: float = WATCHDOG_STALL_SEC,
        interval: float = WATCHDOG_POLL_SEC,
        elector: Optional[LeaderElector] = None,
        exit_fn: Callable[[int], None] = os._exit,
        clock: Callable[[], float] = time.monotonic,
        counters: ApiCounters = API_COUNTERS,
    ):
        super().__init__(name="nhd-stall-watchdog", daemon=True)
        if stall_after <= 0:
            raise ValueError(f"stall_after must be > 0, got {stall_after}")
        self.logger = get_logger(__name__)
        self._beat = beat
        self.stall_after = stall_after
        self.interval = interval
        self.elector = elector
        self._exit_fn = exit_fn
        self._clock = clock
        self._counters = counters
        self._stop_event = threading.Event()
        self.fired = False

    def check(self, now: Optional[float] = None) -> bool:
        """One watchdog pass; returns True when the stall tripped.
        Public so tests drive it with an injected clock, no thread."""
        now = self._clock() if now is None else now
        age = max(now - self._beat(), 0.0)
        self._counters.set("ha_watchdog_loop_age_seconds", age)
        if age <= self.stall_after or self.fired:
            return self.fired
        self.fired = True
        self._counters.inc("ha_watchdog_stalls_total")
        self.logger.error(
            f"scheduling loop stalled ({age:.1f}s since last heartbeat, "
            f"budget {self.stall_after:.1f}s); releasing lease and "
            "exiting crash-only"
        )
        if self.elector is not None:
            self.elector.step_down()
        self._exit_fn(2)
        return True

    def run(self) -> None:
        while not self._stop_event.is_set():
            try:
                self.check()
            except Exception:
                # a broken beat source must not kill the watchdog quietly
                self.logger.exception("watchdog check failed")
            self._stop_event.wait(self.interval)

    def stop(self) -> None:
        self._stop_event.set()


# ---------------------------------------------------------------------------
# Sharded federation: the single LEASE_NAME generalized to a shard table
# ---------------------------------------------------------------------------
#
# PR 5's machinery supports exactly one active leader for the whole fleet:
# one wedged or partitioned replica stalls every node group at once. The
# federation splits the node-group set into S **shards**, each backed by
# its own coordination Lease with an independent fencing epoch, so N
# replicas share the control plane: a replica may hold several shards,
# every fenced write carries the epoch OF THE SHARD OWNING THE TARGET
# NODE, and losing one replica costs only its shards' node groups for one
# handoff — not the fleet (docs/RESILIENCE.md "Federation").

#: how many ticks a non-preferred replica waits on an unheld shard lease
#: before acquiring it anyway (the rendezvous-preferred owner is wedged,
#: partitioned, or gone); bounds the per-shard leadership gap at
#: TTL + patience ticks
SHARD_PATIENCE_TICKS = int(os.environ.get("NHD_SHARD_PATIENCE_TICKS", "2"))


def shard_lease_name(shard: int, n_shards: int) -> str:
    """The coordination Lease backing one shard. S=1 degenerates to the
    PR 5 single lease — a one-shard federation is byte-identical on the
    wire to `--ha` (the regression pin in tests/test_ha.py)."""
    if n_shards == 1:
        return LEASE_NAME
    return f"{LEASE_NAME}-s{shard}"


def presence_lease_name(identity: str) -> str:
    """Per-replica liveness beacon: each federation member renews its own
    presence lease every tick, and peers treat a member as live while the
    beacon is unexpired. This is what lets the current holder of a shard
    notice a freshly joined preferred owner and hand the shard over —
    a replica that holds no shard yet would otherwise be invisible."""
    return f"nhd-scheduler-presence-{identity}"


def _hrw(*parts: object) -> int:
    """Deterministic 64-bit weight for rendezvous hashing — hashlib, not
    hash(): assignments must agree across processes and PYTHONHASHSEED."""
    h = hashlib.blake2s(
        "|".join(str(p) for p in parts).encode(), digest_size=8
    )
    return int.from_bytes(h.digest(), "big")


def shard_for_group(group: str, n_shards: int) -> int:
    """group → shard via highest-random-weight over shard ids: resizing
    the federation moves only ~1/S of the groups."""
    if n_shards <= 1:
        return 0
    return max(range(n_shards), key=lambda s: (_hrw("grp", group, s), s))


def shard_for_groups(groups: Iterable[str], n_shards: int) -> int:
    """A node's (or pod request's) home shard. Nodes can carry several
    groups and a pod can request several; the lexicographic minimum is
    the deterministic tiebreak both sides agree on — a pod whose groups
    straddle shards lands in ONE home shard and reaches the others
    through the spillover queue."""
    groups = sorted(groups)
    return shard_for_group(groups[0] if groups else "default", n_shards)


def rendezvous_owner(shard: int, identities: Iterable[str]) -> Optional[str]:
    """The replica that SHOULD hold this shard among the live members —
    highest-random-weight, so membership changes reassign only the dead
    member's shards and every replica computes the same answer with no
    coordinator."""
    ids = sorted(set(identities))
    if not ids:
        return None
    return max(ids, key=lambda i: (_hrw("own", shard, i), i))


# replica-local shard ownership snapshot for the metrics plane
# (rpc/metrics.py renders nhd_shard_epoch{shard=...}); one process runs
# one replica in production, so module state is the right scope
_SHARD_STATUS_LOCK = threading.Lock()
_SHARD_STATUS: Dict[str, object] = {"identity": "", "n_shards": 0, "owned": {}}


def publish_shard_status(
    identity: str, n_shards: int, owned: Dict[int, int]
) -> None:
    with _SHARD_STATUS_LOCK:
        _SHARD_STATUS["identity"] = identity
        _SHARD_STATUS["n_shards"] = n_shards
        _SHARD_STATUS["owned"] = dict(owned)


def shard_status_snapshot() -> Dict[str, object]:
    with _SHARD_STATUS_LOCK:
        return {
            "identity": _SHARD_STATUS["identity"],
            "n_shards": _SHARD_STATUS["n_shards"],
            "owned": dict(_SHARD_STATUS["owned"]),  # type: ignore[arg-type]
        }


class _MonotonicOnly:
    """Counter surface handed to a :class:`ShardedElector`'s inner
    electors: monotonic renewal counters forward to the replica's shared
    registry (S leases' renewals/failures sum meaningfully on /metrics),
    everything else is dropped — S electors would thrash the
    ha_is_leader/ha_epoch gauges, and per-lease acquire/step-down
    transitions would double-count against the replica-level
    ha_transitions_total that ``_publish()`` maintains."""

    _FORWARD = frozenset({"ha_renewals_total", "ha_renewal_failures_total"})

    def __init__(self, registry: ApiCounters):
        self._registry = registry

    def inc(self, name: str, by: float = 1) -> None:
        if name in self._FORWARD:
            self._registry.inc(name, by)

    def set(self, name: str, value: float) -> None:
        pass

    def get(self, name: str) -> float:
        return self._registry.get(name)


class ShardedElector:
    """One replica's membership in the shard federation: a presence
    beacon plus one :class:`LeaderElector` per shard lease.

    ``tick()`` runs the whole protocol:

    1. renew the presence beacon (peer-visible liveness);
    2. compute the live member set from the peers' beacons;
    3. per shard — owners renew (the PR 5 grace/CAS semantics,
       unchanged, via the inner elector); the rendezvous-preferred
       member acquires unheld/expired shards immediately; everyone else
       waits out a small **patience** budget before grabbing an orphaned
       shard (so the preferred owner wins the common case but a wedged
       one never strands a shard past TTL + patience ticks);
    4. **bounded handoff**: a holder that sees a live better-ranked
       member releases AT MOST ONE shard per tick to it — rebalance
       converges in a few ticks without a thundering mass-release, and
       each handed-off shard goes through the new owner's scoped
       promotion replay before any write (scheduler/core.py).

    Fencing is per shard: ``fencing_epoch_for(shard)`` is the token a
    write targeting that shard's nodes must carry, and a replica holds
    several tokens at once. ``is_leader`` reports shard 0 — the
    federation's **coordinator shard**, which owns the cluster-scoped
    duties exactly one replica may run (TriadSet reconciliation).
    """

    def __init__(
        self,
        backend: ClusterBackend,
        *,
        identity: str,
        peers: Iterable[str],
        n_shards: int,
        ttl: float = LEASE_TTL_SEC,
        clock: Callable[[], float] = time.monotonic,
        counters: ApiCounters = API_COUNTERS,
        patience: int = SHARD_PATIENCE_TICKS,
        on_demote: Optional[Callable[[str], None]] = None,
    ):
        if n_shards < 1:
            raise ValueError(f"n_shards must be >= 1, got {n_shards}")
        self.backend = backend
        self.identity = identity
        self.peers: List[str] = sorted(set(peers) | {identity})
        self.n_shards = n_shards
        self.ttl = ttl
        self.patience = patience
        self.logger = get_logger(__name__)
        self._counters = counters
        # inner electors write through a forwarding surface: monotonic
        # inc()s (renewals, renewal failures, transitions) land in the
        # replica's shared registry — S leases' renewal traffic SUMS
        # meaningfully, and operators alerting on
        # nhd_ha_renewal_failures_total keep their signal under
        # federation — while gauge set()s are dropped (S electors would
        # thrash ha_is_leader/ha_epoch; _publish() writes the
        # replica-level truth for those instead)
        inner_counters = _MonotonicOnly(counters)
        # shard-qualified demotion callback: every lost shard is a
        # demotion event for the dump hook (the presence beacon is NOT —
        # losing it costs rendezvous preference, not leadership)
        self._electors: Dict[int, LeaderElector] = {
            s: LeaderElector(
                backend, identity=identity,
                lease_name=shard_lease_name(s, n_shards),
                ttl=ttl, clock=clock, counters=inner_counters,
                on_demote=(
                    None if on_demote is None
                    else (lambda why, _s=s: on_demote(f"shard {_s}: {why}"))
                ),
            )
            for s in range(n_shards)
        }
        self._presence = LeaderElector(
            backend, identity=identity,
            lease_name=presence_lease_name(identity),
            ttl=ttl, clock=clock, counters=inner_counters,
        )
        self._patience_count: Dict[int, int] = {s: 0 for s in range(n_shards)}
        self._last_live: Set[str] = {identity}

    # -- thread-safe snapshots (inner electors own the locking) ---------

    def owned_shards(self) -> Dict[int, int]:
        """{shard: fencing epoch} for every shard this replica holds."""
        out: Dict[int, int] = {}
        for s, el in self._electors.items():
            epoch = el.fencing_epoch()
            if epoch is not None:
                out[s] = epoch
        return out

    def fencing_epoch_for(self, shard: int) -> Optional[int]:
        return self._electors[shard].fencing_epoch()

    def fencing_epoch(self) -> Optional[int]:
        """Single-lease compatibility surface (S=1 callers)."""
        return self._electors[0].fencing_epoch()

    def lease_name_of(self, shard: int) -> str:
        return shard_lease_name(shard, self.n_shards)

    @property
    def is_leader(self) -> bool:
        """Coordinator duties (TriadSet reconciliation) follow shard 0:
        cluster-scoped writes still need exactly one author."""
        return self._electors[0].is_leader

    @property
    def epoch(self) -> int:
        """Highest epoch among owned shards (logging/metrics figure; the
        per-shard tokens are what fencing actually uses)."""
        return max(self.owned_shards().values(), default=0)

    # -- the protocol ---------------------------------------------------

    def tick(self) -> bool:
        """One federation step; returns True when any shard is held.
        Backend faults never escape — an unreachable API server degrades
        to the inner electors' grace/expiry outcomes."""
        owned_before = set(self.owned_shards())
        self._presence.tick()
        live = self._live_members()
        handed_off = False
        for s in range(self.n_shards):
            el = self._electors[s]
            preferred = rendezvous_owner(s, live)
            if el.is_leader:
                el.tick()
                if (
                    el.is_leader
                    and preferred != self.identity
                    and not handed_off
                ):
                    # bounded handoff: a live better-ranked member exists;
                    # release at most one shard per tick so rebalance
                    # never dumps a replica's whole shard set at once
                    self.logger.warning(
                        f"{self.identity}: handing shard {s} to {preferred}"
                    )
                    el.step_down()
                    handed_off = True
                    self._counters.inc("shard_handoffs_total")
                self._patience_count[s] = 0
                continue
            if preferred == self.identity:
                if el.tick():
                    self._counters.inc("shard_acquisitions_total")
                self._patience_count[s] = 0
                continue
            # not ours by preference: grab it only once it has sat
            # unheld past the patience budget (the preferred member is
            # wedged, partitioned, or its beacon hasn't expired yet)
            try:
                held = bool(
                    self.backend.lease_live(self.lease_name_of(s))
                )
            except TransientBackendError:
                held = True  # unverifiable: don't spend patience on it
            if held:
                self._patience_count[s] = 0
            else:
                self._patience_count[s] += 1
                if self._patience_count[s] > self.patience and el.tick():
                    self._counters.inc("shard_acquisitions_total")
                    self._patience_count[s] = 0
        self._publish(owned_before)
        return bool(self.owned_shards())

    def _live_members(self) -> Set[str]:
        """Members with an unexpired presence beacon (plus ourselves).
        An unverifiable peer counts as absent: wrongly absent costs a
        bounded patience delay, wrongly live could strand a shard on a
        dead member forever."""
        live: Set[str] = {self.identity}
        for peer in self.peers:
            if peer == self.identity:
                continue
            try:
                if self.backend.lease_live(presence_lease_name(peer)) == peer:
                    live.add(peer)
            except TransientBackendError:
                pass
        self._last_live = live
        return live

    def release_shard(self, shard: int) -> None:
        """Give one shard back (failed scoped promotion replay: leading a
        shard without replayed state violates the crash-only contract)."""
        self._electors[shard].step_down()
        self._publish(set(self.owned_shards()) | {shard})

    def step_down(self) -> None:
        """Clean exit: release every shard and the presence beacon so
        peers rebalance in one tick instead of waiting out the TTL."""
        owned_before = set(self.owned_shards())
        for el in self._electors.values():
            el.step_down()
        self._presence.step_down()
        self._publish(owned_before)

    def _publish(self, owned_before: Set[int]) -> None:
        owned = self.owned_shards()
        if set(owned) != owned_before:
            self._counters.inc("ha_transitions_total")
        # the replica-level generalization of the single-leader gauges:
        # "leader" now means "holds at least one shard", and the epoch
        # gauge reports the highest held token (per-shard epochs are on
        # nhd_shard_epoch{shard=...})
        self._counters.set("shard_owned_count", len(owned))
        self._counters.set("ha_is_leader", 1 if owned else 0)
        self._counters.set("ha_epoch", max(owned.values(), default=0))
        publish_shard_status(self.identity, self.n_shards, owned)
