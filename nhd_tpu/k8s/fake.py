"""In-memory cluster backend: the hermetic test/simulation seam.

Gives the framework what the reference never had (SURVEY §4): a way to run
the full scheduler — watches, binding, annotations, restart replay —
without a live cluster. State layout intentionally mirrors what the API
server would hold, so the scheduler can't tell the difference.
"""

from __future__ import annotations

import itertools
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Tuple

from nhd_tpu.k8s.interface import (
    CFG_ANNOTATION,
    CFG_TYPE_ANNOTATION,
    GPU_MAP_ANNOTATION_PREFIX,
    GROUPS_ANNOTATION,
    LEASE_NAME,
    NAD_ANNOTATION,
    SCHEDULER_TAINT,
    SPILLOVER_ANNOTATION,
    TIER_ANNOTATION,
    ClusterBackend,
    LeaseView,
    PodEvent,
    StaleLeaseError,
    WatchEvent,
    parse_spill_record,
    render_spill_record,
)
from nhd_tpu.k8s.retry import API_COUNTERS
from nhd_tpu.utils import get_logger


@dataclass
class FakeNode:
    name: str
    labels: Dict[str, str] = field(default_factory=dict)
    addr: str = "10.0.0.1"
    hugepages_capacity_gb: int = 64
    hugepages_allocatable_gb: int = 64
    ready: bool = True
    unschedulable: bool = False
    taints: List[str] = field(default_factory=lambda: [SCHEDULER_TAINT])


@dataclass
class FakeLease:
    """One coordination lease record (the API server's Lease object)."""

    name: str
    holder: str = ""
    epoch: int = 0          # leaseTransitions: the fencing token
    expires: float = 0.0    # backend-clock deadline


@dataclass
class FakePod:
    name: str
    namespace: str
    uid: str
    scheduler_name: str = "nhd-scheduler"
    phase: str = "Pending"
    node: Optional[str] = None
    annotations: Dict[str, str] = field(default_factory=dict)
    resources: Dict[str, str] = field(default_factory=dict)
    configmap_name: Optional[str] = None
    hostname: str = ""
    subdomain: str = ""
    # creationTimestamp in the backend's clock domain (the sim clock
    # under chaos) — the SLO engine's time-to-bind origin
    created: float = 0.0


class FakeClusterBackend(ClusterBackend):
    """A thread-safe in-memory API server stand-in."""

    def __init__(self) -> None:
        self.logger = get_logger(__name__)
        self._lock = threading.RLock()
        # wakes a blocked poll_watch_events the moment an event lands
        self._watch_cv = threading.Condition(self._lock)
        self.nodes: Dict[str, FakeNode] = {}
        self.pods: Dict[Tuple[str, str], FakePod] = {}
        self.configmaps: Dict[Tuple[str, str], str] = {}  # (ns, name) → text
        self.events: List[PodEvent] = []
        self.triadsets: List[dict] = []
        self._watch: List[WatchEvent] = []
        self._uid = itertools.count(1)
        self.fail_bind_for: set = set()      # (ns, pod) forced bind failures
        self.bind_count = 0
        # record/replay scenario sink (obs/journal.py): when set, every
        # simulation-control mutation below reports (op, kwargs) so a
        # journal can script the exact cluster timeline for replay.
        # Deliberately NOT set on replay's own backend — re-driving a
        # journal must not journal itself.
        self.scenario_sink = None
        # coordination leases (leader election, k8s/lease.py). The clock
        # is injectable so chaos runs drive lease expiry deterministically
        # off the sim's step clock instead of wall time.
        self.clock = time.monotonic
        self.leases: Dict[str, FakeLease] = {}
        # the DEFAULT lease fenced writes are checked against when the
        # caller names none (interface.py); federated writes name the
        # shard lease per call via ``fence_lease``
        self.fence_lease_name = LEASE_NAME
        # every SUCCESSFUL bind: (ns, pod, uid, node, epoch, lease) — the
        # chaos harness's "no pod ever bound under two shard epochs"
        # invariant reads this
        self.bind_log: List[
            Tuple[str, str, str, str, Optional[int], Optional[str]]
        ] = []
        # every SUCCESSFUL preemption eviction: (ns, pod, uid, node,
        # epoch, lease) — the policy-chaos harness's preemption-bound /
        # no-cascade invariants read this (sim/chaos.py)
        self.evict_log: List[
            Tuple[str, str, str, str, Optional[int], Optional[str]]
        ] = []

    # ------------------------------------------------------------------
    # simulation controls (test-facing, not part of ClusterBackend)
    # ------------------------------------------------------------------

    def _scenario(self, op: str, payload: dict) -> None:
        """Report one simulation-control mutation to the scenario sink
        (called OUTSIDE self._lock — the sink does its own locking and
        may do file I/O)."""
        sink = self.scenario_sink
        if sink is not None:
            sink(op, payload)

    def arm_bind_failure(self, ns: str, pod: str) -> None:
        """Force the next bind attempt of (ns, pod) to fail — the
        scenario-visible counterpart of mutating ``fail_bind_for``
        directly, so chaos-armed bind failures land in the journal."""
        with self._lock:
            self.fail_bind_for.add((ns, pod))
        self._scenario("arm_bind_failure", {"ns": ns, "pod": pod})

    def snapshot_stats(self) -> Dict[str, int]:
        """Consistent point-in-time counts while scheduler/controller
        threads are still mutating the backend (CLI demo summary)."""
        with self._lock:
            return {
                "bound_pods": sum(1 for p in self.pods.values() if p.node),
                "total_pods": len(self.pods),
                "nodes": len(self.nodes),
            }

    def add_node(self, name: str, labels: Dict[str, str], *,
                 hugepages_gb: int = 64, addr: str = "",
                 emit_watch: bool = False) -> FakeNode:
        with self._lock:
            node = FakeNode(
                name=name, labels=dict(labels), addr=addr or f"10.0.1.{len(self.nodes) + 1}",
                hugepages_capacity_gb=hugepages_gb, hugepages_allocatable_gb=hugepages_gb,
            )
            self.nodes[name] = node
            if emit_watch:
                # live node arrival (cluster scale-up): the controller
                # translates this into WatchType.NODE_ADD and the
                # scheduler folds the node in without a restart
                self._emit_watch(
                    WatchEvent(kind="node_add", name=name,
                               labels=dict(node.labels))
                )
        self._scenario("add_node", {
            "name": name, "labels": dict(labels),
            "hugepages_gb": hugepages_gb, "addr": node.addr,
            "emit_watch": emit_watch,
        })
        return node

    def remove_node(self, name: str, *, emit_watch: bool = True) -> None:
        """Drop a node from the inventory (decommission/scale-down).
        Emits a ``node_delete`` watch event so the scheduler can retire
        the mirror entry (and its packed row) without a restart."""
        with self._lock:
            node = self.nodes.pop(name, None)
            if node is not None and emit_watch:
                self._emit_watch(
                    WatchEvent(kind="node_delete", name=name,
                               labels=dict(node.labels))
                )
        if node is not None:
            self._scenario("remove_node", {
                "name": name, "emit_watch": emit_watch,
            })

    def create_pod(
        self,
        name: str,
        ns: str = "default",
        *,
        cfg_text: Optional[str] = None,
        cfg_type: str = "triad",
        groups: Optional[str] = None,
        resources: Optional[Dict[str, str]] = None,
        scheduler_name: str = "nhd-scheduler",
        emit_watch: bool = True,
        tier: int = 0,
    ) -> FakePod:
        """Create a Pending pod with its ConfigMap, like a TriadSet would."""
        with self._lock:
            uid = f"uid-{next(self._uid)}"
            pod = FakePod(name=name, namespace=ns, uid=uid,
                          scheduler_name=scheduler_name,
                          resources=dict(resources or {}),
                          created=self.clock())
            pod.annotations[CFG_TYPE_ANNOTATION] = cfg_type
            if groups:
                pod.annotations[GROUPS_ANNOTATION] = groups
            if tier:
                pod.annotations[TIER_ANNOTATION] = str(int(tier))
            if cfg_text is not None:
                cm = f"{name}-cfg"
                self.configmaps[(ns, cm)] = cfg_text
                pod.configmap_name = cm
            self.pods[(ns, name)] = pod
            if emit_watch:
                self._emit_watch(
                    WatchEvent(kind="pod_create", name=name, namespace=ns,
                               annotations=dict(pod.annotations), uid=uid,
                               scheduler_name=pod.scheduler_name)
                )
        self._scenario("create_pod", {
            "name": name, "ns": ns, "cfg_text": cfg_text,
            "cfg_type": cfg_type, "groups": groups,
            "resources": dict(resources or {}),
            "scheduler_name": scheduler_name,
            "emit_watch": emit_watch, "tier": tier,
        })
        return pod

    def delete_pod(self, name: str, ns: str = "default",
                   emit_watch: bool = True) -> None:
        with self._lock:
            pod = self.pods.pop((ns, name), None)
            if pod and emit_watch:
                self._emit_watch(
                    WatchEvent(kind="pod_delete", name=name, namespace=ns,
                               annotations=dict(pod.annotations), uid=pod.uid,
                               scheduler_name=pod.scheduler_name,
                               node=pod.node or "")
                )
        if pod is not None:
            self._scenario("delete_pod", {
                "name": name, "ns": ns, "emit_watch": emit_watch,
            })

    def set_pod_phase(self, name: str, ns: str, phase: str) -> None:
        with self._lock:
            self.pods[(ns, name)].phase = phase

    def cordon_node(self, name: str, cordon: bool = True) -> None:
        with self._lock:
            node = self.nodes[name]
            was = node.unschedulable
            node.unschedulable = cordon
            self._emit_watch(
                WatchEvent(kind="node_update", name=name,
                           labels=dict(node.labels), old_labels=dict(node.labels),
                           unschedulable=cordon, was_unschedulable=was,
                           taints=list(node.taints), old_taints=list(node.taints))
            )
        self._scenario("cordon_node", {"name": name, "cordon": cordon})

    def update_node_labels(self, name: str, new_labels: Dict[str, Optional[str]]) -> None:
        """Merge label changes; a value of None removes the label."""
        with self._lock:
            node = self.nodes[name]
            old = dict(node.labels)
            for k, v in new_labels.items():
                if v is None:
                    node.labels.pop(k, None)
                else:
                    node.labels[k] = v
            self._emit_watch(
                WatchEvent(kind="node_update", name=name,
                           labels=dict(node.labels), old_labels=old,
                           unschedulable=node.unschedulable,
                           was_unschedulable=node.unschedulable,
                           taints=list(node.taints), old_taints=list(node.taints))
            )
        self._scenario("update_node_labels", {
            "name": name, "new_labels": dict(new_labels),
        })

    def add_triadset(self, name: str, ns: str, replicas: int,
                     service_name: str, cfg_text: str) -> None:
        with self._lock:
            self.triadsets.append(
                {"name": name, "ns": ns, "replicas": replicas,
                 "service_name": service_name, "cfg_text": cfg_text}
            )

    # ------------------------------------------------------------------
    # ClusterBackend: node reads
    # ------------------------------------------------------------------

    def get_nodes(self) -> List[str]:
        with self._lock:
            return [n.name for n in self.nodes.values() if n.ready]

    def is_node_active(self, node: str) -> bool:
        with self._lock:
            n = self.nodes.get(node)
            return bool(n and SCHEDULER_TAINT in n.taints and not n.unschedulable)

    def get_node_labels(self, node: str) -> Dict[str, str]:
        with self._lock:
            return dict(self.nodes[node].labels)

    def get_node_addr(self, node: str) -> str:
        with self._lock:
            return self.nodes[node].addr

    def get_node_hugepage_resources(self, node: str) -> Tuple[int, int]:
        with self._lock:
            n = self.nodes[node]
            return (n.hugepages_capacity_gb, n.hugepages_allocatable_gb)

    # ------------------------------------------------------------------
    # ClusterBackend: pod reads
    # ------------------------------------------------------------------

    def _pod(self, pod: str, ns: str) -> Optional[FakePod]:
        return self.pods.get((ns, pod))

    def pod_exists(self, pod: str, ns: str) -> bool:
        with self._lock:
            return (ns, pod) in self.pods

    def get_pod_node(self, pod: str, ns: str) -> Optional[str]:
        with self._lock:
            p = self._pod(pod, ns)
            return p.node if p else None

    def get_pod_annotations(self, pod: str, ns: str) -> Optional[Dict[str, str]]:
        with self._lock:
            p = self._pod(pod, ns)
            return dict(p.annotations) if p else None

    def get_cfg_annotations(self, pod: str, ns: str) -> Optional[str]:
        with self._lock:
            p = self._pod(pod, ns)
            return p.annotations.get(CFG_ANNOTATION) if p else None

    def get_cfg_type(self, pod: str, ns: str) -> Optional[str]:
        with self._lock:
            p = self._pod(pod, ns)
            return p.annotations.get(CFG_TYPE_ANNOTATION) if p else None

    def get_pod_node_groups(self, pod: str, ns: str) -> List[str]:
        with self._lock:
            p = self._pod(pod, ns)
            if p and GROUPS_ANNOTATION in p.annotations:
                return p.annotations[GROUPS_ANNOTATION].split(".")
            return ["default"]

    def get_requested_pod_resources(self, pod: str, ns: str) -> Dict[str, str]:
        with self._lock:
            p = self._pod(pod, ns)
            return dict(p.resources) if p else {}

    def get_pod_created(self, pod: str, ns: str) -> Optional[float]:
        with self._lock:
            p = self._pod(pod, ns)
            return p.created if p else None

    def clock_now(self) -> float:
        return self.clock()

    def get_scheduled_pods(self, scheduler: str) -> List[Tuple[str, str, str, str]]:
        with self._lock:
            return [
                (p.name, p.namespace, p.uid, p.phase)
                for p in self.pods.values()
                if p.scheduler_name == scheduler and p.node is not None
            ]

    def service_pods(self, scheduler: str):
        with self._lock:
            return {
                (p.namespace, p.name, p.uid): (p.phase, p.node)
                for p in self.pods.values()
                if p.scheduler_name == scheduler
            }

    def get_cfg_map(self, pod: str, ns: str) -> Tuple[Optional[str], Optional[str]]:
        with self._lock:
            p = self._pod(pod, ns)
            if not p or not p.configmap_name:
                return (None, None)
            return (p.configmap_name, self.configmaps.get((ns, p.configmap_name)))

    # ------------------------------------------------------------------
    # ClusterBackend: writes
    # ------------------------------------------------------------------

    def _check_fence(
        self, epoch: Optional[int], lease_name: Optional[str] = None
    ) -> None:
        """Reject a fenced write whose epoch a newer acquisition of the
        named lease has already overtaken. Caller holds ``self._lock``,
        so the check is atomic with the write itself — the property that
        makes fencing tokens sound (a deposed leader can't slip a write
        in between the check and the mutation). ``lease_name`` selects
        the shard lease under federation; None = the default lease."""
        if epoch is None:
            return
        lease = self.leases.get(lease_name or self.fence_lease_name)
        if lease is not None and epoch < lease.epoch:
            API_COUNTERS.inc("ha_stale_writes_rejected_total")
            raise StaleLeaseError(
                f"write fenced off: epoch {epoch} is stale "
                f"(current lease epoch {lease.epoch}, "
                f"holder {lease.holder!r})"
            )

    def add_nad_to_pod(
        self, pod: str, ns: str, nad: str, *,
        epoch: Optional[int] = None, fence_lease: Optional[str] = None,
    ) -> bool:
        with self._lock:
            self._check_fence(epoch, fence_lease)
            p = self._pod(pod, ns)
            if p is None:
                return False
            p.annotations[NAD_ANNOTATION] = nad
            return True

    def annotate_pod_config(
        self, ns: str, pod: str, cfg: str, *,
        epoch: Optional[int] = None, fence_lease: Optional[str] = None,
    ) -> bool:
        with self._lock:
            self._check_fence(epoch, fence_lease)
            p = self._pod(pod, ns)
            if p is None:
                return False
            p.annotations[CFG_ANNOTATION] = cfg
            return True

    def annotate_pod_gpu_map(
        self, ns: str, pod: str, gpu_map: Dict[str, int],
        *, epoch: Optional[int] = None, fence_lease: Optional[str] = None,
    ) -> bool:
        with self._lock:
            self._check_fence(epoch, fence_lease)
            p = self._pod(pod, ns)
            if p is None:
                return False
            for dev, devid in gpu_map.items():
                p.annotations[f"{GPU_MAP_ANNOTATION_PREFIX}.{dev}"] = str(devid)
            return True

    def annotate_pod_meta(
        self, ns: str, pod: str, key: str, value: str,
        *, epoch: Optional[int] = None, fence_lease: Optional[str] = None,
    ) -> bool:
        with self._lock:
            self._check_fence(epoch, fence_lease)
            p = self._pod(pod, ns)
            if p is None:
                return False
            p.annotations[key] = value
            return True

    def claim_spillover_pod(
        self, ns: str, pod: str, claim_lease: str, claim_epoch: int,
        *, epoch: Optional[int] = None, fence_lease: Optional[str] = None,
    ) -> bool:
        with self._lock:
            self._check_fence(epoch, fence_lease)
            p = self._pod(pod, ns)
            if p is None:
                return False
            rec = parse_spill_record(p.annotations.get(SPILLOVER_ANNOTATION))
            cur = rec.get("claim")
            if cur is not None and cur != (claim_lease, claim_epoch):
                # a foreign claim blocks us only while it is LIVE: its
                # lease still held under the claimed epoch. A crashed or
                # deposed claimant's shard lease re-acquires with a
                # higher epoch, so its claim goes stale by itself.
                lease = self.leases.get(cur[0])
                if (
                    lease is not None and lease.holder
                    and lease.expires > self.clock()
                    and lease.epoch == cur[1]
                ):
                    return False
            rec["claim"] = (claim_lease, claim_epoch)
            p.annotations[SPILLOVER_ANNOTATION] = render_spill_record(rec)
            return True

    def bind_pod_to_node(
        self, pod: str, node: str, ns: str, *,
        epoch: Optional[int] = None, fence_lease: Optional[str] = None,
    ) -> bool:
        with self._lock:
            self._check_fence(epoch, fence_lease)
            p = self._pod(pod, ns)
            if p is None or (ns, pod) in self.fail_bind_for:
                return False
            p.node = node
            p.phase = "Running"  # kubelet admission, fast-forwarded
            self.bind_count += 1
            self.bind_log.append((
                ns, pod, p.uid, node, epoch,
                (fence_lease or self.fence_lease_name)
                if epoch is not None else None,
            ))
            return True

    def evict_pod(
        self, pod: str, ns: str, *,
        epoch: Optional[int] = None, fence_lease: Optional[str] = None,
    ) -> bool:
        """Preemption eviction: unbind the pod back to Pending. The
        solved-config annotations (and the ConfigMap) survive so the
        scheduler's unwind/release path works from them, and the pod
        keeps its uid — an evicted pod is the SAME incarnation requeued,
        which is what lets the flight recorder show one preempt→rebind
        journey per victim. Fenced exactly like bind (a deposed leader's
        in-flight preemption must not land)."""
        with self._lock:
            self._check_fence(epoch, fence_lease)
            p = self._pod(pod, ns)
            if p is None or p.node is None:
                return False
            self.evict_log.append((
                ns, pod, p.uid, p.node, epoch,
                (fence_lease or self.fence_lease_name)
                if epoch is not None else None,
            ))
            p.node = None
            p.phase = "Pending"
            return True

    def generate_pod_event(self, pod, ns, reason, event_type, message) -> None:
        with self._lock:
            self.events.append(
                PodEvent(pod, ns, reason, event_type, f"NHD: {message}")
            )

    # ------------------------------------------------------------------
    # coordination leases (leader election, k8s/lease.py)
    # ------------------------------------------------------------------

    def _lease_view(self, lease: FakeLease) -> LeaseView:
        return LeaseView(
            name=lease.name, holder=lease.holder,
            epoch=lease.epoch, expires=lease.expires,
        )

    def lease_try_acquire(self, name: str, holder: str, ttl: float) -> LeaseView:
        with self._lock:
            now = self.clock()
            lease = self.leases.setdefault(name, FakeLease(name=name))
            taken = lease.holder and lease.expires > now
            if taken and lease.holder != holder:
                return self._lease_view(lease)   # held by someone else
            # unheld, expired, or our own stale incarnation: every
            # acquisition bumps the epoch — the token must be fresh even
            # for a same-holder re-acquire after a crash/restart
            lease.holder = holder
            lease.epoch += 1
            lease.expires = now + ttl
            return self._lease_view(lease)

    def lease_renew(self, name: str, holder: str, epoch: int, ttl: float) -> bool:
        with self._lock:
            lease = self.leases.get(name)
            if lease is None or lease.holder != holder or lease.epoch != epoch:
                return False
            lease.expires = self.clock() + ttl
            return True

    def lease_release(self, name: str, holder: str, epoch: int) -> bool:
        with self._lock:
            lease = self.leases.get(name)
            if lease is None or lease.holder != holder or lease.epoch != epoch:
                return False
            lease.holder = ""
            lease.expires = 0.0      # epoch survives: tokens never rewind
            return True

    def lease_read(self, name: str) -> Optional[LeaseView]:
        with self._lock:
            lease = self.leases.get(name)
            return self._lease_view(lease) if lease else None

    def lease_live(self, name: str) -> str:
        with self._lock:
            lease = self.leases.get(name)
            if lease is None or not lease.holder:
                return ""
            return lease.holder if lease.expires > self.clock() else ""

    # ------------------------------------------------------------------
    # watch + TriadSets
    # ------------------------------------------------------------------

    def _emit_watch(self, ev: WatchEvent) -> None:
        with self._watch_cv:
            self._watch.append(ev)
            self._watch_cv.notify_all()

    def poll_watch_events(self, timeout: float = 0.0) -> Iterable[WatchEvent]:
        with self._watch_cv:
            if not self._watch and timeout:
                # block until an emitter notifies (or the timeout lapses):
                # the controller's event loop wakes on arrival instead of
                # sleeping out its poll interval (bind latency is queue
                # latency on this path)
                self._watch_cv.wait(timeout)
            out, self._watch = self._watch, []
            return out

    def list_triadsets(self) -> List[dict]:
        with self._lock:
            return list(self.triadsets)

    def list_pods_of_triadset(self, ts: dict) -> List[str]:
        with self._lock:
            prefix = ts["service_name"] + "-"
            return [
                p.name for p in self.pods.values()
                if p.namespace == ts["ns"] and p.name.startswith(prefix)
                and p.name[len(prefix):].isdigit()
            ]

    def create_pod_for_triadset(self, ts: dict, ordinal: int) -> bool:
        name = f"{ts['service_name']}-{ordinal}"
        pod = self.create_pod(name, ts["ns"], cfg_text=ts["cfg_text"])
        pod.hostname = name
        pod.subdomain = ts["service_name"]
        return True

    def update_triadset_status(self, ts: dict, replicas: int) -> bool:
        with self._lock:
            for item in self.triadsets:
                if item["name"] == ts["name"] and item["ns"] == ts["ns"]:
                    item["status_replicas"] = replicas
                    return True
            return False
