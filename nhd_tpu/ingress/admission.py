"""Per-tenant admission queue: bounded lanes, weighted fair dequeue, and
an explicit load-shed ladder (docs/RESILIENCE.md "Layer 9").

The watch-plane FIFO (scheduler/events.py WatchQueue) serves one tenant
perfectly and a hostile mix terribly: a single namespace creating pods at
10x everyone else's rate pushes every other tenant's time-to-bind out
behind its backlog, and overload is only ever expressed implicitly —
queues grow, p99 explodes, nothing is refused with a reason. This queue
replaces that FIFO at the front door:

* **Per-tenant bounded lanes.** TRIAD_POD_CREATE events are laned by
  namespace into bounded deques; everything else (node events, deletes —
  the mirror-consistency traffic) rides an unbounded control lane that is
  always drained first and never shed.
* **Weighted deficit-round-robin dequeue.** The scheduler drains creates
  in DRR order across tenants (weights via NHD_ADMIT_WEIGHTS), so one
  tenant's backlog cannot starve another's next pod, and folds up to
  NHD_ADMIT_BATCH creates into one batched solve.
* **An explicit, monotonic shed ladder.** Pressure — the fullest tenant
  lane's fill fraction, joined with the commit pipeline's occupancy via
  ``pressure_fn`` — moves the queue through ADMIT (0) → DEFER (1) →
  SHED (2). At DEFER, over-rate low-tier pods park in a deferred lane
  (re-admitted fairly when pressure drops); at SHED, over-rate pods are
  refused outright. Every refusal produces a shed record the scheduler
  thread turns into a decision record + pod event + /explain reason +
  journal entry — overload degrades explicitly, never silently.

``NHD_ADMIT=0`` keeps the queue as a pure pass-through FIFO (batched
dequeue, no fairness, no ladder) — the negative-control posture the
tenant-storm chaos cells use to demonstrate the starvation this layer
exists to prevent. All knobs are read at construction time (registered
in config/knobs.py), so harnesses can flip them per cell without
reimporting modules.
"""

from __future__ import annotations

import os
import queue
import threading
from collections import OrderedDict, deque
from typing import Callable, Dict, List, Optional

from nhd_tpu.k8s.retry import API_COUNTERS, ApiCounters
from nhd_tpu.scheduler.events import WatchItem, WatchType

#: the ladder's rungs, in degradation order (monotonic: every rung keeps
#: the restrictions of the rungs below it)
RUNG_ADMIT = 0
RUNG_DEFER = 1
RUNG_SHED = 2


# [the knob reads stay literal os.environ.get calls at the call sites —
# the contract extractor (analysis/contracts.py) and knobs_sync's
# registry↔read cross-reference both key on the literal]


def _parse_float(name: str, raw: str, default: float, *, minimum: float) -> float:
    try:
        val = float(raw) if raw else default
    except ValueError:
        raise ValueError(f"{name} must be a number, got {raw!r}")
    if val < minimum:
        raise ValueError(f"{name} must be >= {minimum}, got {val}")
    return val


def _parse_int(name: str, raw: str, default: int, *, minimum: int) -> int:
    try:
        val = int(raw) if raw else default
    except ValueError:
        raise ValueError(f"{name} must be an integer, got {raw!r}")
    if val < minimum:
        raise ValueError(f"{name} must be >= {minimum}, got {val}")
    return val


def parse_weights(raw: str) -> Dict[str, float]:
    """``"tenant-a=2,default=0.5"`` → weight map. A typo'd entry fails
    loud at construction, not silently at the first contended dequeue."""
    out: Dict[str, float] = {}
    for part in (raw or "").split(","):
        part = part.strip()
        if not part:
            continue
        name, sep, val = part.partition("=")
        if not sep or not name.strip():
            raise ValueError(
                f"NHD_ADMIT_WEIGHTS entry {part!r} is not tenant=weight"
            )
        try:
            w = float(val)
        except ValueError:
            raise ValueError(
                f"NHD_ADMIT_WEIGHTS weight for {name.strip()!r} is not "
                f"a number: {val!r}"
            )
        if w <= 0:
            raise ValueError(
                f"NHD_ADMIT_WEIGHTS weight for {name.strip()!r} must be "
                f"> 0, got {w}"
            )
        out[name.strip()] = w
    return out


class TokenBucket:
    """Per-tenant sustained-rate limiter (classic token bucket) on an
    injectable clock — chaos cells run it on the sim clock, so a failing
    seed replays exactly."""

    def __init__(self, rate: float, burst: float, clock: Callable[[], float]):
        self.rate = float(rate)
        self.burst = float(burst)
        self._clock = clock
        self._tokens = float(burst)
        self._t = clock()

    def take(self, n: float = 1.0) -> bool:
        """Consume *n* tokens if available; False = over-rate. A rate of
        0 disables the limiter (always in-rate)."""
        if self.rate <= 0:
            return True
        now = self._clock()
        self._tokens = min(
            self.burst, self._tokens + max(now - self._t, 0.0) * self.rate
        )
        self._t = now
        if self._tokens >= n:
            self._tokens -= n
            return True
        return False


class _TenantLane:
    """One tenant's bounded admission state: the live deque, the parked
    deferred deque, its token bucket, and its DRR bookkeeping."""

    __slots__ = ("main", "deferred", "bucket", "weight", "deficit")

    def __init__(self, weight: float, bucket: TokenBucket):
        self.main: deque = deque()
        self.deferred: deque = deque()
        self.bucket = bucket
        self.weight = weight
        self.deficit = 0.0

    def depth(self) -> int:
        return len(self.main) + len(self.deferred)


class AdmissionQueue:
    """Drop-in WatchQueue replacement with per-tenant admission.

    The controller (and the scheduler's requeue paths) ``put``; the
    scheduler thread is the only consumer — ``get`` blocks like
    queue.Queue and raises queue.Empty, so the startup flush and the
    run loop work unchanged. The scheduler detects the richer interface
    by duck-typing (``get_creates``) and switches to batched dequeue +
    shed-verdict publishing; tests built on a plain WatchQueue keep the
    exact pre-admission behavior.
    """

    def __init__(
        self,
        *,
        clock: Callable[[], float] = None,
        pressure_fn: Optional[Callable[[], float]] = None,
        counters: Optional[ApiCounters] = None,
    ):
        import time as _time

        self._clock = clock if clock is not None else _time.monotonic
        #: external backpressure (0..1): the scheduler wires the commit
        #: pipeline's occupancy here, coupling ingress admission to the
        #: bind pipeline's depth
        self.pressure_fn = pressure_fn
        self._counters = counters if counters is not None else API_COUNTERS
        env_admit = os.environ.get("NHD_ADMIT", "").lower()
        if env_admit in ("1", "true", "on", ""):
            self.enabled = True
        elif env_admit in ("0", "false", "off"):
            self.enabled = False
        else:
            # same word sets as NHD_ASYNC_COMMIT; a typo'd value must
            # fail loud, not silently disable the overload ladder
            raise ValueError(
                f"NHD_ADMIT must be 1/0/true/false/on/off, got {env_admit!r}"
            )
        self.batch_max = _parse_int(
            "NHD_ADMIT_BATCH", os.environ.get("NHD_ADMIT_BATCH", ""),
            8, minimum=1,
        )
        self.tenant_cap = _parse_int(
            "NHD_ADMIT_TENANT_CAP",
            os.environ.get("NHD_ADMIT_TENANT_CAP", ""), 256, minimum=1,
        )
        self.rate = _parse_float(
            "NHD_ADMIT_RATE", os.environ.get("NHD_ADMIT_RATE", ""),
            0.0, minimum=0.0,
        )
        self.burst = _parse_float(
            "NHD_ADMIT_BURST", os.environ.get("NHD_ADMIT_BURST", ""),
            max(self.rate, 1.0), minimum=1.0,
        )
        self.weights = parse_weights(os.environ.get("NHD_ADMIT_WEIGHTS", ""))
        self.defer_fill = _parse_float(
            "NHD_ADMIT_DEFER_FILL",
            os.environ.get("NHD_ADMIT_DEFER_FILL", ""), 0.5, minimum=0.0,
        )
        self.shed_fill = _parse_float(
            "NHD_ADMIT_SHED_FILL",
            os.environ.get("NHD_ADMIT_SHED_FILL", ""), 0.85, minimum=0.0,
        )
        if self.shed_fill < self.defer_fill:
            # the ladder must be monotonic: the shed rung sits above the
            # defer rung or "escalate" would mean "relax"
            raise ValueError(
                f"NHD_ADMIT_SHED_FILL ({self.shed_fill}) must be >= "
                f"NHD_ADMIT_DEFER_FILL ({self.defer_fill})"
            )
        self._lock = threading.Lock()
        self._not_empty = threading.Condition(self._lock)
        #: non-create traffic (node events, deletes, and — with the
        #: ladder off — everything): unbounded, drained first, never shed
        self._control: deque = deque()
        self._lanes: "OrderedDict[str, _TenantLane]" = OrderedDict()
        self._rr: List[str] = []       # DRR rotation (lane names)
        self._rr_idx = 0
        self._shed: deque = deque()    # refusal records awaiting verdicts
        self.stats: Dict[str, int] = {
            "admitted": 0, "deferred": 0, "readmitted": 0, "shed": 0,
            "requeue_refusals": 0,
        }

    # ------------------------------------------------------------------
    # producer side (controller thread + scheduler requeue paths)
    # ------------------------------------------------------------------

    def _lane(self, tenant: str) -> _TenantLane:
        lane = self._lanes.get(tenant)
        if lane is None:
            lane = _TenantLane(
                self.weights.get(tenant, 1.0),
                TokenBucket(self.rate, self.burst, self._clock),
            )
            self._lanes[tenant] = lane
            self._rr.append(tenant)
        return lane

    def _pressure(self) -> float:
        """Combined overload signal: the fullest tenant lane's fill
        fraction joined (max) with the external pressure_fn — a full
        commit pipeline escalates the ladder even while lanes are
        shallow, which is exactly the state where admitting more solves
        only grows the bind backlog."""
        fill = 0.0
        for lane in self._lanes.values():
            # LIVE depth only: deferred items are parked off the queue,
            # and counting them would hold the rung up forever — the
            # very backlog the defer rung created would block its own
            # recovery. The hard cap still counts total depth, so
            # parking is bounded per tenant either way.
            fill = max(fill, len(lane.main) / float(self.tenant_cap))
        if self.pressure_fn is not None:
            try:
                fill = max(fill, float(self.pressure_fn()))
            except Exception:  # nhdlint: ignore[NHD302]
                # deliberately silent: a broken pressure probe must not
                # take the front door with it (lane fill alone still
                # drives the ladder), and this runs on every put/get —
                # logging here would flood under exactly the overload
                # the ladder exists to manage
                pass
        return fill

    def rung(self) -> int:
        """Current ladder rung (0 ADMIT / 1 DEFER / 2 SHED)."""
        with self._lock:
            return self._rung_locked()

    def _rung_locked(self) -> int:
        if not self.enabled:
            return RUNG_ADMIT
        p = self._pressure()
        if p >= self.shed_fill:
            return RUNG_SHED
        if p >= self.defer_fill:
            return RUNG_DEFER
        return RUNG_ADMIT

    def put(self, item: WatchItem) -> None:
        self._put(item, requeued=False)

    def put_requeue(self, item: WatchItem) -> None:
        """Re-entry for pods the scheduler already admitted once
        (transient-bind requeue, preemptor/victim requeue): bypasses the
        rate bucket and the defer rung — the pod's earlier admission
        already paid them — but still respects the hard lane cap, so a
        requeue storm cannot reinflate the very backlog the ladder just
        shed. A refused requeue produces exactly one shed record; the
        periodic reconcile scan remains the pod's recovery path."""
        self._put(item, requeued=True)

    def put_batch(self, items: List[WatchItem]) -> None:
        """Controller batch seam: admit a whole decode pass under one
        lock acquisition, preserving arrival order."""
        with self._not_empty:
            for item in items:
                self._put_locked(item, requeued=False)
            self._not_empty.notify()

    def _put(self, item: WatchItem, *, requeued: bool) -> None:
        with self._not_empty:
            self._put_locked(item, requeued=requeued)
            self._not_empty.notify()

    def _put_locked(self, item: WatchItem, *, requeued: bool) -> None:
        if not self.enabled or item.type != WatchType.TRIAD_POD_CREATE:
            # ladder off → pure FIFO; control traffic is never laned:
            # deletes and node events are mirror-consistency input, and
            # shedding them would trade overload for state divergence
            self._control.append(item)
            return
        tenant = (item.pod or {}).get("ns", "default")
        lane = self._lane(tenant)
        if lane.depth() >= self.tenant_cap:
            self._refuse_locked(
                item, tenant,
                reason=(
                    f"tenant lane full ({self.tenant_cap} queued)"
                    + (" on requeue" if requeued else "")
                ),
                requeued=requeued,
            )
            return
        if requeued:
            lane.main.append(item)
            self.stats["admitted"] += 1
            self._counters.inc("admission_admitted_total")
            return
        rung = self._rung_locked()
        within_rate = lane.bucket.take()
        tier = self._item_tier(item)
        if rung >= RUNG_SHED and not within_rate:
            self._refuse_locked(
                item, tenant,
                reason=(
                    f"over tenant rate ({self.rate:g}/s) at shed rung "
                    f"(pressure >= {self.shed_fill:g})"
                ),
                requeued=False,
            )
            return
        if rung >= RUNG_DEFER and not within_rate and tier <= 0:
            # the middle rung: over-rate best-effort traffic parks
            # instead of shedding — re-admitted fairly when pressure
            # drops (the recovery half of the ladder)
            lane.deferred.append(item)
            self.stats["deferred"] += 1
            self._counters.inc("admission_deferred_total")
            return
        lane.main.append(item)
        self.stats["admitted"] += 1
        self._counters.inc("admission_admitted_total")

    @staticmethod
    def _item_tier(item: WatchItem) -> int:
        try:
            return int((item.pod or {}).get("tier") or 0)
        except (TypeError, ValueError):
            return 0

    def _refuse_locked(
        self, item: WatchItem, tenant: str, *, reason: str, requeued: bool
    ) -> None:
        pod = item.pod or {}
        # _locked suffix contract: every caller holds _lock already
        self._shed.append({  # nhdlint: ignore[NHD201]
            "ns": pod.get("ns", "default"),
            "pod": pod.get("name", "?"),
            "uid": pod.get("uid", ""),
            "corr": item.corr,
            "tenant": tenant,
            "reason": reason,
            "requeued": requeued,
            "t": self._clock(),
        })
        self.stats["shed"] += 1
        self._counters.inc("admission_shed_total")
        if requeued:
            self.stats["requeue_refusals"] += 1
            self._counters.inc("admission_requeue_refusals_total")

    # ------------------------------------------------------------------
    # consumer side (the scheduler thread only)
    # ------------------------------------------------------------------

    def get(
        self, block: bool = True, timeout: Optional[float] = None
    ) -> WatchItem:
        """One item, control lane first — the WatchQueue contract
        (blocking get with timeout, queue.Empty when nothing arrives)."""
        with self._not_empty:
            if block:
                self._not_empty.wait_for(self._ready_locked, timeout=timeout)
            item = self._pop_one_locked()
            if item is None:
                raise queue.Empty
            return item

    def get_creates(self, limit: int) -> List[WatchItem]:
        """Up to *limit* additional TRIAD_POD_CREATEs in DRR order,
        non-blocking — the scheduler calls this after a blocking get
        returned a create, folding the run into one batched solve.
        Control-lane traffic is never pulled: its items interleave with
        creates in arrival order only through get()."""
        out: List[WatchItem] = []
        if limit <= 0:
            return out
        with self._lock:
            self._recover_locked()
            while len(out) < limit:
                item = self._pop_create_locked()
                if item is None:
                    break
                out.append(item)
        return out

    def batch_limit(self) -> int:
        """How many creates one scheduling batch may fold right now:
        NHD_ADMIT_BATCH, halved at the defer rung and floored to 1 at
        the shed rung — the backpressure coupling between queue/commit
        depth and the scheduler's batch admission."""
        with self._lock:
            rung = self._rung_locked()
        if rung >= RUNG_SHED:
            return 1
        if rung >= RUNG_DEFER:
            return max(1, self.batch_max // 2)
        return self.batch_max

    def _any_locked(self) -> bool:
        if self._control:
            return True
        return any(lane.main for lane in self._lanes.values())

    def _ready_locked(self) -> bool:
        """The blocking get's wake predicate: live work, or parked work
        that is recoverable right now (rung back at ADMIT) — a consumer
        must not sleep out its timeout while re-admission is due."""
        if self._any_locked():
            return True
        if self._rung_locked() != RUNG_ADMIT:
            return False
        return any(lane.deferred for lane in self._lanes.values())

    def _pop_one_locked(self) -> Optional[WatchItem]:
        if self._control:
            return self._control.popleft()
        self._recover_locked()
        return self._pop_create_locked()

    def _pop_create_locked(self) -> Optional[WatchItem]:
        """One create in weighted deficit-round-robin order. The
        rotation and deficits persist across calls, so fairness holds at
        every granularity — single gets, batch folds, across batches."""
        n = len(self._rr)
        for _ in range(2 * n):   # two sweeps: one may only fund deficits
            if n == 0:
                return None
            self._rr_idx %= n
            name = self._rr[self._rr_idx]
            lane = self._lanes[name]
            if not lane.main:
                lane.deficit = 0.0
                self._rr_idx += 1
                continue
            if lane.deficit < 1.0:
                # fund at most once per visit, and only below one
                # credit — an idle lane cannot bank a burst
                lane.deficit += lane.weight
            if lane.deficit >= 1.0:
                lane.deficit -= 1.0
                if lane.deficit < 1.0:
                    # credit spent: the rotation MUST move on, or a
                    # deep lane would pop every call until empty and
                    # starve everyone behind it (weight > 1 lanes keep
                    # the slot while credit remains — that surplus IS
                    # the weight)
                    self._rr_idx += 1
                return lane.main.popleft()
            self._rr_idx += 1
        return None

    def _recover_locked(self) -> None:
        """The ladder's recovery half: once pressure drops below the
        defer rung, parked pods re-enter their tenant's live lane (in
        arrival order; DRR keeps re-admission fair across tenants)."""
        if self._rung_locked() != RUNG_ADMIT:
            return
        for lane in self._lanes.values():
            while lane.deferred:
                lane.main.append(lane.deferred.popleft())
                self.stats["readmitted"] += 1
                self._counters.inc("admission_readmitted_total")

    def drain_shed(self) -> List[dict]:
        """Pop every pending refusal record. The scheduler thread — the
        single writer — turns each into its decision record, pod event,
        /explain reason and journal entry exactly once."""
        with self._lock:
            out = list(self._shed)
            self._shed.clear()
        return out

    # ------------------------------------------------------------------
    # depth/metrics surface
    # ------------------------------------------------------------------

    def empty(self) -> bool:
        """True when a get() would find nothing to pop right now.
        Deferred items at a raised rung deliberately read as empty —
        they are parked, not drainable, and the drive loops that poll
        empty() must not spin on them (qsize/depths still count them:
        parked work IS backlog)."""
        with self._lock:
            return not self._ready_locked()

    def qsize(self) -> int:
        """TRUE ingress backlog: control + every tenant lane, deferred
        included — the nhd_event_queue_depth gauge under this layer."""
        with self._lock:
            return len(self._control) + sum(
                lane.depth() for lane in self._lanes.values()
            )

    def depths(self) -> Dict[str, object]:
        """Per-lane depth snapshot for /metrics and the fleet payload:
        summed total, per-tenant depths, the max tenant depth, deferred
        total and the current rung — one consistent read."""
        with self._lock:
            tenants = {
                name: lane.depth() for name, lane in self._lanes.items()
                if lane.depth()
            }
            return {
                "control": len(self._control),
                "tenants": tenants,
                "max_tenant": max(tenants.values(), default=0),
                "deferred": sum(
                    len(lane.deferred) for lane in self._lanes.values()
                ),
                "total": len(self._control) + sum(
                    lane.depth() for lane in self._lanes.values()
                ),
                "rung": self._rung_locked(),
            }
