"""Overload-robust front door: per-tenant admission between the
controller's watch decode and the scheduler's batch machinery
(docs/RESILIENCE.md "Layer 9 — Overload & admission")."""

from nhd_tpu.ingress.admission import (
    RUNG_ADMIT,
    RUNG_DEFER,
    RUNG_SHED,
    AdmissionQueue,
    TokenBucket,
)

__all__ = [
    "AdmissionQueue",
    "TokenBucket",
    "RUNG_ADMIT",
    "RUNG_DEFER",
    "RUNG_SHED",
]
