"""Logger factory for the framework.

Equivalent role to the reference's NHDCommon.GetLogger (NHDCommon.py:20-38):
one logger per module, colored when attached to a TTY. Defaults to WARNING
(the reference's INFO narration is extremely chatty in the matcher); set
NHD_TPU_LOG_LEVEL=INFO to get it.

``NHD_LOG_JSON=1`` switches every record to one-line JSON stamped with the
active flight-recorder correlation ID (nhd_tpu/obs), so log lines join
against traces and the recent-decisions view: grep the corr id from either
side. The env var is read when a logger first builds its handler — set it
before the process imports the framework, like the log level.
"""

from __future__ import annotations

import json
import logging
import os
import sys

from nhd_tpu.obs.recorder import current_corr_id

_FMT = "%(asctime)s %(levelname)-7s %(name)s: %(message)s"

_COLORS = {
    "DEBUG": "\033[36m",
    "INFO": "\033[32m",
    "WARNING": "\033[33m",
    "ERROR": "\033[31m",
    "CRITICAL": "\033[1;31m",
}
_RESET = "\033[0m"


class _TtyColorFormatter(logging.Formatter):
    def format(self, record: logging.LogRecord) -> str:
        msg = super().format(record)
        color = _COLORS.get(record.levelname)
        return f"{color}{msg}{_RESET}" if color else msg


class JsonFormatter(logging.Formatter):
    """One JSON object per record: ts (epoch seconds), level, logger,
    thread, msg, corr (the context correlation ID or null), and exc for
    records carrying exception info."""

    def format(self, record: logging.LogRecord) -> str:
        out = {
            "ts": round(record.created, 6),
            "level": record.levelname,
            "logger": record.name,
            "thread": record.threadName,
            "msg": record.getMessage(),
            "corr": current_corr_id(),
        }
        if record.exc_info:
            out["exc"] = self.formatException(record.exc_info)
        return json.dumps(out, ensure_ascii=False)


def _pick_formatter() -> logging.Formatter:
    if os.environ.get("NHD_LOG_JSON") == "1":
        return JsonFormatter()
    if sys.stderr.isatty():
        return _TtyColorFormatter(_FMT)
    return logging.Formatter(_FMT)


def get_logger(name: str) -> logging.Logger:
    """Return a configured logger for *name* (idempotent per name)."""
    logger = logging.getLogger(name)
    if not logger.handlers:
        handler = logging.StreamHandler(sys.stderr)
        handler.setFormatter(_pick_formatter())
        logger.addHandler(handler)
        logger.setLevel(os.environ.get("NHD_TPU_LOG_LEVEL", "WARNING").upper())
        logger.propagate = False
    return logger
