"""Logger factory for the framework.

Equivalent role to the reference's NHDCommon.GetLogger (NHDCommon.py:20-38):
one logger per module, colored when attached to a TTY. Defaults to WARNING
(the reference's INFO narration is extremely chatty in the matcher); set
NHD_TPU_LOG_LEVEL=INFO to get it.
"""

from __future__ import annotations

import logging
import os
import sys

_FMT = "%(asctime)s %(levelname)-7s %(name)s: %(message)s"

_COLORS = {
    "DEBUG": "\033[36m",
    "INFO": "\033[32m",
    "WARNING": "\033[33m",
    "ERROR": "\033[31m",
    "CRITICAL": "\033[1;31m",
}
_RESET = "\033[0m"


class _TtyColorFormatter(logging.Formatter):
    def format(self, record: logging.LogRecord) -> str:
        msg = super().format(record)
        color = _COLORS.get(record.levelname)
        return f"{color}{msg}{_RESET}" if color else msg


def get_logger(name: str) -> logging.Logger:
    """Return a configured logger for *name* (idempotent per name)."""
    logger = logging.getLogger(name)
    if not logger.handlers:
        handler = logging.StreamHandler(sys.stderr)
        fmt_cls = _TtyColorFormatter if sys.stderr.isatty() else logging.Formatter
        handler.setFormatter(fmt_cls(_FMT))
        logger.addHandler(handler)
        logger.setLevel(os.environ.get("NHD_TPU_LOG_LEVEL", "WARNING").upper())
        logger.propagate = False
    return logger
