"""Keeping a process off a wedged tunnel-backed TPU plugin.

This dev image's sitecustomize registers a remote-TPU PJRT plugin
("axon") in every interpreter. Two traps, shared by every entry point
that must run on CPU (tests/conftest.py, bench.py, tools/export_tpu.py):

- the plugin initializes even under ``JAX_PLATFORMS=cpu`` (the
  registration overrides the *config*, which beats the env var), and a
  wedged tunnel then blocks forever inside ``make_c_api_client``;
- popping every non-cpu backend factory breaks Pallas, whose import
  registers TPU lowering rules and needs the "tpu" platform to at least
  be *known* — only the tunnel-backed plugin may be dropped.

This is the single copy of that dance. Call before any jax backend
initialization (importing jax is fine; creating arrays is not).
"""

from __future__ import annotations


def force_cpu_backend(jax=None):
    """Pin this process to the CPU backend, immune to a wedged tunnel."""
    if jax is None:
        import jax
    try:  # pragma: no cover - environment-specific
        from jax._src import xla_bridge as _xb

        _xb._backend_factories.pop("axon", None)
    except Exception:  # nhdlint: ignore[NHD302]
        pass  # private-API probe; absence of the factory is the goal
    jax.config.update("jax_platforms", "cpu")
    return jax
