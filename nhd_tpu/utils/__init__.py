from nhd_tpu.utils.logging import get_logger
from nhd_tpu.utils.platform import force_cpu_backend

__all__ = ["get_logger", "force_cpu_backend"]
