"""Tracing and profiling hooks.

The reference has no observability beyond logs (SURVEY §5.1). Here:

* ``phase`` — a context-managed wall-clock phase timer accumulating into a
  dict, for callers instrumenting multi-stage flows (BatchScheduler keeps
  its own typed BatchStats fields for the solve/select/assign breakdown).
  When the flight recorder (nhd_tpu/obs) is enabled, each phase also
  lands in the span ring under the context correlation ID — existing
  call sites join the trace with no edits;
* ``span`` — re-exported from the flight recorder for call sites that
  want a span without a local accumulator dict;
* ``profiler_trace`` — wraps a block in ``jax.profiler.trace`` when a
  directory is given (view with TensorBoard / xprof), no-op otherwise.
  bench.py enables it via NHD_BENCH_PROFILE=<dir>.
"""

from __future__ import annotations

import contextlib
import time
from typing import Dict, Iterator, Optional

from nhd_tpu.obs.recorder import get_recorder, span

__all__ = ["phase", "profiler_trace", "span"]


@contextlib.contextmanager
def phase(acc: Dict[str, float], name: str) -> Iterator[None]:
    """Accumulate the block's wall time into ``acc[name]`` (and the
    flight-recorder ring, when tracing is on)."""
    t0 = time.perf_counter()
    try:
        yield
    finally:
        dt = time.perf_counter() - t0
        acc[name] = acc.get(name, 0.0) + dt
        rec = get_recorder()
        if rec is not None:
            rec.record(name, time.monotonic() - dt, dt, cat="phase")


@contextlib.contextmanager
def profiler_trace(log_dir: Optional[str]) -> Iterator[None]:
    """jax.profiler.trace(log_dir) when a directory is given; else no-op."""
    if not log_dir:
        yield
        return
    import jax

    with jax.profiler.trace(log_dir):
        yield
