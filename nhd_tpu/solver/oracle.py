"""Serial oracle matcher: exact reference semantics, deterministic order.

A clean-room reimplementation of the reference's filter→intersect→select
solver (Matcher.py:27-452), used for two things:

1. the correctness oracle the batched JAX solver is property-tested against;
2. the serial baseline the benchmark compares against (BASELINE.md north
   star: ≥100× this loop).

Semantics notes (each is a load-bearing reference quirk, kept):
* NUMA combinations are enumerated per resource type, then intersected on
  the per-group prefix; CPU combos carry one extra trailing slot for the
  top-level misc cores (Matcher.py:345,442-444).
* SMT ceil-division for SMT-tolerant requests (Matcher.py:179-201) lives in
  CpuRequest.physical_cores.
* GPU-requesting pods skip nodes placed on within MIN_BUSY_SECS
  (Matcher.py:103-111).
* PCI map mode additionally requires each NIC choice to have enough free
  GPUs on its PCIe switch (Matcher.py:295-335).
* Node selection: CPU-only pods prefer GPU-less nodes, else first candidate
  in iteration order (Matcher.py:404-421); the final combo maximizes GPU
  packing skew (Matcher.py:423-452).

Deliberate deviations from the reference (all documented, all pinned by
tests — the JAX solver is property-tested against THIS oracle):

* Combination order: the reference stores combos in Python sets, so its
  tie-breaking order is CPython-hash order (Matcher.py:129,141). Here
  combinations stay in itertools.product order, making every tie-break
  deterministic. Feasible *sets* are identical.
* Top-level misc-core SMT: the reference gates the ceil-halving on a plain
  Enum member (`req_cpus['misc'][1]`, Matcher.py:198) which is truthy even
  for SMT_DISABLED — so the reference *always* ceil-halves misc cores on
  SMT nodes. Four lines earlier it correctly uses `.value` for group cores
  (Matcher.py:182-190). This oracle honors the flag as intended: SMT-OFF
  misc cores cost one physical core each.
* Group/active filtering lives here (see filter_pod_resources) rather than
  in the scheduler wrapper.

Reference quirk kept (and worth knowing): PCI-mode intersection requires
free GPUs per PCIe switch ≥ the number of *NICs chosen* on that switch
(Matcher.py:313-322) — not ≥ the GPUs actually requested. A multi-GPU
group can therefore match a node whose switch holds only one free GPU and
then fail at physical assignment; the scheduler handles that by failing
the pod, exactly as the reference does (NHDScheduler.py:296-299).
"""

from __future__ import annotations

from dataclasses import dataclass
from itertools import product
from typing import Dict, List, Optional, Sequence, Tuple, Union

from nhd_tpu.core.node import HostNode
from nhd_tpu.core.request import PodRequest
from nhd_tpu.core.topology import MapMode, PodTopology
from nhd_tpu.utils import get_logger

NumaCombo = Tuple[int, ...]
NicCombo = Tuple[Tuple[int, int], ...]  # per group: (numa, nic ordinal)


@dataclass
class MatchResult:
    """A chosen node plus the NUMA/NIC mapping to realize on it."""

    node: str
    mapping: Dict[str, tuple]  # {'gpu': NumaCombo, 'cpu': NumaCombo+misc, 'nic': NicCombo}


@dataclass
class FeasibleSets:
    """Per-node feasible combination lists (the reference's `filts[0]`)."""

    gpu: Dict[str, List[NumaCombo]]
    cpu: Dict[str, List[NumaCombo]]
    nic: Dict[str, List[NicCombo]]
    candidates: List[str]


class OracleMatcher:
    """Schedules one pod at a time against the host-side node mirror."""

    def __init__(self) -> None:
        self.logger = get_logger(__name__)

    # ------------------------------------------------------------------

    def find_node(
        self,
        nodes: Dict[str, HostNode],
        req: Union[PodRequest, PodTopology],
        *,
        now: Optional[float] = None,
        respect_busy: bool = True,
    ) -> Optional[MatchResult]:
        """Find the best node + mapping for one pod (reference: Matcher.py:27-63)."""
        if isinstance(req, PodTopology):
            req = PodRequest.from_topology(req)

        if req.map_mode not in (MapMode.NUMA, MapMode.PCI):
            self.logger.error(f"invalid map mode {req.map_mode}")
            return None

        nodes = self.filter_pod_resources(nodes, req)
        filts = self.filter_numa_topology(nodes, req, now=now, respect_busy=respect_busy)
        if not filts.candidates:
            return None

        self.intersect_resources(nodes, filts, req.map_mode)

        node = self.select_node(filts, req, nodes)
        if node is None:
            return None

        mapping = self.choose_mapping(node, nodes[node].numa_nodes, filts)
        return MatchResult(node, mapping)

    # ------------------------------------------------------------------
    # stage 1: pod-level resource filter
    # ------------------------------------------------------------------

    def filter_pod_resources(
        self, nodes: Dict[str, HostNode], req: PodRequest
    ) -> Dict[str, HostNode]:
        """Maintenance + hugepages (reference: Matcher.py:65-84), plus the
        node-group ∩ pod-groups and active checks the reference performs
        scheduler-side before calling the matcher (NHDScheduler.py:235-247)
        — folded in here so direct matcher users get full semantics and the
        JAX solver's group/active predicates have an oracle to test against."""
        return {
            name: node
            for name, node in nodes.items()
            if node.active
            and not node.maintenance
            and req.hugepages_gb <= node.mem.free_hugepages_gb
            and req.node_groups & set(node.groups)
        }

    # ------------------------------------------------------------------
    # stage 2: per-resource NUMA feasibility
    # ------------------------------------------------------------------

    @staticmethod
    def _numa_combos(
        demands: Sequence[float], free: Sequence[float], numa_nodes: int
    ) -> List[NumaCombo]:
        """All assignments of demand slots onto NUMA nodes whose per-node
        sums fit the free vector (reference: Matcher.py:118-129,203-212)."""
        out: List[NumaCombo] = []
        for combo in product(range(numa_nodes), repeat=len(demands)):
            totals = [0.0] * numa_nodes
            for slot, numa in enumerate(combo):
                totals[numa] += demands[slot]
            if all(totals[i] <= free[i] for i in range(numa_nodes)):
                out.append(combo)
        return out

    def filter_numa_topology(
        self,
        nodes: Dict[str, HostNode],
        req: PodRequest,
        *,
        now: Optional[float] = None,
        respect_busy: bool = True,
    ) -> FeasibleSets:
        """Per-node combination enumeration for GPU, CPU, NIC
        (reference: Matcher.py:86-280)."""
        filts = FeasibleSets(gpu={}, cpu={}, nic={}, candidates=list(nodes.keys()))
        req_gpus = req.gpu_counts()

        # --- GPUs (reference: Matcher.py:97-149) ---
        for name, node in nodes.items():
            if sum(req_gpus) > 0 and respect_busy and node.is_busy(now):
                filts.candidates.remove(name)
                continue
            combos = self._numa_combos(
                req_gpus, node.free_gpus_per_numa(), node.numa_nodes
            )
            if not combos:
                filts.candidates.remove(name)
            filts.gpu[name] = combos
        if not filts.candidates:
            return FeasibleSets(gpu={}, cpu={}, nic={}, candidates=[])

        # --- CPUs (reference: Matcher.py:152-222) ---
        for name, node in nodes.items():
            if name not in filts.candidates:
                continue
            slots = req.cpu_slot_counts(node.smt_enabled)
            combos = self._numa_combos(
                slots, node.free_cpu_cores_per_numa(), node.numa_nodes
            )
            if not combos:
                filts.candidates.remove(name)
            filts.cpu[name] = combos

        # --- NICs (reference: Matcher.py:224-276) ---
        bw = req.nic_bw()
        for name, node in nodes.items():
            if name not in filts.candidates:
                continue
            combos = self._nic_combos(node, bw)
            if not combos:
                filts.candidates.remove(name)
            filts.nic[name] = combos

        return filts

    @staticmethod
    def _nic_combos(node: HostNode, bw: List[Tuple[float, float]]) -> List[NicCombo]:
        """All (numa, nic ordinal) assignments per group whose summed rx/tx
        demands fit every chosen NIC's headroom. Groups may share a NIC; the
        subtraction is joint (reference: Matcher.py:242-268, without the
        per-combination deepcopy).
        """
        free = node.free_nic_bw_per_numa()
        out: List[NicCombo] = []
        n_groups = len(bw)
        for numa_combo in product(range(node.numa_nodes), repeat=n_groups):
            # each group picks one NIC ordinal within its assigned NUMA node
            per_group_choices = [range(len(free[numa])) for numa in numa_combo]
            for picks in product(*per_group_choices):
                usage: Dict[Tuple[int, int], List[float]] = {}
                ok = True
                for g in range(n_groups):
                    key = (numa_combo[g], picks[g])
                    acc = usage.setdefault(key, [0.0, 0.0])
                    acc[0] += bw[g][0]
                    acc[1] += bw[g][1]
                for (numa, idx), (rx, tx) in usage.items():
                    if rx > free[numa][idx][0] or tx > free[numa][idx][1]:
                        ok = False
                        break
                if ok:
                    out.append(tuple(zip(numa_combo, picks)))
        return out

    # ------------------------------------------------------------------
    # stage 3: cross-resource intersection
    # ------------------------------------------------------------------

    @staticmethod
    def prune_pci_nic_combos(
        node: HostNode, nic_combos: List[NicCombo]
    ) -> List[NicCombo]:
        """PCI map mode: keep NIC combos whose PCIe switches hold at least
        as many free GPUs as NICs chosen on them — the kept reference
        quirk (Matcher.py:295-335; see module docstring). Shared with the
        explainer (solver/explain.py) so both report identical verdicts
        by construction."""
        gpu_per_sw = node.free_gpus_per_pciesw()
        nic_sw = node.nic_pciesw_per_numa()
        kept: List[NicCombo] = []
        for combo in nic_combos:
            switch_counts: Dict[int, int] = {}
            for numa, idx in combo:
                sw = nic_sw[numa][idx]
                switch_counts[sw] = switch_counts.get(sw, 0) + 1
            if all(
                gpu_per_sw.get(sw, 0) >= count
                for sw, count in switch_counts.items()
            ):
                kept.append(combo)
        return kept

    def intersect_resources(
        self, nodes: Dict[str, HostNode], filts: FeasibleSets, map_mode: MapMode
    ) -> None:
        """Keep only combinations whose per-group NUMA prefix is feasible for
        all three resource types; PCI mode first prunes NIC combos without
        enough free GPUs on their switches (reference: Matcher.py:283-391).
        Mutates ``filts`` in place.
        """
        if map_mode == MapMode.PCI:
            for name in list(filts.candidates):
                filts.nic[name] = self.prune_pci_nic_combos(
                    nodes[name], filts.nic[name]
                )

        for name in list(filts.candidates):
            gpu_prefixes = set(filts.gpu[name])
            cpu_prefixes = {c[:-1] for c in filts.cpu[name]}
            nic_prefixes = {tuple(numa for numa, _ in c) for c in filts.nic[name]}
            common = gpu_prefixes & cpu_prefixes & nic_prefixes
            if not common:
                filts.candidates.remove(name)
                continue
            filts.gpu[name] = [c for c in filts.gpu[name] if c in common]
            filts.cpu[name] = [c for c in filts.cpu[name] if c[:-1] in common]
            filts.nic[name] = [
                c for c in filts.nic[name]
                if tuple(numa for numa, _ in c) in common
            ]

    # ------------------------------------------------------------------
    # stage 4: node selection + mapping choice
    # ------------------------------------------------------------------

    def select_node(
        self, filts: FeasibleSets, req: PodRequest, nodes: Dict[str, HostNode]
    ) -> Optional[str]:
        """CPU-only pods prefer the first GPU-less node; otherwise the first
        candidate in iteration order (reference: Matcher.py:393-421)."""
        if not filts.candidates:
            return None
        if not req.needs_gpu:
            for name in filts.candidates:
                if nodes[name].total_gpus() == 0:
                    return name
        return filts.candidates[0]

    def choose_mapping(
        self, node: str, numa_nodes: int, filts: FeasibleSets
    ) -> Dict[str, tuple]:
        """Pick the GPU combo maximizing packing skew (max-min of per-NUMA
        group counts), then the first CPU/NIC combos sharing its prefix
        (reference: Matcher.py:423-452). First maximal combo wins."""

        def skew(combo: NumaCombo) -> int:
            counts = [combo.count(n) for n in range(numa_nodes)]
            return max(counts) - min(counts)

        gpu_list = filts.gpu[node]
        best = max(range(len(gpu_list)), key=lambda i: (skew(gpu_list[i]), -i))
        gpu_combo = gpu_list[best]

        cpu_combo = next(c for c in filts.cpu[node] if c[:-1] == gpu_combo)
        nic_combo = next(
            c for c in filts.nic[node]
            if tuple(numa for numa, _ in c) == gpu_combo
        )
        return {"gpu": gpu_combo, "cpu": cpu_combo, "nic": nic_combo}


_default = OracleMatcher()


def find_node(
    nodes: Dict[str, HostNode],
    req: Union[PodRequest, PodTopology],
    *,
    now: Optional[float] = None,
    respect_busy: bool = True,
) -> Optional[MatchResult]:
    """Module-level convenience wrapper over OracleMatcher.find_node."""
    return _default.find_node(nodes, req, now=now, respect_busy=respect_busy)
