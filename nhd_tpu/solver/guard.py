"""Solver data-plane fault tolerance: a detect → degrade → repair ladder.

PRs 8-10 made the solver fast by making it stateful and device-resident
(fused AOT megaround, delta-maintained resident arrays, SPMD mesh) — and
every one of those layers assumed the accelerator plane never fails. An
XLA runtime error, a poisoned resident row or a lost mesh device
mid-round surfaced as an unhandled exception in the scheduling loop,
with no analog of the API-layer classification/retry/requeue machinery
(k8s/retry.py) the control plane has had since PR 2. This module is that
missing layer, built on the one property the repo already verifies
continuously: **HostNodes are the source of truth and device state is a
cache** (SURVEY §5.4 re-derivability, the ClusterDelta parity
invariant). The guard *spends* that property at failure time:

* **Detect** — :func:`classify_device_fault` splits raised device-plane
  errors into *transient* (XLA runtime faults, transport errors across a
  TPU tunnel, injected chaos faults, detected corruption — substrate
  health, mirroring the 429/5xx stance of ``k8s/retry.classify``) and
  *terminal* (``INVALID_ARGUMENT``/``UNIMPLEMENTED``, TypeError/
  ValueError — facts about the program that repetition will not fix).
  A budgeted **resident-state audit** (:func:`audit_device_rows`) runs
  periodic + on-suspicion bit-exact spot checks of device rows against
  the host mirror, and :meth:`SolverGuard.screen_rank` screens every
  pulled rank tensor before winners are materialized (the packed tensor
  is int32, so the screen is the integer analog of a NaN/inf screen:
  non-negative ranking values, node indices inside the padded axis; a
  float dtype is itself a defect and IS NaN/inf-screened).

* **Degrade** — an explicit rung ladder with bounded retries per rung:
  mesh megaround → single-device megaround → host
  (``solve_bucket_ranked``). A transient fault condemns the
  ``DeviceClusterState`` and re-dispatches the round — never a wrong or
  partial bind (claims only apply after a clean solve; anything already
  staged at commit time rides the PR 2 unwind+requeue path). The rung
  floor is process-wide: the next batch (and every streaming tile
  context) is rebuilt at the allowed rung through
  ``BatchScheduler.make_context``/``refresh_context``.

* **Repair** — resident arrays rebuild from host truth through the
  sanctioned chokepoints (``DeviceClusterState.rebuild_resident`` /
  a fresh ``DeviceClusterState`` over the live ``ClusterArrays``), the
  guard re-promotes one rung per ``NHD_GUARD_PROBE_ROUNDS`` clean
  rounds, and a shape key whose program keeps faulting is QUARANTINED
  (AOT-quarantine style: its artifact moves to ``quarantine/``, its
  installed program is dropped, and dispatches re-trace live) so one
  poisoned bucket can't wedge the fleet.

Environment knobs (``NHD_GUARD_*``, read per call so chaos cells and
tests can flip them): ``NHD_GUARD`` (1; 0 disables the layer — the
chaos negative control), ``NHD_GUARD_RETRIES`` (attempts per rung per
round), ``NHD_GUARD_PROBE_ROUNDS`` (clean rounds per re-promotion),
``NHD_GUARD_AUDIT_INTERVAL`` (batches between periodic audits),
``NHD_GUARD_AUDIT_ROWS`` (rows per audit; 0 = every row),
``NHD_GUARD_SHAPE_FAULTS`` (faults before a shape key is quarantined).
docs/RESILIENCE.md "Layer 8" has the failure model; docs/OPERATIONS.md
has the knob table and the degraded-mode runbook.
"""

from __future__ import annotations

import os
import threading
from typing import Callable, Iterable, List, Optional

import numpy as np

from nhd_tpu.utils import get_logger

# ---------------------------------------------------------------------------
# rungs
# ---------------------------------------------------------------------------

RUNG_MESH = 0     # full fidelity: SPMD megaround over the device mesh
RUNG_SINGLE = 1   # single-device megaround (mesh condemned)
RUNG_HOST = 2     # host solve path (device plane condemned entirely)

RUNG_NAMES = ("mesh", "single-device", "host")


class DeviceCorruptionError(RuntimeError):
    """Resident device state diverged from the host mirror (audit), or a
    pulled rank tensor failed the value-domain screen. Transient by
    definition: the host mirror is the source of truth, so a rebuild
    repairs it."""


class InjectedDeviceFault(RuntimeError):
    """A chaos-injected device-plane fault (sim/faults.py
    DeviceFaultInjector). Classified transient, like the real XLA
    runtime faults it stands in for."""


def _xla_error_types() -> tuple:
    types: list = []
    try:
        from jax.errors import JaxRuntimeError  # noqa: WPS433

        types.append(JaxRuntimeError)
    except (ImportError, AttributeError):
        pass  # older jax: fall through to the jaxlib name
    try:
        from jax._src.lib import xla_client

        types.append(xla_client.XlaRuntimeError)
    except (ImportError, AttributeError):
        pass  # classification degrades to the stdlib set
    return tuple(types)


_XLA_ERRORS = _xla_error_types()

#: substrings of an XLA runtime error that mean "a fact about the
#: program", not about device health — retrying or degrading cannot fix
#: a malformed program, and burning the retry budget on one would open
#: the ladder against a healthy device (same stance as retry.classify's
#: terminal-4xx rule)
_TERMINAL_MARKERS = ("INVALID_ARGUMENT", "UNIMPLEMENTED")


def classify_device_fault(exc: BaseException) -> bool:
    """True when *exc* is a transient device-plane fault (retry/degrade
    may help), False when it is terminal (a fact about the program or
    the call — surface it). Mirrors ``k8s/retry.classify``: transient =
    substrate health (5xx/status-0 there; XLA runtime faults, transport
    errors, detected corruption here), terminal = deterministic facts
    (4xx there; INVALID_ARGUMENT / TypeError / ValueError here)."""
    if isinstance(exc, (DeviceCorruptionError, InjectedDeviceFault)):
        return True
    if _XLA_ERRORS and isinstance(exc, _XLA_ERRORS):
        msg = str(exc)
        return not any(m in msg for m in _TERMINAL_MARKERS)
    if isinstance(exc, (OSError, MemoryError)):
        # transport failure across the TPU tunnel / host memory pressure:
        # a lower rung (smaller footprint, no relay) can genuinely help
        return True
    return False


# ---------------------------------------------------------------------------
# fault injection seam (sim/faults.py DeviceFaultInjector)
# ---------------------------------------------------------------------------

_INJECTOR: Optional[Callable[[str, str], None]] = None


def set_fault_injector(fn: Optional[Callable[[str, str], None]]) -> None:
    """Install (or clear, with None) the chaos fault injector. The
    injector is called at every device-plane dispatch site with
    ``(site, detail)`` and may raise :class:`InjectedDeviceFault` (or
    sleep, for slow-dispatch faults). Process-global, like the device
    plane it faults — ChaosSim restricts device profiles to solo mode."""
    global _INJECTOR
    _INJECTOR = fn


def maybe_inject(site: str, detail: str = "") -> None:
    """The dispatch-site hook: no-op unless a chaos injector is
    installed (one attribute read on the hot path)."""
    if _INJECTOR is not None:
        _INJECTOR(site, detail)


# ---------------------------------------------------------------------------
# the resident-state audit
# ---------------------------------------------------------------------------


def audit_device_rows(dev, rows: Iterable[int]) -> List[str]:
    """Bit-exact spot check of resident device rows against the host
    mirror (the ClusterDelta parity contract extended one hop further:
    not only must the packed arrays re-derive from HostNodes, the
    device copies must equal the packed arrays). Returns defect strings
    ([] = every sampled row bit-exact). O(|rows|) device pull per
    array; never on the hot path — the guard budgets and schedules it.

    Rows the caller staged but not yet flushed are the device being
    legitimately behind, so callers flush first (the audit entrypoints
    in solver/batch.py do)."""
    from nhd_tpu.solver.kernel import _ARG_ORDER, _MUTABLE

    n = min(dev.N, dev.cluster.n_nodes)
    wanted = {int(r) for r in rows if 0 <= int(r) < n}
    idx_all = np.asarray(sorted(wanted), np.int64)
    # staged-but-unflushed claim rows: the MUTABLE arrays legitimately
    # lag the host there until the next flush (stage_rows defers the
    # scatter into the next dispatch) — and with the flag-only wholesale
    # mode (NHD_DEVICE_DELTA=0) every mutable array lags. Static arrays
    # are never claim-mutated, so they are judged at EVERY sampled row.
    staged = set(getattr(dev, "_staged_rows", None) or ())
    if getattr(dev, "_staged", False) and not staged:
        idx_mut = np.zeros(0, np.int64)
    else:
        idx_mut = np.asarray(sorted(wanted - staged), np.int64)
    if idx_all.size == 0:
        return []
    errs: List[str] = []
    names = getattr(dev.cluster, "names", [])
    # dispatch every gather, THEN start one batched device→host flush
    # before the first blocking pull: on the tunnel-attached TPU each
    # separate transfer pays ~65-84 ms of relay latency regardless of
    # size (docs/TPU_STATUS.md), so 14 sequential pulls would turn one
    # audit into ~1 s of scheduler stall
    gathers = {}
    for name in _ARG_ORDER:
        idx = idx_mut if name in _MUTABLE else idx_all
        if idx.size == 0:
            continue
        gathers[name] = (idx, dev._dev[name][idx])
    for _idx, g in gathers.values():
        try:
            g.copy_to_host_async()
        except (AttributeError, NotImplementedError, RuntimeError):
            # prefetch hint only; the sync pull below still works.
            # AttributeError: host-rung numpy rows; the others: backends
            # without async host copies (XlaRuntimeError is a
            # RuntimeError)
            pass
    for name, (idx, g) in gathers.items():
        want = np.asarray(getattr(dev.cluster, name)[idx])
        # the audit IS a sanctioned host pull of device-resident values
        got = np.asarray(g)
        if want.shape != got.shape:
            errs.append(
                f"{name}: device rows shape {got.shape} != host {want.shape}"
            )
            continue
        if not np.array_equal(want, got):
            bad = [
                int(idx[i]) for i in range(len(idx))
                if not np.array_equal(want[i], got[i])
            ][:4]
            errs.append(
                f"{name}: device rows {bad} != host mirror "
                f"(nodes {[names[r] for r in bad if r < len(names)]})"
            )
    return errs


def _counters():
    from nhd_tpu.k8s.retry import API_COUNTERS

    return API_COUNTERS


# ---------------------------------------------------------------------------
# the guard
# ---------------------------------------------------------------------------


class SolverGuard:
    """Process-wide fault-boundary state: the degradation floor, the
    audit schedule, and the shape-key quarantine. One instance per
    process (``GUARD``), like the jit cache and the AOT program table it
    protects — streaming tile workers share it, so every state
    transition happens under the lock (counters are ApiCounters, already
    thread-safe). Retry ATTEMPT counting is caller-local (an argument to
    :meth:`on_fault`), so concurrent tiles can never launder each
    other's budgets into an unbounded retry loop."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._floor = RUNG_MESH
        self._clean_rounds = 0
        self._batches = 0
        self._last_audit = 0
        self._audits = 0
        self._suspicion = False
        self._shape_faults: dict = {}
        self._quarantined: set = set()
        self.logger = get_logger(__name__)
        #: loop-liveness hook (Scheduler wires its ``_beat``): audits and
        #: recovery retries can legitimately outlast one watchdog budget,
        #: and the stall watchdog must read them as progress, not a wedge
        self.heartbeat: Optional[Callable[[], None]] = None

    # -- configuration (env, read per call so chaos cells flip them) ---

    def active(self) -> bool:
        """The whole layer on/off (NHD_GUARD=0 is the chaos negative
        control: faults surface raw and corruption persists)."""
        return os.environ.get("NHD_GUARD", "1") != "0"

    def retries_per_rung(self) -> int:
        return max(1, int(os.environ.get("NHD_GUARD_RETRIES", "2")))

    def probe_rounds(self) -> int:
        return max(1, int(os.environ.get("NHD_GUARD_PROBE_ROUNDS", "8")))

    def audit_interval(self) -> int:
        return int(os.environ.get("NHD_GUARD_AUDIT_INTERVAL", "64"))

    def audit_rows(self) -> int:
        return int(os.environ.get("NHD_GUARD_AUDIT_ROWS", "16"))

    def shape_fault_limit(self) -> int:
        return max(1, int(os.environ.get("NHD_GUARD_SHAPE_FAULTS", "3")))

    def reset(self) -> None:
        """Back to full fidelity and a clean ledger (test/chaos-cell
        isolation; counters live in API_COUNTERS and reset there)."""
        with self._lock:
            self._floor = RUNG_MESH
            self._clean_rounds = 0
            self._batches = 0
            self._last_audit = 0
            self._audits = 0
            self._suspicion = False
            self._shape_faults.clear()
            self._quarantined.clear()
        _counters().set("guard_rung", RUNG_MESH)
        _counters().set("guard_quarantined_shapes", 0)

    # -- posture -------------------------------------------------------

    @property
    def floor(self) -> int:
        return self._floor

    def allow_mesh(self) -> bool:
        return self._floor <= RUNG_MESH

    def allow_device(self) -> bool:
        return self._floor < RUNG_HOST

    def _beat(self) -> None:
        hb = self.heartbeat
        if hb is None:
            return
        try:
            hb()
        except Exception:  # nhdlint: ignore[NHD302]
            # justified broad catch: the heartbeat is an arbitrary
            # embedder-supplied callback — ANY exception type it raises
            # must be absorbed, because a broken liveness hook breaking
            # fault recovery would turn one bug into two outages
            pass

    # -- detect / degrade ----------------------------------------------

    def on_fault(
        self, exc: BaseException, *, rung: int, attempt: int,
        shape_key: str = "",
    ) -> str:
        """Classify one device-plane fault and decide the caller's next
        move: ``"retry"`` (re-dispatch the round — possibly at a lower
        rung; the caller rebuilds its device state from host truth
        first) or ``"raise"`` (terminal, or the ladder is exhausted).

        ``rung``: the rung the failed attempt ran at. ``attempt``:
        1-based fault count for THIS round, tracked by the caller —
        every ``retries_per_rung()`` faults drop one rung, and a fault
        past the whole ladder's budget propagates."""
        self._beat()
        c = _counters()
        transient = classify_device_fault(exc)
        c.inc("guard_faults_total")
        if isinstance(exc, DeviceCorruptionError):
            c.inc("guard_corruptions_total")
        if not transient:
            c.inc("guard_giveups_total")
            self.logger.error(
                f"solver guard: terminal device-plane fault at rung "
                f"{RUNG_NAMES[rung]} (surfacing): {exc!r}"
            )
            return "raise"
        if shape_key:
            self._note_shape_fault(shape_key)
        with self._lock:
            self._suspicion = True
            self._clean_rounds = 0
        per = self.retries_per_rung()
        if attempt > per * (RUNG_HOST + 1):
            # absolute backstop: whatever the rung accounting saw, a
            # round never retries past the whole ladder's budget
            c.inc("guard_giveups_total")
            return "raise"
        if attempt % per == 0:
            # this rung's budget is spent: degrade (or give up past host)
            if rung >= RUNG_HOST:
                c.inc("guard_giveups_total")
                self.logger.error(
                    "solver guard: host rung exhausted its retry budget; "
                    f"surfacing: {exc!r}"
                )
                return "raise"
            self._degrade(rung + 1, exc)
        c.inc("guard_retries_total")
        self.logger.warning(
            f"solver guard: transient device-plane fault (attempt "
            f"{attempt} at rung {RUNG_NAMES[rung]}); re-dispatching the "
            f"round from host truth: {exc!r}"
        )
        return "retry"

    def _degrade(self, floor: int, exc: BaseException) -> None:
        with self._lock:
            if floor <= self._floor:
                return
            old, self._floor = self._floor, min(floor, RUNG_HOST)
            self._clean_rounds = 0
        c = _counters()
        c.inc("guard_degradations_total")
        c.set("guard_rung", self._floor)
        self.logger.error(
            f"solver guard: degrading {RUNG_NAMES[old]} -> "
            f"{RUNG_NAMES[self._floor]} (bounded retries exhausted): "
            f"{exc!r}"
        )

    # -- repair / re-promotion -----------------------------------------

    def condemn_device(self, exc: BaseException) -> None:
        """Force the floor straight to the host rung: the device plane
        is unreachable (even REBUILDING resident state faults — e.g. a
        dead tunnel fails the device_put itself), so walking the ladder
        one rung at a time would just re-fault at every device rung.
        Clean probe rounds at the host rung re-promote as usual once
        the substrate returns."""
        with self._lock:
            self._suspicion = True
            self._clean_rounds = 0
        _counters().inc("guard_faults_total")
        self._degrade(RUNG_HOST, exc)

    def note_repair(self) -> None:
        """A resident state was rebuilt from host truth (the repair
        chokepoint fired)."""
        _counters().inc("guard_repairs_total")

    def note_round_clean(self) -> None:
        """One solver round completed without a device-plane fault.
        After ``probe_rounds()`` consecutive clean rounds at a degraded
        floor, re-promote ONE rung (gradual: a flappy device earns its
        way back one probe window at a time)."""
        if self._floor == RUNG_MESH:
            return
        with self._lock:
            if self._floor == RUNG_MESH:
                return
            self._clean_rounds += 1
            if self._clean_rounds < self.probe_rounds():
                return
            self._clean_rounds = 0
            self._floor -= 1
            floor = self._floor
        c = _counters()
        c.inc("guard_promotions_total")
        c.set("guard_rung", floor)
        self.logger.warning(
            f"solver guard: re-promoting to rung {RUNG_NAMES[floor]} "
            f"after {self.probe_rounds()} clean probe rounds"
        )

    # -- the audit schedule --------------------------------------------

    def audit_due(self) -> bool:
        """Called once per batch: True when this batch should open with
        a resident-state audit — on the periodic cadence
        (NHD_GUARD_AUDIT_INTERVAL batches) or on suspicion (any fault
        since the last audit)."""
        if not self.active():
            return False
        with self._lock:
            self._batches += 1
            due = self._suspicion
            interval = self.audit_interval()
            if interval > 0 and self._batches - self._last_audit >= interval:
                due = True
            if due:
                self._last_audit = self._batches
                self._suspicion = False
            return due

    def run_audit(self, dev) -> List[str]:
        """One budgeted audit pass over *dev*: NHD_GUARD_AUDIT_ROWS
        rows (0 = every row), sampled as a rotating window so bounded
        budgets still reach every row eventually — deterministically (no
        RNG), so a chaos seed replays bit-exactly. Returns the defects;
        the caller repairs (rebuild_resident) when any are found."""
        self._beat()
        budget = self.audit_rows()
        n = min(dev.N, dev.cluster.n_nodes)
        if n <= 0:
            return []
        with self._lock:
            start = (self._audits * max(budget, 1)) % n
            self._audits += 1
        if budget <= 0 or budget >= n:
            rows: Iterable[int] = range(n)
            sampled = n
        else:
            rows = [(start + i) % n for i in range(budget)]
            sampled = budget
        errs = audit_device_rows(dev, rows)
        c = _counters()
        c.inc("guard_audits_total")
        c.inc("guard_audit_rows_total", sampled)
        if errs:
            c.inc("guard_corruptions_total")
        self._beat()
        return errs

    # -- the rank-tensor screen ----------------------------------------

    def screen_rank(self, arr: np.ndarray, n_padded: int) -> Optional[str]:
        """Value-domain screen of one pulled [9, T, R] rank tensor
        before any winner is materialized — the integer analog of a
        NaN/inf screen (the packed tensor is int32 by contract; a float
        dtype is itself a defect and gets the literal screen). Cheap:
        O(T*R) host compares on an array the round pulled anyway.
        Returns the defect string, or None when clean."""
        if arr.ndim != 3 or arr.shape[0] != 9:
            return f"rank tensor shape {arr.shape} != (9, T, R)"
        if np.issubdtype(arr.dtype, np.floating):
            if not np.isfinite(arr).all():
                return "non-finite values in rank tensor"
            return f"rank tensor dtype {arr.dtype} (int32 contract)"
        val, idx = arr[0], arr[1]
        if (val < 0).any():
            return "negative ranking values (sel encoding is >= 0)"
        if ((idx < 0) | (idx >= n_padded)).any():
            return f"ranked node index outside [0, {n_padded})"
        return None

    # -- shape-key quarantine ------------------------------------------

    def shape_quarantined(self, key_str: str) -> bool:
        return key_str in self._quarantined

    def _note_shape_fault(self, key_str: str) -> None:
        with self._lock:
            n = self._shape_faults.get(key_str, 0) + 1
            self._shape_faults[key_str] = n
            if n < self.shape_fault_limit() or key_str in self._quarantined:
                return
            self._quarantined.add(key_str)
            count = len(self._quarantined)
        _counters().set("guard_quarantined_shapes", count)
        self.logger.error(
            f"solver guard: quarantining shape {key_str} after {n} "
            "faults — its AOT artifact is retired and dispatches "
            "re-trace live (one poisoned bucket must not wedge the rest)"
        )
        self._forget_aot(key_str)

    def _forget_aot(self, key_str: str) -> None:
        """Retire the quarantined shape's AOT program + on-disk artifact
        (a corrupt or miscompiled cached program may be the fault source;
        the next dispatch — and the next restart — must re-trace)."""
        try:
            from nhd_tpu.solver import aot
            from nhd_tpu.solver.kernel import parse_ranked_shape_key

            parsed = parse_ranked_shape_key(key_str)
            if parsed is not None:
                aot.forget(aot.ShapeKey("ranked", *parsed))
        except Exception as exc:
            # quarantine bookkeeping must never turn into a second fault
            self.logger.warning(
                f"solver guard: could not retire AOT artifact for "
                f"{key_str}: {exc}"
            )


#: process-wide guard (one device plane per process, one jit cache, one
#: AOT program table — and one degradation floor over all of them)
GUARD = SolverGuard()
