"""Static combination tables for the batched solver.

The reference enumerates NUMA assignments with itertools.product per pod per
node per call (Matcher.py:118,203,242). Here the enumeration happens once,
as dense numpy tables indexed by a *combo axis*, shared by every pod/node of
a bucket — the solve becomes tensor algebra over that axis.

Orderings are load-bearing: combo index c encodes the per-slot NUMA digits
base-NUMA with slot 0 most significant, i.e. exactly itertools.product
order (row-major, last slot fastest). NIC pick index a does the same base
MAX_NIC. "First feasible" tie-breaks in the oracle therefore translate to
argmax/argmin over these axes.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import lru_cache

import numpy as np


@dataclass(frozen=True)
class ComboTables:
    """All static tables for a (n_groups, n_numa, max_nic) bucket."""

    G: int          # groups per pod in this bucket
    U: int          # NUMA nodes (padded max)
    K: int          # max NICs per NUMA node
    C: int          # U**G NUMA combos
    A: int          # K**G NIC pick combos

    combo: np.ndarray        # [C, G] int8 — NUMA of each group
    combo_onehot: np.ndarray  # [C, G, U] float32
    combo_maxdig: np.ndarray  # [C] int8 — max NUMA digit (node-numa validity)
    skew: np.ndarray          # [C] int32 — max-min of per-NUMA group counts
    misc_onehot: np.ndarray   # [U, U] float32 — misc-slot NUMA choice
    pick: np.ndarray          # [A, G] int8 — NIC ordinal of each group
    choose_onehot: np.ndarray  # [C, A, G, U, K] float32 — 1 iff group g uses (u,k)
    chosen_cnt: np.ndarray    # [C, A, U, K] float32 — groups sharing NIC (u,k)
    need_max: np.ndarray      # [C, A, U] int32 — NICs needed per NUMA (max ordinal+1)


def _digits(n: int, base: int, width: int) -> np.ndarray:
    """[n? no: base**width, width] digit table, slot 0 most significant."""
    idx = np.arange(base**width, dtype=np.int64)
    out = np.zeros((base**width, width), dtype=np.int8)
    for slot in range(width):
        shift = base ** (width - 1 - slot)
        out[:, slot] = (idx // shift) % base
    return out


@lru_cache(maxsize=None)
def get_tables(n_groups: int, n_numa: int, max_nic: int) -> ComboTables:
    G, U, K = n_groups, n_numa, max(max_nic, 1)
    C, A = U**G, K**G

    combo = _digits(C, U, G) if G > 0 else np.zeros((1, 0), np.int8)
    pick = _digits(A, K, G) if G > 0 else np.zeros((1, 0), np.int8)

    combo_onehot = np.zeros((C, G, U), np.float32)
    for c in range(C):
        for g in range(G):
            combo_onehot[c, g, combo[c, g]] = 1.0

    combo_maxdig = (
        combo.max(axis=1).astype(np.int8) if G > 0 else np.zeros((C,), np.int8)
    )

    # packing skew of a combo: max-min of per-NUMA group counts
    # (reference node_delta, Matcher.py:428-431)
    counts = combo_onehot.sum(axis=1)  # [C, U]
    skew = (counts.max(axis=1) - counts.min(axis=1)).astype(np.int32)

    misc_onehot = np.eye(U, dtype=np.float32)

    choose_onehot = np.zeros((C, A, G, U, K), np.float32)
    for c in range(C):
        for a in range(A):
            for g in range(G):
                choose_onehot[c, a, g, combo[c, g], pick[a, g]] = 1.0
    chosen_cnt = choose_onehot.sum(axis=2)  # [C, A, U, K]

    # NICs a pick needs to exist per NUMA: max chosen ordinal + 1
    need_max = np.zeros((C, A, U), np.int32)
    for c in range(C):
        for a in range(A):
            for g in range(G):
                u = combo[c, g]
                need_max[c, a, u] = max(need_max[c, a, u], int(pick[a, g]) + 1)

    return ComboTables(
        G=G, U=U, K=K, C=C, A=A,
        combo=combo, combo_onehot=combo_onehot, combo_maxdig=combo_maxdig,
        skew=skew, misc_onehot=misc_onehot, pick=pick,
        choose_onehot=choose_onehot, chosen_cnt=chosen_cnt, need_max=need_max,
    )
