"""AOT StableHLO program cache: zero-cold-start serving for the solver.

A scheduler restart used to pay the full trace + XLA compile of the
fused solve+rank program on its FIRST real pod (~2.5 s measured in r03's
cold-start bench) — the one latency a crash-only design pays most often.
This module grows the ``artifacts/solver_*.stablehlo.bin`` export
(tools/export_tpu.py) into a first-class runtime cache:

* **Export on first trace** — when saving is on, every fused program the
  live scheduler traces (kernel.dispatch_ranked) is exported via
  ``jax.export`` and written to the cache directory on a background
  worker, so the serving path never waits on serialization. One artifact
  per compiled shape: ``ranked_g{G}_u{U}_k{K}_r{R}_t{Tp}_n{Np}``.
* **Versioned cache keys** — each artifact carries a sidecar meta JSON
  with the jax/jaxlib versions, the solver *program fingerprint* (a hash
  over kernel.py + combos.py sources, so editing the solver math
  invalidates every stale program), the platform list and the jax.export
  calling-convention version. A mismatched or unreadable artifact is
  QUARANTINED (moved to ``<dir>/quarantine/``, never deleted — the
  operator may want the evidence) with one warning per run, and the
  dispatch falls back to a live re-trace; serving is never blocked on a
  stale cache.
* **Prewarm** — ``prewarm()`` (daemon flag ``--prewarm``, cli.py)
  deserializes every valid artifact at start, compiles it, runs it once
  on zeros, and installs it in the in-memory program table that
  ``kernel.dispatch_ranked`` consults before tracing. First-bind latency
  drops to steady-state (bench[first-bind], bench.py), and because
  prewarm records each shape key into the jit stats, steady-state
  dispatches count as cache hits — the ``nhd_jit_*`` zero-recompile
  invariant (tests/test_aot.py) is measured, not assumed.

Environment: ``NHD_AOT_DIR`` (cache directory, default ``artifacts/aot``),
``NHD_AOT_SAVE=1`` (export on first trace), ``NHD_AOT=0`` (disable the
layer entirely). docs/PERFORMANCE.md has the operations recipe.
"""

from __future__ import annotations

import json
import os
import threading
import time
from dataclasses import dataclass, asdict
from typing import Dict, List, Optional

from nhd_tpu.utils import get_logger

AOT_SCHEMA_VERSION = 1
DEFAULT_DIR = os.path.join("artifacts", "aot")

#: fields a sidecar meta must match for the blob to load
_VERSIONED_FIELDS = ("jax_version", "jaxlib_version", "fingerprint")


@dataclass(frozen=True)
class ShapeKey:
    """Identity of one compiled solver program: kind + every dim the
    program specializes on (the same dims kernel.ranked_shape_key puts
    in the jit-stats key). ``mesh``: the kernel.mesh_desc of a sharded
    program ("nodes8"), "" for single-device — a mesh program is a
    DIFFERENT compilation with baked-in shardings, so it caches, exports
    and prewarm-loads under its own key (and is only loadable on a host
    exposing at least that many devices)."""

    kind: str  # "ranked" — the fused solve+rank production program
    G: int
    U: int
    K: int
    R: int
    Tp: int
    Np: int
    mesh: str = ""

    def name(self) -> str:
        return (
            f"{self.kind}_g{self.G}_u{self.U}_k{self.K}"
            f"_r{self.R}_t{self.Tp}_n{self.Np}"
            + (f"_m{self.mesh}" if self.mesh else "")
        )


_FINGERPRINT: Optional[str] = None


def program_fingerprint() -> str:
    """Hash over the solver-program sources: any edit to the kernel math
    or the combo tables changes it, invalidating every cached program
    (deserializing a pre-edit artifact would silently serve the OLD
    placement semantics)."""
    global _FINGERPRINT
    if _FINGERPRINT is None:
        import hashlib
        import inspect

        import nhd_tpu.solver.combos as combos
        import nhd_tpu.solver.kernel as kernel

        h = hashlib.sha256()
        for mod in (kernel, combos):
            h.update(inspect.getsource(mod).encode())
        _FINGERPRINT = h.hexdigest()[:16]
    return _FINGERPRINT


def _versions() -> Dict[str, str]:
    import jax
    import jaxlib

    return {
        "jax_version": jax.__version__,
        "jaxlib_version": jaxlib.version.__version__,
        "fingerprint": program_fingerprint(),
    }


class AotCache:
    """The in-process program table + on-disk artifact cache."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._programs: Dict[ShapeKey, object] = {}
        self._dir: Optional[str] = None      # None -> env/default
        self._save: Optional[bool] = None    # None -> env
        self._exporting: set = set()         # keys with an export queued
        self._export_threads: List[threading.Thread] = []
        self._warned_quarantine = False
        self._warned_export = False
        self.logger = get_logger(__name__)

    # -- configuration -------------------------------------------------

    def configure(
        self, directory: Optional[str] = None, save: Optional[bool] = None,
    ) -> None:
        with self._lock:
            if directory is not None:
                self._dir = directory
            if save is not None:
                self._save = save

    def reset(self) -> None:
        """Drop installed programs and configuration (test isolation)."""
        self.drain()
        with self._lock:
            self._programs.clear()
            self._exporting.clear()
            self._dir = None
            self._save = None
            self._warned_quarantine = False
            self._warned_export = False

    def enabled(self) -> bool:
        return os.environ.get("NHD_AOT", "1") != "0"

    def directory(self) -> str:
        return self._dir or os.environ.get("NHD_AOT_DIR", DEFAULT_DIR)

    def saving(self) -> bool:
        if self._save is not None:
            return self._save
        return os.environ.get("NHD_AOT_SAVE", "0") == "1"

    def _paths(self, key: ShapeKey):
        base = os.path.join(self.directory(), key.name())
        return base + ".stablehlo.bin", base + ".json"

    # -- the dispatch-side surface ------------------------------------

    def lookup(self, key: ShapeKey):
        """The prewarmed program for *key*, or None (live-jit fallback).
        In-memory only — disk is consulted once, at prewarm()."""
        return self._programs.get(key)

    def maybe_export(self, key: ShapeKey, fn, args) -> None:
        """Export-on-first-trace: queue a background export of the live
        jitted *fn* at *args*' shapes, once per key per process, when
        saving is on and no artifact exists yet. The serving dispatch
        never waits on serialization (drain() joins, for tests and the
        seed probe)."""
        if not (self.enabled() and self.saving()):
            return
        bin_path, _ = self._paths(key)
        with self._lock:
            if key in self._exporting or os.path.exists(bin_path):
                return
            self._exporting.add(key)
        specs = tuple((tuple(a.shape), str(a.dtype)) for a in args)
        t = threading.Thread(
            target=self._export, args=(key, fn, specs),
            name=f"nhd-aot-export-{key.name()}", daemon=True,
        )
        with self._lock:
            self._export_threads.append(t)
        t.start()

    def drain(self) -> None:
        """Wait for queued exports to land (probe/test determinism)."""
        with self._lock:
            threads, self._export_threads = self._export_threads, []
        for t in threads:
            t.join()

    def _export(self, key: ShapeKey, fn, specs) -> None:
        try:
            import jax
            from jax import export as jexport

            arg_specs = tuple(
                jax.ShapeDtypeStruct(shape, dtype) for shape, dtype in specs
            )
            exported = jexport.export(fn, platforms=("cpu", "tpu"))(*arg_specs)
            blob = exported.serialize()
            meta = {
                "aot_schema": AOT_SCHEMA_VERSION,
                **asdict(key),
                **_versions(),
                "platforms": list(exported.platforms),
                "calling_convention_version":
                    exported.calling_convention_version,
                "bytes": len(blob),
                # artifact metadata stamp, not placement input
                "created_unix": time.time(),  # nhdlint: ignore[NHD402]
            }
            os.makedirs(self.directory(), exist_ok=True)
            bin_path, meta_path = self._paths(key)
            for path, data in (
                (bin_path, blob),
                (meta_path, json.dumps(meta, indent=1, sort_keys=True).encode()),
            ):
                tmp = f"{path}.tmp.{os.getpid()}"
                with open(tmp, "wb") as fh:
                    fh.write(data)
                os.replace(tmp, path)
            self.logger.info(f"aot: exported {key.name()} ({len(blob)} bytes)")
        except Exception as exc:
            # export is an optimization for the NEXT restart — it must
            # never break the run that volunteered it. But the worker
            # thread is otherwise invisible: count every failure
            # (nhd_aot_export_failures_total) and log the first with its
            # shape key, or an export plane dead for the daemon's whole
            # life would read as "cache warm" forever
            from nhd_tpu.k8s.retry import API_COUNTERS

            API_COUNTERS.inc("aot_export_failures_total")
            with self._lock:
                warned, self._warned_export = self._warned_export, True
            if not warned:
                self.logger.warning(
                    f"aot: export of {key.name()} failed (cache skipped, "
                    f"serving unaffected): {exc}"
                )

    def forget(self, key: ShapeKey) -> None:
        """Drop *key*'s installed program and quarantine its on-disk
        artifact — the solver guard's poisoned-program hook
        (solver/guard.py shape quarantine): a shape whose dispatches
        keep faulting must not be served from the cache again, this run
        or the next. Idempotent; a key with no artifact just loses its
        table entry."""
        with self._lock:
            self._programs.pop(key, None)
        bin_path, meta_path = self._paths(key)
        if os.path.exists(meta_path) or os.path.exists(bin_path):
            self._quarantine(
                meta_path, "solver guard: program faulted repeatedly"
            )

    # -- prewarm -------------------------------------------------------

    def _quarantine(self, meta_path: str, why: str) -> None:
        """Move a stale/broken artifact pair OUT of the load path but
        never delete it — the operator may want the evidence. One
        warning per run covers every quarantined artifact."""
        qdir = os.path.join(self.directory(), "quarantine")
        os.makedirs(qdir, exist_ok=True)
        moved = []
        for path in (meta_path, meta_path[: -len(".json")] + ".stablehlo.bin"):
            if os.path.exists(path):
                dest = os.path.join(qdir, os.path.basename(path))
                # never clobber an earlier quarantined generation of the
                # same shape (exported anew, quarantined again after the
                # next upgrade) — the no-deletion promise covers them all
                n = 1
                while os.path.exists(dest):
                    dest = os.path.join(
                        qdir, f"{os.path.basename(path)}.{n}"
                    )
                    n += 1
                try:
                    os.replace(path, dest)
                    moved.append(dest)
                except OSError:
                    pass
        with self._lock:
            warned, self._warned_quarantine = self._warned_quarantine, True
        if not warned:
            self.logger.warning(
                f"aot: quarantined stale artifact(s) under {qdir} "
                f"(first: {os.path.basename(meta_path)}: {why}); affected "
                "shapes re-trace live"
            )

    def _validate_meta(self, meta: dict) -> Optional[str]:
        if meta.get("aot_schema") != AOT_SCHEMA_VERSION:
            return f"schema {meta.get('aot_schema')!r} != {AOT_SCHEMA_VERSION}"
        want = _versions()
        for field in _VERSIONED_FIELDS:
            if meta.get(field) != want[field]:
                return (
                    f"{field} {meta.get(field)!r} != {want[field]!r}"
                )
        import jax

        platform = jax.default_backend()
        if platform not in meta.get("platforms", []):
            return f"platform {platform!r} not in {meta.get('platforms')!r}"
        return None

    def prewarm(self, progress: Optional[callable] = None) -> dict:
        """Deserialize, compile and install every valid artifact in the
        cache directory; quarantine the rest. Mesh artifacts (sharded
        programs) install under their mesh-qualified key when this host
        exposes enough devices — too few devices SKIPS the artifact
        (it is not stale, just inapplicable here: a single-chip restart
        must not quarantine the slice's programs). Returns a summary
        dict (loaded / quarantined / skipped / seconds / keys).

        ``progress`` is invoked (no args, exceptions swallowed) after
        EVERY artifact processed — loaded, quarantined or skipped. The
        CLI wires ``Scheduler._beat`` here so a long multi-artifact
        compile at startup advances the loop heartbeat per artifact and
        the stall watchdog never reads prewarm as a wedged loop."""
        t0 = time.perf_counter()
        summary = {
            "loaded": 0, "quarantined": 0, "skipped": 0,
            "keys": [], "seconds": 0.0,
        }
        directory = self.directory()
        if not (self.enabled() and os.path.isdir(directory)):
            summary["seconds"] = time.perf_counter() - t0
            return summary
        import jax
        import numpy as np
        from jax import export as jexport

        from nhd_tpu.obs.jitstats import JIT_STATS
        from nhd_tpu.solver.kernel import (
            _ARG_ORDER,
            _POD_ARG_ORDER,
            mesh_shardings,
            parse_mesh_desc,
            ranked_shape_key,
        )

        def _tick() -> None:
            # per-artifact liveness: a broken callback must not break
            # the prewarm that volunteered to report progress
            if progress is None:
                return
            try:
                progress()
            except Exception:  # nhdlint: ignore[NHD302]
                # justified broad catch: progress is an arbitrary
                # caller-supplied callback; prewarm must finish whatever
                # it raises
                pass

        for fname in sorted(os.listdir(directory)):
            if not fname.endswith(".json"):
                continue
            meta_path = os.path.join(directory, fname)
            try:
                with open(meta_path) as fh:
                    meta = json.load(fh)
            except (OSError, ValueError) as exc:
                self._quarantine(meta_path, f"unreadable meta: {exc}")
                summary["quarantined"] += 1
                _tick()
                continue
            why = self._validate_meta(meta)
            if why is not None:
                self._quarantine(meta_path, why)
                summary["quarantined"] += 1
                _tick()
                continue
            desc = meta.get("mesh", "")
            parsed = parse_mesh_desc(desc)
            # LOCAL devices, like every other mesh consumer
            # (resolve_mesh_spec, batch._resolve_mesh): on a
            # multi-controller slice jax.devices() counts every host's
            # chips, the gate would pass, and the numpy warm-up below
            # would fail — quarantining artifacts the docstring promises
            # to skip
            if parsed is not None and parsed[1] > len(jax.local_devices()):
                summary["skipped"] += 1
                _tick()
                continue
            try:
                key = ShapeKey(
                    meta["kind"], meta["G"], meta["U"], meta["K"],
                    meta["R"], meta["Tp"], meta["Np"], desc,
                )
                bin_path = meta_path[: -len(".json")] + ".stablehlo.bin"
                with open(bin_path, "rb") as fh:
                    blob = fh.read()
                exported = jexport.deserialize(bytearray(blob))
                # one wrapper per DISTINCT artifact, installed once in
                # the program table — not a per-call construction. A
                # sharded program re-binds to the live mesh via explicit
                # in_shardings (the exported module bakes the LAYOUT but
                # the call needs this host's device assignment).
                if parsed is not None:
                    from nhd_tpu.parallel.sharding import make_mesh

                    axis, n_dev = parsed
                    mesh = make_mesh(jax.local_devices()[:n_dev], axis=axis)
                    node_spec, repl_spec = mesh_shardings(mesh)
                    prog = jax.jit(  # nhdlint: ignore[NHD104]
                        exported.call,
                        in_shardings=(node_spec,) * len(_ARG_ORDER)
                        + (repl_spec,) * len(_POD_ARG_ORDER),
                    )
                else:
                    prog = jax.jit(exported.call)  # nhdlint: ignore[NHD104]
                zeros = tuple(
                    np.zeros(a.shape, a.dtype) for a in exported.in_avals
                )
                # the warm-up dispatch IS the point: compile now, at
                # daemon start, so the first real pod pays steady-state
                jax.block_until_ready(prog(*zeros))  # nhdlint: ignore[NHD107]
            except Exception as exc:
                self._quarantine(meta_path, f"deserialize/compile: {exc}")
                summary["quarantined"] += 1
                _tick()
                continue
            with self._lock:
                self._programs[key] = prog
            # the loaded program's first production dispatch must count
            # as a cache HIT: record the key now, inside the warmup
            JIT_STATS.record_use(
                "solve_ranked",
                ranked_shape_key(
                    key.G, key.U, key.K, key.R, key.Tp, key.Np, key.mesh
                ),
            )
            summary["loaded"] += 1
            summary["keys"].append(key.name())
            _tick()
        summary["seconds"] = time.perf_counter() - t0
        return summary


#: process-wide cache (one jit cache per process, one program table)
AOT = AotCache()


def lookup(key: ShapeKey):
    return AOT.lookup(key)


def maybe_export(key: ShapeKey, fn, args) -> None:
    AOT.maybe_export(key, fn, args)


def forget(key: ShapeKey) -> None:
    AOT.forget(key)


def configure(directory: Optional[str] = None, save: Optional[bool] = None):
    AOT.configure(directory, save)


def prewarm(progress: Optional[callable] = None) -> dict:
    return AOT.prewarm(progress)


def reset() -> None:
    AOT.reset()


# ---------------------------------------------------------------------------
# first-bind probe: the measured unit of bench[first-bind] (bench.py).
# Runs in a FRESH process (jit caches are process-global, so an in-process
# "cold" number would be a lie): builds the same tiny fake cluster the
# cold-start bench uses, optionally prewarms, binds one pod through the
# real scheduler, and prints one JSON line with the timings.
# ---------------------------------------------------------------------------

def _first_bind_probe(prewarm_first: bool, save: bool) -> dict:
    import queue as queue_mod

    from nhd_tpu.k8s.fake import FakeClusterBackend
    from nhd_tpu.scheduler.core import Scheduler
    from nhd_tpu.scheduler.events import WatchQueue
    from nhd_tpu.sim import (
        SynthNodeSpec, make_node_labels, make_triad_config,
    )

    if save:
        configure(save=True)
    out = {"prewarm_s": 0.0, "programs": 0, "quarantined": 0}
    if prewarm_first:
        summary = prewarm()
        out["prewarm_s"] = summary["seconds"]
        out["programs"] = summary["loaded"]
        out["quarantined"] = summary["quarantined"]
    backend = FakeClusterBackend()
    for i in range(8):
        spec = SynthNodeSpec(name=f"aot-node{i:02d}")
        backend.add_node(
            spec.name, make_node_labels(spec), hugepages_gb=spec.hugepages_gb
        )
    sched = Scheduler(
        backend, WatchQueue(), queue_mod.Queue(), respect_busy=False
    )
    sched.build_initial_node_list()
    backend.create_pod(
        "aot-probe-0", cfg_text=make_triad_config(gpus_per_group=1)
    )
    t0 = time.perf_counter()
    sched.attempt_scheduling_batch([("aot-probe-0", "default", "uid-aot")])
    out["first_bind_s"] = time.perf_counter() - t0
    out["bound"] = backend.pods[("default", "aot-probe-0")].node
    if out["bound"] is None:
        # a failed bind is usually FASTER than a successful one — letting
        # it through would hand the bench-smoke gate an "improved"
        # first-bind figure from a broken scheduler
        raise RuntimeError("first-bind probe pod did not bind")
    if save:
        AOT.drain()  # the seed run's whole job is leaving artifacts behind
    return out


def main(argv: Optional[List[str]] = None) -> int:
    import argparse
    import sys

    ap = argparse.ArgumentParser(
        prog="python -m nhd_tpu.solver.aot", description=__doc__,
    )
    ap.add_argument("--first-bind-probe", action="store_true",
                    help="bind one pod through the real scheduler in this "
                         "fresh process and print timing JSON")
    ap.add_argument("--prewarm", action="store_true",
                    help="prewarm from the AOT cache (NHD_AOT_DIR) first")
    ap.add_argument("--save", action="store_true",
                    help="export traced programs back to the cache")
    ap.add_argument("--platform", default="cpu",
                    help="jax platform for the probe (default cpu)")
    args = ap.parse_args(argv)
    if not args.first_bind_probe:
        ap.print_help()
        return 2
    if args.platform == "cpu":
        from nhd_tpu.utils import force_cpu_backend

        force_cpu_backend()
    result = _first_bind_probe(args.prewarm, args.save)
    print(json.dumps(result))
    return 0


if __name__ == "__main__":
    import sys

    # run the CANONICAL module's main: under `python -m`, this file is
    # the `__main__` module while kernel.dispatch_ranked imports
    # `nhd_tpu.solver.aot` — configuring the `__main__` copy's cache
    # would leave the dispatch path pointing at a different singleton
    from nhd_tpu.solver.aot import main as _canonical_main

    sys.exit(_canonical_main())
