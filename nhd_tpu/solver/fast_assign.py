"""Vectorized physical-ID assignment for batch scheduling.

HostNode.assign_physical_ids walks Python object graphs per pod (~0.4 ms);
at 10k-pod gang scale that dwarfs the batched solve. FastCluster keeps the
allocation state (core/GPU/NIC/hugepage occupancy) in packed numpy arrays
and reproduces the same policies with a handful of vector ops per winner:

* cores: first-fit in core order; SMT-ON takes sibling pairs interleaved
  [c, c+P, ...], SMT-OFF takes one logical core per fully-free pair
  (HostNode.free_cpu_batch semantics, reference Node.py:502-519);
* GPUs: first free GPU on the chosen NIC's PCIe switch, else first free on
  the group's NUMA node (reference Node.py:648-655,495-500);
* NICs: joint rx/tx bandwidth accounting, pods_used marking.

Gather-then-commit per winner: all picks are resolved against a scratch
overlay first, so a failure (e.g. the PCI quirk, see oracle.py) leaves the
state untouched — no unwind pass.

Equivalence with HostNode.assign_physical_ids is property-tested
(tests/test_fast_assign.py); `sync_to_nodes` writes the final state back to
the HostNode mirror, which stays the durable source of truth.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

import numpy as np

from nhd_tpu.core.node import HostNode
from nhd_tpu.core.request import PodRequest
from nhd_tpu.core.topology import MapMode, NicDir, PodTopology, SmtMode


class FastAssignError(RuntimeError):
    """Assignment could not satisfy the promised mapping (state untouched)."""


@dataclass
class GroupAssignment:
    numa: int
    group_cpus: List[int]        # proc cores incl. GPU feeders, hand-out order
    helper_cpus: List[int]
    gpu_devids: List[int]
    nic_uk: Tuple[int, int]
    nic_flat: int                # index into HostNode.nics, -1 if none
    nic_mac: str = ""
    gpu_rows: List[int] = field(default_factory=list)  # FastCluster gpu slots


@dataclass
class AssignRecord:
    """Everything needed to materialize a solved PodTopology later."""

    node_index: int
    node_name: str
    groups: List[GroupAssignment] = field(default_factory=list)
    misc_cpus: List[int] = field(default_factory=list)
    data_vlan: int = 0
    gwip: str = ""
    nic_list: List[Tuple[int, float, NicDir]] = field(default_factory=list)


class FastCluster:
    """Packed allocation state for a set of HostNodes."""

    def __init__(self, nodes: Dict[str, HostNode], U: int, K: int, arrays=None,
                 static_cache: Optional[dict] = None):
        self.arrays = arrays  # optional ClusterArrays kept in sync on assign
        self.names = list(nodes.keys())
        self.node_objs = [nodes[n] for n in self.names]
        N = len(self.node_objs)
        self.U, self.K = U, K
        for node in self.node_objs:
            node._ensure_packed()

        # --- static topology matrices (never mutated by assignment) ---
        # Shared across FastCluster builds over the same unchanged node set
        # via ``static_cache`` (one dict per BatchScheduler): a label
        # reparse rebuilds a node's packed arrays, so array identity is the
        # generation token; the cache entry pins node_objs, keeping the
        # id()s valid (see _bucket_arrays for why pinning matters).
        from nhd_tpu.core.node import pack_generation_key

        key = pack_generation_key(self.node_objs, U, K)
        st = None
        if static_cache is not None:
            ent = static_cache.get("entry")
            if ent is not None and ent["key"] == key:
                st = ent
        if st is None:
            st = self._build_static(key)
            if static_cache is not None:
                static_cache["entry"] = st
        self.P = st["P"]
        self.L = st["L"]
        self.smt = st["smt"]
        self.phys = st["phys"]
        self.core_socket = st["core_socket"]
        self.gpu_numa = st["gpu_numa"]
        self.gpu_sw = st["gpu_sw"]
        self.gpu_devid = st["gpu_devid"]
        self.n_gpus = st["n_gpus"]
        self.nic_flat = st["nic_flat"]
        self.nic_cap = st["nic_cap"]
        self.nic_sw = st["nic_sw"]
        self.gpu_sw_dense = st["gpu_sw_dense"]
        self._nic_idx = st["nic_idx"]
        GM = self.gpu_numa.shape[1]
        L = self.L

        # --- dynamic allocation state (fresh per build) ---
        self.core_used = np.ones((N, L), bool)       # pad: used
        self.gpu_used = np.ones((N, GM), bool)
        self.nic_rx_used = np.zeros((N, U, K), np.float64)
        self.nic_tx_used = np.zeros((N, U, K), np.float64)
        self.nic_pods = np.zeros((N, U, K), np.int32)
        self.hp_free = np.zeros(N, np.int64)
        # homogeneous fast path: when every node shares node0's packed
        # layout (the federation/bench norm — one SKU per tile), the
        # whole build collapses to a few np.stack calls; the per-node
        # fancy-index loop below was ~45 µs/node, the dominant cost of
        # an 8192-node streaming-tile context (~0.4 s)
        homog = False
        if N and self.node_objs[0]._core_used is not None:
            n0 = self.node_objs[0]
            uu0, kk0, valid0 = self._nic_idx[0]
            nc0, ng0, nn0 = len(n0.cores), len(n0.gpus), len(n0.nics)
            homog = all(
                n._core_used is not None
                and len(n.cores) == nc0
                and len(n.gpus) == ng0
                and len(n.nics) == nn0
                for n in self.node_objs
            ) and all(
                (u is None and uu0 is None)
                or (
                    u is not None and uu0 is not None
                    and np.array_equal(u, uu0) and np.array_equal(k, kk0)
                    and np.array_equal(v, valid0)
                )
                for u, k, v in self._nic_idx
            )
        self._sync_plan = None
        if homog:
            self.core_used[:, :nc0] = np.stack(
                [n._core_used for n in self.node_objs]
            )
            if ng0:
                self.gpu_used[:, :ng0] = np.stack(
                    [n._gpu_used for n in self.node_objs]
                )
            if nn0 and uu0 is not None:
                bw = np.stack([n._nic_bw for n in self.node_objs])
                pods_m = np.stack([n._nic_pods for n in self.node_objs])
                self.nic_rx_used[:, uu0, kk0] = bw[:, valid0, 0]
                self.nic_tx_used[:, uu0, kk0] = bw[:, valid0, 1]
                self.nic_pods[:, uu0, kk0] = pods_m[:, valid0]
            self.hp_free[:] = [
                n.mem.free_hugepages_gb for n in self.node_objs
            ]
            # prebuilt sync bindings (see sync_to_nodes)
            self._sync_plan = (
                nc0, ng0,
                uu0 if nn0 else None, kk0, valid0,
                [n._core_used for n in self.node_objs],
                [n._gpu_used for n in self.node_objs],
                [n._nic_bw for n in self.node_objs],
                [n._nic_pods for n in self.node_objs],
                [n.mem for n in self.node_objs],
            )
        else:
            for i, node in enumerate(self.node_objs):
                if node._core_used is not None:
                    self.core_used[i, : len(node.cores)] = node._core_used
                else:
                    # non-identity core layout (hand-assembled node)
                    for c in node.cores:
                        self.core_used[i, c.core] = c.used
                m = len(node.gpus)
                if m:
                    self.gpu_used[i, :m] = node._gpu_used
                uu, kk, valid = self._nic_idx[i]
                if uu is not None:
                    self.nic_rx_used[i, uu, kk] = node._nic_bw[valid, 0]
                    self.nic_tx_used[i, uu, kk] = node._nic_bw[valid, 1]
                    self.nic_pods[i, uu, kk] = node._nic_pods[valid]
                self.hp_free[i] = node.mem.free_hugepages_gb

        self._touched: set = set()

        # native assignment core (ctypes; None → pure-numpy path)
        from nhd_tpu import native as _native

        self._lib = _native.LIB
        if self._lib is not None:
            self._req_cache: Dict[PodRequest, tuple] = {}
            self._bucket_cache: Dict[int, tuple] = {}
            self._out_cores = np.zeros(self.L + 8, np.int32)
            self._out_counts = np.zeros(64, np.int32)
            self._out_gpus = np.zeros(max(GM, 1), np.int32)
            # base addresses + row strides for raw-pointer passing
            self._addr = {
                name: (arr.ctypes.data, arr.strides[0])
                for name, arr in (
                    ("core_socket", self.core_socket),
                    ("gpu_numa", self.gpu_numa),
                    ("gpu_sw", self.gpu_sw),
                )
            }

    def _build_static(self, key) -> dict:
        """One pass over the node objects extracting everything assignment
        never mutates; the result is shareable between FastCluster builds."""
        N = len(self.node_objs)
        U, K = self.U, self.K
        P = max((n.cores_per_proc * n.sockets for n in self.node_objs), default=1)
        L = max((len(n.cores) for n in self.node_objs), default=1)
        GM = max((len(n.gpus) for n in self.node_objs), default=1) or 1

        smt = np.zeros(N, bool)
        phys = np.zeros(N, np.int32)
        core_socket = np.full((N, L), -1, np.int8)
        gpu_numa = np.full((N, GM), -1, np.int8)
        gpu_sw = np.full((N, GM), -1, np.int64)
        gpu_devid = np.full((N, GM), -1, np.int32)
        n_gpus = np.zeros(N, np.int32)
        nic_flat = np.full((N, U, K), -1, np.int32)
        nic_cap = np.zeros((N, U, K), np.float64)   # schedulable Gbps
        nic_sw = np.full((N, U, K), -1, np.int64)
        gpu_sw_dense = np.full((N, GM), -1, np.int32)  # encode_cluster ids
        nic_idx: List[Tuple] = []

        for i, node in enumerate(self.node_objs):
            smt[i] = node.smt_enabled
            phys[i] = node.cores_per_proc * node.sockets
            if node._core_socket is not None:
                core_socket[i, : len(node.cores)] = node._core_socket
            else:
                for c in node.cores:
                    core_socket[i, c.core] = c.socket
            m = len(node.gpus)
            n_gpus[i] = m
            if m:
                gpu_numa[i, :m] = node._gpu_numa
                gpu_sw[i, :m] = node._gpu_sw
                gpu_devid[i, :m] = node._gpu_devid
                # dense switch ids precomputed by _pack_state (the single
                # definition of the sorted-switches mapping)
                gpu_sw_dense[i, :m] = node._gpu_sw_dense
            nb = len(node.nics)
            if nb:
                u, k = node._nic_u, node._nic_k
                valid = (u < U) & (k < K)
                uu, kk = u[valid], k[valid]
                nic_flat[i, uu, kk] = np.arange(nb, dtype=np.int32)[valid]
                nic_cap[i, uu, kk] = node._nic_cap[valid]
                nic_sw[i, uu, kk] = node._nic_sw[valid]
                nic_idx.append((uu, kk, valid))
            else:
                nic_idx.append((None, None, None))

        return {
            "key": key, "node_objs": self.node_objs, "P": P, "L": L,
            "smt": smt, "phys": phys, "core_socket": core_socket,
            "gpu_numa": gpu_numa, "gpu_sw": gpu_sw, "gpu_devid": gpu_devid,
            "n_gpus": n_gpus, "nic_flat": nic_flat, "nic_cap": nic_cap,
            "nic_sw": nic_sw, "gpu_sw_dense": gpu_sw_dense,
            "nic_idx": nic_idx,
        }

    def _row_addr(self, name: str, n: int) -> int:
        base, stride = self._addr[name]
        return base + n * stride

    def refresh_node(self, i: int) -> None:
        """Re-read node *i*'s dynamic allocation state from its HostNode
        — the inverse of sync_to_nodes, for one row. The delta layer
        (solver/encode.py ClusterDelta) patches a persistent context's
        FastCluster through this after out-of-band churn (pod release,
        restart replay, watch events) mutated the host mirror between
        batches; everything static is untouched, so the call is a few
        vector writes. Callers must have ruled out a packed-topology
        rebuild (pack generation change) — that invalidates the static
        matrices and demands a full FastCluster rebuild."""
        node = self.node_objs[i]
        self.core_used[i] = True
        if node._core_used is not None:
            self.core_used[i, : len(node.cores)] = node._core_used
        else:
            for c in node.cores:
                self.core_used[i, c.core] = c.used
        self.gpu_used[i] = True
        m = len(node.gpus)
        if m:
            self.gpu_used[i, :m] = node._gpu_used
        self.nic_rx_used[i] = 0.0
        self.nic_tx_used[i] = 0.0
        self.nic_pods[i] = 0
        uu, kk, valid = self._nic_idx[i]
        if uu is not None:
            self.nic_rx_used[i, uu, kk] = node._nic_bw[valid, 0]
            self.nic_tx_used[i, uu, kk] = node._nic_bw[valid, 1]
            self.nic_pods[i, uu, kk] = node._nic_pods[valid]
        self.hp_free[i] = node.mem.free_hugepages_gb

    # ------------------------------------------------------------------
    # round-level native assignment
    # ------------------------------------------------------------------

    def round_supported(self) -> bool:
        return self._lib is not None and self.arrays is not None

    def round_ok_for(self, pods) -> bool:
        """Bucket within the native round call's fixed-buffer limits
        (mirrors the -100 guard in nhd_assign_round); callers fall back to
        the per-pod path otherwise."""
        return (
            self.round_supported()
            and pods.G <= 16
            and self.L <= 4096
            and self.gpu_used.shape[1] <= 512
            and self.U * self.K <= 128
        )

    def _bucket_arrays(self, pods) -> tuple:
        """[T, G] raw demand arrays for a bucket (cached across rounds —
        dataclasses.replace shares the underlying requests list).

        The cache entry PINS the keyed requests list: an id() key is only
        unique while the object lives, and CPython reuses ids aggressively
        — without the pin, a later bucket's fresh list could collide with
        a dead one's id and be served the WRONG demand arrays (this
        happened in practice under the streaming chunk pattern: phantom
        -1/-2 assignment failures and silent accounting drift)."""
        key = id(pods.requests)
        got = self._bucket_cache.get(key)
        if got is not None:
            return got[1]
        T, G = len(pods.requests), pods.G
        t_proc = np.zeros((T, G), np.int32)
        t_proc_smt = np.zeros((T, G), np.int32)
        t_help = np.zeros((T, G), np.int32)
        t_help_smt = np.zeros((T, G), np.int32)
        t_gpus = np.zeros((T, G), np.int32)
        t_misc = np.zeros(T, np.int32)
        t_misc_smt = np.zeros(T, np.int32)
        for t, r in enumerate(pods.requests):
            for g, grp in enumerate(r.groups):
                t_proc[t, g] = grp.proc.count
                t_proc_smt[t, g] = int(grp.proc.smt)
                t_help[t, g] = grp.misc.count
                t_help_smt[t, g] = int(grp.misc.smt)
                t_gpus[t, g] = grp.gpus
            t_misc[t] = r.misc.count
            t_misc_smt[t] = int(r.misc.smt)
        maxc = int((t_proc.sum(1) + t_help.sum(1) + t_misc).max(initial=1)) + 2
        gmx = max(int(t_gpus.sum(1).max(initial=0)), 1)
        got = (t_proc, t_proc_smt, t_help, t_help_smt, t_gpus,
               t_misc, t_misc_smt, maxc, gmx)
        # bound the cache: a persistent-context FastCluster sees a fresh
        # requests list per schedule() call; without eviction the pins
        # accumulate forever. Recompute cost is trivial, so a coarse
        # clear-on-overflow keeps within-call reuse and bounds memory.
        if len(self._bucket_cache) >= 64:
            self._bucket_cache.clear()
        self._bucket_cache[key] = (pods.requests, got)
        return got

    def assign_round(self, pods, w_node, w_type, w_c, w_m, *,
                     set_busy: bool):
        """Place one round's winners in a single native call; returns
        (status[W], cores[W,MAXC], counts[W,2G+1], nic_flat[W,G], gpus[W,GMX]).

        Mutates occupancy AND the attached solver ClusterArrays exactly as
        per-pod assign + _update_arrays would (parity-tested)."""
        from nhd_tpu.core.node import ENABLE_NIC_SHARING

        (t_proc, t_proc_smt, t_help, t_help_smt, t_gpus,
         t_misc, t_misc_smt, maxc, gmx) = self._bucket_arrays(pods)
        G = pods.G
        W = len(w_node)
        a = self.arrays
        d = lambda arr: arr.ctypes.data
        status = np.zeros(W, np.int32)
        out_cores = np.zeros((W, maxc), np.int32)
        out_counts = np.zeros((W, 2 * G + 1), np.int32)
        out_nic = np.zeros((W, max(G, 1)), np.int32)
        out_gpus = np.zeros((W, gmx), np.int32)
        out_pick = np.zeros(W, np.int32)
        t_pci = pods.map_pci.astype(np.uint8)

        rc = self._lib.nhd_assign_round(
            d(self.core_used), d(self.core_socket), d(self.phys),
            d(self.smt), self.L,
            d(self.gpu_used), d(self.gpu_numa), d(self.gpu_sw),
            d(self.gpu_sw_dense), d(self.n_gpus), self.gpu_used.shape[1],
            d(self.nic_flat), d(self.nic_sw), d(self.nic_rx_used),
            d(self.nic_tx_used), d(self.nic_pods), d(self.nic_cap),
            self.U, self.K,
            d(self.hp_free),
            d(a.cpu_free), d(a.gpu_free), d(a.gpu_free_sw), d(a.nic_free),
            d(a.hp_free), d(a.busy), a.gpu_free_sw.shape[1],
            int(set_busy), int(ENABLE_NIC_SHARING),
            G, d(t_proc), d(t_proc_smt), d(t_help), d(t_help_smt),
            d(t_gpus), d(pods.rx), d(pods.tx), d(t_misc), d(t_misc_smt),
            d(pods.hp), d(t_pci),
            W, d(w_node), d(w_type), d(w_c), d(w_m),
            d(status), d(out_cores), d(out_counts), d(out_nic), d(out_gpus),
            d(out_pick), maxc, gmx,
        )
        if rc != 0:
            raise FastAssignError(f"native round call failed: rc={rc}")
        self._touched.update(int(n) for n in w_node)
        return status, out_cores, out_counts, out_nic, out_gpus, out_pick

    def nic_list_from_round(self, pods, w, t, buffers) -> List[Tuple[int, float, NicDir]]:
        """Consumed-NIC list for winner ``w`` (cheap; no record needed)."""
        out_nic = buffers[3]
        out = []
        for g, grp in enumerate(pods.requests[t].groups):
            flat = int(out_nic[w, g])
            if grp.nic_rx_gbps > 0:
                out.append((flat, grp.nic_rx_gbps, NicDir.RX))
            if grp.nic_tx_gbps > 0:
                out.append((flat, grp.nic_tx_gbps, NicDir.TX))
        return out

    def _build_record(
        self, n, req, cores_row, counts_row, gpu_rows_flat, nic_flats
    ) -> AssignRecord:
        """Unpack flat assignment buffers (one pod's worth — identical
        layout for the per-pod and round-level native calls) into an
        AssignRecord. Single definition keeps both paths bit-identical."""
        node = self.node_objs[n]
        rec = AssignRecord(
            node_index=n, node_name=self.names[n],
            data_vlan=node.data_vlan, gwip=node.gwip,
        )
        cores_at = 0
        gpus_at = 0
        for g, grp in enumerate(req.groups):
            n_proc = int(counts_row[2 * g])
            n_help = int(counts_row[2 * g + 1])
            group_cpus = [int(c) for c in cores_row[cores_at : cores_at + n_proc]]
            cores_at += n_proc
            helpers = [int(c) for c in cores_row[cores_at : cores_at + n_help]]
            cores_at += n_help
            gpu_rows = [int(gpu_rows_flat[gpus_at + j]) for j in range(grp.gpus)]
            gpus_at += grp.gpus
            flat = int(nic_flats[g])
            uk = (-1, -1)
            mac = ""
            if flat >= 0:
                nic = node.nics[flat]
                uk = (nic.numa_node, nic.idx)
                mac = nic.mac
            rec.groups.append(
                GroupAssignment(
                    uk[0], group_cpus, helpers,
                    [int(self.gpu_devid[n, j]) for j in gpu_rows],
                    uk, flat, mac, gpu_rows,
                )
            )
            if grp.nic_rx_gbps > 0:
                rec.nic_list.append((flat, grp.nic_rx_gbps, NicDir.RX))
            if grp.nic_tx_gbps > 0:
                rec.nic_list.append((flat, grp.nic_tx_gbps, NicDir.TX))
        n_misc = int(counts_row[2 * req.n_groups])
        rec.misc_cpus = [int(c) for c in cores_row[cores_at : cores_at + n_misc]]
        return rec

    def record_from_round(self, pods, w, n, t, buffers) -> AssignRecord:
        """Materialize an AssignRecord for winner ``w`` from round buffers."""
        out_cores, out_counts, out_nic, out_gpus = buffers[1:5]
        return self._build_record(
            n, pods.requests[t], out_cores[w], out_counts[w],
            out_gpus[w], out_nic[w],
        )

    # ------------------------------------------------------------------

    def _cpu_batch(
        self, used_row: np.ndarray, n: int, numa: int, num: int, smt_req: SmtMode
    ) -> Optional[List[int]]:
        """First-fit cores on ``numa`` against an overlay row; None if short.
        Mirrors HostNode.free_cpu_batch exactly."""
        if num == 0:
            return []
        P = int(self.phys[n])
        socket = self.core_socket[n, :P]
        if self.smt[n]:
            free_pair = (
                (socket == numa) & ~used_row[:P] & ~used_row[P : 2 * P]
            )
            cand = np.flatnonzero(free_pair)
            if smt_req == SmtMode.ON:
                pairs = num // 2
                if len(cand) < pairs + (num % 2):
                    return None
                out: List[int] = []
                for c in cand[:pairs]:
                    out.extend((int(c), int(c) + P))
                if num % 2:
                    out.append(int(cand[pairs]))
                return out
            if len(cand) < num:
                return None
            return [int(c) for c in cand[:num]]
        free = (socket == numa) & ~used_row[:P]
        cand = np.flatnonzero(free)
        if len(cand) < num:
            return None
        return [int(c) for c in cand[:num]]

    def _pick_gpu(
        self, gpu_row: np.ndarray, n: int, sw: int, numa: int, pci_mode: bool
    ) -> Optional[int]:
        """First free GPU on PCIe switch ``sw``; NUMA fallback unless PCI mode."""
        ng = int(self.n_gpus[n])
        if ng == 0:
            return None
        free = ~gpu_row[:ng]
        on_sw = free & (self.gpu_sw[n, :ng] == sw)
        idx = np.flatnonzero(on_sw)
        if len(idx):
            return int(idx[0])
        if pci_mode:
            return None
        on_numa = free & (self.gpu_numa[n, :ng] == numa)
        idx = np.flatnonzero(on_numa)
        return int(idx[0]) if len(idx) else None

    # ------------------------------------------------------------------

    def _reselect_picks(self, n: int, combo, req: PodRequest):
        """First NIC pick (product order) feasible against LIVE state — the
        mapping's pick is a solve-time snapshot that an earlier claim on the
        same node may have consumed (mirrors select_pick in the C core).
        Returns per-group ordinals, or None."""
        from nhd_tpu.core.node import ENABLE_NIC_SHARING
        from nhd_tpu.solver.combos import get_tables

        G = req.n_groups
        if G == 0:
            return ()
        bw = req.nic_bw()
        for pick in get_tables(G, self.U, self.K).pick:
            ok = True
            joint: Dict[Tuple[int, int], List[float]] = {}
            for g in range(G):
                u, k = int(combo[g]), int(pick[g])
                if self.nic_flat[n, u, k] < 0:
                    ok = False
                    break
                acc = joint.setdefault((u, k), [0.0, 0.0])
                acc[0] += bw[g][0]
                acc[1] += bw[g][1]
            if not ok:
                continue
            for (u, k), (rx, tx) in joint.items():
                if rx <= 0 and tx <= 0:
                    continue
                if ENABLE_NIC_SHARING:
                    free_rx = self.nic_cap[n, u, k] - self.nic_rx_used[n, u, k]
                    free_tx = self.nic_cap[n, u, k] - self.nic_tx_used[n, u, k]
                elif self.nic_pods[n, u, k] > 0:
                    free_rx = free_tx = 0.0
                else:
                    free_rx = free_tx = self.nic_cap[n, u, k]
                if rx > free_rx or tx > free_tx:
                    ok = False
                    break
            if ok and req.map_mode == MapMode.PCI:
                # PCI mode: the pick must also admit the GPU assignment
                # (every GPU off the chosen NIC's switch) — simulate it
                gpu_sim = self.gpu_used[n].copy()
                for g in range(G):
                    if not ok:
                        break
                    u, k = int(combo[g]), int(pick[g])
                    for _ in range(req.groups[g].gpus):
                        j = self._pick_gpu(
                            gpu_sim, n, int(self.nic_sw[n, u, k]),
                            int(combo[g]), True,
                        )
                        if j is None:
                            ok = False
                            break
                        gpu_sim[j] = True
            if ok:
                return tuple(int(p) for p in pick)
        return None

    def assign(
        self, n: int, mapping: Dict[str, tuple], req: PodRequest
    ) -> AssignRecord:
        """Resolve and commit one pod's physical assignment on node row n.

        The NIC pick is re-selected against live state (multi-claim rounds
        can consume the solve-time pick); the realized choice is visible in
        the returned record's nic_uk fields. Raises FastAssignError with no
        state change when any pick fails.
        """
        picks = self._reselect_picks(n, mapping["gpu"], req)
        if picks is None:
            raise FastAssignError(
                f"no feasible NIC pick on {self.names[n]} (stale claim)"
            )
        mapping = {
            "gpu": mapping["gpu"],
            "cpu": mapping["cpu"],
            "nic": tuple(zip(mapping["gpu"], picks)),
        }
        node = self.node_objs[n]
        used_row = self.core_used[n].copy()
        gpu_row = self.gpu_used[n].copy()
        rec = AssignRecord(
            node_index=n, node_name=self.names[n],
            data_vlan=node.data_vlan, gwip=node.gwip,
        )
        nic_rx_add: Dict[Tuple[int, int], float] = {}
        nic_tx_add: Dict[Tuple[int, int], float] = {}

        # the native per-pod call shares the round path's fixed-buffer
        # limits (its out_counts scratch holds 2G+1 entries; a >16-group pod
        # is possible on small-lattice clusters) — larger pods take the
        # numpy path below
        if self._lib is not None and req.n_groups <= 16:
            return self._assign_native(
                n, node, mapping, req, used_row, gpu_row, rec,
                nic_rx_add, nic_tx_add,
            )

        for gi, g in enumerate(req.groups):
            numa = int(mapping["gpu"][gi])
            u, k = (int(x) for x in mapping["nic"][gi])
            flat = int(self.nic_flat[n, u, k])
            if flat < 0 and (g.needs_nic or g.gpus):
                raise FastAssignError(f"no NIC at numa {u} idx {k} on {rec.node_name}")

            group_cpus = self._cpu_batch(used_row, n, numa, g.proc.count, g.proc.smt)
            if group_cpus is None:
                raise FastAssignError(
                    f"short of {g.proc.count} proc cores on numa {numa}"
                )
            used_row[group_cpus] = True

            gpu_ids: List[int] = []
            gpu_rows: List[int] = []
            for _ in range(g.gpus):
                sw = int(self.nic_sw[n, u, k]) if flat >= 0 else -1
                j = self._pick_gpu(
                    gpu_row, n, sw, numa, req.map_mode == MapMode.PCI
                )
                if j is None:
                    raise FastAssignError(
                        f"no free GPU for group {gi} (sw={sw}, numa={numa})"
                    )
                gpu_row[j] = True
                gpu_ids.append(int(self.gpu_devid[n, j]))
                gpu_rows.append(j)

            helpers = self._cpu_batch(used_row, n, numa, g.misc.count, g.misc.smt)
            if helpers is None:
                raise FastAssignError(
                    f"short of {g.misc.count} helper cores on numa {numa}"
                )
            used_row[helpers] = True

            if g.nic_rx_gbps > 0:
                nic_rx_add[(u, k)] = nic_rx_add.get((u, k), 0.0) + g.nic_rx_gbps
            if g.nic_tx_gbps > 0:
                nic_tx_add[(u, k)] = nic_tx_add.get((u, k), 0.0) + g.nic_tx_gbps

            mac = node.nics[flat].mac if flat >= 0 else ""
            rec.groups.append(
                GroupAssignment(
                    numa, group_cpus, helpers, gpu_ids, (u, k), flat, mac, gpu_rows
                )
            )

        misc_numa = int(mapping["cpu"][-1])
        misc = self._cpu_batch(used_row, n, misc_numa, req.misc.count, req.misc.smt)
        if misc is None:
            raise FastAssignError(
                f"short of {req.misc.count} misc cores on numa {misc_numa}"
            )
        used_row[misc] = True
        rec.misc_cpus = misc

        return self._commit(
            n, mapping, req, rec, used_row, gpu_row, nic_rx_add, nic_tx_add
        )

    def _commit(
        self, n, mapping, req, rec, used_row, gpu_row, nic_rx_add, nic_tx_add
    ) -> AssignRecord:
        """Apply a fully-resolved assignment (shared by both pick paths)."""
        if req.hugepages_gb > self.hp_free[n]:
            raise FastAssignError("hugepages exhausted")

        self.core_used[n] = used_row
        self.gpu_used[n] = gpu_row
        self.hp_free[n] -= req.hugepages_gb
        for (u, k), add in nic_rx_add.items():
            self.nic_rx_used[n, u, k] += add
        for (u, k), add in nic_tx_add.items():
            self.nic_tx_used[n, u, k] += add
        if not rec.nic_list:  # _build_record-produced records arrive filled
            for ga, g in zip(rec.groups, req.groups):
                if ga.nic_flat < 0:
                    continue
                if g.nic_rx_gbps > 0:
                    rec.nic_list.append((ga.nic_flat, g.nic_rx_gbps, NicDir.RX))
                if g.nic_tx_gbps > 0:
                    rec.nic_list.append((ga.nic_flat, g.nic_tx_gbps, NicDir.TX))
        # only NICs actually serving rx/tx cores are claimed — a zero-
        # bandwidth group's mapped NIC stays free (the reference's nic_list
        # only carries NIC-serving cores, NHDScheduler.py:302-304)
        claimed_uks = {
            ga.nic_uk
            for ga, g in zip(rec.groups, req.groups)
            if ga.nic_flat >= 0 and g.needs_nic
        }
        for uk in claimed_uks:
            self.nic_pods[n, uk[0], uk[1]] += 1
        self._touched.add(n)

        if self.arrays is not None:
            self._update_arrays(n, mapping, req, rec, claimed_uks)
        return rec

    def _req_arrays(self, req: PodRequest) -> tuple:
        """Flattened per-type demand arrays for the native call (cached —
        gang batches share one entry)."""
        got = self._req_cache.get(req)
        if got is None:
            G = req.n_groups
            got = (
                np.asarray([g.proc.count for g in req.groups], np.int32),
                np.asarray([int(g.proc.smt) for g in req.groups], np.int32),
                np.asarray([g.misc.count for g in req.groups], np.int32),
                np.asarray([int(g.misc.smt) for g in req.groups], np.int32),
                np.asarray([g.gpus for g in req.groups], np.int32),
                np.zeros(G, np.int32),   # scratch: g_numa
                np.zeros(G, np.int64),   # scratch: g_nic_sw
            )
            self._req_cache[req] = got
        return got

    def _assign_native(
        self, n, node, mapping, req, used_row, gpu_row, rec,
        nic_rx_add, nic_tx_add,
    ) -> AssignRecord:
        """One C call resolves every core/GPU pick (native/nhd_assign.cc)."""
        g_proc, g_proc_smt, g_help, g_help_smt, g_gpus, g_numa, g_nic_sw = (
            self._req_arrays(req)
        )
        flats = []
        nic_flat_row = self.nic_flat[n]
        nic_sw_row = self.nic_sw[n]
        for gi, g in enumerate(req.groups):
            u, k = mapping["nic"][gi]
            flat = int(nic_flat_row[u, k])
            if flat < 0 and (g.needs_nic or g.gpus):
                raise FastAssignError(
                    f"no NIC at numa {u} idx {k} on {rec.node_name}"
                )
            flats.append((u, k, flat))
            g_numa[gi] = mapping["gpu"][gi]
            g_nic_sw[gi] = int(nic_sw_row[u, k]) if flat >= 0 else -1

        addr = self._row_addr
        rc = self._lib.nhd_assign_pod(
            used_row.ctypes.data, addr("core_socket", n),
            int(self.phys[n]), int(self.smt[n]),
            gpu_row.ctypes.data, addr("gpu_numa", n), addr("gpu_sw", n),
            int(self.n_gpus[n]),
            req.n_groups,
            g_numa.ctypes.data, g_nic_sw.ctypes.data,
            g_proc.ctypes.data, g_proc_smt.ctypes.data,
            g_help.ctypes.data, g_help_smt.ctypes.data, g_gpus.ctypes.data,
            int(mapping["cpu"][-1]), req.misc.count, int(req.misc.smt),
            int(req.map_mode == MapMode.PCI),
            self._out_cores.ctypes.data, self._out_counts.ctypes.data,
            self._out_gpus.ctypes.data,
        )
        if rc < 0:
            stage = {-1: "proc cores", -2: "free GPU", -3: "helper cores",
                     -4: "misc cores"}.get(rc, "resources")
            raise FastAssignError(f"short of {stage} on {rec.node_name}")

        cores_at = 0
        gpus_at = 0
        for gi, g in enumerate(req.groups):
            u, k, flat = flats[gi]
            n_proc = int(self._out_counts[2 * gi])
            n_help = int(self._out_counts[2 * gi + 1])
            group_cpus = self._out_cores[cores_at : cores_at + n_proc].tolist()
            cores_at += n_proc
            helpers = self._out_cores[cores_at : cores_at + n_help].tolist()
            cores_at += n_help
            gpu_rows = [int(self._out_gpus[gpus_at + j]) for j in range(g.gpus)]
            gpus_at += g.gpus
            gpu_ids = [int(self.gpu_devid[n, j]) for j in gpu_rows]
            if g.nic_rx_gbps > 0:
                nic_rx_add[(u, k)] = nic_rx_add.get((u, k), 0.0) + g.nic_rx_gbps
            if g.nic_tx_gbps > 0:
                nic_tx_add[(u, k)] = nic_tx_add.get((u, k), 0.0) + g.nic_tx_gbps
            mac = node.nics[flat].mac if flat >= 0 else ""
            rec.groups.append(
                GroupAssignment(
                    int(g_numa[gi]), group_cpus, helpers, gpu_ids,
                    (u, k), flat, mac, gpu_rows
                )
            )
        n_misc = int(self._out_counts[2 * req.n_groups])
        rec.misc_cpus = self._out_cores[cores_at : cores_at + n_misc].tolist()

        return self._commit(
            n, mapping, req, rec, used_row, gpu_row, nic_rx_add, nic_tx_add
        )

    def _update_arrays(self, n, mapping, req, rec, claimed_uks) -> None:
        """Incrementally maintain the solver-visible ClusterArrays row —
        the O(groups) delta replaces a full node re-projection per round.

        The CPU decrement per slot equals the slot's physical-core demand:
        SMT-ON consumes ceil(count/2) full sibling pairs, SMT-OFF poisons
        one otherwise-free pair per core, non-SMT is 1:1 — exactly the
        feasibility demand, so free-pair counts stay consistent.
        """
        from nhd_tpu.core.node import ENABLE_NIC_SHARING

        arrays = self.arrays
        slots = req.cpu_slot_counts(bool(self.smt[n]))
        for g_i, numa in enumerate(mapping["gpu"]):
            arrays.cpu_free[n, int(numa)] -= slots[g_i]
        arrays.cpu_free[n, int(mapping["cpu"][-1])] -= slots[-1]

        for ga in rec.groups:
            for j in ga.gpu_rows:
                # decrement by the *chosen* GPU's NUMA node: the PCI-switch
                # preference can pick a GPU off the group's NUMA node
                # (reference Node.py:648-655 matches switch only)
                arrays.gpu_free[n, int(self.gpu_numa[n, j])] -= 1
                arrays.gpu_free_sw[n, int(self.gpu_sw_dense[n, j])] -= 1

        for (u, k) in claimed_uks:
            if ENABLE_NIC_SHARING:
                arrays.nic_free[n, u, k, 0] = (
                    self.nic_cap[n, u, k] - self.nic_rx_used[n, u, k]
                )
                arrays.nic_free[n, u, k, 1] = (
                    self.nic_cap[n, u, k] - self.nic_tx_used[n, u, k]
                )
            else:
                arrays.nic_free[n, u, k, :] = 0.0

        arrays.hp_free[n] -= req.hugepages_gb

    # ------------------------------------------------------------------

    def sync_to_nodes(self) -> None:
        """Write allocation changes back to the HostNode mirror — one
        vector write per packed array per touched node (the component
        objects are views over these arrays, core/node.py _pack_state).

        On a homogeneous cluster the per-node bindings (target arrays,
        NIC index maps) are prebuilt at construction (``_sync_plan``), so
        the loop touches only local lists — the attribute walks were the
        dominant cost of a 1k-node gang's final sync. A node whose packed
        arrays were rebuilt since the plan (identity mismatch) falls back
        to the re-reading path."""
        plan = self._sync_plan
        if plan is not None and self._touched:
            nc0, ng0, uu0, kk0, valid0, cores_l, gpus_l, bw_l, pods_l, mem_l = plan
            idx = np.fromiter(
                self._touched, np.int64, len(self._touched)
            )
            # gather every touched row in a handful of big vector ops;
            # the loop below only scatters into the per-node arrays —
            # per-node fancy gathers were ~2 µs apiece × 3 × N
            cu = self.core_used[idx, :nc0]
            gu = self.gpu_used[idx, :ng0] if ng0 else None
            if uu0 is not None:
                bwt = np.stack(
                    [
                        self.nic_rx_used[idx][:, uu0, kk0],
                        self.nic_tx_used[idx][:, uu0, kk0],
                    ],
                    axis=-1,
                )
                pd = self.nic_pods[idx][:, uu0, kk0]
            hp = self.hp_free[idx]
            objs = self.node_objs
            for j, n in enumerate(idx.tolist()):
                dst = cores_l[n]
                if objs[n]._core_used is not dst:
                    self._sync_one(n)
                    continue
                dst[:] = cu[j]
                if ng0:
                    gpus_l[n][:] = gu[j]
                if uu0 is not None:
                    bw_l[n][valid0] = bwt[j]
                    pods_l[n][valid0] = pd[j]
                mem_l[n].free_hugepages_gb = int(hp[j])
            self._touched.clear()
            return
        for n in self._touched:
            self._sync_one(n)
        self._touched.clear()

    def _sync_one(self, n: int) -> None:
        """Sync one node row, re-reading its current packed bindings."""
        node = self.node_objs[n]
        if node._core_used is not None:
            node._core_used[:] = self.core_used[n, : len(node.cores)]
        else:
            for c in node.cores:
                c.used = bool(self.core_used[n, c.core])
        m = len(node.gpus)
        if m:
            node._gpu_used[:] = self.gpu_used[n, :m]
        uu, kk, valid = self._nic_idx[n]
        if uu is not None:
            node._nic_bw[valid, 0] = self.nic_rx_used[n, uu, kk]
            node._nic_bw[valid, 1] = self.nic_tx_used[n, uu, kk]
            node._nic_pods[valid] = self.nic_pods[n, uu, kk]
        node.mem.free_hugepages_gb = int(self.hp_free[n])


def apply_record_to_topology(rec: AssignRecord, top: PodTopology) -> None:
    """Fill a PodTopology with the physical IDs a FastCluster assignment
    chose — the same field-filling assign_physical_ids performs inline
    (reference Node.py:663-841), decoupled from the hot path."""
    for ga, pg in zip(rec.groups, top.proc_groups):
        if pg.vlan is not None:
            pg.vlan.vlan = rec.data_vlan
        cursor = 0
        for gpu, devid in zip(pg.gpus, ga.gpu_devids):
            gpu.device_id = devid
        for gpu in pg.gpus:
            for feeder in gpu.cpu_cores:
                feeder.core = ga.group_cpus[cursor]
                cursor += 1
        for core in pg.proc_cores:
            core.core = ga.group_cpus[cursor]
            cursor += 1
            if core.nic_dir in (NicDir.RX, NicDir.TX):
                pair = top.nic_pair_for_core(core)
                if pair is not None:
                    pair.mac = ga.nic_mac
        for helper, c in zip(pg.misc_cores, ga.helper_cpus):
            helper.core = c
    for mc, c in zip(top.misc_cores, rec.misc_cpus):
        mc.core = c
    if top.ctrl_vlan is not None:
        top.ctrl_vlan.vlan = rec.data_vlan
    top.set_data_default_gw(rec.gwip)
