"""Structured unschedulability diagnosis ("why won't this pod place?").

The reference's only debugging story is grepping the matcher's verbose
logs for the node that rejected a pod (reference README.md:161-171 shows
the documented workflow). This module answers the same question as data:
for one pod against the current node mirror, report each node's FIRST
failing predicate — in the exact order the matcher applies them
(Matcher.py:65-391 / solver/oracle.py) — plus a cluster-wide summary.

Serial per-node evaluation via the oracle stages (exact semantics, no
tensor blow-up): explaining is a one-pod operator query, not a hot path.
"""

from __future__ import annotations

from collections import Counter
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Union

from nhd_tpu.core.node import HostNode
from nhd_tpu.core.request import PodRequest
from nhd_tpu.core.topology import MapMode, PodTopology
from nhd_tpu.solver.oracle import OracleMatcher

# predicate order mirrors the matcher pipeline (oracle.find_node)
R_INACTIVE = "node-inactive"            # cordoned / missing scheduler taint
R_MAINTENANCE = "maintenance"
R_HUGEPAGES = "insufficient-hugepages"
R_GROUPS = "node-group-mismatch"
R_BUSY = "busy-backoff"                 # GPU pod within MIN_BUSY_SECS window
R_GPU = "gpu-numa-fit"
R_CPU = "cpu-numa-fit"
R_NIC = "nic-bandwidth-fit"
R_PCI = "pci-switch-pairing"
R_INTERSECT = "cross-resource-numa-intersection"
R_INVALID_MODE = "invalid-map-mode"     # matcher rejects unconditionally
R_OK = "schedulable"


@dataclass
class NodeVerdict:
    node: str
    reason: str
    detail: str = ""


@dataclass
class ExplainReport:
    pod_summary: str
    verdicts: List[NodeVerdict] = field(default_factory=list)
    schedulable_nodes: List[str] = field(default_factory=list)
    # policy verdict (nhd_tpu/policy/, present only with NHD_POLICY=1):
    # the pod's tier, the scoring mode, and the score-term breakdown per
    # schedulable node — (class, quantized score); the highest-scoring
    # schedulable node is what the fused megaround's ranking picks first
    policy: Optional[dict] = None

    @property
    def summary(self) -> Dict[str, int]:
        return dict(Counter(v.reason for v in self.verdicts))

    def render(self) -> str:
        """Human-readable report (CLI output)."""
        lines = [f"pod: {self.pod_summary}"]
        counts = self.summary
        lines.append(
            "summary: "
            + ", ".join(f"{r}={n}" for r, n in sorted(counts.items()))
        )
        if self.schedulable_nodes:
            lines.append(
                f"schedulable on {len(self.schedulable_nodes)} node(s): "
                + ", ".join(self.schedulable_nodes[:8])
                + ("..." if len(self.schedulable_nodes) > 8 else "")
            )
        else:
            lines.append("UNSCHEDULABLE on every node")
        if self.policy is not None and self.policy.get("scores"):
            ranked = sorted(
                self.policy["scores"].items(),
                key=lambda kv: -kv[1]["score"],
            )
            lines.append(
                f"policy: tier={self.policy['tier']} "
                f"mode={self.policy['score_mode']} "
                + ", ".join(
                    f"{n}={s['class']}:{s['score']}" for n, s in ranked[:8]
                )
            )
        for v in self.verdicts:
            if v.reason != R_OK:
                lines.append(
                    f"  {v.node}: {v.reason}"
                    + (f" ({v.detail})" if v.detail else "")
                )
        return "\n".join(lines)


def explain(
    nodes: Dict[str, HostNode],
    req: Union[PodRequest, PodTopology],
    *,
    now: Optional[float] = None,
    respect_busy: bool = True,
) -> ExplainReport:
    """Per-node first-failing-predicate report for one pod."""
    if isinstance(req, PodTopology):
        req = PodRequest.from_topology(req)
    matcher = OracleMatcher()

    gpus = sum(req.gpu_counts())
    bw = req.nic_bw()
    report = ExplainReport(
        pod_summary=(
            f"{req.n_groups} group(s), {gpus} GPU(s), "
            f"{sum(rx + tx for rx, tx in bw):.0f} Gbps NIC, "
            f"{req.hugepages_gb} GiB hugepages, map={req.map_mode.name}, "
            f"groups={sorted(req.node_groups)}"
        )
    )

    if req.map_mode not in (MapMode.NUMA, MapMode.PCI):
        # the matcher refuses these outright (oracle.find_node) — report
        # that, not per-node feasibility
        report.verdicts = [
            NodeVerdict(name, R_INVALID_MODE,
                        f"map mode {req.map_mode.name} is not schedulable")
            for name in nodes
        ]
        return report

    for name, node in nodes.items():
        report.verdicts.append(_explain_node(matcher, name, node, req,
                                             now=now,
                                             respect_busy=respect_busy))
    report.schedulable_nodes = [
        v.node for v in report.verdicts if v.reason == R_OK
    ]
    _attach_policy(report, nodes, req)
    return report


def _attach_policy(report: ExplainReport, nodes, req) -> None:
    """Score-term breakdown for the schedulable nodes (policy engine):
    answers "the pod CAN run on 12 nodes — why did it land THERE" as
    data. Off (None) unless NHD_POLICY=1."""
    from nhd_tpu import policy as _policy

    if not _policy.enabled():
        return
    from nhd_tpu.policy.classes import CLASSES, node_class_index
    from nhd_tpu.policy.scoring import score_mode, score_row

    row = score_row(req)
    scores = {}
    for name in report.schedulable_nodes:
        idx = node_class_index(nodes[name])
        scores[name] = {
            "class": CLASSES.name_of(idx),
            "score": int(row[min(idx, len(row) - 1)]),
        }
    report.policy = {
        "tier": getattr(req, "tier", 0),
        "score_mode": score_mode(),
        "scores": scores,
    }


def _explain_node(
    matcher: OracleMatcher,
    name: str,
    node: HostNode,
    req: PodRequest,
    *,
    now: Optional[float],
    respect_busy: bool,
) -> NodeVerdict:
    # stage 1: pod-level filters, split into individual reasons
    if not node.active:
        return NodeVerdict(name, R_INACTIVE)
    if node.maintenance:
        return NodeVerdict(name, R_MAINTENANCE)
    if req.hugepages_gb > node.mem.free_hugepages_gb:
        return NodeVerdict(
            name, R_HUGEPAGES,
            f"need {req.hugepages_gb} GiB, free {node.mem.free_hugepages_gb}",
        )
    if not (req.node_groups & set(node.groups)):
        return NodeVerdict(
            name, R_GROUPS,
            f"node groups {sorted(node.groups)}",
        )
    if sum(req.gpu_counts()) > 0 and respect_busy and node.is_busy(now):
        return NodeVerdict(name, R_BUSY)

    # stage 2: per-resource NUMA feasibility, in matcher order
    gpu_combos = matcher._numa_combos(
        req.gpu_counts(), node.free_gpus_per_numa(), node.numa_nodes
    )
    if not gpu_combos:
        return NodeVerdict(
            name, R_GPU,
            f"need {list(req.gpu_counts())}, "
            f"free/numa {node.free_gpus_per_numa()}",
        )
    cpu_combos = matcher._numa_combos(
        req.cpu_slot_counts(node.smt_enabled),
        node.free_cpu_cores_per_numa(), node.numa_nodes,
    )
    if not cpu_combos:
        return NodeVerdict(
            name, R_CPU,
            f"need {list(req.cpu_slot_counts(node.smt_enabled))} phys, "
            f"free/numa {node.free_cpu_cores_per_numa()}",
        )
    nic_combos = matcher._nic_combos(node, req.nic_bw())
    if not nic_combos:
        free = node.free_nic_bw_per_numa()
        return NodeVerdict(
            name, R_NIC,
            f"need {[f'{rx:.0f}/{tx:.0f}' for rx, tx in req.nic_bw()]} "
            f"rx/tx Gbps, headroom/numa "
            f"{[[f'{r:.0f}/{t:.0f}' for r, t in numa] for numa in free]}",
        )

    # stage 3: PCI switch pairing, then cross-type intersection
    if req.map_mode == MapMode.PCI:
        nic_combos = matcher.prune_pci_nic_combos(node, nic_combos)
        if not nic_combos:
            return NodeVerdict(
                name, R_PCI,
                f"free GPUs per switch {node.free_gpus_per_pciesw()}",
            )

    gpu_prefixes = set(gpu_combos)
    cpu_prefixes = {c[:-1] for c in cpu_combos}
    nic_prefixes = {tuple(n for n, _ in c) for c in nic_combos}
    if not (gpu_prefixes & cpu_prefixes & nic_prefixes):
        return NodeVerdict(
            name, R_INTERSECT,
            "per-resource NUMA assignments never coincide",
        )
    return NodeVerdict(name, R_OK)
