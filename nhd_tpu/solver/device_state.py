"""Device-resident cluster state for multi-round batch scheduling.

solve_bucket re-ships every node array host→device per call — harmless for
an on-package CPU backend, wasteful for a real accelerator (and painful
when the TPU sits across a network tunnel, as on this dev image). This
keeps the padded node arrays resident on device for a whole batch and
applies each round's claims as small donated scatters: upload is O(claimed
rows), download is the compact per-(type, node) decision tensors
(SURVEY §7 hard part 5: host↔device state coherence without re-upload).

Scatter index vectors are padded to power-of-two lengths (repeating the
last index — idempotent for row `set`) so round-to-round claim counts reuse
the jit cache.
"""

from __future__ import annotations

from typing import Dict, Iterable, List

import jax
import jax.numpy as jnp
import numpy as np

from nhd_tpu.solver.encode import ClusterArrays
from nhd_tpu.solver.kernel import (
    SolveOut,
    USE_PALLAS,
    _pad_pow2,
    get_solver,
)

# node arrays that claims mutate; the rest are uploaded once and never touched
_MUTABLE = ("busy", "hp_free", "cpu_free", "gpu_free", "nic_free", "gpu_free_sw")
_STATIC = (
    "numa_nodes", "smt", "active", "maintenance", "gpuless", "group_mask",
    "nic_count", "nic_sw",
)
_ARG_ORDER = (
    "numa_nodes", "smt", "active", "maintenance", "busy", "gpuless",
    "group_mask", "hp_free", "cpu_free", "gpu_free", "nic_count",
    "nic_free", "nic_sw", "gpu_free_sw",
)


def _pad_rows(a: np.ndarray, size: int) -> np.ndarray:
    if a.shape[0] == size:
        return a
    return np.concatenate(
        [a, np.zeros((size - a.shape[0], *a.shape[1:]), a.dtype)], axis=0
    )


from functools import partial


@partial(jax.jit, donate_argnums=(0,))
def _scatter_all(arrays, idx, rows):
    # one dispatch updates every mutable array (a tunnel-attached TPU pays
    # per-call latency); donation lets XLA update buffers in place since the
    # caller rebinds the results over the inputs
    return {
        name: arrays[name].at[idx].set(rows[name]) for name in arrays
    }


class DeviceClusterState:
    """Padded node arrays living on device for the duration of a batch."""

    def __init__(self, cluster: ClusterArrays):
        self.cluster = cluster
        self.N = cluster.n_nodes
        self.Np = _pad_pow2(self.N, floor=128 if USE_PALLAS else 8)
        self._dev: Dict[str, jax.Array] = {}
        for name in _ARG_ORDER:
            self._dev[name] = jnp.asarray(
                _pad_rows(getattr(cluster, name), self.Np)
            )

    def update_rows(self, indices: Iterable[int]) -> None:
        """Re-ship the claimed nodes' rows (host ClusterArrays → device)."""
        idx_list = sorted(set(indices))
        if not idx_list:
            return
        padded_len = _pad_pow2(len(idx_list), floor=8)
        idx = np.full(padded_len, idx_list[-1], np.int32)
        idx[: len(idx_list)] = idx_list
        mutable = {name: self._dev[name] for name in _MUTABLE}
        rows = {name: getattr(self.cluster, name)[idx] for name in _MUTABLE}
        updated = _scatter_all(mutable, jnp.asarray(idx), rows)
        self._dev.update(updated)

    def solve(self, pods) -> SolveOut:
        """solve_bucket against the resident arrays (same outputs)."""
        T = pods.n_types
        Tp = _pad_pow2(T)

        def pad_t(a):
            return _pad_rows(a, Tp)

        solver = get_solver(pods.G, self.cluster.U, self.cluster.K)
        out = solver(
            *[self._dev[name] for name in _ARG_ORDER],
            pad_t(pods.cpu_dem_smt), pad_t(pods.cpu_dem_raw),
            pad_t(pods.gpu_dem), pad_t(pods.rx), pad_t(pods.tx),
            pad_t(pods.hp), pad_t(pods.needs_gpu), pad_t(pods.map_pci),
            pad_t(pods.group_mask),
        )
        return SolveOut(*(x[:T, : self.N] if x.ndim == 2 else x for x in out))
