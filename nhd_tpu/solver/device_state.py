"""Device-resident cluster state for multi-round batch scheduling.

solve_bucket re-ships every node array host→device per call — harmless for
an on-package CPU backend, wasteful for a real accelerator (and painful
when the TPU sits across a network tunnel, as on this dev image). This
keeps the padded node arrays resident on device for a whole batch and
applies each round's claims as small donated scatters: upload is O(claimed
rows), download is the compact per-(type, node) decision tensors
(SURVEY §7 hard part 5: host↔device state coherence without re-upload).

With a multi-device ``Mesh`` the resident arrays shard along the node axis
(``NamedSharding(mesh, P("nodes"))``) and the solve runs the SAME fused
ranked megaround as the single-device path, SPMD over the mesh
(kernel.get_ranked_solver_mesh via the one kernel.dispatch_ranked seam) —
this is the production multi-chip path (SURVEY §2 parallelism bullet 1):
each device owns a node shard, per-round row scatters update only the
owning shard (shard-local index buckets through a shard_map — no
cross-shard gathers), and only the packed [9, T, R] decision tensor
gathers back over ICI.

Scatter index vectors are padded to power-of-two lengths (repeating the
last index — idempotent for row `set`) so round-to-round claim counts reuse
the jit cache.
"""

from __future__ import annotations

import os
from functools import lru_cache
from typing import Dict, Iterable, Optional

import jax
import jax.numpy as jnp
import numpy as np

from nhd_tpu.obs.jitstats import JIT_STATS
from nhd_tpu.solver.encode import ClusterArrays
from nhd_tpu.solver.kernel import (
    SolveOut,
    _ARG_ORDER,
    _MUTABLE,
    _STATIC,
    _pad_pow2,
    _pad_rows_to as _pad_rows,
    dispatch_ranked,
    get_solver,
    pad_nodes,
)


def _pad_own(a: np.ndarray, size: int) -> np.ndarray:
    """_pad_rows_to, but NEVER aliasing the input: when no padding is
    needed, _pad_rows returns the host array itself, and the CPU
    backend's jnp.asarray can be ZERO-COPY — a donated dispatch (the
    speculative megaround) would then mutate the HOST mirror through
    the alias, double-applying every claim the native verify applies
    again (caught by the ClusterDelta parity invariant: the delta
    layer's capacity == the device padding made rows == Np the norm,
    where the old per-batch flow only hit it on exact-power-of-two
    clusters)."""
    if a.shape[0] == size:
        return a.copy()
    return _pad_rows(a, size)


def _donate_default() -> bool:
    """Whether row-scatter dispatches donate the resident arrays: on
    accelerators the update is in place in HBM; the CPU backend ignores
    donation (with a warning), so don't ask. One probe shared by the
    single-device and mesh scatters — their donation behavior must not
    drift."""
    try:
        return jax.default_backend() != "cpu"
    except Exception:
        return False  # backend probe only decides donation, never
        #               correctness


def _delta_enabled() -> bool:
    """Row-scatter delta uploads (default on). NHD_DEVICE_DELTA=0 keeps
    the wholesale async re-upload instead — the right call on a relay
    that charges per FLUSH and nothing per byte (docs/TPU_STATUS.md r4),
    where one stable re-upload program beats scatter-width variants."""
    return os.environ.get("NHD_DEVICE_DELTA", "1") == "1"


@lru_cache(maxsize=None)
def _get_row_scatter(n_arrays: int, donate: bool):
    """ONE jitted program scattering W rows into *n_arrays* resident
    arrays jointly (donated on accelerators — the update is in place in
    HBM). The index vector is padded to a power-of-two width with the
    last index repeated (idempotent for row `set`), so churn rounds of
    different delta sizes reuse ~log2(N) compiled variants instead of
    one per width."""

    def fn(arrays, idx, rows):
        return tuple(a.at[idx].set(r) for a, r in zip(arrays, rows))

    kwargs = {"donate_argnums": (0,)} if donate else {}
    return jax.jit(fn, **kwargs)


@lru_cache(maxsize=None)
def _get_mesh_row_scatter(n_arrays: int, mesh, donate: bool):
    """The mesh counterpart of _get_row_scatter: each device scatters
    ONLY its shard's rows, addressed by SHARD-LOCAL indices — a
    shard_map over the ``nodes`` axis, so no cross-shard gather (or any
    collective at all) is inserted. Inputs: resident arrays (node-
    sharded [Np, ...]), idx [n_dev, Wp] int32 (row 0 of each device's
    slice = its local index bucket), rows (one [n_dev, Wp, ...] per
    array). Index buckets pad with an idempotent slot (see
    DeviceClusterState._scatter_mesh), so ~log2(N) width variants cover
    every delta size, same economy as the single-device scatter."""
    from jax.experimental.shard_map import shard_map
    from jax.sharding import PartitionSpec as P

    (axis,) = mesh.axis_names

    def body(arrays, idx, rows):
        return tuple(
            a.at[idx[0]].set(r[0]) for a, r in zip(arrays, rows)
        )

    fn = shard_map(
        body, mesh=mesh,
        in_specs=(P(axis), P(axis), P(axis)),
        out_specs=P(axis),
    )
    kwargs = {"donate_argnums": (0,)} if donate else {}
    return jax.jit(fn, **kwargs)

# _ARG_ORDER/_MUTABLE/_STATIC now live in kernel.py (the single
# argument-order contract, shared with the fused programs and the AOT
# layer) and are re-exported here for the speculative megaround and
# older callers.


class DeviceClusterState:
    """Padded node arrays living on device for the duration of a batch.

    ``mesh``: a 1-D ``jax.sharding.Mesh`` over a ``nodes`` axis. When given
    (and it has >1 device), the resident arrays are laid out node-sharded
    across the mesh and ``solve`` runs the SPMD sharded solver; without it,
    everything lives on the default single device.
    """

    def __init__(
        self,
        cluster: ClusterArrays,
        mesh: Optional["jax.sharding.Mesh"] = None,
        *,
        capacity: Optional[int] = None,
    ):
        self.cluster = cluster
        self.N = cluster.n_nodes
        self.mesh = mesh if (mesh is not None and mesh.devices.size > 1) else None
        n_dev = self.mesh.devices.size if self.mesh else 1
        # ``capacity``: the delta layer's padded row bucket (encode.py
        # ClusterDelta) — sizing the resident arrays to it means node
        # adds inside the bucket reach the device as row scatters, never
        # a reallocation; crossing the bucket rebuilds this object
        self.Np = pad_nodes(max(self.N, capacity or 0), n_dev, floor=8)
        self._node_sharding = None
        if self.mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec as P

            (axis,) = self.mesh.axis_names
            self._node_sharding = NamedSharding(self.mesh, P(axis))
            from nhd_tpu.k8s.retry import API_COUNTERS

            # mesh observability (nhd_mesh_*): posture gauges set at
            # build — scrapers see the sharding layout, not just totals
            API_COUNTERS.set("mesh_devices", n_dev)
            API_COUNTERS.set("mesh_shard_rows", self.Np // n_dev)
        self._dev: Dict[str, jax.Array] = {}
        # claim-dirty state: the touched row set (scattered before the
        # next solve dispatch when the delta path is on — single device
        # AND mesh, which buckets rows per shard) or, with
        # NHD_DEVICE_DELTA=0, a plain flag driving the wholesale
        # async re-upload — see stage_rows
        self._staged: bool = False
        self._staged_rows: set = set()
        for name in _ARG_ORDER:
            self._dev[name] = self._put(
                _pad_own(getattr(cluster, name), self.Np)
            )

    def _put(self, padded: np.ndarray) -> jax.Array:
        """Upload one padded node array with the resident placement —
        node-sharded on a mesh, plain on a single device. The single
        placement rule the initial upload and every recovery re-upload
        share."""
        if self._node_sharding is not None:
            return jax.device_put(padded, self._node_sharding)
        return jnp.asarray(padded)

    def stage_rows(self, indices: Iterable[int]) -> None:
        """Mark claim-mutated rows dirty; they reach the device before
        the next solve dispatch. Default (NHD_DEVICE_DELTA=1): ONE
        donated row-scatter over the pow-2-padded index bucket updates
        exactly the claimed rows of the mutable arrays — per-round
        upload is O(claimed rows), not O(cluster) — and on a mesh the
        scatter runs per shard with shard-local index buckets
        (_scatter_mesh), so staged in-batch claims pay the same
        O(claimed rows) there too. With the delta path off, the mutable
        arrays re-upload wholesale (async device_put, batched into the
        next flush) — the right trade on a relay that charges ~65 ms
        per FLUSH and nothing per byte (docs/TPU_STATUS.md r4), where
        scatter-width program variants cost more than the bytes they
        save."""
        for i in indices:
            self._staged = True
            self._staged_rows.add(int(i))
        if self._staged_rows and not _delta_enabled():
            self._staged_rows.clear()  # flag-only mode: wholesale flush

    def _flush_staged(self) -> None:
        if not self._staged:
            return
        self._staged = False
        rows, self._staged_rows = self._staged_rows, set()
        if rows and _delta_enabled() and len(rows) < self.N:
            self._scatter(
                _MUTABLE,
                np.fromiter(sorted(rows), np.int64, len(rows)),
            )
        else:
            self._rebuild_mutable()

    def _scatter(self, names, rows: np.ndarray) -> None:
        """Donated row-scatter of *rows* (host-mirror truth) into the
        named resident arrays — ONE dispatch whatever the array count.
        The index vector pads to its power-of-two bucket by repeating
        the last row (idempotent), so ~log2(N) program variants cover
        every delta size. Mesh-sharded residents route to the per-shard
        form (_scatter_mesh)."""
        if self.mesh is not None:
            self._scatter_mesh(names, rows)
            return
        W = len(rows)
        Wp = _pad_pow2(W, floor=8)
        idx = np.empty(Wp, np.int32)
        idx[:W] = rows
        idx[W:] = rows[-1]
        JIT_STATS.record_use(
            "row_scatter", f"A{len(names)}_W{Wp}_N{self.Np}"
        )
        from nhd_tpu.solver import guard

        guard.maybe_inject("upload", f"scatter_W{Wp}_N{self.Np}")
        fn = _get_row_scatter(len(names), _donate_default())
        arrays = tuple(self._dev[name] for name in names)
        host_rows = tuple(
            jnp.asarray(np.ascontiguousarray(getattr(self.cluster, name)[idx]))
            for name in names
        )
        try:
            out = fn(arrays, jnp.asarray(idx), host_rows)
        except BaseException:
            # the dispatch may have donated the resident arrays: restore
            # them from the host mirror (source of truth)
            for name in names:
                self._dev[name] = self._put(
                    _pad_own(getattr(self.cluster, name), self.Np)
                )
            raise
        for name, arr in zip(names, out):
            self._dev[name] = arr
        from nhd_tpu.k8s.retry import API_COUNTERS

        API_COUNTERS.inc("device_state_rows_uploaded_total", W)

    def _scatter_mesh(self, names, rows: np.ndarray) -> None:
        """Mesh-sharded row scatter (the PR 9 open item): dirty GLOBAL
        row indices bucket by owning shard (shard = row // shard_rows),
        each shard gets a SHARD-LOCAL index vector plus its rows' host-
        mirror values, and ONE donated shard_map dispatch scatters every
        shard's bucket in place — churn on a mesh pays O(changed rows),
        never the wholesale re-shard it used to.

        Buckets pad to one shared pow-2 width (jit-cache reuse, ~log2 N
        variants): a shard's pad slots repeat its last dirty row
        (idempotent row set, like the single-device scatter), and a
        shard with NO dirty rows writes its own row 0 back — host-mirror
        truth for live rows, zeros for padding rows past the cluster
        (both exactly what the device already holds)."""
        n_dev = self.mesh.devices.size
        shard_rows = self.Np // n_dev
        buckets: list = [[] for _ in range(n_dev)]
        for g in rows.tolist():
            buckets[g // shard_rows].append(g)
        W = len(rows)
        Wp = _pad_pow2(max(max(len(b) for b in buckets), 1), floor=8)
        idx = np.empty((n_dev, Wp), np.int32)   # shard-local indices
        gidx = np.empty((n_dev, Wp), np.int64)  # global rows (host gather)
        for s, b in enumerate(buckets):
            if b:
                k = len(b)
                idx[s, :k] = [g - s * shard_rows for g in b]
                idx[s, k:] = b[-1] - s * shard_rows
                gidx[s, :k] = b
                gidx[s, k:] = b[-1]
            else:
                # idempotent no-op bucket: re-write the shard's row 0
                idx[s, :] = 0
                gidx[s, :] = s * shard_rows
        JIT_STATS.record_use(
            "mesh_row_scatter", f"A{len(names)}_W{Wp}_N{self.Np}_D{n_dev}"
        )
        from nhd_tpu.solver import guard

        guard.maybe_inject(
            "upload", f"mesh_scatter_W{Wp}_N{self.Np}_D{n_dev}"
        )
        fn = _get_mesh_row_scatter(len(names), self.mesh, _donate_default())
        arrays = tuple(self._dev[name] for name in names)
        live = gidx < self.N  # rows past the cluster hold device zeros
        host_rows = []
        for name in names:
            src = getattr(self.cluster, name)
            data = np.zeros((n_dev, Wp, *src.shape[1:]), src.dtype)
            data[live] = src[gidx[live]]
            host_rows.append(jax.device_put(data, self._node_sharding))
        try:
            out = fn(
                arrays,
                jax.device_put(idx, self._node_sharding),
                tuple(host_rows),
            )
        except BaseException:
            # the dispatch may have donated the resident arrays: restore
            # them from the host mirror (source of truth)
            for name in names:
                self._dev[name] = self._put(
                    _pad_own(getattr(self.cluster, name), self.Np)
                )
            raise
        for name, arr in zip(names, out):
            self._dev[name] = arr
        from nhd_tpu.k8s.retry import API_COUNTERS

        API_COUNTERS.inc("device_state_rows_uploaded_total", W)
        API_COUNTERS.inc("mesh_rows_uploaded_total", W)

    def scatter_rows(self, rows: np.ndarray) -> None:
        """Delta-layer sync (encode.ClusterDelta.drain_dirty → here):
        scatter the changed rows of ALL resident arrays — watch events
        touch arrays the claim path never does (active, maintenance,
        group_mask) — and pick up any row growth inside the capacity
        bucket. Mesh-sharded residents take the same O(changed rows)
        path through per-shard scatters (_scatter_mesh); only
        storm-sized deltas or NHD_DEVICE_DELTA=0 fall back to the
        wholesale re-upload (counted per posture, so the spmd bench can
        assert zero mesh wholesale fallbacks in a steady round)."""
        self.N = self.cluster.n_nodes
        if self.N > self.Np:
            raise ValueError(
                f"cluster grew past the resident capacity bucket "
                f"({self.N} > {self.Np}); rebuild DeviceClusterState"
            )
        if rows.size == 0:
            return
        self._flush_staged()  # claim updates first, in their own mode
        if not _delta_enabled() or rows.size >= self.N // 2:
            # storm-sized deltas: past ~half the rows, one contiguous
            # re-upload beats gathering scattered rows host-side (the
            # gather + index conversion costs more than the bytes saved)
            for name in _ARG_ORDER:
                self._dev[name] = self._put(
                    _pad_own(getattr(self.cluster, name), self.Np)
                )
            from nhd_tpu.k8s.retry import API_COUNTERS

            API_COUNTERS.inc("device_state_rows_uploaded_total", self.N)
            if self.mesh is not None:
                API_COUNTERS.inc("mesh_wholesale_uploads_total")
            return
        self._scatter(_ARG_ORDER, rows.astype(np.int64))

    def _pod_args(self, pods) -> list:
        """The 10 pod-type arrays padded to the pow-2 type bucket, in
        _solve's positional order (kernel._POD_ARG_ORDER) — shared by
        the plain and fused solve paths so the argument list cannot
        drift between them."""
        from nhd_tpu.solver.kernel import _POD_ARG_ORDER

        Tp = _pad_pow2(pods.n_types)
        return [
            _pad_rows(getattr(pods, name), Tp) for name in _POD_ARG_ORDER
        ]

    def update_rows(self, indices: Iterable[int]) -> None:
        """Re-ship claim-mutated state (host ClusterArrays → device):
        wholesale async re-upload of the mutable arrays (the host mirror
        is the source of truth; ``indices`` only gates emptiness)."""
        for _ in indices:
            self._rebuild_mutable()
            return

    def _solve_raw(self, pods) -> SolveOut:
        """The padded PLAIN solver call against the resident arrays
        ([Tp, Np] outputs, still on device) — the single-device
        parity/debug surface. Mesh-resident state serves ONLY the fused
        ranked megaround (solve_ranked): the legacy unfused sharded
        solver is gone, so a plain mesh solve has no program to run."""
        if self.mesh is not None:
            raise RuntimeError(
                "mesh-resident state runs the fused ranked megaround; "
                "use solve_ranked (the unfused sharded solver was "
                "removed — kernel.get_ranked_solver_mesh is the one "
                "mesh program)"
            )
        self._flush_staged()
        JIT_STATS.record_use(
            "solve",
            f"G{pods.G}_U{self.cluster.U}_K{self.cluster.K}"
            f"_T{_pad_pow2(pods.n_types)}_N{self.Np}",
        )
        solver = get_solver(pods.G, self.cluster.U, self.cluster.K)
        return solver(
            *[self._dev[name] for name in _ARG_ORDER],
            *self._pod_args(pods),
        )

    def solve(self, pods) -> SolveOut:
        """solve_bucket against the resident arrays (same outputs)."""
        out = self._solve_raw(pods)
        T = pods.n_types
        return SolveOut(*(x[:T, : self.N] if x.ndim == 2 else x for x in out))

    def solve_ranked(self, pods, R: int) -> jax.Array:
        """Solve + on-device top-R ranking: only the packed [9, Tp, R]
        decision tensor leaves the device (the free-total gathers read
        the RESIDENT free arrays, which stage_rows/update_rows keep live
        between rounds).

        Single device and mesh share ONE seam (kernel.dispatch_ranked):
        any claim-dirty state flushes (delta scatters, or the async
        wholesale re-upload with NHD_DEVICE_DELTA=0 — the right trade on
        a relay that charges per FLUSH and nothing per byte), then ONE
        fused solve+rank dispatch. On a mesh the same program runs SPMD
        over the node-sharded resident arrays
        (kernel.get_ranked_solver_mesh) — the rank's top_k over the
        sharded node axis is the one collective, and the replicated
        packed tensor is the round's single gather."""
        R = min(R, self.Np)
        self._flush_staged()
        if self.mesh is not None:
            from nhd_tpu.k8s.retry import API_COUNTERS

            API_COUNTERS.inc("mesh_solves_total")
        return dispatch_ranked(
            pods.G, self.cluster.U, self.cluster.K, R,
            _pad_pow2(pods.n_types), self.Np,
            [self._dev[name] for name in _ARG_ORDER]
            + self._pod_args(pods),
            mesh=self.mesh,
        )

    def rebuild_resident(self) -> None:
        """Re-derive EVERY resident array from the host mirror (source
        of truth) — the guard's repair chokepoint (solver/guard.py):
        after a detected corruption or a failed dispatch, the whole
        device plane rebuilds from the live ClusterArrays in place (same
        capacity bucket, same sharding), and any staged-but-unflushed
        claim rows are dropped — their values are host truth already, so
        the wholesale re-upload subsumes them."""
        self.N = self.cluster.n_nodes
        if self.N > self.Np:
            raise ValueError(
                f"cluster grew past the resident capacity bucket "
                f"({self.N} > {self.Np}); rebuild DeviceClusterState"
            )
        self._staged = False
        self._staged_rows.clear()
        for name in _ARG_ORDER:
            self._dev[name] = self._put(
                _pad_own(getattr(self.cluster, name), self.Np)
            )
        from nhd_tpu.k8s.retry import API_COUNTERS

        API_COUNTERS.inc("device_state_rows_uploaded_total", self.N)
        if self.mesh is not None:
            API_COUNTERS.inc("mesh_wholesale_uploads_total")

    def _rebuild_mutable(self) -> None:
        """Re-upload the claim-mutated resident arrays wholesale from the
        host mirror (source of truth) — the staged-claim fallback mode
        (NHD_DEVICE_DELTA=0 / mesh) and the recovery path when a dispatch
        that donated them fails midway. Counts its full row set so the
        upload economy stays honest in wholesale mode — an O(changed)
        assertion judged on a counter this path skipped would be
        vacuously green exactly where uploads are heaviest."""
        for name in _MUTABLE:
            self._dev[name] = self._put(
                _pad_own(getattr(self.cluster, name), self.Np)
            )
        from nhd_tpu.k8s.retry import API_COUNTERS

        API_COUNTERS.inc("device_state_rows_uploaded_total", self.N)
        if self.mesh is not None:
            API_COUNTERS.inc("mesh_wholesale_uploads_total")

    def megaround(self, bucket_pods: list, needs: list, respect_busy: bool):
        """Run the speculative on-device multi-round (solver/speculate.py)
        against the resident arrays: ONE dispatch executes up to
        spec_iters() claim rounds for every bucket jointly and mutates
        the resident state with the aggregate claim deltas (donated).

        ``bucket_pods``: PodTypeArrays per bucket, in bucket-dict order;
        ``needs``: per-bucket int32 [Tp] pending-pod counts. Returns the
        DEVICE tensors (claims [iters, N] packed int32 words, counts
        [iters, N], need_left [Tt], iters_used scalar), all still in
        flight — the dispatch is async, so the caller overlaps host prep
        (FastCluster join, pod grouping) under the relay turnaround, and
        must copy_to_host_async ALL FOUR before the first np.asarray so
        they ride one batched flush (batch._speculate_dispatch does). On
        a mesh the same program runs SPMD over the node-sharded resident
        arrays (claims bit-identical to single-device; the megaround
        docstring has the sharding story)."""
        from nhd_tpu.solver.speculate import _get_megaround, spec_iters

        self._flush_staged()
        shapes = tuple(
            (pods.G, _pad_pow2(pods.n_types)) for pods in bucket_pods
        )
        JIT_STATS.record_use(
            "megaround",
            "B" + "_".join(f"G{g}T{t}" for g, t in shapes)
            + f"_U{self.cluster.U}_K{self.cluster.K}_N{self.Np}",
        )
        out_shardings_key = None
        if self._node_sharding is not None:
            from jax.sharding import NamedSharding, PartitionSpec as P

            out_shardings_key = (
                self._node_sharding, NamedSharding(self.mesh, P())
            )
        fn = _get_megaround(
            shapes, self.cluster.U, self.cluster.K, spec_iters(),
            respect_busy, donate=True,
            out_shardings_key=out_shardings_key,
        )
        pod_args = []
        for pods in bucket_pods:
            pod_args.extend(self._pod_args(pods))
        need = jnp.asarray(np.concatenate(
            [_pad_rows(n.astype(np.int32), tp) for n, (_, tp) in
             zip(needs, shapes)]
        ))
        mutable = {name: self._dev[name] for name in _MUTABLE}
        static = {name: self._dev[name] for name in _STATIC}
        from nhd_tpu.solver import guard

        guard.maybe_inject("megaround", f"B{len(bucket_pods)}_N{self.Np}")
        try:
            new_mutable, claims, counts, need_left, it = fn(
                mutable, static, need, *pod_args
            )
        except BaseException:
            # the dispatch donated the mutable arrays: restore them from
            # the host mirror (source of truth)
            self._rebuild_mutable()
            raise
        self._dev.update(new_mutable)
        return claims, counts, need_left, it
