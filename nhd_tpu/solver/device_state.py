"""Device-resident cluster state for multi-round batch scheduling.

solve_bucket re-ships every node array host→device per call — harmless for
an on-package CPU backend, wasteful for a real accelerator (and painful
when the TPU sits across a network tunnel, as on this dev image). This
keeps the padded node arrays resident on device for a whole batch and
applies each round's claims as small donated scatters: upload is O(claimed
rows), download is the compact per-(type, node) decision tensors
(SURVEY §7 hard part 5: host↔device state coherence without re-upload).

With a multi-device ``Mesh`` the resident arrays shard along the node axis
(``NamedSharding(mesh, P("nodes"))``) and the solve runs SPMD via the
pjit-compiled sharded solver (parallel/sharding.py) — this is the
production multi-chip path (SURVEY §2 parallelism bullet 1): each device
owns a node shard, per-round row scatters update only the owning shard,
and the [T, N] decision tensors gather back over ICI.

Scatter index vectors are padded to power-of-two lengths (repeating the
last index — idempotent for row `set`) so round-to-round claim counts reuse
the jit cache.
"""

from __future__ import annotations

from typing import Dict, Iterable, Optional

import jax
import jax.numpy as jnp
import numpy as np

from nhd_tpu.obs.jitstats import JIT_STATS
from nhd_tpu.solver.encode import ClusterArrays
from nhd_tpu.solver.kernel import (
    SolveOut,
    _ARG_ORDER,
    _MUTABLE,
    _STATIC,
    _get_ranker,
    _pad_pow2,
    _pad_rows_to as _pad_rows,
    dispatch_ranked,
    get_solver,
    pad_nodes,
)

# _ARG_ORDER/_MUTABLE/_STATIC now live in kernel.py (the single
# argument-order contract, shared with the fused programs and the AOT
# layer) and are re-exported here for the speculative megaround and
# older callers.


class DeviceClusterState:
    """Padded node arrays living on device for the duration of a batch.

    ``mesh``: a 1-D ``jax.sharding.Mesh`` over a ``nodes`` axis. When given
    (and it has >1 device), the resident arrays are laid out node-sharded
    across the mesh and ``solve`` runs the SPMD sharded solver; without it,
    everything lives on the default single device.
    """

    def __init__(self, cluster: ClusterArrays, mesh: Optional["jax.sharding.Mesh"] = None):
        self.cluster = cluster
        self.N = cluster.n_nodes
        self.mesh = mesh if (mesh is not None and mesh.devices.size > 1) else None
        n_dev = self.mesh.devices.size if self.mesh else 1
        self.Np = pad_nodes(self.N, n_dev, floor=8)
        self._node_sharding = None
        if self.mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec as P

            self._node_sharding = NamedSharding(self.mesh, P("nodes"))
        self._dev: Dict[str, jax.Array] = {}
        # claim-dirty flag: the mutable arrays re-upload wholesale (async)
        # before the next solve dispatch — see update_rows
        self._staged: bool = False
        for name in _ARG_ORDER:
            self._dev[name] = self._put(
                _pad_rows(getattr(cluster, name), self.Np)
            )

    def _put(self, padded: np.ndarray) -> jax.Array:
        """Upload one padded node array with the resident placement —
        node-sharded on a mesh, plain on a single device. The single
        placement rule the initial upload and every recovery re-upload
        share."""
        if self._node_sharding is not None:
            return jax.device_put(padded, self._node_sharding)
        return jnp.asarray(padded)

    def stage_rows(self, indices: Iterable[int]) -> None:
        """Mark the resident mutable arrays claim-dirty: the host mirror
        re-uploads wholesale (async device_put, batched into the next
        flush) before the next solve dispatch. The per-row scatter this
        replaces was O(claimed-rows) on upload bytes but lazily compiled
        a fresh program per scatter-width bucket — on the tunnel relay,
        which charges ~65 ms per FLUSH and nothing per byte, the stable
        single program wins outright (docs/TPU_STATUS.md r4)."""
        for _ in indices:
            self._staged = True
            return

    def _flush_staged(self) -> None:
        if self._staged:
            self._staged = False
            self._rebuild_mutable()

    def _pod_args(self, pods) -> list:
        """The 9 pod-type arrays padded to the pow-2 type bucket, in
        _solve's positional order — shared by the plain and fused solve
        paths so the argument list cannot drift between them."""
        Tp = _pad_pow2(pods.n_types)
        return [
            _pad_rows(a, Tp)
            for a in (
                pods.cpu_dem_smt, pods.cpu_dem_raw, pods.gpu_dem,
                pods.rx, pods.tx, pods.hp, pods.needs_gpu, pods.map_pci,
                pods.group_mask,
            )
        ]

    def update_rows(self, indices: Iterable[int]) -> None:
        """Re-ship claim-mutated state (host ClusterArrays → device):
        wholesale async re-upload of the mutable arrays (the host mirror
        is the source of truth; ``indices`` only gates emptiness)."""
        for _ in indices:
            self._rebuild_mutable()
            return

    def _solve_raw(self, pods) -> SolveOut:
        """The padded solver call against the resident arrays
        ([Tp, Np] outputs, still on device)."""
        self._flush_staged()
        JIT_STATS.record_use(
            "solve",
            f"G{pods.G}_U{self.cluster.U}_K{self.cluster.K}"
            f"_T{_pad_pow2(pods.n_types)}_N{self.Np}"
            + ("_mesh" if self.mesh is not None else ""),
        )
        if self.mesh is not None:
            from nhd_tpu.parallel.sharding import get_sharded_solver

            solver = get_sharded_solver(
                pods.G, self.cluster.U, self.cluster.K, self.mesh
            )
        else:
            solver = get_solver(pods.G, self.cluster.U, self.cluster.K)
        return solver(
            *[self._dev[name] for name in _ARG_ORDER],
            *self._pod_args(pods),
        )

    def solve(self, pods) -> SolveOut:
        """solve_bucket against the resident arrays (same outputs)."""
        out = self._solve_raw(pods)
        T = pods.n_types
        return SolveOut(*(x[:T, : self.N] if x.ndim == 2 else x for x in out))

    def solve_ranked(self, pods, R: int) -> jax.Array:
        """Solve + on-device top-R ranking: only the packed [9, Tp, R]
        decision tensor leaves the device (the free-total gathers read
        the RESIDENT free arrays, which stage_rows/update_rows keep live
        between rounds).

        Single device: any claim-dirty state re-uploads asynchronously,
        then ONE fused solve+rank dispatch — its result pull is the
        round's single relay flush (per-flush latency dominates the round
        on the tunnel-attached TPU, so flush count is the metric that
        matters). Mesh: the pjit SPMD solve + a replicated-output ranker —
        top_k over the sharded node axis is the one collective this adds."""
        R = min(R, self.Np)
        if self._node_sharding is not None:
            out = self._solve_raw(pods)
            from jax.sharding import NamedSharding, PartitionSpec as P

            ranker = _get_ranker(R, NamedSharding(self.mesh, P()))
            return ranker(
                out.cand, out.pref, out.best_c, out.best_m, out.best_a,
                out.n_picks,
                self._dev["gpu_free"], self._dev["cpu_free"],
                self._dev["hp_free"],
            )

        self._flush_staged()  # async wholesale re-upload of dirty state
        # same fused program (and AOT artifact) as the host path: claim
        # updates reach the device as a wholesale async re-upload of the
        # mutable arrays (see update_rows), NOT as a fused scatter — the
        # relay charges per FLUSH, uploads batch into the next flush for
        # free, and every distinct scatter-width variant used to lazily
        # compile its own program mid-run (~1 s each through the tunnel)
        return dispatch_ranked(
            pods.G, self.cluster.U, self.cluster.K, R,
            _pad_pow2(pods.n_types), self.Np,
            [self._dev[name] for name in _ARG_ORDER]
            + self._pod_args(pods),
        )

    def _rebuild_mutable(self) -> None:
        """Re-upload the claim-mutated resident arrays wholesale from the
        host mirror (source of truth) — the recovery path when a dispatch
        that donated them fails midway."""
        for name in _MUTABLE:
            self._dev[name] = self._put(
                _pad_rows(getattr(self.cluster, name), self.Np)
            )

    def megaround(self, bucket_pods: list, needs: list, respect_busy: bool):
        """Run the speculative on-device multi-round (solver/speculate.py)
        against the resident arrays: ONE dispatch executes up to
        spec_iters() claim rounds for every bucket jointly and mutates
        the resident state with the aggregate claim deltas (donated).

        ``bucket_pods``: PodTypeArrays per bucket, in bucket-dict order;
        ``needs``: per-bucket int32 [Tp] pending-pod counts. Returns the
        DEVICE tensors (claims [iters, N] packed int32 words, counts
        [iters, N], need_left [Tt], iters_used scalar), all still in
        flight — the dispatch is async, so the caller overlaps host prep
        (FastCluster join, pod grouping) under the relay turnaround, and
        must copy_to_host_async ALL FOUR before the first np.asarray so
        they ride one batched flush (batch._speculate_dispatch does). On
        a mesh the same program runs SPMD over the node-sharded resident
        arrays (claims bit-identical to single-device; the megaround
        docstring has the sharding story)."""
        from nhd_tpu.solver.speculate import _get_megaround, spec_iters

        self._flush_staged()
        shapes = tuple(
            (pods.G, _pad_pow2(pods.n_types)) for pods in bucket_pods
        )
        JIT_STATS.record_use(
            "megaround",
            "B" + "_".join(f"G{g}T{t}" for g, t in shapes)
            + f"_U{self.cluster.U}_K{self.cluster.K}_N{self.Np}",
        )
        out_shardings_key = None
        if self._node_sharding is not None:
            from jax.sharding import NamedSharding, PartitionSpec as P

            out_shardings_key = (
                self._node_sharding, NamedSharding(self.mesh, P())
            )
        fn = _get_megaround(
            shapes, self.cluster.U, self.cluster.K, spec_iters(),
            respect_busy, donate=True,
            out_shardings_key=out_shardings_key,
        )
        pod_args = []
        for pods in bucket_pods:
            pod_args.extend(self._pod_args(pods))
        need = jnp.asarray(np.concatenate(
            [_pad_rows(n.astype(np.int32), tp) for n, (_, tp) in
             zip(needs, shapes)]
        ))
        mutable = {name: self._dev[name] for name in _MUTABLE}
        static = {name: self._dev[name] for name in _STATIC}
        try:
            new_mutable, claims, counts, need_left, it = fn(
                mutable, static, need, *pod_args
            )
        except BaseException:
            # the dispatch donated the mutable arrays: restore them from
            # the host mirror (source of truth)
            self._rebuild_mutable()
            raise
        self._dev.update(new_mutable)
        return claims, counts, need_left, it
