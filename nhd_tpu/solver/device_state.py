"""Device-resident cluster state for multi-round batch scheduling.

solve_bucket re-ships every node array host→device per call — harmless for
an on-package CPU backend, wasteful for a real accelerator (and painful
when the TPU sits across a network tunnel, as on this dev image). This
keeps the padded node arrays resident on device for a whole batch and
applies each round's claims as small donated scatters: upload is O(claimed
rows), download is the compact per-(type, node) decision tensors
(SURVEY §7 hard part 5: host↔device state coherence without re-upload).

With a multi-device ``Mesh`` the resident arrays shard along the node axis
(``NamedSharding(mesh, P("nodes"))``) and the solve runs SPMD via the
pjit-compiled sharded solver (parallel/sharding.py) — this is the
production multi-chip path (SURVEY §2 parallelism bullet 1): each device
owns a node shard, per-round row scatters update only the owning shard,
and the [T, N] decision tensors gather back over ICI.

Scatter index vectors are padded to power-of-two lengths (repeating the
last index — idempotent for row `set`) so round-to-round claim counts reuse
the jit cache.
"""

from __future__ import annotations

import os
from typing import Dict, Iterable, Optional

import jax
import jax.numpy as jnp
import numpy as np

from nhd_tpu.solver.encode import ClusterArrays
from nhd_tpu.solver.kernel import (
    RankOut,
    SolveOut,
    _get_ranker,
    pallas_enabled,
    _pad_pow2,
    get_solver,
    pad_nodes,
)

_pallas_mesh_warned = False


def _warn_pallas_mesh_once() -> None:
    global _pallas_mesh_warned
    if not _pallas_mesh_warned:
        _pallas_mesh_warned = True
        from nhd_tpu.utils import get_logger

        get_logger(__name__).warning(
            "NHD_TPU_PALLAS=1 is ignored on the sharded (mesh) solve path;"
            " solving via the pjit SPMD solver without the Pallas kernel"
        )


# node arrays that claims mutate; the rest are uploaded once and never touched
_MUTABLE = ("busy", "hp_free", "cpu_free", "gpu_free", "nic_free", "gpu_free_sw")
_STATIC = (
    "numa_nodes", "smt", "active", "maintenance", "gpuless", "group_mask",
    "nic_count", "nic_sw",
)
_ARG_ORDER = (
    "numa_nodes", "smt", "active", "maintenance", "busy", "gpuless",
    "group_mask", "hp_free", "cpu_free", "gpu_free", "nic_count",
    "nic_free", "nic_sw", "gpu_free_sw",
)


def _pad_rows(a: np.ndarray, size: int) -> np.ndarray:
    if a.shape[0] == size:
        return a
    return np.concatenate(
        [a, np.zeros((size - a.shape[0], *a.shape[1:]), a.dtype)], axis=0
    )


from functools import partial


def _scatter_donation() -> bool:
    """Whether the row scatter donates its input buffers. Donation is the
    right default (in-place update, no extra HBM); NHD_TPU_SCATTER=fresh
    disables it — an A/B knob for the tunnel-attached TPU, where the
    measured 838 ms per 40-row update (docs/TPU_STATUS.md) is suspected
    to be donation forcing buffer round-trips through the relay."""
    mode = os.environ.get("NHD_TPU_SCATTER", "donate").lower()
    if mode not in ("donate", "fresh"):
        raise ValueError(
            f"NHD_TPU_SCATTER must be 'donate' or 'fresh', got {mode!r}"
        )
    return mode != "fresh"


# fail fast on a typo'd value at import (matching the scheduler's env
# knobs) — the per-call read above stays so a bench can A/B in-process
_scatter_donation()


def _scatter_impl(arrays, idx, rows):
    # one dispatch updates every mutable array (a tunnel-attached TPU pays
    # per-call latency)
    return {
        name: arrays[name].at[idx].set(rows[name]) for name in arrays
    }


_scatter_donate = jax.jit(_scatter_impl, donate_argnums=(0,))
_scatter_fresh = jax.jit(_scatter_impl)


def _scatter_all(arrays, idx, rows):
    fn = _scatter_donate if _scatter_donation() else _scatter_fresh
    return fn(arrays, idx, rows)


from functools import lru_cache


@lru_cache(maxsize=None)
def _get_sharded_scatter(sharding, donate: bool = True):
    """Row scatter that pins its outputs to the node sharding — global row
    indices, each shard applies the rows it owns."""

    kwargs = {"donate_argnums": (0,)} if donate else {}
    return jax.jit(
        _scatter_impl,
        out_shardings={name: sharding for name in _MUTABLE},
        **kwargs,
    )


class DeviceClusterState:
    """Padded node arrays living on device for the duration of a batch.

    ``mesh``: a 1-D ``jax.sharding.Mesh`` over a ``nodes`` axis. When given
    (and it has >1 device), the resident arrays are laid out node-sharded
    across the mesh and ``solve`` runs the SPMD sharded solver; without it,
    everything lives on the default single device.
    """

    def __init__(self, cluster: ClusterArrays, mesh: Optional["jax.sharding.Mesh"] = None):
        self.cluster = cluster
        self.N = cluster.n_nodes
        self.mesh = mesh if (mesh is not None and mesh.devices.size > 1) else None
        n_dev = self.mesh.devices.size if self.mesh else 1
        # the sharded solver never lowers through Pallas (per-shard node
        # extents fall below the kernel's lane tile), so on the mesh path
        # NHD_TPU_PALLAS must not inflate padding it can't benefit from
        use_pallas = pallas_enabled() and self.mesh is None
        if pallas_enabled() and self.mesh is not None:
            _warn_pallas_mesh_once()
        self.Np = pad_nodes(self.N, n_dev, floor=128 if use_pallas else 8)
        self._node_sharding = None
        if self.mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec as P

            self._node_sharding = NamedSharding(self.mesh, P("nodes"))
        self._dev: Dict[str, jax.Array] = {}
        for name in _ARG_ORDER:
            padded = _pad_rows(getattr(cluster, name), self.Np)
            if self._node_sharding is not None:
                self._dev[name] = jax.device_put(padded, self._node_sharding)
            else:
                self._dev[name] = jnp.asarray(padded)

    def update_rows(self, indices: Iterable[int]) -> None:
        """Re-ship the claimed nodes' rows (host ClusterArrays → device)."""
        idx_list = sorted(set(indices))
        if not idx_list:
            return
        padded_len = _pad_pow2(len(idx_list), floor=8)
        idx = np.full(padded_len, idx_list[-1], np.int32)
        idx[: len(idx_list)] = idx_list
        mutable = {name: self._dev[name] for name in _MUTABLE}
        rows = {name: getattr(self.cluster, name)[idx] for name in _MUTABLE}
        scatter = (
            _get_sharded_scatter(self._node_sharding, _scatter_donation())
            if self._node_sharding is not None
            else _scatter_all
        )
        updated = scatter(mutable, jnp.asarray(idx), rows)
        self._dev.update(updated)

    def _solve_raw(self, pods) -> SolveOut:
        """The padded solver call against the resident arrays
        ([Tp, Np] outputs, still on device)."""
        Tp = _pad_pow2(pods.n_types)

        def pad_t(a):
            return _pad_rows(a, Tp)

        if self.mesh is not None:
            from nhd_tpu.parallel.sharding import get_sharded_solver

            solver = get_sharded_solver(
                pods.G, self.cluster.U, self.cluster.K, self.mesh
            )
        else:
            solver = get_solver(pods.G, self.cluster.U, self.cluster.K)
        return solver(
            *[self._dev[name] for name in _ARG_ORDER],
            pad_t(pods.cpu_dem_smt), pad_t(pods.cpu_dem_raw),
            pad_t(pods.gpu_dem), pad_t(pods.rx), pad_t(pods.tx),
            pad_t(pods.hp), pad_t(pods.needs_gpu), pad_t(pods.map_pci),
            pad_t(pods.group_mask),
        )

    def solve(self, pods) -> SolveOut:
        """solve_bucket against the resident arrays (same outputs)."""
        out = self._solve_raw(pods)
        T = pods.n_types
        return SolveOut(*(x[:T, : self.N] if x.ndim == 2 else x for x in out))

    def solve_ranked(self, pods, R: int) -> RankOut:
        """Solve + on-device top-R ranking: only [Tp, R] decision tensors
        leave the device (the free-total gathers read the RESIDENT free
        arrays, which update_rows keeps live between rounds). On a mesh
        the rank outputs are pinned replicated — top_k over the sharded
        node axis is the one collective this adds."""
        out = self._solve_raw(pods)
        R = min(R, self.Np)
        if self._node_sharding is not None:
            from jax.sharding import NamedSharding, PartitionSpec as P

            ranker = _get_ranker(R, NamedSharding(self.mesh, P()))
        else:
            ranker = _get_ranker(R)
        return ranker(
            out.cand, out.pref, out.best_c, out.best_m, out.best_a,
            out.n_picks,
            self._dev["gpu_free"], self._dev["cpu_free"],
            self._dev["hp_free"],
        )
