"""Gang/batch scheduling: greedy rounds over the batched solve.

The reference schedules strictly sequentially — each pod's claim mutates
node state before the next pod is considered (NHDScheduler.py:425-436).
A 10k-pod batch can't afford 10k serial solves, so this module runs
*greedy rounds* (SURVEY §7 hard part 2):

  round:  1. one batched feasibility solve against the current state
          2. every pending pod takes its type's best candidate node, packing
             each node up to an optimistic capacity estimate before
             spilling to the next (the reference's first-fit packing shape)
          3. claims apply in pod-index order, re-verified against live
             state (NIC picks re-selected; see fast_assign/select_pick) —
             a node's first claim ran on fresh feasibility so its failure
             is final, later same-node failures are stale and retry
          4. applied claims update the solver arrays incrementally; next
             round

Serializability: claims are applied one at a time against live state, so
the batch equals *a* sequential execution in pod-index-per-node order —
every applied claim was feasible when made. Placement can still differ
from the reference's strict global order (capacity estimates decide when a
gang spills to the next node), the documented extension that buys the
~1000× throughput. Single-pod batches reproduce the oracle exactly in NUMA
map mode; in PCI mode the live pick re-selection can place pods the oracle
fail-then-bails on (docs/PARITY.md "Batch-mode extensions").

Busy back-off note: with respect_busy=True (live default) a node accepts
at most one GPU pod per MIN_BUSY_SECS, exactly like the reference
(Matcher.py:103-111) — a 10k-GPU-pod benchmark must disable it, as the
back-off, not the solver, becomes the rate limit.
"""

from __future__ import annotations

import threading as _threading
import time
from dataclasses import dataclass, field, replace
from typing import Dict, List, NamedTuple, Optional, Sequence, Tuple

import numpy as np

from nhd_tpu.core.node import AssignmentError, HostNode
from nhd_tpu.core.request import PodRequest
from nhd_tpu.core.topology import MapMode, NicDir, PodTopology
from nhd_tpu.solver.device_state import DeviceClusterState
from nhd_tpu.solver.encode import (
    ClusterDelta,
    encode_cluster,
    encode_pods,
    refresh_node_row,
)
from nhd_tpu.solver.guard import (
    GUARD,
    RUNG_HOST,
    RUNG_MESH,
    RUNG_SINGLE,
    DeviceCorruptionError,
)
from nhd_tpu.solver.kernel import bucket_tractable
from nhd_tpu.solver.oracle import find_node as oracle_find_node
from nhd_tpu.solver.fast_assign import (
    AssignRecord,
    FastAssignError,
    FastCluster,
    apply_record_to_topology,
)
from nhd_tpu.obs.recorder import get_recorder
from nhd_tpu.solver.jax_matcher import decode_mapping
from nhd_tpu.solver.kernel import (
    _pad_pow2,
    mesh_desc,
    rank_budget,
    ranked_shape_key,
    solve_bucket_ranked,
)
from nhd_tpu.utils import get_logger


@dataclass
class BatchItem:
    """One pod to place: its numeric request plus (optionally) the full
    topology object to fill with physical IDs."""

    key: Tuple[str, str]                 # (namespace, podname)
    request: PodRequest
    topology: Optional[PodTopology] = None


class BatchAssignment(NamedTuple):
    """One pod's placement verdict. A NamedTuple, not a dataclass: a
    gang sweep materializes one of these per pod (100k at federation
    scale) and the C-level tuple constructor is ~2× a dataclass
    __init__; immutability is part of the contract (callers remap via
    _replace)."""

    key: Tuple[str, str]
    node: Optional[str]                  # None → unschedulable
    mapping: Optional[Dict[str, tuple]] = None
    nic_list: Optional[list] = None      # (nic_index, speed, dir) consumed
    round_no: int = -1
    failed: bool = False                 # terminal assignment failure (vs
    #                                      merely no candidate node)


from collections import namedtuple

# host-side view of the on-device top-R ranking (kernel.RankOut): all
# arrays are [T, R] — the [T, N] solve outputs never reach the host
RankHost = namedtuple(
    "RankHost", "val idx best_c best_m best_a n_picks free_gpu free_cpu free_hp"
)

# one in-flight speculative dispatch (see _speculate_dispatch): the four
# device tensors ride ONE batched flush; ``certifiable`` records the
# saturation-certificate preconditions evaluated at dispatch time
SpecDispatch = namedtuple(
    "SpecDispatch",
    "bucket_keys bucket_pods claims counts need_left iters_used certifiable",
)


@dataclass
class ScheduleContext:
    """Persistent per-cluster solve state reusable across schedule() calls.

    Built once via BatchScheduler.make_context and passed to schedule():
    the cluster encode, the FastCluster allocation arrays and the
    device-resident (possibly mesh-sharded) arrays all survive between
    calls, so streaming pod chunks through the same node tile
    (solver/streaming.py) pays O(claimed rows), not O(tile), per chunk.
    The HostNode mirror stays in sync (FastCluster.sync_to_nodes is
    incremental over touched nodes).

    With a ``delta`` (solver/encode.py ClusterDelta) the context also
    survives CHURN between calls: watch events noted on the delta fold
    into the packed arrays as row patches at the next refresh_context,
    FastCluster rows re-read, and the device-resident arrays take the
    same rows as one donated scatter — a steady round pays host encode
    + upload proportional to changed rows, not cluster size. ``nodes``
    is then the delta's row-aligned VIEW (live dict order plus in-place
    tombstones), not the live dict itself.
    """

    nodes: Dict[str, "HostNode"]
    cluster: "ClusterArrays"
    fast: Optional["FastCluster"]
    dev: Optional["DeviceClusterState"]
    now: float
    delta: Optional["ClusterDelta"] = None


_FC_EXECUTOR = None


def _fc_executor():
    """Single shared worker for off-thread FastCluster builds (the build
    overlaps round 1's solve; one worker is enough — schedule() joins the
    future before any assignment)."""
    global _FC_EXECUTOR
    if _FC_EXECUTOR is None:
        from concurrent.futures import ThreadPoolExecutor

        _FC_EXECUTOR = ThreadPoolExecutor(
            max_workers=1, thread_name_prefix="nhd-fastcluster"
        )
    return _FC_EXECUTOR


class GcPin:
    """Process-wide heap pin for scheduler sweeps (see
    BatchScheduler.schedule): gc.freeze() excludes the pre-existing
    heap (node mirror, contexts) from collection, AND automatic
    collection is disabled outright for the pin's duration — even with
    the old heap frozen, the young generations re-scan the sweep's own
    accumulating result objects every ~2k allocations, measured at
    ~50% of the federation sweep's materialize phase. A sweep's
    garbage is bounded by the batch; the re-enabled collector reclaims
    it at the next natural collection after release.

    Reentrancy is tracked with an explicit flag, NOT
    gc.get_freeze_count(): interpreter startup can leave a nonzero
    permanent generation (observed 375 objects on this image), and
    keying on the count would silently disable pinning forever. The
    streaming sweep takes the pin once for its whole run; the per-tile
    BatchScheduler calls inside it see ``active`` and leave gc alone.
    An embedding app that manages its own freeze/disable state should
    set NHD_TPU_GC_PIN=0 (our release would clobber its arrangement)."""

    active = False
    _lock = _threading.Lock()

    @classmethod
    def acquire(cls):
        """Take the pin; returns an opaque token for release(), or None
        when another sweep holds it / NHD_TPU_GC_PIN=0. The token CARRIES
        the prior gc-enabled state — a shared class attribute would turn
        the concurrent-acquire race into a permanently disabled
        collector (both acquirers could record enabled=False)."""
        import gc
        import os

        if os.environ.get("NHD_TPU_GC_PIN", "1") == "0":
            return None
        with cls._lock:
            if cls.active:
                return None
            cls.active = True
        was_enabled = gc.isenabled()
        gc.freeze()
        gc.disable()
        return (True, was_enabled)

    @classmethod
    def release(cls, token) -> None:
        if token:
            import gc

            if token[1]:
                gc.enable()
            gc.unfreeze()
            # under the same lock as acquire: the unlocked write published
            # `active = False` with no happens-before edge to the gc calls
            # above, so a racing acquire() could freeze/disable gc while
            # this thread was still unfreezing (nhdlint NHD201 catch)
            with cls._lock:
                cls.active = False


_GC_PIN_MIN_ITEMS = 4096


def _unique_rows(cols):
    """``np.unique(axis=0)`` over parallel int columns, via ONE packed
    int64 key — the structured-dtype sort behind axis-unique measured
    4-10× a scalar unique at round-sized inputs (40-1000 rows), which
    was the whole materialize win. Each column is shifted by its own
    minimum before packing — NIC rows carry a ``-1`` no-NIC sentinel
    (native nhd_assign writes it for CPU-only groups), and packing a
    negative would break key injectivity (two distinct rows colliding
    = a pod handed another row's consumed-NIC tuple). Falls back to
    the axis form when the packed key would overflow int64 (never at
    sane lattices — the bit budget is the sum of per-column ranges).

    Returns ``(rows, inverse)``: the distinct rows (original,
    unshifted values) as an [U, len(cols)] array and the per-input
    index into it."""
    bits = 0
    spans = []
    for c in cols:
        lo = int(c.min()) if len(c) else 0
        span = (int(c.max()) - lo + 1) if len(c) else 1
        spans.append((lo, span))
        bits += max(span - 1, 1).bit_length()
    if bits <= 62:
        key = np.zeros(len(cols[0]), np.int64)
        for c, (lo, span) in zip(cols, spans):
            key = key * span + (c.astype(np.int64, copy=False) - lo)
        _, first_idx, inv = np.unique(
            key, return_index=True, return_inverse=True
        )
        rows = np.stack([c[first_idx] for c in cols], axis=1)
        return rows, inv
    mat = np.stack(
        [c.astype(np.int64, copy=False) for c in cols], axis=1
    )
    return np.unique(mat, axis=0, return_inverse=True)


def _gc_pinned(fn):
    """Wrap a schedule call in GcPin acquire/release — but only for
    gang-scale batches (>= _GC_PIN_MIN_ITEMS items). Pinning every
    small bind would promote the whole young heap to the oldest
    generation per call (gc.unfreeze feeds the permanent set into
    gen2), starving generational collection on the frequent small-batch
    path of a long-running daemon — the exact stall class the pin
    exists to prevent."""
    import functools

    @functools.wraps(fn)
    def wrapper(self, nodes, items, **kwargs):
        held = GcPin.acquire() if len(items) >= _GC_PIN_MIN_ITEMS else False
        try:
            return fn(self, nodes, items, **kwargs)
        finally:
            GcPin.release(held)

    return wrapper


def _accelerator_backend() -> bool:
    import jax

    try:
        return jax.default_backend() != "cpu"
    except Exception:
        return False


def _rung_of(dev) -> int:
    """The ladder rung a solve attempt runs at, read off its device
    state (solver/guard.py): mesh-sharded resident arrays, single-device
    resident arrays, or the pure host path."""
    if dev is None:
        return RUNG_HOST
    return RUNG_MESH if dev.mesh is not None else RUNG_SINGLE


def _pipeline_enabled() -> bool:
    """Universal round pipelining (docs/PERFORMANCE.md "Host round
    loop"): every round dispatches round r+1's solves — respecting the
    claims this round just staged — before running its own host phases,
    so select/materialize/sync execute UNDER the in-flight device
    compute. NHD_PIPELINE: ``1`` forces on, ``0`` is the kill switch
    (strict dispatch-at-round-start ordering, the bit-exactness control
    the pipeline-parity suite pins placements against), ``auto``
    (default) = on exactly when the default backend is an accelerator —
    the overlap needs a device to hide under, and on a host-only
    backend the early dispatch just steals cores from the very host
    phases it is supposed to hide (measured −1.5% sustained churn on
    CPU CI). Read per schedule() call so tests can toggle it without
    rebuilding schedulers."""
    import os

    val = os.environ.get("NHD_PIPELINE", "auto").lower()
    if val in ("1", "true", "on"):
        return True
    if val in ("0", "false", "off"):
        return False
    return _accelerator_backend()


def _cpu_small_max() -> int:
    """Pending-pod count at or below which a round's solves run on the
    HOST CPU backend instead of the accelerator: every accelerator
    dispatch+sync pays the relay turnaround (~65 ms on the tunnel TPU,
    docs/TPU_STATUS.md), while the same jitted solve on the host CPU takes
    ~5-30 ms at benchmark shapes — so small batches and few-pod tail
    rounds are faster OFF the chip. Same program, same semantics; only
    the placement device changes."""
    import os

    return int(os.environ.get("NHD_TPU_CPU_SMALL", "1024"))


def _cpu_small_nodes() -> int:
    """Node-count ceiling for the CPU routing above: the host solve cost
    scales with nodes × combo lattice (a G=2 bucket at a 4096-node
    streaming tile walks ~360 MB of predicate tensors, ~0.7 s on this
    1-core host — far worse than the 65 ms relay turnaround it avoids),
    so big-tile tail rounds stay on the accelerator."""
    import os

    return int(os.environ.get("NHD_TPU_CPU_SMALL_NODES", "1536"))


@dataclass
class BatchStats:
    rounds: int = 0
    solve_seconds: float = 0.0
    select_seconds: float = 0.0
    assign_seconds: float = 0.0
    scheduled: int = 0
    failed: int = 0
    # elapsed seconds from batch start to the end of each round — a pod
    # placed in round r has bind latency <= round_end_seconds[r]
    round_end_seconds: List[float] = field(default_factory=list)
    # fine-grained wall breakdown (encode / spec_dispatch / spec_pull /
    # native_assign / materialize) — the overhead war's tracked metric
    phases: Dict[str, float] = field(default_factory=dict)
    # event counts (per-round pending, speculative claims/rejects) — the
    # round-convergence diagnostics the phase floats can't carry
    counters: Dict[str, int] = field(default_factory=dict)
    # cluster shape bucket this batch ran at ("U{U}_K{K}_N{n}"), set by
    # schedule() once the cluster is encoded; while set, every phase is
    # ALSO attributed per shape into the process jit-stats table
    # (obs/jitstats.py record_phase — the perf-telemetry pipeline's
    # device-phase attribution). Aggregation paths that merge sub-batch
    # stats (solver/streaming.py) leave it empty so tile phases are
    # never double-counted.
    shape_hint: str = ""

    def phase_add(self, name: str, dt: float) -> None:
        self.phases[name] = self.phases.get(name, 0.0) + dt
        if self.shape_hint:
            from nhd_tpu.obs.jitstats import JIT_STATS

            JIT_STATS.record_phase(name, self.shape_hint, dt)

    def count_add(self, name: str, k: int) -> None:
        self.counters[name] = self.counters.get(name, 0) + int(k)

    def bind_latency_percentile(self, results, q: float) -> float:
        """p-th percentile bind latency over placed pods (seconds)."""
        lats = sorted(
            self.round_end_seconds[r.round_no]
            for r in results
            if r.node is not None and 0 <= r.round_no < len(self.round_end_seconds)
        )
        if not lats:
            return 0.0
        # nearest-rank percentile: ceil(q/100 * n) - 1
        rank = max(0, -(-int(q * len(lats)) // 100) - 1)
        return lats[min(rank, len(lats) - 1)]


class BatchScheduler:
    """Schedules a whole pending batch against the host node mirror.

    ``use_fast`` (default) routes physical assignment through the
    vectorized FastCluster (solver/fast_assign.py) and syncs the HostNode
    mirror once at the end; with it off, every winner goes through
    HostNode.assign_physical_ids object-by-object (the reference path) —
    kept for cross-checking, ~13× slower per pod.
    """

    def __init__(
        self,
        *,
        respect_busy: bool = True,
        max_rounds: int = 10_000,
        use_fast: bool = True,
        register_pods: bool = True,
        device_state: str = "auto",
        mesh: object = "auto",
    ):
        self.logger = get_logger(__name__)
        self.respect_busy = respect_busy
        self.max_rounds = max_rounds
        self.use_fast = use_fast
        self.register_pods = register_pods
        # FastCluster static-topology cache, shared across schedule() calls
        # over the same unchanged node set (fast_assign.py _build_static)
        self._fc_static: dict = {}
        # "auto": resident device arrays + per-round row scatters pay off on
        # real accelerators (especially across a tunnel/PCIe) but are pure
        # overhead on the CPU backend, where solve inputs are already host
        # memory. NHD_TPU_DEVICE_STATE=1/0 overrides "auto" from the
        # environment — chaos/soak runs use it to drive the resident-state
        # (and, with NHD_TPU_SPECULATE=1, the speculative) path through
        # the full scheduler on CPU
        if device_state not in (True, False, "auto"):
            raise ValueError(
                f"device_state must be True, False or 'auto', got {device_state!r}"
            )
        if device_state == "auto":
            import os

            env = os.environ.get("NHD_TPU_DEVICE_STATE")
            if env is not None:
                if env not in ("0", "1"):
                    raise ValueError(
                        f"NHD_TPU_DEVICE_STATE must be 0 or 1, got {env!r}"
                    )
                device_state = env == "1"
        self.device_state = device_state
        # mesh: "auto" → shard the solve over every visible device whenever
        # more than one exists (the production multi-chip path, SURVEY §7
        # step 6); None → force single-device; or pass an explicit 1-D
        # jax.sharding.Mesh over a "nodes" axis
        if mesh is not None and mesh != "auto":
            if "nodes" not in getattr(mesh, "axis_names", ()):
                raise ValueError(
                    "mesh must be 'auto', None, or a jax.sharding.Mesh "
                    f"with a 'nodes' axis, got {mesh!r}"
                )
            if device_state is False:
                raise ValueError(
                    "device_state=False conflicts with an explicit mesh: "
                    "sharded arrays must be device-resident"
                )
        self.mesh = mesh

    def _resolve_mesh(self):
        if self.device_state is False:
            return None  # host-only path: mesh would be dead weight
        if self.mesh != "auto":
            return self.mesh
        import jax

        from nhd_tpu.parallel.sharding import make_mesh

        try:
            # local_devices, NOT devices: each scheduler process runs an
            # independent computation over its own node shard (multihost
            # pattern). A mesh over jax.devices() after
            # jax.distributed.initialize would span every host and demand
            # lockstep cross-host collectives that don't exist here. A
            # global SPMD solve is still available by passing an explicit
            # mesh.
            devices = jax.local_devices()
        except Exception:
            return None
        return make_mesh(devices) if len(devices) > 1 else None

    def _select_winners(
        self, pods, out: RankHost, node_claimed: Dict[int, int], G: int
    ):
        """Vectorized capacity-aware packing for one bucket's round: the
        per-type winner extraction that used to run as a ``by_type``
        dict build plus a per-pod ``zip(pod_type, pod_index)`` loop, as
        pure array ops over the ranked candidates — bit-exact with the
        loop by construction (same greedy rank-order fill against the
        same optimistic capacity estimates, same one-bucket-per-node
        blocking, same pod-index consumption order per type).

        Returns ``(w_pod, w_node, w_type, w_rank)`` sorted by pod index
        (the native apply order), or None when the bucket wins nothing
        this round. Mutates ``node_claimed`` with this bucket's claimed
        nodes, exactly like the loop's ``setdefault`` per claim."""
        cap = self._capacity_at(pods, out)            # [T, R], 0 off-prefix
        T, R = cap.shape
        if node_claimed:
            # one-bucket-per-node rule: nodes another bucket claimed this
            # round are blocked (static within a bucket)
            blocked = np.asarray(
                [n for n, g in node_claimed.items() if g != G], np.int64
            )
            if len(blocked):
                cap[np.isin(out.idx, blocked)] = 0
        # greedy fill in rank order, whole bucket at once: each type
        # takes min(cap, need left) at every rank position
        need_t = np.bincount(pods.pod_type, minlength=T)
        cap = np.minimum(cap, need_t[:, None])
        cum = np.cumsum(cap, axis=1)
        take = np.clip(need_t[:, None] - (cum - cap), 0, cap)
        k_t = take.sum(axis=1)                        # winners per type
        if not k_t.any():
            return None
        take_flat = take.ravel()
        w_node = np.repeat(out.idx.ravel(), take_flat).astype(
            np.int32, copy=False
        )
        w_rank = np.repeat(np.tile(np.arange(R, dtype=np.int32), T),
                           take_flat)
        w_type = np.repeat(np.arange(T, dtype=np.int32), k_t)
        # pods of a type consume claims in pod-index order: pod_index is
        # ascending within the encode, so a stable sort by type keeps it,
        # and each type's first k_t pods are its winners
        order = np.argsort(pods.pod_type, kind="stable")
        podid_sorted = pods.pod_index[order]
        types_sorted = pods.pod_type[order]
        starts = np.concatenate(([0], np.cumsum(need_t)[:-1]))
        ordinal = (
            np.arange(len(types_sorted), dtype=np.int64)
            - starts[types_sorted]
        )
        w_pod = podid_sorted[ordinal < k_t[types_sorted]].astype(
            np.int64, copy=False
        )
        for n in np.unique(w_node).tolist():
            node_claimed.setdefault(int(n), G)
        o = np.argsort(w_pod, kind="stable")
        return (
            np.ascontiguousarray(w_pod[o]),
            np.ascontiguousarray(w_node[o]),
            np.ascontiguousarray(w_type[o]),
            np.ascontiguousarray(w_rank[o]),
        )

    def _capacity_at(self, pods, rank: RankHost) -> np.ndarray:
        """Optimistic copies-per-node estimate cap[T, R] over the ranked
        candidates for one round.

        Built from node-total aggregates gathered on device at the ranked
        nodes (cheap, may overestimate — the assignment re-verifies and
        stale claims retry; underestimates only cost extra rounds):
        feasible NIC picks at the best combo, total free GPUs / cores /
        hugepages over per-pod demand. GPU pods cap at 1 per node whenever
        the busy back-off applies (reference: one placement per node per
        window, Matcher.py:103-111).
        """
        INF = np.int64(1 << 30)
        cand = rank.val > 0
        cap = np.where(cand, np.maximum(rank.n_picks, 1), 0).astype(np.int64)

        gpus_tot = pods.gpu_dem.sum(axis=1)
        gpu_cap = np.where(
            gpus_tot[:, None] > 0,
            rank.free_gpu // np.maximum(gpus_tot, 1)[:, None],
            INF,
        )
        cpu_tot = np.minimum(
            pods.cpu_dem_smt.sum(axis=1), pods.cpu_dem_raw.sum(axis=1)
        )
        cpu_cap = np.where(
            cpu_tot[:, None] > 0,
            rank.free_cpu // np.maximum(cpu_tot, 1)[:, None],
            INF,
        )
        hp_cap = np.where(
            pods.hp[:, None] > 0,
            rank.free_hp // np.maximum(pods.hp, 1)[:, None],
            INF,
        )
        cap = np.minimum(cap, np.minimum(gpu_cap, np.minimum(cpu_cap, hp_cap)))
        if self.respect_busy:
            cap = np.where(pods.needs_gpu[:, None], np.minimum(cap, 1), cap)
        cap = np.where(cand, np.maximum(cap, 1), 0)
        return cap

    def _speculate_dispatch(self, dev, all_buckets, is_pending):
        """Round 0 of the speculative path: ONE device dispatch runs the
        whole greedy claim loop (solver/speculate.py megaround) for every
        eligible bucket jointly — PCI-map-mode types included (r5: the
        loop projects their per-switch GPU consumption through the
        static slot→switch map, solver/speculate.py). Returns None when
        nothing is eligible."""
        from nhd_tpu.solver.kernel import _pad_pow2

        from nhd_tpu.solver.speculate import _T_SHIFT

        bucket_keys, bucket_pods, needs = [], [], []
        t_total = 0
        need_total = 0
        for G, full in all_buckets.items():
            mask = is_pending[full.pod_index]
            # keep the FULL type rows (no _filter_types shrink) AND keep
            # empty/all-PCI buckets in the dispatch: absent types and
            # dead buckets just carry zero need, and the stable
            # bucket_shapes tuple means every sub-call of a streaming
            # chunk (spill offers often hold pods of only some buckets)
            # reuses ONE compiled megaround — a changed bucket subset was
            # paying a fresh ~1 s trace+compile through the tunnel per
            # distinct subset (r5: 4 subset shapes = 4.4 s of cfg5's
            # spec_dispatch). The loop body skips zero-need buckets at
            # runtime via lax.cond, so they cost no device compute.
            pods = replace(
                full,
                pod_type=full.pod_type[mask],
                pod_index=full.pod_index[mask],
            )
            Tp = _pad_pow2(pods.n_types)
            need = np.bincount(pods.pod_type, minlength=Tp).astype(np.int32)
            U, K = dev.cluster.U, dev.cluster.K
            word_overflow = (
                (U**pods.G) * (max(K, 1) ** pods.G) * U >= (1 << _T_SHIFT)
            )
            if word_overflow or not bucket_tractable(pods.G, U, K):
                if not need.any():
                    # a zero-need bucket whose lattice is word-overflowing
                    # or intractable must NOT ride along for shape
                    # stability: merely building its combo tables
                    # (get_tables) is the explosion the tractability
                    # budget exists to prevent. It can never GAIN need
                    # within a chunk (oversized pods are pre-routed to
                    # the serial path), so skipping it keeps shapes
                    # stable across the chunk's sub-calls anyway.
                    continue
                # the packed claim word's (c*U+m)*A + a field would
                # overflow (an NHD_TPU_MAX_LATTICE raise can get here):
                # classic rounds handle any lattice
                return None
            bucket_keys.append(G)
            bucket_pods.append(pods)
            needs.append(need)
            t_total += Tp
            need_total += int(need.sum())
        if (
            not bucket_keys
            or need_total == 0
            or t_total >= (1 << (31 - _T_SHIFT))
        ):
            # nothing to speculate, or the global
            # type axis would overflow the claim word's type field
            return None
        # saturation-certificate preconditions (see the spec_round
        # consumer): with these, the loop's projected state provably
        # upper-bounds true state, so a no-candidate exit is final
        from nhd_tpu.core.node import ENABLE_NIC_SHARING

        certifiable = (
            not ENABLE_NIC_SHARING
            and dev.cluster.uniform_nic_caps
            and not any(
                need[: pods.n_types][pods.map_pci].any()
                for pods, need in zip(bucket_pods, needs)
            )
        )
        # returns the IN-FLIGHT device tensors (claims, counts, need
        # left, iterations used). The copy_to_host_async here is
        # load-bearing: on the tunnel relay it STARTS the ~65 ms flush
        # immediately (measured r5 — asarray later completes in
        # flush-minus-elapsed), so every millisecond of host prep
        # between dispatch and pull (FastCluster join, expand prep)
        # hides under the in-flight flush
        claims_arr, counts_arr, need_arr, it_arr = dev.megaround(
            bucket_pods, needs, self.respect_busy
        )
        try:
            claims_arr.copy_to_host_async()
            counts_arr.copy_to_host_async()
            need_arr.copy_to_host_async()
            it_arr.copy_to_host_async()
        except (AttributeError, NotImplementedError, RuntimeError):
            pass  # best-effort prefetch hint; backend without async host
            #      copies just pays the full flush at the sync pull
            #      (AttributeError covers host-backend numpy results)
        return SpecDispatch(
            bucket_keys, bucket_pods, claims_arr, counts_arr,
            need_arr, it_arr, certifiable,
        )

    def _expand_speculative(self, spec, claims_np, counts_np, cluster):
        """Expand the megaround's packed claim tensor into per-bucket
        winner ARRAYS: pods of a type consume its claims in (iteration,
        node) order, re-sorted to pod-index order within the bucket (the
        classic apply order). Returns
        ({G: (pods, w_pod, w_node, w_type, w_c, w_m, w_a)}, node_claimed)
        with every w_* an int32 numpy array — at gang scale this path
        handles ~10k claims and per-claim Python tuples were the
        measurable cost of the select phase."""
        from nhd_tpu.solver.kernel import _pad_pow2
        from nhd_tpu.solver.speculate import decode_claims_grouped

        bucket_keys, bucket_pods = spec.bucket_keys, spec.bucket_pods
        shapes = tuple((p.G, _pad_pow2(p.n_types)) for p in bucket_pods)
        decoded = decode_claims_grouped(
            claims_np, shapes, tuple(bucket_keys), cluster.U, cluster.K,
            counts_np,
        )
        out = {}
        node_claimed: Dict[int, int] = {}
        for gk, pods in zip(bucket_keys, bucket_pods):
            per_type = decoded.get(gk, {})
            if not per_type:
                continue
            # pod ids per type in pod-index order: pod_index is ascending
            # within the encode, so a stable sort by type keeps it
            order = np.argsort(pods.pod_type, kind="stable")
            types_sorted = pods.pod_type[order]
            podid_sorted = pods.pod_index[order]
            t_vals, t_starts = np.unique(types_sorted, return_index=True)
            t_bounds = np.append(t_starts, len(types_sorted))
            t_slice = {
                int(t): (int(lo), int(hi))
                for t, lo, hi in zip(t_vals, t_bounds[:-1], t_bounds[1:])
            }
            cols: List[List[np.ndarray]] = [[] for _ in range(6)]
            for t, (nds, cs, ms, As) in per_type.items():
                span = t_slice.get(int(t))
                if span is None:
                    continue
                lo, hi = span
                k = min(hi - lo, len(nds))
                if k == 0:
                    continue
                cols[0].append(podid_sorted[lo : lo + k])
                cols[1].append(nds[:k])
                cols[2].append(np.full(k, int(t), np.int64))
                cols[3].append(cs[:k])
                cols[4].append(ms[:k])
                cols[5].append(As[:k])
            if not cols[0]:
                continue
            w_pod, w_node, w_type, w_c, w_m, w_a = (
                np.concatenate(c) for c in cols
            )
            o = np.argsort(w_pod, kind="stable")
            entry = (
                pods,
                np.ascontiguousarray(w_pod[o], np.int64),
                np.ascontiguousarray(w_node[o], np.int32),
                np.ascontiguousarray(w_type[o], np.int32),
                np.ascontiguousarray(w_c[o], np.int32),
                np.ascontiguousarray(w_m[o], np.int32),
                np.ascontiguousarray(w_a[o], np.int32),
            )
            out[gk] = entry
            for n in np.unique(w_node).tolist():
                node_claimed.setdefault(int(n), gk)
        return out, node_claimed

    @staticmethod
    def _spec_tuples(expanded):
        """Adapter for the object-assignment fallback: per-bucket winner
        arrays → (claims tuples, bucket_out with a synthetic RankHost
        carrying each claim's (c, m, a) at its rank position)."""
        claims: List[Tuple[int, int, int, int, int]] = []
        bucket_out = {}
        for gk, (pods, w_pod, w_node, w_type, w_c, w_m, w_a) in (
            expanded.items()
        ):
            T = pods.n_types
            counts = np.bincount(w_type, minlength=T)
            r_spec = int(counts.max(initial=0)) or 1
            val = np.zeros((T, r_spec), np.int32)
            idx = np.zeros((T, r_spec), np.int32)
            bc = np.zeros((T, r_spec), np.int32)
            bm = np.zeros((T, r_spec), np.int32)
            ba = np.zeros((T, r_spec), np.int32)
            # rank position = per-type claim ordinal, in (iter, node)
            # order; winners are pod-sorted but pods of one type consume
            # claims in order, so the per-type ordinal is the running
            # count of that type among the sorted winners
            seen = np.zeros(T, np.int64)
            for pod_i, n, t, c, m, a in zip(
                w_pod.tolist(), w_node.tolist(), w_type.tolist(),
                w_c.tolist(), w_m.tolist(), w_a.tolist(),
            ):
                j = int(seen[t])
                seen[t] += 1
                val[t, j] = 1
                idx[t, j] = n
                bc[t, j] = c
                bm[t, j] = m
                ba[t, j] = a
                claims.append((pod_i, n, gk, t, j))
            zeros = np.zeros((T, r_spec), np.int32)
            bucket_out[gk] = (
                pods,
                RankHost(val, idx, bc, bm, ba,
                         np.ones((T, r_spec), np.int32),
                         zeros, zeros, zeros),
            )
        claims.sort()
        return claims, bucket_out

    def _schedule_serial(
        self, nodes, items, indices, results, stats, now, apply
    ) -> set:
        """Oracle-driven sequential scheduling for combo-oversized pods
        (reference-exact semantics; claims hit the HostNode mirror).
        Returns the TOUCHED node names — winners plus busy-stamped
        failed attempts (set_busy lands before the assignment can
        fail) — so delta-maintained callers patch every mutated row,
        not just the claimed ones."""
        from nhd_tpu.sim.requests import request_to_topology

        touched: set = set()
        for i in indices:
            item = items[i]
            m = oracle_find_node(
                nodes, item.request, now=now, respect_busy=self.respect_busy
            )
            if m is None:
                continue
            if not apply:
                results[i] = BatchAssignment(item.key, m.node, m.mapping)
                continue
            node = nodes[m.node]
            touched.add(m.node)
            try:
                top = item.topology or request_to_topology(item.request)
                node.set_busy(now)
                nic_list = node.assign_physical_ids(m.mapping, top)
            except (AssignmentError, ValueError) as exc:
                self.logger.error(
                    f"serial assignment failed for {item.key}: {exc}"
                )
                stats.failed += 1
                continue
            node.claim_nic_pods(sorted({x[0] for x in nic_list}))
            if self.register_pods:
                node.add_scheduled_pod(item.key[1], item.key[0], top)
            results[i] = BatchAssignment(item.key, m.node, m.mapping, nic_list)
            stats.scheduled += 1
        return touched

    def make_context(
        self, nodes: Dict[str, HostNode], *, now: Optional[float] = None,
        interner=None, delta: Optional[ClusterDelta] = None,
    ) -> ScheduleContext:
        """Encode *nodes* once into a reusable ScheduleContext.

        Pass the result to repeated schedule() calls over the same node set
        (the streaming tile pattern): the encode, FastCluster arrays, and
        device-resident state all persist, and each call pays only for the
        rows its claims touch. Busy stamps are resolved against *now* once,
        at context creation. ``interner``: share one GroupInterner across
        several contexts so pod encodes (group_mask bit positions) are
        valid against every one of them — the streaming tiler passes its
        batch-wide interner here.

        ``delta``: build the context over an incrementally-maintained
        ClusterDelta instead of a fresh encode — the context then
        survives churn between calls (refresh_context folds noted events
        in as row patches). The delta must have been created over
        *nodes*; the context's ``nodes`` becomes the delta's row-aligned
        view.
        """
        if now is None:
            now = time.monotonic()
        if delta is not None:
            if delta.nodes is not nodes:
                raise ValueError(
                    "delta was built over a different nodes dict"
                )
            delta.refresh(now)
            delta.consume_full()
            delta.drain_dirty()  # fresh fast/dev below derive from arrays
            cluster = delta.arrays
            nodes = delta.view
        else:
            cluster = encode_cluster(nodes, now=now, interner=interner)
            if not self.respect_busy:
                cluster.busy[:] = False
        fast = (
            FastCluster(nodes, cluster.U, cluster.K, arrays=cluster,
                        static_cache=self._fc_static)
            if self.use_fast
            else None
        )
        mesh, use_dev = self._guard_posture()
        dev = (
            self._build_dev(
                cluster, mesh,
                delta.capacity if delta is not None else None,
            )
            if use_dev else None
        )
        return ScheduleContext(nodes, cluster, fast, dev, now, delta)

    def _guard_posture(self):
        """(mesh, use_dev) for a fresh device-state build, with the
        solver guard's degradation floor applied: a condemned mesh
        strips to single-device, a condemned device plane strips to the
        host path (solver/guard.py ladder). With the guard at full
        fidelity this is exactly the pre-guard auto logic."""
        mesh = self._resolve_mesh()
        if GUARD.active() and not GUARD.allow_mesh():
            mesh = None
        use_dev = (
            self.device_state is True
            or (
                self.device_state == "auto"
                and (_accelerator_backend() or mesh is not None)
            )
        )
        if GUARD.active() and not GUARD.allow_device():
            use_dev = False
        return mesh, use_dev

    def _build_dev(self, cluster, mesh, capacity):
        """Construct device-resident state under the guard's fault
        boundary: the BUILD itself dispatches device_puts, and on a
        hard-down device (dead tunnel) it faults exactly like a solve
        would — walking the ladder rung by rung would re-fault at every
        device rung, so a transient construction failure condemns the
        device plane straight to the host rung and returns None. With
        the guard off (or a terminal fault) it raises as before."""
        from nhd_tpu.solver.guard import classify_device_fault

        if not GUARD.active():
            return DeviceClusterState(cluster, mesh, capacity=capacity)
        try:
            return DeviceClusterState(cluster, mesh, capacity=capacity)
        except Exception as exc:
            if not classify_device_fault(exc):
                raise
            self.logger.error(
                "solver guard: device-state build failed (device plane "
                f"unreachable); condemning to the host rung: {exc!r}"
            )
            GUARD.condemn_device(exc)
            return None

    def _reposture_dev(self, ctx: ScheduleContext) -> None:
        """Rebuild a persistent context's device state when the guard's
        floor moved between batches — degradation condemns the resident
        plane (or just its mesh), re-promotion after clean probe rounds
        re-derives it from host truth at the faster rung. A no-op when
        the posture already matches (the steady-state branch)."""
        mesh, use_dev = self._guard_posture()
        cur = ctx.dev
        if use_dev == (cur is not None) and (
            cur is None or (cur.mesh is not None) == (mesh is not None)
        ):
            return
        capacity = ctx.delta.capacity if ctx.delta is not None else None
        ctx.dev = (
            self._build_dev(ctx.cluster, mesh, capacity)
            if use_dev else None
        )
        if ctx.dev is not None:
            GUARD.note_repair()

    def _guard_recover(self, dev, cluster, context):
        """Condemn + rebuild the device plane after a transient fault,
        at the guard's (possibly degraded) allowed rung: resident arrays
        re-derive wholesale from the host ClusterArrays — the SURVEY
        §5.4 re-derivability contract spent at failure time. Returns the
        replacement device state (None = host rung) and re-points a
        persistent context at it so later batches inherit the posture."""
        new = None
        if dev is not None and GUARD.allow_device():
            mesh = dev.mesh if GUARD.allow_mesh() else None
            capacity = (
                context.delta.capacity
                if context is not None and context.delta is not None
                else None
            )
            new = self._build_dev(cluster, mesh, capacity)
            if new is not None:
                GUARD.note_repair()
        elif dev is not None:
            self.logger.error(
                "solver guard: device state condemned; this batch "
                "continues on the host solve path"
            )
        if context is not None:
            context.dev = new
        return new

    def _guard_audit(self, dev, cluster, context, stats):
        """Batch-start resident-state audit (solver/guard.py): flush any
        staged claim rows (the device may legitimately lag them), then
        bit-exact spot-check the budgeted row sample against the host
        mirror. Corruption repairs IN PLACE (rebuild_resident — host
        truth wins) before any solve reads the poisoned rows. A device
        fault inside the audit itself takes the same recover path as a
        round fault. Returns the (possibly replaced) device state."""
        t0 = time.perf_counter()
        try:
            dev._flush_staged()
            errs = GUARD.run_audit(dev)
            if errs:
                for e in errs[:4]:
                    self.logger.error(f"resident-state audit: {e}")
                dev.rebuild_resident()
                GUARD.note_repair()
            return dev
        except Exception as exc:
            if GUARD.on_fault(
                exc, rung=_rung_of(dev), attempt=1
            ) != "retry":
                raise
            return self._guard_recover(dev, cluster, context)
        finally:
            stats.phase_add("guard_audit", time.perf_counter() - t0)

    def refresh_context(
        self, ctx: ScheduleContext, *, now: Optional[float] = None,
    ) -> ScheduleContext:
        """Bring a delta-built ScheduleContext current between batches:
        busy decay plus every noted event fold into the packed arrays as
        row patches, the same rows re-read into FastCluster and scatter
        into the device-resident arrays — O(changed rows) end to end.
        A fallback rebuild inside the delta (new group bit, padding or
        capacity overflow, compaction...) re-derives FastCluster and the
        resident device state wholesale; the ClusterArrays object (and
        the view dict) keep their identity, so the context stays valid
        either way."""
        delta = ctx.delta
        if delta is None:
            raise ValueError("refresh_context needs a delta-built context")
        if now is None:
            now = time.monotonic()
        if GUARD.active():
            # guard posture drift: a degradation (or re-promotion after
            # clean probe rounds) between batches rebuilds the resident
            # plane at the allowed rung before this batch's rows scatter
            self._reposture_dev(ctx)
        delta.refresh(now)
        ctx.now = now
        if delta.consume_full():
            delta.drain_dirty()
            ctx.fast = (
                FastCluster(
                    ctx.nodes, ctx.cluster.U, ctx.cluster.K,
                    arrays=ctx.cluster, static_cache=self._fc_static,
                )
                if self.use_fast else None
            )
            if ctx.dev is not None:
                ctx.dev = self._build_dev(
                    ctx.cluster, ctx.dev.mesh, delta.capacity
                )
            return ctx
        rows = delta.drain_dirty()
        if rows.size:
            if ctx.fast is not None:
                if len(ctx.fast.names) != delta.n_rows:
                    # rows appended into padded-capacity slots: the
                    # packed solver arrays grew in place, FastCluster's
                    # fixed-N matrices cannot — rebuild it
                    ctx.fast = FastCluster(
                        ctx.nodes, ctx.cluster.U, ctx.cluster.K,
                        arrays=ctx.cluster, static_cache=self._fc_static,
                    )
                else:
                    for i in rows.tolist():
                        ctx.fast.refresh_node(i)
            if ctx.dev is not None:
                ctx.dev.scatter_rows(rows)
        elif ctx.dev is not None:
            ctx.dev.scatter_rows(rows)  # still syncs row-count growth
        return ctx

    @_gc_pinned
    def schedule(
        self,
        nodes: Dict[str, HostNode],
        items: Sequence[BatchItem],
        *,
        now: Optional[float] = None,
        apply: bool = True,
        context: Optional[ScheduleContext] = None,
        encoded: Optional[Dict[int, "PodTypeArrays"]] = None,
        offer: Optional[Sequence[int]] = None,
    ) -> Tuple[List[BatchAssignment], BatchStats]:
        """Place every item it can; mutates ``nodes`` when ``apply``.

        Gang-scale calls take the GcPin: the pre-existing heap (node
        mirror, contexts) is gc.freeze-pinned and automatic collection
        is disabled for the sweep — both the major pass over a large
        mirror and the young-gen re-scans of the sweep's own result
        objects are stalls the scheduler, not the caller, should
        prevent. Skipped when the streaming sweep already holds the pin
        for its whole run.

        Items without a topology get a synthetic one (sim.requests), so
        physical assignment always runs — claims must hit the host mirror
        for subsequent rounds to see them.

        With ``context`` (from make_context over the same ``nodes``), the
        per-call encode and array construction are skipped; combo-oversized
        pods are rejected there (the caller pre-routes them — see
        solver/streaming.py).

        ``encoded``/``offer``: reuse a prior encode_pods of the FULL
        ``items`` list (built against the context cluster's interner) and
        restrict the schedulable set to the ``offer`` indices — the
        streaming tiler encodes each pod chunk once and offers shrinking
        subsets of it to successive tiles, instead of re-encoding (and
        re-hashing) the leftovers per tile. With ``offer``, result slots
        outside the offer are None (not allocated — a late spill offers a
        handful of pods out of a 100k chunk); the caller reads only the
        offered indices.
        """
        from nhd_tpu.sim.requests import request_to_topology

        stats = BatchStats()
        # results materialize lazily: placed pods get their real entry at
        # assignment, unplaced offered slots are back-filled before return
        # (building 10k placeholder objects up front was measurable wall)
        results: List[Optional[BatchAssignment]] = [None] * len(items)
        if now is None:
            now = context.now if context is not None else time.monotonic()

        if context is not None and context.nodes is not nodes:
            raise ValueError(
                "context was built for a different nodes dict"
            )
        node_list = list(nodes.values())
        # contextless one-shot batch (bench/tests): the encode routes
        # through an EPHEMERAL ClusterDelta — its init rebuild is the
        # sanctioned encode chokepoint (NHD108), and the serial
        # oversized pre-pass below folds its claims back in as O(winner)
        # row patches instead of a second full re-encode. The production
        # round paths pass a persistent delta-built context instead.
        ephemeral: Optional[ClusterDelta] = None
        if context is not None:
            cluster = context.cluster
        else:
            ephemeral = ClusterDelta(
                nodes, now=now, respect_busy=self.respect_busy
            )
            cluster = ephemeral.arrays
        # per-shape phase attribution key: the (U, K, node-count) bucket
        # this batch's programs specialize on
        stats.shape_hint = f"U{cluster.U}_K{cluster.K}_N{len(node_list)}"

        # ONE fused pass collects the schedulable set AND the combo-
        # oversized subset (tractability memoized per group count: one
        # bucket verdict covers a whole gang; two separate comprehensions
        # each touching 10k request objects were measurable wall). From
        # here ``pending`` lives as an int64 array — membership updates
        # are np.isin over winner arrays, not Python set diffs.
        _tract: Dict[int, bool] = {}
        pending_l: List[int] = []
        oversized: List[int] = []
        _sched_modes = (MapMode.NUMA, MapMode.PCI)
        _U, _K = cluster.U, cluster.K
        t_pre = time.perf_counter()
        for i in range(len(items)) if offer is None else offer:
            r = items[i].request
            if r.map_mode not in _sched_modes:
                continue
            pending_l.append(i)
            G = len(r.groups)
            v = _tract.get(G)
            if v is None:
                v = _tract[G] = bucket_tractable(G, _U, _K)
            if not v:
                oversized.append(i)
        pending = np.asarray(pending_l, np.int64)
        del pending_l
        stats.phase_add("prepass", time.perf_counter() - t_pre)
        if oversized and context is not None and context.delta is None:
            # serial claims would mutate the HostNode mirror behind the
            # context's packed arrays (a delta-built context absorbs them
            # as row patches below)
            raise ValueError(
                "combo-oversized pods cannot be scheduled through a "
                "persistent context; route them to the serial path first"
            )
        if oversized:
            # NOTE: the pre-pass gives oversized pods their claims before any
            # greedy round, so in a capacity-contended mixed batch they win
            # over lower-indexed tractable pods — a documented exception to
            # the lowest-index conflict rule (every claim is still feasible
            # when made; single-pod batches are unaffected)
            touched = self._schedule_serial(
                nodes, items, oversized, results, stats, now, apply
            )
            pending = pending[~np.isin(pending, oversized)]
            if apply and context is not None:
                # the serial pass touched O(winners) rows (busy-stamped
                # failures included): fold them in as delta patches + a
                # device row scatter — the get-or-apply-deltas form of
                # the contextless path below
                context.delta.note_all(touched)
                self.refresh_context(context, now=now)
            elif apply:
                # contextless: the serial mutations fold into the
                # ephemeral delta as O(touched) row patches
                # (bit-identical to a re-encode by the delta parity
                # contract; the arrays object keeps its identity).
                # Device state is built below, from the already-patched
                # arrays.
                ephemeral.note_all(touched)
                ephemeral.refresh(now)
                ephemeral.drain_dirty()

        fast_future = None
        # deferred to round 0, right AFTER the first device dispatch: the
        # build runs on a worker thread, and on a single-core host it
        # would otherwise steal the GIL from the encode that gates the
        # dispatch — submitted after it, the build's CPU time hides
        # entirely under the in-flight relay flush (free), instead of
        # delaying the flush's start (paid)
        submit_fast = False
        if context is not None:
            fast = context.fast if apply else None
            dev = context.dev
        else:
            fast = None
            submit_fast = self.use_fast and apply
            # keep node arrays resident on device across rounds; per-round
            # uploads shrink to the claimed rows (solver/device_state.py).
            # A multi-device mesh implies resident state: sharded arrays must
            # live on their devices for the SPMD solve. The guard's
            # degradation floor applies here too (_guard_posture), and a
            # build that faults on a dead device condemns to the host
            # rung instead of crashing the batch (_build_dev).
            mesh, use_dev = self._guard_posture()
            dev = self._build_dev(cluster, mesh, None) if use_dev else None
        guard_on = GUARD.active()
        if guard_on and dev is not None and GUARD.audit_due():
            # periodic + on-suspicion resident-state audit BEFORE any
            # solve of this batch reads the resident rows: a corrupted
            # row repairs from host truth here, so a clean batch's binds
            # are bit-identical to a fault-free run (the device-faults
            # chaos invariant)
            dev = self._guard_audit(dev, cluster, context, stats)
        records: Dict[int, AssignRecord] = {}
        busy_nodes: set = set()
        all_buckets = None
        is_pending = None
        # top-R rank budget, fixed at round 1 (the largest round) so every
        # round's ranker hits the same jit program
        R = None
        # solves for round r+1, dispatched by round r before it runs its
        # host phases (universal round pipelining; NHD_PIPELINE=0 kills
        # it for parity testing — placements are bit-exact either way)
        prelaunched = None
        pipeline_on = apply and _pipeline_enabled()
        # speculative on-device multi-round (solver/speculate.py): round 0
        # runs the whole greedy-round loop in ONE device dispatch and the
        # host re-verifies its claims through the normal native apply;
        # anything the native core rejects retries in classic rounds
        from nhd_tpu.solver.speculate import (
            spec_iters as _spec_iters,
            speculate_enabled,
        )

        from nhd_tpu.policy.scoring import scoring_active

        spec_ok = (
            apply
            and dev is not None
            and speculate_enabled()
            # the megaround claims on feasibility alone — under a live
            # (non-uniform) heterogeneity scoring matrix its round-0
            # claims would bypass the policy ranking, so policy batches
            # run classic rounds (whose fused solve+rank carries the
            # score terms). NHD_POLICY=0 and the uniform matrix keep the
            # speculative fast path.
            and not scoring_active()
        )

        t_batch = time.perf_counter()
        t_batch_mono = time.monotonic()
        for round_no in range(self.max_rounds):
            if not len(pending):
                break
            stats.rounds = round_no + 1
            if round_no < 8:
                stats.count_add(f"pending_r{round_no}", len(pending))

            t0 = time.perf_counter()
            if all_buckets is None:
                # type-level tensors never change across rounds —
                # encode the whole pending set once (or reuse the
                # caller's chunk-wide encode) and only filter
                # membership below
                pend_list = pending.tolist()  # np iteration boxes per
                #                               element; tolist is C
                all_buckets = encoded if encoded is not None else encode_pods(
                    [items[i].request for i in pend_list],
                    cluster.interner,
                    indices=pend_list,
                )
                stats.phase_add("encode", time.perf_counter() - t0)
                # R >= the largest per-type pod count: every ranked
                # candidate carries capacity >= 1, so the top-R cut
                # can never force an extra round
                max_need = max(
                    (
                        int(np.bincount(b.pod_type).max())
                        for b in all_buckets.values()
                        if len(b.pod_type)
                    ),
                    default=1,
                )
                # backend decides the cap, not device-residency: even
                # the non-resident path executes (and pulls) on the
                # default backend
                R = rank_budget(
                    max_need, cluster.n_nodes,
                    accelerator=_accelerator_backend(),
                )
                is_pending = np.zeros(len(items), bool)
            is_pending[:] = False
            is_pending[pending] = True

            # dispatch every bucket's solve+rank before pulling any result:
            # jax dispatch is async, so the buckets' XLA programs overlap
            # instead of serializing on the first np.asarray block.
            # ``use_cpu``: small rounds run the SAME jitted programs on
            # the host CPU backend against the host cluster arrays (always
            # true state) — an accelerator dispatch pays the fixed relay
            # turnaround, which swamps small solves (_cpu_small_max)
            def _membership(full, mask):
                """Restrict pod membership WITHOUT shrinking the type
                rows: the padded (G, Tp) bucket shape stays stable across
                every round (and every streaming tile of a chunk), so the
                whole batch reuses ONE compiled solve program — a late
                round whose alive types shrank the bucket was paying a
                fresh multi-second trace+compile through the tunnel for a
                solve that itself takes milliseconds. Absent type rows
                simply select nothing."""
                return replace(
                    full,
                    pod_type=full.pod_type[mask],
                    pod_index=full.pod_index[mask],
                )

            def _shape_key(G, pods, host: bool) -> str:
                """The ranked_shape_key this bucket's dispatch runs
                under — matches kernel.dispatch_ranked's key exactly, so
                the guard's quarantine attribution joins on it."""
                if host or dev is None:
                    Np_k = _pad_pow2(cluster.n_nodes, floor=8)
                    desc = ""
                else:
                    Np_k = dev.Np
                    desc = mesh_desc(dev.mesh)
                return ranked_shape_key(
                    G, cluster.U, cluster.K, min(R, Np_k),
                    _pad_pow2(pods.n_types), Np_k, desc,
                )

            def _stamp(exc: BaseException, G, pods, host: bool) -> None:
                """Attribute a dispatch/pull fault to its bucket's shape
                key (best effort — some exception types refuse new
                attributes) for the guard's quarantine ledger."""
                try:
                    exc._nhd_shape_key = _shape_key(G, pods, host)
                except (AttributeError, TypeError):
                    pass  # slotted / C-extension exception types

            def _dispatch_solves(use_cpu: bool = False):
                launched = []
                if use_cpu:
                    import jax

                    with jax.default_device(jax.devices("cpu")[0]):
                        for G, full in all_buckets.items():
                            mask = is_pending[full.pod_index]
                            if not mask.any():
                                continue
                            pods = _membership(full, mask)
                            try:
                                out = solve_bucket_ranked(cluster, pods, R)
                            except Exception as exc:
                                _stamp(exc, G, pods, host=True)
                                raise
                            launched.append((G, pods, out))
                    return launched
                for G, full in all_buckets.items():
                    mask = is_pending[full.pod_index]
                    if not mask.any():
                        continue
                    pods = _membership(full, mask)
                    try:
                        out = (
                            dev.solve_ranked(pods, R) if dev
                            else solve_bucket_ranked(cluster, pods, R)
                        )
                    except Exception as exc:
                        _stamp(exc, G, pods, host=dev is None)
                        raise
                    launched.append((G, pods, out))
                return launched

            def _route_cpu(n_pending: int) -> bool:
                return (
                    dev is not None
                    and _accelerator_backend()
                    and n_pending <= _cpu_small_max()
                    and cluster.n_nodes <= _cpu_small_nodes()
                )

            def _prelaunch() -> float:
                """Dispatch round r+1's solves NOW — the arrays (and the
                staged claim rows, via the stage_rows scatter the
                dispatch flushes) already carry this round's claims, so
                the host phases that follow overlap the next round's XLA
                compute. Universal across postures: the native path, the
                object fallback, CPU-routed small rounds (_route_cpu),
                mesh-sharded and streaming-tile sub-calls all feed the
                same ``prelaunched`` seam. A prelaunch fault costs only
                the pipelining: recover the device plane now and let the
                next round dispatch fresh under its own boundary (a
                faulted batch never prelaunches again this round-trip).
                Returns the host dispatch seconds — attributed to the
                dedicated ``prelaunch`` phase (not solve, not assign):
                the coarse phases stay comparable artifact-to-artifact,
                and the dispatch cost stays visible in the phase table
                instead of inflating whichever window it runs inside."""
                nonlocal prelaunched, spec_ok, dev
                is_pending[:] = False
                is_pending[pending] = True
                t_pl = time.perf_counter()
                try:
                    prelaunched = _dispatch_solves(_route_cpu(len(pending)))
                    stats.count_add("prelaunched_rounds", 1)
                except Exception as exc:
                    if not guard_on or GUARD.on_fault(
                        exc, rung=_rung_of(dev), attempt=1,
                        shape_key=getattr(exc, "_nhd_shape_key", ""),
                    ) != "retry":
                        raise
                    prelaunched = None
                    spec_ok = False
                    dev = self._guard_recover(dev, cluster, context)
                dt = time.perf_counter() - t_pl
                stats.phase_add("prelaunch", dt)
                return dt

            # ---- solve phase, under the guard's fault boundary ------
            # Any exception out of a device dispatch, an async pull or
            # the rank-tensor screen is classified (solver/guard.py);
            # a transient fault condemns the device state, rebuilds it
            # from host truth at a (possibly degraded) rung, and
            # RE-DISPATCHES the whole round — none of this round's
            # claims has been applied yet, so a retried round can never
            # produce a wrong or partial bind. Terminal faults and an
            # exhausted ladder surface to the scheduler's _guarded
            # isolation exactly as before the guard existed.
            guard_attempts = 0
            while True:
                # (pod index, node index, bucket G, type, rank position)
                claims: List[Tuple[int, int, int, int, int]] = []
                bucket_out = {}
                # pins the jax RankOuts whose buffers RankHost's
                # zero-copy views alias, for the round's lifetime —
                # correctness must not hinge on any particular backend's
                # buffer-export semantics
                keepalive: List[object] = []
                spec = None
                claims_np = counts_np = None
                try:
                    use_cpu_round = _route_cpu(len(pending))
                    if use_cpu_round and guard_attempts == 0:
                        stats.count_add("cpu_routed_rounds", 1)
                    spec_round = (
                        spec_ok and round_no == 0 and not use_cpu_round
                    )
                    if prelaunched is not None:
                        # round r-1 dispatched this round's solves right
                        # after its native assign; its result
                        # materialization ran under the XLA compute (the
                        # round-pipelining that keeps host work off the
                        # critical path)
                        launched = prelaunched
                        prelaunched = None
                    else:
                        if spec_round:
                            t_sp = time.perf_counter()
                            spec = self._speculate_dispatch(
                                dev, all_buckets, is_pending
                            )
                            stats.phase_add(
                                "spec_dispatch", time.perf_counter() - t_sp
                            )
                            launched = []
                        if spec is None:
                            # nothing to speculate, or a small CPU-routed
                            # batch: classic round
                            spec_round = False
                            launched = _dispatch_solves(use_cpu_round)
                    if submit_fast:
                        # first dispatch is in flight: the build's CPU
                        # time now hides under the relay flush (see
                        # submit_fast above)
                        submit_fast = False
                        fast_future = _fc_executor().submit(
                            FastCluster, nodes, cluster.U, cluster.K,
                            arrays=cluster, static_cache=self._fc_static,
                        )
                    if fast_future is not None:
                        # join here, while the just-dispatched solves (or
                        # the in-flight megaround) compute in the XLA
                        # pool: the build hides under the relay
                        # turnaround, and the worker never outlives
                        # schedule()
                        t_j = time.perf_counter()
                        fast = fast_future.result()
                        fast_future = None
                        stats.phase_add(
                            "fast_join", time.perf_counter() - t_j
                        )
                    if spec_round:
                        # ONE relay flush pulls the claim tensor AND its
                        # counts plane; the flush was started by the
                        # copy_to_host_async at dispatch
                        # (_speculate_dispatch), so the FastCluster join
                        # above ran under it and this asarray pays only
                        # the remaining flush time (sequential asarray
                        # pulls without the async batch each pay a full
                        # ~65 ms turnaround — measured 130 ms vs 65 ms,
                        # docs/TPU_STATUS.md r4)
                        t_pull = time.perf_counter()
                        # the speculative round's ONE sanctioned flush
                        # (NHD107): all four tensors were
                        # copy_to_host_async'd at dispatch
                        claims_np = np.asarray(spec.claims)  # nhdlint: ignore[NHD107]
                        counts_np = np.asarray(spec.counts)  # nhdlint: ignore[NHD107]
                        spec_need_left = int(np.asarray(spec.need_left).sum())  # nhdlint: ignore[NHD107]
                        spec_it = int(np.asarray(spec.iters_used))  # nhdlint: ignore[NHD107]
                        stats.phase_add(
                            "spec_pull", time.perf_counter() - t_pull
                        )
                    for G, pods, out in launched:
                        try:
                            out.copy_to_host_async()  # batch bucket pulls
                        except (AttributeError, NotImplementedError,
                                RuntimeError):
                            pass  # prefetch hint only; sync pull works
                    for G, pods, out in launched:
                        # pull results to host in ONE transfer — the rank
                        # output is a single packed [9, Tp, R] tensor
                        # because each device→host transfer costs ~84 ms
                        # of relay latency on the tunnel-attached TPU
                        # regardless of size (nine separate field pulls
                        # were the round bottleneck, docs/TPU_STATUS.md).
                        # RankHost's fields are zero-copy row views on
                        # CPU; `keepalive` pins the owning array for the
                        # round's lifetime
                        keepalive.append(out)
                        T = pods.n_types
                        try:
                            # the classic round's ONE sanctioned flush
                            # (NHD107): the copy_to_host_async loop above
                            # batched every bucket pull
                            arr = np.asarray(out)  # nhdlint: ignore[NHD107]
                            if guard_on:
                                # value-domain screen BEFORE any winner
                                # materializes (the int analog of a
                                # NaN/inf screen, solver/guard.py).
                                # CPU-routed rounds solved at the HOST
                                # pad even when resident state exists —
                                # screening by dev.Np there would admit
                                # corrupt indices in [host_Np, dev.Np)
                                npad = (
                                    dev.Np
                                    if dev is not None and not use_cpu_round
                                    else _pad_pow2(
                                        cluster.n_nodes, floor=8
                                    )
                                )
                                defect = GUARD.screen_rank(arr, npad)
                                if defect:
                                    raise DeviceCorruptionError(
                                        f"rank-tensor screen: {defect}"
                                    )
                        except Exception as exc:
                            _stamp(exc, G, pods, host=use_cpu_round
                                   or dev is None)
                            raise
                        bucket_out[G] = (pods, RankHost(*arr[:, :T]))
                    break
                except Exception as exc:
                    if not guard_on:
                        raise
                    guard_attempts += 1
                    if GUARD.on_fault(
                        exc, rung=_rung_of(dev), attempt=guard_attempts,
                        shape_key=getattr(exc, "_nhd_shape_key", ""),
                    ) != "retry":
                        raise
                    # a faulted batch never speculates again: the classic
                    # round's host re-verification is the conservative
                    # posture while the device plane is suspect
                    spec_ok = False
                    prelaunched = None
                    dev = self._guard_recover(dev, cluster, context)
            if guard_on:
                GUARD.note_round_clean()
            stats.solve_seconds += time.perf_counter() - t0

            t0 = time.perf_counter()
            # node index → bucket G of its claims this round. A node only
            # accepts claims from ONE bucket per round so the native round
            # calls (one per bucket) preserve pod-index application order
            # per node — cross-bucket interleaving on a node would otherwise
            # break the documented serialization order
            node_claimed: Dict[int, int] = {}
            spec_winners = None
            if spec_round:
                # the device already ran the whole claim loop — expand its
                # packed tensor into per-bucket winner arrays (the native
                # apply's direct input); the per-type capacity select
                # below is skipped entirely
                spec_winners, node_claimed = self._expand_speculative(
                    spec, claims_np, counts_np, cluster
                )
            # per-bucket vectorized winner arrays for this round:
            # {G: (pods, w_pod, w_node, w_type, w_rank)} — claim TUPLES
            # are only materialized for the dry-run and object-fallback
            # paths (the per-pod tuple builds were the select phase's
            # dominant cost at gang scale, r14 profile)
            winners: Dict[int, tuple] = {}
            for G, (pods, out) in ({} if spec_round else bucket_out).items():
                if not apply:
                    # dry-run: every pod reports its own snapshot match (the
                    # reference's FindNode answer), with no contention model —
                    # a conflict "loser" would wrongly read as unschedulable.
                    # Candidates arrive pre-ranked from the device (desc sel
                    # value = pref then low-node-index, kernel._rank_body);
                    # valid prefix length per type:
                    n_cands = (out.val > 0).sum(axis=1)
                    for t, pod_i in zip(pods.pod_type, pods.pod_index):
                        t = int(t)
                        if n_cands[t] > 0:
                            claims.append(
                                (int(pod_i), int(out.idx[t, 0]), G, t, 0)
                            )
                    continue

                # capacity-aware packing (the reference's first-fit shape):
                # each type fills its ranked candidates up to an optimistic
                # per-node capacity estimate (claims are re-verified against
                # live state at assignment, so an overestimate just costs a
                # retry) — one vectorized pass per bucket (_select_winners)
                w = self._select_winners(pods, out, node_claimed, G)
                if w is not None:
                    winners[G] = (pods, *w)
            # assignment order = pod index order: per node this is a valid
            # sequential execution (claims re-verified as they apply); the
            # first claim a node actually processes ran against fresh
            # feasibility, so its failure is final — later same-node
            # failures are stale contention and retry next round
            claims.sort()
            applied_on_node: set = set()
            stats.select_seconds += time.perf_counter() - t0

            if not claims and not winners and not spec_winners:
                if spec_round:
                    # an empty speculation is not a saturation verdict —
                    # fall through to a classic round (keep the round
                    # timeline aligned for bind-latency percentiles)
                    stats.round_end_seconds.append(
                        time.perf_counter() - t_batch
                    )
                    continue
                break  # no pod could be placed: remaining are unschedulable

            t0 = time.perf_counter()
            newly_scheduled: List[int] = []

            round_ok = (
                apply
                and fast is not None
                and fast.round_supported()
                and all(
                    fast.round_ok_for(po)
                    for po in (
                        [v[0] for v in spec_winners.values()]
                        if spec_round
                        else [bucket_out[G][0] for G in bucket_out]
                    )
                )
            )
            if spec_round and not round_ok:
                # object-assignment fallback consumes claim tuples + a
                # synthetic RankHost — materialize them from the arrays
                claims, bucket_out = self._spec_tuples(spec_winners)
            elif not round_ok and winners:
                # classic object-assignment fallback: pod-sorted claim
                # tuples from the vectorized winner arrays
                claims = [
                    (int(p), int(n), G, int(t), int(j))
                    for G, (_po, w_pod, w_node, w_type, w_rank) in (
                        winners.items()
                    )
                    for p, n, t, j in zip(
                        w_pod.tolist(), w_node.tolist(),
                        w_type.tolist(), w_rank.tolist(),
                    )
                ]
                claims.sort()
            if round_ok:
                # one native call per bucket places every winner of the
                # round (native/nhd_assign.cc::nhd_assign_round) and
                # mutates the packed state + solver arrays. The winner
                # arrays come straight from the speculative expand, or
                # from the classic round's vectorized select — the
                # claims→array expansion (per-claim tuples regrouped via
                # np.fromiter) is gone: the (c, m) gathers index the rank
                # tensors with the winner arrays directly.
                native_in = []
                if spec_round:
                    for G, (pods, w_pod, w_node, w_type, w_c, w_m, _a) in (
                        spec_winners.items()
                    ):
                        native_in.append(
                            (G, pods, w_pod, w_node, w_type, w_c, w_m)
                        )
                else:
                    for G, (pods, w_pod, w_node, w_type, w_rank) in (
                        winners.items()
                    ):
                        out = bucket_out[G][1]
                        w_c = np.ascontiguousarray(
                            out.best_c[w_type, w_rank], np.int32)
                        w_m = np.ascontiguousarray(
                            out.best_m[w_type, w_rank], np.int32)
                        native_in.append(
                            (G, pods, w_pod, w_node, w_type, w_c, w_m)
                        )
                native_out = []
                t_na = time.perf_counter()
                for G, pods, w_pod, w_node, w_type, w_c, w_m in native_in:
                    buffers = fast.assign_round(
                        pods, w_node, w_type, w_c, w_m,
                        set_busy=self.respect_busy,
                    )
                    native_out.append(
                        (G, pods, w_pod, w_node, w_type, buffers, w_c, w_m)
                    )
                stats.phase_add("native_assign", time.perf_counter() - t_na)
                # BIND stamp = native-verify completion: every surviving
                # claim of the round is now applied to the authoritative
                # packed state (occupancy + solver arrays); the result
                # materialization and mirror sync below are bookkeeping
                # that lags the commit (VERDICT r3 item 2: stamp bind as
                # the chunk's verify completes, not at sweep end)
                stats.round_end_seconds.append(time.perf_counter() - t_batch)
                if dev is not None:
                    # deferred: the scatter fuses into the next round's
                    # solve dispatch (device_state.stage_rows)
                    dev.stage_rows(node_claimed)

                # pending update, vectorized: a winner leaves pending when
                # its assignment succeeded (status >= 0) OR it was the
                # first claim its node processed and failed (final — it
                # ran against fresh feasibility); later same-node failures
                # are stale contention and retry next round. claims.sort()
                # put winners in pod-index order. "First on node" is
                # tracked ACROSS the per-bucket native calls in their
                # application order (classic rounds never share a node
                # between buckets, so the cross-bucket tracking is a
                # no-op there; the speculative round can share). In the
                # speculative round NO failure is final — its claims were
                # solved against projected state mid-loop, not a fresh
                # snapshot, so every failure retries classically.
                removed: List[np.ndarray] = []
                first_masks: List[np.ndarray] = []
                seen_first: set = set()
                round_rejects = 0
                for G, pods, w_pod, w_node, w_type, buffers, w_c, w_m in (
                    native_out
                ):
                    ok = buffers[0] >= 0
                    round_rejects += int((~ok).sum())
                    if round_no < 8:
                        stats.count_add(f"claims_r{round_no}", len(w_pod))
                        stats.count_add(
                            f"rejects_r{round_no}", int((~ok).sum())
                        )
                    first = np.zeros(len(w_pod), bool)
                    if not spec_round:
                        uniq, fi = np.unique(w_node, return_index=True)
                        fresh = [
                            i for u, i in zip(uniq.tolist(), fi.tolist())
                            if u not in seen_first
                        ]
                        first[fresh] = True
                        seen_first.update(uniq.tolist())
                    first_masks.append(first)
                    removed.append(w_pod[ok | first])
                if removed:
                    pending = pending[
                        ~np.isin(pending, np.concatenate(removed))
                    ]

                # SATURATION CERTIFICATE: the loop exited before its
                # iteration cap with need left — i.e. its final exact
                # solve found NO eligible (type, node) pair against the
                # projected state. When every projection component is
                # provably optimistic-or-exact w.r.t. true state — zero
                # native rejects (deltas applied exactly as projected),
                # no PCI types with need (their NUMA-pool deltas can be
                # pessimistic), uniform per-node NIC caps + sharing off
                # (candidacy depends only on free-NIC counts, which the
                # loop tracks exactly) — infeasible-under-projection
                # implies infeasible in reality, and the leftover pods
                # are unschedulable WITHOUT a classic confirmation round
                # (one whole relay flush on a saturated gang, ~45% of
                # cfg3's wall). Any failed precondition just falls back
                # to the confirmation round.
                if (
                    spec_round
                    and len(pending)
                    and spec.certifiable
                    and round_rejects == 0
                    and spec_need_left > 0
                    and spec_it < _spec_iters()
                ):
                    stats.count_add(
                        "certified_unschedulable", len(pending)
                    )
                    pending = pending[:0]

                # dispatch round r+1's solves NOW (round pipelining,
                # NHD_PIPELINE): the result materialization below runs
                # under the next XLA compute (a small leftover routes to
                # the host CPU backend: its solve beats the accelerator's
                # fixed relay turnaround). The dispatch seconds shift the
                # assign-phase clock (t0): they are solve work executing
                # inside the assign window, and leaving them in `assign`
                # made the pipelined figure incomparable to the
                # NHD_PIPELINE=0 control.
                if (
                    pipeline_on
                    and len(pending)
                    and round_no + 1 < self.max_rounds
                ):
                    t0 += _prelaunch()

                t_mat = time.perf_counter()
                U_, K_ = cluster.U, cluster.K
                names = cluster.names
                want_record = self.register_pods
                BA_make = BatchAssignment._make
                for bi, (G, pods, w_pod, w_node, w_type, buffers, w_c, w_m) in (
                    enumerate(native_out)
                ):
                    # materialize, vectorized: the round's mapping points
                    # and consumed-NIC tuples are batch-decoded in one
                    # numpy uniquing pass each — winners draw from a
                    # handful of distinct (combo, misc, pick) points, so
                    # decode_mapping runs once per point, not once per
                    # pod, and the per-winner Python loop shrinks to the
                    # BatchAssignment._make scatter (tuple.__new__
                    # directly; the generated __new__ is a Python frame,
                    # ~2x the cost). Failures are handled in a separate
                    # small pass (their final-vs-retry verdict is the
                    # precomputed `first` mask).
                    status = buffers[0]
                    ok = status >= 0
                    w_node_l = w_node.tolist()
                    applied_on_node.update(w_node_l)
                    all_ok = bool(ok.all())
                    if not all_ok:
                        # failure pass: a first-on-node failure is final
                        # (it ran against fresh feasibility); later
                        # same-node failures — and every speculative
                        # failure — retry classically
                        first = first_masks[bi]
                        w_pod_all = w_pod.tolist()
                        for w in np.nonzero(~ok)[0].tolist():
                            if spec_round or not first[w]:
                                continue
                            pod_i, n = w_pod_all[w], w_node_l[w]
                            item = items[pod_i]
                            self.logger.error(
                                f"assignment failed for {item.key} on "
                                f"{names[n]}: stage {int(status[w])}"
                            )
                            results[pod_i] = BatchAssignment(
                                item.key, None, failed=True
                            )
                            stats.failed += 1
                        sel = np.nonzero(ok)[0]
                        n_ok = len(sel)
                        if n_ok == 0:
                            continue
                        widx_l = sel.tolist()
                        pods_sel = w_pod[sel].tolist()
                        nodes_sel = w_node[sel].tolist()
                        types_sel = w_type[sel]
                        cc, mm = w_c[sel], w_m[sel]
                        pp, rows_sel = buffers[5][sel], buffers[3][sel]
                    else:
                        n_ok = len(w_node_l)
                        widx_l = range(n_ok)
                        pods_sel = w_pod.tolist()
                        nodes_sel = w_node_l
                        types_sel = w_type
                        cc, mm = w_c, w_m
                        pp, rows_sel = buffers[5], buffers[3]
                    busy_nodes.update(nodes_sel)
                    # the NIC pick is re-selected against live state in
                    # the native call — decode the actual choices, one
                    # lru hit per DISTINCT point
                    uq, inv = _unique_rows((cc, mm, pp))
                    mappings = [
                        decode_mapping(G, U_, K_, c_, m_, a_)
                        for c_, m_, a_ in uq.tolist()
                    ]
                    maps_sel = [mappings[i] for i in inv.ravel().tolist()]
                    names_sel = [names[n] for n in nodes_sel]
                    types_l = types_sel.tolist()
                    if want_record:
                        # record path (registration or topology fills
                        # pending): per-pod object work by necessity
                        for w, pod_i, nm, n, t, mp in zip(
                            widx_l, pods_sel, names_sel, nodes_sel,
                            types_l, maps_sel,
                        ):
                            item = items[pod_i]
                            rec = fast.record_from_round(
                                pods, w, n, t, buffers
                            )
                            records[pod_i] = rec
                            results[pod_i] = BA_make((
                                item.key, nm, mp, rec.nic_list,
                                round_no, False,
                            ))
                        stats.scheduled += n_ok
                        continue
                    # consumed-NIC tuples, batch-built per DISTINCT
                    # (type, per-group NIC row) key — shared immutable
                    # TUPLES by design (the record path keeps its
                    # per-pod list from the assignment record)
                    rows2d = np.asarray(rows_sel).reshape(n_ok, -1)
                    uqk, ninv = _unique_rows(
                        (np.asarray(types_sel),)
                        + tuple(rows2d[:, g] for g in range(rows2d.shape[1]))
                    )
                    nic_tmpl: Dict[int, list] = {
                        t: [
                            (g, bw, d)
                            for g, grp in enumerate(pods.requests[t].groups)
                            for bw, d in (
                                (grp.nic_rx_gbps, NicDir.RX),
                                (grp.nic_tx_gbps, NicDir.TX),
                            )
                            if bw > 0
                        ]
                        for t in set(uqk[:, 0].tolist())
                    }
                    nics = [
                        tuple((row[g], bw, d) for g, bw, d in nic_tmpl[t])
                        for t, *row in uqk.tolist()
                    ]
                    nic_sel = [nics[i] for i in ninv.ravel().tolist()]
                    for w, pod_i, nm, n, t, mp, nl in zip(
                        widx_l, pods_sel, names_sel, nodes_sel, types_l,
                        maps_sel, nic_sel,
                    ):
                        item = items[pod_i]
                        if item.topology is not None:
                            rec = fast.record_from_round(
                                pods, w, n, t, buffers
                            )
                            records[pod_i] = rec
                            nl = rec.nic_list
                        results[pod_i] = BA_make((
                            item.key, nm, mp, nl, round_no, False,
                        ))
                    stats.scheduled += n_ok
                stats.phase_add("materialize", time.perf_counter() - t_mat)
                stats.assign_seconds += time.perf_counter() - t0
                continue

            for pod_i, n, G, t, j in claims:
                pods, out = bucket_out[G]
                mapping = decode_mapping(
                    G, cluster.U, cluster.K,
                    int(out.best_c[t, j]), int(out.best_m[t, j]),
                    int(out.best_a[t, j]),
                )
                node = node_list[n]
                item = items[pod_i]
                if not apply:
                    # dry-run: snapshot match per pod (no claims, see below)
                    results[pod_i] = BatchAssignment(
                        item.key, node.name, mapping, None, round_no
                    )
                    newly_scheduled.append(pod_i)
                    continue

                if (
                    self.respect_busy
                    and item.request.needs_gpu
                    and cluster.busy[n]
                ):
                    # node took a placement earlier this round (snapshot-busy
                    # nodes are never selected for GPU pods): defer, like the
                    # native round path's -8 (reference: Matcher.py:103-111)
                    continue

                is_first = n not in applied_on_node
                applied_on_node.add(n)

                if fast is not None:
                    try:
                        rec = fast.assign(n, mapping, item.request)
                    except FastAssignError as exc:
                        if not is_first or spec_round:
                            continue  # stale same-node claim: retry
                        self.logger.error(
                            f"assignment failed for {item.key} on {node.name}: {exc}"
                        )
                        results[pod_i] = BatchAssignment(item.key, None, failed=True)
                        newly_scheduled.append(pod_i)
                        stats.failed += 1
                        continue
                    records[pod_i] = rec
                    busy_nodes.add(n)
                    if self.respect_busy:
                        cluster.busy[n] = True
                    # report the realized NIC picks (assign may re-select
                    # against live state under multi-claim)
                    realized = {
                        "gpu": mapping["gpu"],
                        "cpu": mapping["cpu"],
                        "nic": tuple(ga.nic_uk for ga in rec.groups),
                    }
                    results[pod_i] = BatchAssignment(
                        item.key, node.name, realized, rec.nic_list, round_no
                    )
                    newly_scheduled.append(pod_i)
                    stats.scheduled += 1
                    continue

                # object path (reference-style, for cross-checking)
                try:
                    top = item.topology or request_to_topology(item.request)
                except ValueError as exc:
                    self.logger.error(
                        f"cannot materialize topology for {item.key}: {exc}"
                    )
                    results[pod_i] = BatchAssignment(item.key, None, failed=True)
                    newly_scheduled.append(pod_i)
                    stats.failed += 1
                    continue
                node.set_busy(now)  # reference: NHDScheduler.py:289
                try:
                    nic_list = node.assign_physical_ids(mapping, top)
                except AssignmentError as exc:
                    if not is_first or spec_round:
                        continue  # stale same-node claim: retry
                    # promised mapping didn't materialize (PCI quirk etc.):
                    # fail the pod like the reference (NHDScheduler.py:296-299)
                    self.logger.error(
                        f"assignment failed for {item.key} on {node.name}: {exc}"
                    )
                    results[pod_i] = BatchAssignment(item.key, None, failed=True)
                    newly_scheduled.append(pod_i)  # drop from pending
                    stats.failed += 1
                    continue
                nidx = sorted({x[0] for x in nic_list})
                node.claim_nic_pods(nidx)
                node.add_scheduled_pod(item.key[1], item.key[0], top)
                if self.respect_busy:
                    cluster.busy[n] = True
                results[pod_i] = BatchAssignment(
                    item.key, node.name, mapping, nic_list, round_no
                )
                newly_scheduled.append(pod_i)
                stats.scheduled += 1
            stats.assign_seconds += time.perf_counter() - t0

            # incremental device-state update: the fast path maintained the
            # arrays at assign time; the object path re-projects claimed rows
            t0 = time.perf_counter()
            if fast is None:
                for n in node_claimed:
                    refresh_node_row(cluster, n, node_list[n], now=now)
                    if not self.respect_busy:
                        cluster.busy[n] = False
            if dev is not None and apply:
                dev.stage_rows(node_claimed)
            stats.assign_seconds += time.perf_counter() - t0
            stats.round_end_seconds.append(time.perf_counter() - t_batch)

            if newly_scheduled:
                pending = pending[~np.isin(pending, newly_scheduled)]
            if not apply:
                break  # without claims, later rounds would repeat choices
            # universal pipelining, object-fallback leg: the claims above
            # applied to the packed arrays (fast.assign / the row
            # refreshes), so round r+1's solves can dispatch before this
            # round's trailing bookkeeping
            if pipeline_on and len(pending) and round_no + 1 < self.max_rounds:
                _prelaunch()

        # fast path: one final sync of the HostNode mirror + topology fills
        if fast is not None:
            t0 = time.perf_counter()
            fast.sync_to_nodes()
            # every scheduled pod stamps its node busy (reference:
            # NHDScheduler.py:289) — tracked independently of records, since
            # headless round-path winners don't materialize one
            for n in busy_nodes:
                node_list[n].set_busy(now)
            for pod_i, rec in records.items():
                item = items[pod_i]
                node = node_list[rec.node_index]
                if item.topology is not None:
                    apply_record_to_topology(rec, item.topology)
                    if self.register_pods:
                        node.add_scheduled_pod(
                            item.key[1], item.key[0], item.topology
                        )
                elif self.register_pods:
                    try:
                        top = request_to_topology(item.request)
                    except ValueError as exc:
                        # the pod IS scheduled (claims applied); only the
                        # bookkeeping object can't be synthesized
                        self.logger.warning(
                            f"skipping pod registration for {item.key}: {exc}"
                        )
                        continue
                    apply_record_to_topology(rec, top)
                    node.add_scheduled_pod(item.key[1], item.key[0], top)
            stats.phase_add("final_sync", time.perf_counter() - t0)
            stats.assign_seconds += time.perf_counter() - t0

        # flight-recorder spans (obs/): per-round intervals reconstructed
        # from round_end_seconds plus one whole-schedule span. The single
        # get_recorder() read above this block is the hot path's entire
        # tracing cost when the recorder is off (bench.py ≤2% acceptance).
        rec = get_recorder()
        if rec is not None:
            prev = 0.0
            for r, end in enumerate(stats.round_end_seconds):
                rec.record(
                    f"round{r}", t_batch_mono + prev, max(end - prev, 0.0),
                    cat="solver",
                    attrs={
                        "claims": stats.counters.get(f"claims_r{r}"),
                        "rejects": stats.counters.get(f"rejects_r{r}"),
                    },
                )
                prev = end
            rec.record(
                "schedule", t_batch_mono, time.perf_counter() - t_batch,
                cat="solver",
                attrs={"pods": len(items), "rounds": stats.rounds,
                       "scheduled": stats.scheduled, "failed": stats.failed},
            )

        # back-fill the lazy result slots: every offered-but-unplaced pod
        # reports an explicit unschedulable entry
        t_bf = time.perf_counter()
        for i in range(len(items)) if offer is None else offer:
            if results[i] is None:
                results[i] = BatchAssignment(items[i].key, None)
        stats.phase_add("backfill", time.perf_counter() - t_bf)
        return results, stats
