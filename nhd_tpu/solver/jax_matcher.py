"""JaxMatcher: the oracle-compatible front door of the batched solver.

Implements the same contract as OracleMatcher.find_node (and therefore the
reference's Matcher.FindNode, Matcher.py:27) but runs the feasibility solve
as one jitted tensor program — and, through find_nodes, amortizes it over a
whole pending batch at once (the BASELINE.json north star).

Selection semantics mirror the oracle exactly for a single pod:
* node: preferred (CPU-only pod × GPU-less node) first, then first
  candidate in node order;
* combo: skew-maximal feasible, first wins;
* misc NUMA / NIC pick: first feasible in product order.
"""

from __future__ import annotations

from functools import lru_cache
from types import MappingProxyType
from typing import Dict, List, Optional, Sequence, Union

import numpy as np

from nhd_tpu.core.node import HostNode
from nhd_tpu.core.request import PodRequest
from nhd_tpu.core.topology import MapMode, PodTopology
from nhd_tpu.solver.combos import get_tables
from nhd_tpu.solver.encode import encode_cluster, encode_pods
from nhd_tpu.solver.kernel import bucket_tractable, solve_bucket
from nhd_tpu.solver.oracle import MatchResult
from nhd_tpu.solver.oracle import find_node as oracle_find_node
from nhd_tpu.utils import get_logger


@lru_cache(maxsize=65536)
def decode_mapping(G: int, U: int, K: int, c: int, m: int, a: int):
    """(combo, misc-numa, pick) indices → the oracle's mapping, as a
    read-only view.

    Memoized: gang batches decode the same few (combo, pick) points tens of
    thousands of times. The MappingProxyType return enforces immutability —
    a mutation would otherwise corrupt the shared cache entry for every
    later pod decoding the same point (OracleMatcher returns fresh dicts;
    values are tuples either way, so reads are interchangeable).
    """
    tables = get_tables(G, U, K)
    combo = tuple(int(x) for x in tables.combo[c])
    pick = tuple(int(x) for x in tables.pick[a])
    return MappingProxyType({
        "gpu": combo,
        "cpu": combo + (int(m),),
        "nic": tuple(zip(combo, pick)),
    })


class JaxMatcher:
    """Batched matcher over a host-side node mirror."""

    def __init__(self) -> None:
        self.logger = get_logger(__name__)

    def find_node(
        self,
        nodes: Dict[str, HostNode],
        req: Union[PodRequest, PodTopology],
        *,
        now: Optional[float] = None,
        respect_busy: bool = True,
    ) -> Optional[MatchResult]:
        """Single-pod entry point, drop-in for OracleMatcher.find_node."""
        if isinstance(req, PodTopology):
            req = PodRequest.from_topology(req)
        results = self.find_nodes(
            nodes, [req], now=now, respect_busy=respect_busy
        )
        return results[0]

    def find_nodes(
        self,
        nodes: Dict[str, HostNode],
        reqs: Sequence[PodRequest],
        *,
        now: Optional[float] = None,
        respect_busy: bool = True,
    ) -> List[Optional[MatchResult]]:
        """Evaluate a whole pending batch against the *current* state at
        once. Every result is computed against the same snapshot — no claims
        are applied between pods (that is BatchScheduler's job)."""
        results: List[Optional[MatchResult]] = [None] * len(reqs)

        valid_idx = [
            i for i, r in enumerate(reqs)
            if r.map_mode in (MapMode.NUMA, MapMode.PCI)
        ]
        if not valid_idx:
            return results

        # one-shot snapshot evaluation (the reference-parity surface):
        # no rounds, no events — a delta would have nothing to reuse.
        # Sanctioned NHD108 chokepoint (analysis/rules_tracing.py
        # _ENCODE_SANCTIONED "jax_matcher:find_nodes").
        cluster = encode_cluster(nodes, now=now)
        if not respect_busy:
            cluster.busy[:] = False

        # pods whose combo lattice is too large for dense enumeration take
        # the serial oracle (identical semantics, no tensor blow-up)
        tractable = [
            i for i in valid_idx
            if bucket_tractable(reqs[i].n_groups, cluster.U, cluster.K)
        ]
        for i in set(valid_idx) - set(tractable):
            results[i] = oracle_find_node(
                nodes, reqs[i], now=now, respect_busy=respect_busy
            )

        buckets = encode_pods(
            [reqs[i] for i in tractable], cluster.interner, indices=tractable
        )

        for G, pods in buckets.items():
            out = solve_bucket(cluster, pods)
            # np.array (copy): zero-copy views must not outlive the jax
            # arrays they alias (see solver/batch.py bucket_out note).
            # NHD107-suppressed: find_nodes is the oracle-parity surface,
            # one pull per bucket per call, not a round loop
            cand = np.array(out.cand)  # nhdlint: ignore[NHD107]
            pref = np.array(out.pref)  # nhdlint: ignore[NHD107]
            best_c = np.array(out.best_c)  # nhdlint: ignore[NHD107]
            best_m = np.array(out.best_m)  # nhdlint: ignore[NHD107]
            best_a = np.array(out.best_a)  # nhdlint: ignore[NHD107]

            N = cand.shape[1]
            # lexicographic (pref desc, node index asc) via one argmax
            sel_val = pref * (N + 1) + (N - np.arange(N))[None, :]
            sel_val = np.where(cand, sel_val, 0)
            node_pick = np.argmax(sel_val, axis=1)  # [T]
            has_node = sel_val[np.arange(len(node_pick)), node_pick] > 0

            for t, pod_i in zip(pods.pod_type, pods.pod_index):
                if not has_node[t]:
                    continue
                n = int(node_pick[t])
                mapping = decode_mapping(
                    G, cluster.U, cluster.K,
                    int(best_c[t, n]), int(best_m[t, n]), int(best_a[t, n]),
                )
                results[int(pod_i)] = MatchResult(cluster.names[n], mapping)
        return results
