"""Speculative on-device multi-round: a whole batch's greedy rounds in
ONE dispatch.

Measured on the tunnel-attached TPU (docs/TPU_STATUS.md): the raw bucket
solve is ~2.4 ms, but every jitted call pays ~0.3-1 s of relay latency,
so a 3-round cfg4 batch spends seconds on dispatch overhead alone. This
module moves the round LOOP into the jitted program: a
``lax.while_loop`` iterates (solve → per-node type election → claim →
aggregate state update) against the resident node arrays and returns a
packed claims tensor — the host pays ONE dispatch + one ~O(iters×N) pull
for what used to be rounds × (dispatch + pull).

Claims are SPECULATIVE: the device applies aggregate resource deltas
(the same projections the solve itself checks — cpu/gpu per NUMA, NIC
headroom per slot, hugepages, busy, per-switch GPUs), then the host
re-verifies every claim through the normal native assignment exactly
like a classic round (solver/batch.py round apply). A marginal claim
the native core rejects just retries in the classic rounds that
follow; conservation is untouched.

PCI-map-mode types speculate too (r5; they were excluded through r4):
the solve's ``pci_ok`` predicate already certifies the claim, and the
chosen (combo, pick) determines the NIC slots whose switches supply
the GPUs — the loop projects ``gpu_free_sw`` deltas through the static
``nic_sw`` slot→switch map. PCI claims are capped at ONE copy per
node per iteration: a second copy's native NIC re-pick can land on
different switches than the first, which the aggregate per-(c, a)
projection cannot express. The native verify (which re-picks NICs and
GPUs against live state, PCI-aware) stays the safety net; NUMA-mode
GPU claims do not decrement ``gpu_free_sw`` mid-loop (which switch
they draw from is the native picker's choice), an optimism the verify
also absorbs.

Selection policy per iteration — chosen to approximate the classic
rounds' pod-index interleave (docs/DESIGN.md "the over-claim is
load-bearing"): every feasible node elects ONE type — highest selection
preference first (the gpuless-node preference, Matcher.py:393-421),
then the type with the largest remaining need (balanced mixes) — and
each type keeps its elected nodes only up to its remaining need,
preferring low node indices (the reference's first-candidate order).

MULTI-COPY claims (round 4): an elected node takes up to cap(t, n)
copies of its type in ONE iteration — the same optimistic per-node
capacity estimate the classic select applies host-side
(batch._capacity_at: free totals over per-pod demand, NIC slots,
busy=1 for GPU pods) — so a capacity-matched gang lands in ~one
iteration per type instead of one iteration per pod-per-node. The
claims tensor gains a parallel counts plane; the host expands a
count-k claim into k consecutive pods of the type (the native verify
re-selects NIC picks per copy against live state, as it always did).
With NIC sharing disabled (the reference default, Node.py:20) the NIC
projection switches from per-pick bandwidth deltas to OCCUPANCY: a
copy consumes the number of DISTINCT NICs the solve's chosen
(combo, pick) touches per NUMA — groups of one pod sharing a NIC
(the joint-bandwidth semantics the solve and the native first-
feasible pick both honor) count once — and the loop zeroes that many
lowest-indexed free NICs per NUMA. r5: the earlier one-NIC-per-group
count was conservative under in-pod sharing and stranded the last
pods of a full cluster into an extra classic round.

Reference parity anchor: the loop realizes the same round semantics as
solver/batch.py (SURVEY §7 hard part 2), which batches the reference's
strictly sequential claim loop (NHDScheduler.py:425-436).

Placement-parity note: on capacity-matched workloads (the headline
benchmarks) the speculative batch places everything the classic rounds
place, in one dispatch. On saturated heterogeneous clusters the greedy
packing ORDER differs, so totals can deviate by packing noise (measured
±2 pods over 20 random 60-pod/12-node seeds, net -0.25%;
tests/test_speculate.py) — same class of documented deviation as the
streaming tiler's tile-local preference (solver/streaming.py). The
path is opt-in by backend (auto = accelerators only) and every claim is
still natively verified, so conservation is exact regardless.
"""

from __future__ import annotations

import os
from functools import lru_cache
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from nhd_tpu.solver.combos import get_tables
from nhd_tpu.solver.kernel import _solve

# The per-(iter, node) claim word, one int32, -1 = no claim:
#   word = t_global * 2^21 + (c * U + m) * A_bucket(t) + a
# (c*U + m)*A + a < (C*A)*U <= MAX_LATTICE * 16 = 2^20 for every
# tractable lattice, and t_global < 1024 (the 31 - _T_SHIFT bound
# enforced at dispatch, batch._speculate_dispatch), so the word always
# fits int32 — and the whole claim tensor leaves the device in ONE
# transfer (each pull pays ~84 ms of relay latency on the tunnel,
# docs/TPU_STATUS.md).
_T_SHIFT = 21


def spec_iters() -> int:
    """Claim-loop depth: one pod per node per iteration, so this bounds
    pods-per-node per dispatch; leftovers take classic rounds."""
    return int(os.environ.get("NHD_TPU_SPEC_ITERS", "16"))


def speculate_enabled() -> bool:
    """NHD_TPU_SPECULATE: 1 forces on, 0 forces off, auto (default) =
    on exactly when the default backend is an accelerator — on CPU the
    extra per-iteration solves cost more than the dispatches they save."""
    val = os.environ.get("NHD_TPU_SPECULATE", "auto").lower()
    if val in ("1", "true", "on"):
        return True
    if val in ("0", "false", "off"):
        return False
    if val != "auto":
        raise ValueError(f"NHD_TPU_SPECULATE must be 0/1/auto, got {val!r}")
    import jax as _jax

    try:
        return _jax.default_backend() != "cpu"
    except Exception:
        return False


@lru_cache(maxsize=None)
def _get_megaround(
    bucket_shapes: Tuple[Tuple[int, int], ...],  # ((G, Tp) per bucket)
    U: int,
    K: int,
    iters: int,
    respect_busy: bool,
    donate: bool,
    out_shardings_key=None,  # (node_sharding, replicated) on a mesh
):
    """The jitted multi-bucket claim loop for one batch shape.

    On a multi-device mesh the SAME program runs SPMD: the resident node
    arrays arrive node-sharded, GSPMD partitions the loop (the per-node
    election's argmax/argsort over the node axis induce the collectives),
    and the claims come back bit-identical to the single-device run
    (pinned by tests/test_speculate.py). ``out_shardings_key`` keeps the
    updated mutable arrays node-sharded for the classic sharded solves
    that may follow.

    Args (all device arrays):
      mutable: dict of the 6 claim-mutated node arrays (device_state)
      static:  dict of the 9 never-mutated node arrays
      need:    [sum(Tp)] int32 — pending pod count per global type row
      *pod_args: 10 padded pod-type arrays per bucket, flattened in
                 bucket order (device_state._pod_args layout)

    Returns (new_mutable, claims [iters, N] int32 packed words, need_left).
    """
    # the single node-array order contract lives in device_state; import
    # here (device_state imports THIS module lazily, so no cycle)
    from nhd_tpu.solver.device_state import _ARG_ORDER, _MUTABLE

    tables = [get_tables(G, U, K) for G, _ in bucket_shapes]
    offsets = np.cumsum([0] + [tp for _, tp in bucket_shapes])
    t_total = int(offsets[-1])
    # per-global-type pick-axis width, for the packed claim word
    a_mult = np.concatenate([
        np.full(tp, get_tables(G, U, K).A, np.int32)
        for G, tp in bucket_shapes
    ])

    def fn(mutable, static, need, *pod_args):
        N = mutable["hp_free"].shape[0]
        arrays = {**static}
        smt = static["smt"]

        from nhd_tpu.core.node import ENABLE_NIC_SHARING

        # per-bucket demand projections are state-independent: hoist out
        # of the loop so each iteration only re-solves and re-elects
        per_bucket = []
        for b, (tb, (G, Tp)) in enumerate(zip(tables, bucket_shapes)):
            # 10-array pod stride (kernel._POD_ARG_ORDER): class_score
            # is the policy engine's score-term input, unused here — the
            # megaround claims on feasibility, so batch.py disables
            # speculation whenever a non-uniform scoring matrix is live
            # (round-0 claims must not bypass the policy ranking)
            (cpu_dem_smt, cpu_dem_raw, gpu_dem, rx, tx, hp, needs_gpu,
             map_pci, group_mask, _class_score) = (
                pod_args[10 * b : 10 * b + 10]
            )
            combo_onehot = jnp.asarray(tb.combo_onehot)
            choose = jnp.asarray(tb.choose_onehot)
            misc = jnp.asarray(tb.misc_onehot)
            f32 = jnp.float32
            # NIC-needing groups per (type, combo, numa): the occupancy
            # consumption (and per-copy capacity divisor) of a claim
            needs_nic_g = ((rx + tx) > 0).astype(f32)        # [Tp, G]
            # distinct NICs a claim at (combo, pick) occupies per NUMA:
            # groups of ONE pod may share a NIC (the solve's joint-
            # bandwidth predicate and the native first-feasible pick both
            # honor it, kernel._solve / fast_assign._reselect_picks), so
            # occupancy counts distinct chosen (u, k) slots with any
            # NIC-needing group — NOT one NIC per group, which strands
            # the last same-NUMA-sharing pods of a full cluster (r5)
            occ_slots = jnp.einsum(
                "tg,caguk->tcauk", needs_nic_g, choose
            ).reshape(Tp, tb.C * tb.A, U, K)
            per_bucket.append(dict(
                pod_args=pod_args[10 * b : 10 * b + 10],
                G=G, C=tb.C, A=tb.A,
                nic_occ=(occ_slots > 0).astype(f32).sum(-1),  # [Tp,C*A,U]
                # per-(u, k) GPU demand at (combo, pick), PCI types only:
                # the chosen slot's switch supplies the GPUs (gpu_free_sw
                # projection) — zero rows for NUMA-mode types
                gpu_uk=jnp.einsum(
                    "tg,caguk->tcauk",
                    (gpu_dem * map_pci[:, None]).astype(f32), choose,
                ).reshape(Tp, tb.C * tb.A, U, K),
                map_pci=map_pci,
                # [Tp, C, U] per-combo group demand
                cpu_g_smt=jnp.einsum(
                    "tg,cgu->tcu", cpu_dem_smt[:, :-1].astype(f32), combo_onehot),
                cpu_g_raw=jnp.einsum(
                    "tg,cgu->tcu", cpu_dem_raw[:, :-1].astype(f32), combo_onehot),
                # [Tp, M(=U), U] misc-slot demand
                cpu_m_smt=cpu_dem_smt[:, -1].astype(f32)[:, None, None]
                * misc[None],
                cpu_m_raw=cpu_dem_raw[:, -1].astype(f32)[:, None, None]
                * misc[None],
                gpu_g=jnp.einsum("tg,cgu->tcu", gpu_dem.astype(f32), combo_onehot),
                # [Tp, C*A, U, K] per-(combo, pick) NIC demand
                nic_rx=jnp.einsum("tg,caguk->tcauk", rx, choose).reshape(
                    Tp, tb.C * tb.A, U, K),
                nic_tx=jnp.einsum("tg,caguk->tcauk", tx, choose).reshape(
                    Tp, tb.C * tb.A, U, K),
                hp=hp.astype(jnp.int32),
                has_nic=jnp.any((rx + tx) > 0, axis=1),
                needs_gpu=needs_gpu,
            ))

        n_idx = jnp.arange(N, dtype=jnp.int32)

        a_mult_dev = jnp.asarray(a_mult)

        # static slot→switch one-hot for the PCI gpu_free_sw projection:
        # nic_sw never mutates, so the [N, U, K, S] map is loop-invariant
        # and hoisted like the per-bucket demand projections
        S = mutable["gpu_free_sw"].shape[1]
        sw_onehot = (
            arrays["nic_sw"][:, :, :, None]
            == jnp.arange(S)[None, None, None, :]
        ).astype(jnp.float32)  # [N, U, K, S]

        def body(state):
            it, need, mutable, claims, counts, progress = state
            cur = {**arrays, **mutable}

            cand_rows, val_rows, c_rows, m_rows, a_rows = [], [], [], [], []
            for b, tb in enumerate(tables):
                # dead buckets (all needs zero — spill offers often hold
                # pods of one bucket only, and late iterations drain
                # buckets at different rates) skip their solve at RUNTIME:
                # the bucket stays in the program so the compiled shape is
                # stable across every sub-call of a streaming chunk
                Tp_b = bucket_shapes[b][1]
                lo_b = int(offsets[b])

                def _solve_b(_, b=b, tb=tb):
                    out = _solve(
                        tb,
                        *[cur[name] for name in _ARG_ORDER],
                        *per_bucket[b]["pod_args"],
                    )
                    val = jnp.where(
                        out.cand,
                        out.pref * (N + 1) + (N - n_idx)[None, :],
                        0,
                    )
                    return (
                        out.cand, val,
                        out.best_c.astype(jnp.int32),
                        out.best_m.astype(jnp.int32),
                        out.best_a.astype(jnp.int32),
                    )

                def _skip_b(_, Tp_b=Tp_b):
                    z = jnp.zeros((Tp_b, N), jnp.int32)
                    return jnp.zeros((Tp_b, N), bool), z, z, z, z

                cand_b, val_b, c_b, m_b, a_b = jax.lax.cond(
                    jnp.sum(need[lo_b : lo_b + Tp_b]) > 0,
                    _solve_b, _skip_b, operand=None,
                )
                cand_rows.append(cand_b)
                val_rows.append(val_b)
                c_rows.append(c_b)
                m_rows.append(m_b)
                a_rows.append(a_b)
            cand = jnp.concatenate(cand_rows)      # [Tt, N]
            val = jnp.concatenate(val_rows)        # [Tt, N] int32
            best_c = jnp.concatenate(c_rows)
            best_m = jnp.concatenate(m_rows)
            best_a = jnp.concatenate(a_rows)

            # --- per-node type election (pure [Tt, N] bool/int ops) ---
            elig = cand & (need > 0)[:, None]
            # preference class dominates (gpuless nodes prefer CPU-only
            # types, like the reference's selection preference), then
            # remaining need (keeps the type mix balanced per node)
            key = jnp.where(
                elig,
                (val // (N + 1)) * (1 << 24) + jnp.minimum(need, 1 << 20)[:, None],
                -1,
            )
            elect = jnp.argmax(key, axis=0)        # [N]
            win = (
                elig
                & (jnp.arange(t_total, dtype=elect.dtype)[:, None] == elect[None, :])
            )

            # --- everything after the election runs at [N] scale: exactly
            # one type wins per node, so the capacity bound, the demand
            # gathers and the claim deltas are all per-NODE lookups at
            # (elect, best_c, best_m) — [Tp, N, U]-wide versions of these
            # were the measured hot spot of the on-chip loop ---
            INF = jnp.float32(1 << 20)
            f32 = jnp.float32
            gather_n = lambda x: jnp.take_along_axis(
                x, elect[None, :], axis=0)[0]
            c_n = gather_n(best_c)                 # [N]
            m_n = gather_n(best_m)
            a_n = gather_n(best_a)

            cpu_free_u = cur["cpu_free"].astype(f32)      # [N, U]
            gpu_free_u = cur["gpu_free"].astype(f32)
            hp_free_n = cur["hp_free"].astype(f32)
            # free NICs per (node, numa): with sharing off the encode sets
            # free = cap (> 0) iff the NIC is unoccupied
            free_nic_cnt = jnp.sum(
                (cur["nic_free"][..., 0] > 0).astype(f32), axis=2
            )  # [N, U]

            # per-node gathered quantities, bucket-merged via the elect
            # range masks (each node's elected row lives in one bucket)
            cpu_dem_n = jnp.zeros((N, U), f32)   # demand at chosen (c, m)
            gpu_dem_n = jnp.zeros((N, U), f32)
            nic_occ_n = jnp.zeros((N, U), f32)   # distinct NICs consumed
            #                                      per numa at (c, a)
            guk_n = jnp.zeros((N, U, K), f32)    # PCI per-slot GPU demand
            hp_n = jnp.zeros(N, f32)
            cap1_n = jnp.zeros(N, bool)          # force single-copy rows
            for b, (G, Tp) in enumerate(bucket_shapes):
                pb = per_bucket[b]
                lo = int(offsets[b])
                in_b = (elect >= lo) & (elect < lo + Tp)      # [N]
                tloc = jnp.clip(elect - lo, 0, Tp - 1)
                cb = jnp.clip(c_n, 0, pb["C"] - 1)
                mb = jnp.clip(m_n, 0, U - 1)
                sel = in_b[:, None]
                dem = jnp.where(
                    smt[:, None],
                    pb["cpu_g_smt"][tloc, cb] + pb["cpu_m_smt"][tloc, mb],
                    pb["cpu_g_raw"][tloc, cb] + pb["cpu_m_raw"][tloc, mb],
                )  # [N, U]
                cpu_dem_n = jnp.where(sel, dem, cpu_dem_n)
                gpu_dem_n = jnp.where(sel, pb["gpu_g"][tloc, cb], gpu_dem_n)
                ca = cb * pb["A"] + jnp.clip(a_n, 0, pb["A"] - 1)
                nic_occ_n = jnp.where(
                    sel, pb["nic_occ"][tloc, ca], nic_occ_n)
                guk_n = jnp.where(
                    sel[..., None], pb["gpu_uk"][tloc, ca], guk_n)
                hp_n = jnp.where(in_b, pb["hp"].astype(f32)[tloc], hp_n)
                one = pb["needs_gpu"][tloc] if respect_busy else False
                # PCI claims: one copy per iteration — a later copy's
                # native NIC re-pick can move to other switches than the
                # (c, a) projection assumes
                one = one | pb["map_pci"][tloc]
                if ENABLE_NIC_SHARING:
                    one = one | pb["has_nic"][tloc]
                cap1_n = jnp.where(in_b, one, cap1_n)

            # multi-copy capacity at the chosen (combo, misc), per NUMA —
            # k copies all apply at the same (c, m), so the bound is
            # per-NUMA at that placement (node totals over-claim and the
            # native verify rejects the overflow)
            def _div_min_u(free_u, dem_u):
                per_u = jnp.where(
                    dem_u > 0,
                    jnp.floor(free_u / jnp.maximum(dem_u, 1e-6)), INF,
                )
                return jnp.min(per_u, axis=1)      # [N]

            cap_n = _div_min_u(cpu_free_u, cpu_dem_n)
            cap_n = jnp.minimum(cap_n, _div_min_u(gpu_free_u, gpu_dem_n))
            if not ENABLE_NIC_SHARING:
                # occupancy bound: free NICs per NUMA over distinct NICs
                # the chosen (combo, pick) occupies there, min across NUMAs
                cap_n = jnp.minimum(
                    cap_n, _div_min_u(free_nic_cnt, nic_occ_n))
            cap_n = jnp.minimum(cap_n, jnp.where(
                hp_n > 0,
                jnp.floor(hp_free_n / jnp.maximum(hp_n, 1e-6)), INF,
            ))
            # GPU pods under the busy back-off (and NIC-demanding types
            # under sharing, whose bandwidth projection can't express
            # pick disjointness) claim one copy per iteration
            cap_n = jnp.where(cap1_n, jnp.minimum(cap_n, 1.0), cap_n)
            cap_n = jnp.maximum(cap_n, 0.0).astype(jnp.int32)

            # --- type-side fill: hand the best-ranked elected nodes their
            # copies until the type's need runs out. The per-node take is
            # BALANCED at ceil(need / elected nodes): an unbalanced
            # capacity-fill concentrates one type on the first nodes and
            # (measured) costs placements on tight instances — the
            # balanced spread keeps the classic interleave's packing shape
            # while still claiming multiple copies per dispatch, and
            # degrades to exactly the old one-per-node interleave as a
            # type's need runs out ---
            n_win = jnp.sum(win, axis=1).astype(jnp.int32)      # [Tt]
            fair = (need + jnp.maximum(n_win, 1) - 1) // jnp.maximum(n_win, 1)
            # every elected CANDIDATE node may take at least one copy even
            # when the capacity projection says 0 — the projection is
            # conservative (per-copy ceil loses SMT-sibling sharing across
            # copies), the solve's cand is the real one-copy verdict, and
            # a marginal over-claim just retries after the native verify
            # (exactly the r3 single-copy optimism). Multi-copy engages on
            # top wherever the projection clearly allows it.
            capw = jnp.where(
                win,
                jnp.minimum(jnp.maximum(cap_n, 1)[None, :], fair[:, None]),
                0,
            )
            # fill in descending-val order WITHOUT argsort: val encodes
            # pref then low-node-index, so the fill order is simply
            # "pref-2 winners by node index, then pref-1 winners by node
            # index" — two exclusive cumsums give each winner its
            # fill-prefix (argsort pairs here were the hottest op of the
            # on-chip loop)
            hi = win & (val // (N + 1) == 2)
            cap_hi = jnp.where(hi, capw, 0)
            cap_lo = jnp.where(win & ~hi, capw, 0)
            prefix_hi = jnp.cumsum(cap_hi, axis=1) - cap_hi
            prefix_lo = (
                jnp.sum(cap_hi, axis=1, keepdims=True)
                + jnp.cumsum(cap_lo, axis=1) - cap_lo
            )
            prefix = jnp.where(hi, prefix_hi, prefix_lo)
            take = jnp.where(
                win, jnp.clip(need[:, None] - prefix, 0, capw), 0
            )  # [Tt, N]

            count_n = jnp.max(take, axis=0)          # [N] copies claimed
            taken_any = count_n > 0
            tsel = elect                             # take>0 only on elect row

            # --- aggregate claim deltas, all at [N, U] scale ---
            new_mut = dict(mutable)
            busy_new = mutable["busy"]
            if respect_busy:
                # a node goes busy on ANY placement, exactly like the
                # classic apply (NHDScheduler.py:289 per batch.py) — not
                # just GPU-needing claims
                busy_new = busy_new | taken_any
            k_n = count_n.astype(f32)                # [N]
            cpu_delta = k_n[:, None] * cpu_dem_n
            gpu_delta = k_n[:, None] * gpu_dem_n
            hp_delta = (k_n * hp_n).astype(jnp.int32)
            if ENABLE_NIC_SHARING:
                # per-pick bandwidth deltas (single-copy for NIC types):
                # gather each node's (combo, pick) demand row per bucket
                nic_delta = jnp.zeros((N, U, K, 2), jnp.float32)
                for b, (G, Tp) in enumerate(bucket_shapes):
                    pb = per_bucket[b]
                    lo = int(offsets[b])
                    in_b = (elect >= lo) & (elect < lo + Tp)
                    tloc = jnp.clip(elect - lo, 0, Tp - 1)
                    ca = (
                        jnp.clip(c_n, 0, pb["C"] - 1) * pb["A"]
                        + jnp.clip(a_n, 0, pb["A"] - 1)
                    )
                    w = (k_n * in_b.astype(f32))[:, None, None]
                    nic_delta = nic_delta.at[..., 0].add(
                        w * pb["nic_rx"][tloc, ca])
                    nic_delta = nic_delta.at[..., 1].add(
                        w * pb["nic_tx"][tloc, ca])
            else:
                nic_consume = k_n[:, None] * nic_occ_n       # [N, U]
            new_mut["cpu_free"] = (
                mutable["cpu_free"].astype(jnp.float32) - cpu_delta
            ).astype(mutable["cpu_free"].dtype)
            new_mut["gpu_free"] = (
                mutable["gpu_free"].astype(jnp.float32) - gpu_delta
            ).astype(mutable["gpu_free"].dtype)
            if ENABLE_NIC_SHARING:
                new_mut["nic_free"] = mutable["nic_free"] - nic_delta
            else:
                # zero out the consumed count of lowest-indexed free NICs
                # per (node, numa) — occupancy is the whole story with
                # sharing off (encode: free = cap iff unoccupied)
                unocc = mutable["nic_free"][..., 0] > 0        # [N, U, K]
                used = unocc & (
                    jnp.cumsum(unocc.astype(jnp.int32), axis=2)
                    <= nic_consume[..., None]
                )
                new_mut["nic_free"] = jnp.where(
                    used[..., None], 0.0, mutable["nic_free"]
                )
            new_mut["hp_free"] = mutable["hp_free"] - hp_delta
            # PCI claims drain the chosen slots' switches: route the
            # per-(u, k) GPU demand through the hoisted static
            # slot→switch map (nic_sw carries dense per-node switch ids)
            sw_delta = jnp.einsum(
                "nuk,nuks->ns", k_n[:, None, None] * guk_n, sw_onehot
            )
            new_mut["gpu_free_sw"] = (
                mutable["gpu_free_sw"].astype(f32) - sw_delta
            ).astype(mutable["gpu_free_sw"].dtype)
            new_mut["busy"] = busy_new

            # --- record the iteration's claims (one packed word/node,
            # plus the copy count in the parallel counts plane) ---
            word = (
                tsel.astype(jnp.int32) * (1 << _T_SHIFT)
                + (c_n * U + m_n) * a_mult_dev[tsel]
                + a_n
            )
            enc = jnp.where(taken_any, word, -1)
            claims = jax.lax.dynamic_update_slice(
                claims, enc[None, :], (it, 0))
            counts = jax.lax.dynamic_update_slice(
                counts, jnp.where(taken_any, count_n, 0)[None, :], (it, 0))

            need = need - jnp.sum(take, axis=1).astype(need.dtype)
            return (it + 1, need, new_mut, claims, counts,
                    jnp.any(taken_any))

        def cond(state):
            it, need, _mut, _c, _cnt, progress = state
            return (it < iters) & (jnp.sum(need) > 0) & progress

        init = (
            jnp.asarray(0, jnp.int32),
            need,
            mutable,
            jnp.full((iters, N), -1, jnp.int32),
            jnp.zeros((iters, N), jnp.int32),
            jnp.asarray(True),
        )
        it, need, mutable, claims, counts, _ = jax.lax.while_loop(
            cond, body, init
        )
        # ``it`` distinguishes the exit reason for the host's saturation
        # certificate: it < iters with need left means the loop ended on
        # progress=False — the exact solve found NO eligible (type, node)
        # pair against the projected state
        return mutable, claims, counts, need, it

    kwargs = {"donate_argnums": (0,)} if donate else {}
    if out_shardings_key is not None:
        node_sharding, replicated = out_shardings_key
        kwargs["out_shardings"] = (
            {name: node_sharding for name in _MUTABLE},
            replicated,
            replicated,
            replicated,
            replicated,
        )
    return jax.jit(fn, **kwargs)


def decode_claims_grouped(
    claims: np.ndarray,       # [iters, N] int32 packed words, -1 = none
    bucket_shapes: Sequence[Tuple[int, int]],
    bucket_keys: Sequence[int],
    U: int,
    K: int,
    counts: Optional[np.ndarray] = None,  # [iters, N] int32 copies, 0 = none
) -> Dict[int, Dict[int, Tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray]]]:
    """Unpack the device claim tensor into
    {bucket key: {local type: (nodes, c, m, a) arrays}} with array order =
    (iteration, node index) — the order speculative copies were made. A
    count-k claim (multi-copy) expands to k consecutive entries.

    Fully vectorized: at gang scale the tensor carries ~10k claims and a
    per-claim Python loop was the measurable cost of the expand phase."""
    offsets = np.cumsum([0] + [tp for _, tp in bucket_shapes])
    a_width = np.concatenate([
        np.full(tp, get_tables(G, U, K).A, np.int64)
        for G, tp in bucket_shapes
    ])
    out: Dict[int, Dict[int, tuple]] = {gk: {} for gk in bucket_keys}
    its, nodes = np.nonzero(claims >= 0)   # row-major == (iter, node) order
    if not len(its):
        return out
    word = claims[its, nodes].astype(np.int64)
    cnt = (
        counts[its, nodes].astype(np.int64)
        if counts is not None
        else np.ones(len(its), np.int64)
    )
    tg = word >> _T_SHIFT
    rest = word & ((1 << _T_SHIFT) - 1)
    aw = a_width[tg]
    a = rest % aw
    cm = rest // aw
    c = cm // U
    m = cm % U
    # stable sort groups claims by global type, preserving (iter, node)
    # order within each type
    order = np.argsort(tg, kind="stable")
    tg_s = tg[order]
    cnt_s = cnt[order]
    # multi-copy expansion: k copies become k consecutive rows (pods of a
    # type consume them in order, so copy order within a claim is moot)
    nodes_s = np.repeat(nodes[order], cnt_s)
    c_s = np.repeat(c[order], cnt_s)
    m_s = np.repeat(m[order], cnt_s)
    a_s = np.repeat(a[order], cnt_s)
    tg_x = np.repeat(tg_s, cnt_s)
    uniq, starts = np.unique(tg_x, return_index=True)
    bounds = np.append(starts, len(tg_x))
    b_of = np.searchsorted(offsets, uniq, side="right") - 1
    for u, b, lo, hi in zip(uniq, b_of, bounds[:-1], bounds[1:]):
        t_local = int(u - offsets[b])
        out[bucket_keys[int(b)]][t_local] = (
            nodes_s[lo:hi], c_s[lo:hi], m_s[lo:hi], a_s[lo:hi]
        )
    return out


def decode_claims(
    claims: np.ndarray,
    bucket_shapes: Sequence[Tuple[int, int]],
    bucket_keys: Sequence[int],
    U: int,
    K: int,
    counts: Optional[np.ndarray] = None,
) -> Dict[int, Dict[int, List[Tuple[int, int, int, int]]]]:
    """decode_claims_grouped with per-claim tuple lists (test/debug API)."""
    grouped = decode_claims_grouped(
        claims, bucket_shapes, bucket_keys, U, K, counts
    )
    return {
        gk: {
            t: list(zip(n.tolist(), c.tolist(), m.tolist(), a.tolist()))
            for t, (n, c, m, a) in per.items()
        }
        for gk, per in grouped.items()
    }
