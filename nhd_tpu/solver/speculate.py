"""Speculative on-device multi-round: a whole batch's greedy rounds in
ONE dispatch.

Measured on the tunnel-attached TPU (docs/TPU_STATUS.md): the raw bucket
solve is ~2.4 ms, but every jitted call pays ~0.3-1 s of relay latency,
so a 3-round cfg4 batch spends seconds on dispatch overhead alone. This
module moves the round LOOP into the jitted program: a
``lax.while_loop`` iterates (solve → per-node type election → claim →
aggregate state update) against the resident node arrays and returns a
packed claims tensor — the host pays ONE dispatch + one ~O(iters×N) pull
for what used to be rounds × (dispatch + pull).

Claims are SPECULATIVE: the device applies aggregate resource deltas
(the same projections the solve itself checks — cpu/gpu per NUMA, NIC
headroom per slot, hugepages, busy), then the host re-verifies every
claim through the normal native assignment exactly like a classic round
(solver/batch.py round apply). A marginal claim the native core rejects
just retries in the classic rounds that follow; conservation is
untouched. PCI-map-mode types are excluded (their per-switch GPU
projection ``gpu_free_sw`` is chosen by the native device-pick, not
derivable from (combo, pick) alone) and take the classic rounds.

Selection policy per iteration — chosen to approximate the classic
rounds' pod-index interleave (docs/DESIGN.md "the over-claim is
load-bearing"): every feasible node elects ONE type — highest selection
preference first (the gpuless-node preference, Matcher.py:393-421),
then the type with the largest remaining need (balanced mixes) — and
each type keeps its elected nodes only up to its remaining need,
preferring low node indices (the reference's first-candidate order).
One pod per node per iteration; a node's k-th pod lands in iteration k
with combo/misc/pick chosen against the then-current state, exactly as
the k-th claim of a classic round sequence would.

Reference parity anchor: the loop realizes the same round semantics as
solver/batch.py (SURVEY §7 hard part 2), which batches the reference's
strictly sequential claim loop (NHDScheduler.py:425-436).

Placement-parity note: on capacity-matched workloads (the headline
benchmarks) the speculative batch places everything the classic rounds
place, in one dispatch. On saturated heterogeneous clusters the greedy
packing ORDER differs, so totals can deviate by packing noise (measured
±2 pods over 20 random 60-pod/12-node seeds, net -0.25%;
tests/test_speculate.py) — same class of documented deviation as the
streaming tiler's tile-local preference (solver/streaming.py). The
path is opt-in by backend (auto = accelerators only) and every claim is
still natively verified, so conservation is exact regardless.
"""

from __future__ import annotations

import os
from functools import lru_cache
from typing import Dict, List, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from nhd_tpu.solver.combos import get_tables
from nhd_tpu.solver.kernel import _pad_pow2, _solve

# The per-(iter, node) claim word, one int32, -1 = no claim:
#   word = t_global * 2^21 + (c * U + m) * A_bucket(t) + a
# (c*U + m)*A + a < (C*A)*U <= MAX_LATTICE * 16 = 2^20 for every
# tractable lattice, and t_global < 128, so the word always fits int32 —
# and the whole claim tensor leaves the device in ONE transfer (each
# pull pays ~84 ms of relay latency on the tunnel, docs/TPU_STATUS.md).
_T_SHIFT = 21


def spec_iters() -> int:
    """Claim-loop depth: one pod per node per iteration, so this bounds
    pods-per-node per dispatch; leftovers take classic rounds."""
    return int(os.environ.get("NHD_TPU_SPEC_ITERS", "16"))


def speculate_enabled() -> bool:
    """NHD_TPU_SPECULATE: 1 forces on, 0 forces off, auto (default) =
    on exactly when the default backend is an accelerator — on CPU the
    extra per-iteration solves cost more than the dispatches they save."""
    val = os.environ.get("NHD_TPU_SPECULATE", "auto").lower()
    if val in ("1", "true", "on"):
        return True
    if val in ("0", "false", "off"):
        return False
    if val != "auto":
        raise ValueError(f"NHD_TPU_SPECULATE must be 0/1/auto, got {val!r}")
    import jax as _jax

    try:
        return _jax.default_backend() != "cpu"
    except Exception:
        return False


@lru_cache(maxsize=None)
def _get_megaround(
    bucket_shapes: Tuple[Tuple[int, int], ...],  # ((G, Tp) per bucket)
    U: int,
    K: int,
    iters: int,
    respect_busy: bool,
    donate: bool,
    out_shardings_key=None,  # (node_sharding, replicated) on a mesh
):
    """The jitted multi-bucket claim loop for one batch shape.

    On a multi-device mesh the SAME program runs SPMD: the resident node
    arrays arrive node-sharded, GSPMD partitions the loop (the per-node
    election's argmax/argsort over the node axis induce the collectives),
    and the claims come back bit-identical to the single-device run
    (pinned by tests/test_speculate.py). ``out_shardings_key`` keeps the
    updated mutable arrays node-sharded for the classic sharded solves
    that may follow.

    Args (all device arrays):
      mutable: dict of the 6 claim-mutated node arrays (device_state)
      static:  dict of the 8 never-mutated node arrays
      need:    [sum(Tp)] int32 — pending pod count per global type row
      *pod_args: 9 padded pod-type arrays per bucket, flattened in
                 bucket order (device_state._pod_args layout)

    Returns (new_mutable, claims [iters, N] int32 packed words, need_left).
    """
    # the single node-array order contract lives in device_state; import
    # here (device_state imports THIS module lazily, so no cycle)
    from nhd_tpu.solver.device_state import _ARG_ORDER, _MUTABLE

    tables = [get_tables(G, U, K) for G, _ in bucket_shapes]
    offsets = np.cumsum([0] + [tp for _, tp in bucket_shapes])
    t_total = int(offsets[-1])
    # per-global-type pick-axis width, for the packed claim word
    a_mult = np.concatenate([
        np.full(tp, get_tables(G, U, K).A, np.int32)
        for G, tp in bucket_shapes
    ])

    def fn(mutable, static, need, *pod_args):
        N = mutable["hp_free"].shape[0]
        arrays = {**static}
        smt = static["smt"]

        # per-bucket demand projections are state-independent: hoist out
        # of the loop so each iteration only re-solves and re-elects
        per_bucket = []
        for b, (tb, (G, Tp)) in enumerate(zip(tables, bucket_shapes)):
            (cpu_dem_smt, cpu_dem_raw, gpu_dem, rx, tx, hp, needs_gpu,
             map_pci, group_mask) = pod_args[9 * b : 9 * b + 9]
            combo_onehot = jnp.asarray(tb.combo_onehot)
            choose = jnp.asarray(tb.choose_onehot)
            misc = jnp.asarray(tb.misc_onehot)
            f32 = jnp.float32
            per_bucket.append(dict(
                pod_args=pod_args[9 * b : 9 * b + 9],
                G=G, C=tb.C, A=tb.A,
                # [Tp, C, U] per-combo group demand
                cpu_g_smt=jnp.einsum(
                    "tg,cgu->tcu", cpu_dem_smt[:, :-1].astype(f32), combo_onehot),
                cpu_g_raw=jnp.einsum(
                    "tg,cgu->tcu", cpu_dem_raw[:, :-1].astype(f32), combo_onehot),
                # [Tp, M(=U), U] misc-slot demand
                cpu_m_smt=cpu_dem_smt[:, -1].astype(f32)[:, None, None]
                * misc[None],
                cpu_m_raw=cpu_dem_raw[:, -1].astype(f32)[:, None, None]
                * misc[None],
                gpu_g=jnp.einsum("tg,cgu->tcu", gpu_dem.astype(f32), combo_onehot),
                # [Tp, C*A, U, K] per-(combo, pick) NIC demand
                nic_rx=jnp.einsum("tg,caguk->tcauk", rx, choose).reshape(
                    Tp, tb.C * tb.A, U, K),
                nic_tx=jnp.einsum("tg,caguk->tcauk", tx, choose).reshape(
                    Tp, tb.C * tb.A, U, K),
                hp=hp.astype(jnp.int32),
                needs_gpu=needs_gpu,
            ))

        n_idx = jnp.arange(N, dtype=jnp.int32)

        a_mult_dev = jnp.asarray(a_mult)

        def body(state):
            it, need, mutable, claims, progress = state
            cur = {**arrays, **mutable}

            cand_rows, val_rows, c_rows, m_rows, a_rows = [], [], [], [], []
            for b, tb in enumerate(tables):
                out = _solve(
                    tb,
                    *[cur[name] for name in _ARG_ORDER],
                    *per_bucket[b]["pod_args"],
                    use_pallas=False,
                )
                cand_rows.append(out.cand)
                val_rows.append(
                    jnp.where(
                        out.cand,
                        out.pref * (N + 1) + (N - n_idx)[None, :],
                        0,
                    )
                )
                c_rows.append(out.best_c)
                m_rows.append(out.best_m)
                a_rows.append(out.best_a)
            cand = jnp.concatenate(cand_rows)      # [Tt, N]
            val = jnp.concatenate(val_rows)        # [Tt, N] int32
            best_c = jnp.concatenate(c_rows)
            best_m = jnp.concatenate(m_rows)
            best_a = jnp.concatenate(a_rows)

            # --- per-node type election ---
            elig = cand & (need > 0)[:, None]
            # preference class dominates (gpuless nodes prefer CPU-only
            # types, like the reference's selection preference), then
            # remaining need (keeps the type mix balanced per node)
            key = jnp.where(
                elig,
                (val // (N + 1)) * (1 << 24) + jnp.minimum(need, 1 << 20)[:, None],
                -1,
            )
            elect = jnp.argmax(key, axis=0)        # [N]
            any_elig = jnp.any(elig, axis=0)
            win = (
                elig
                & (jnp.arange(t_total, dtype=elect.dtype)[:, None] == elect[None, :])
            )

            # --- type-side cap: keep the best `need_t` elected nodes ---
            score = jnp.where(win, val, 0)
            # rank positions within each row, descending score (stable):
            order = jnp.argsort(-score, axis=1)
            rank_pos = jnp.argsort(order, axis=1)
            keep = win & (rank_pos < need[:, None])  # [Tt, N]

            taken_any = jnp.any(keep, axis=0)        # [N]
            tsel = jnp.argmax(keep, axis=0)          # [N] chosen global type
            gather_n = lambda x: jnp.take_along_axis(
                x, tsel[None, :], axis=0)[0]
            c_n = gather_n(best_c)
            m_n = gather_n(best_m)
            a_n = gather_n(best_a)

            # --- aggregate claim deltas, per bucket ---
            new_mut = dict(mutable)
            hp_delta = jnp.zeros(N, jnp.int32)
            busy_new = mutable["busy"]
            cpu_delta = jnp.zeros((N, U), jnp.float32)
            gpu_delta = jnp.zeros((N, U), jnp.float32)
            nic_delta = jnp.zeros((N, U, K, 2), jnp.float32)
            for b, (G, Tp) in enumerate(bucket_shapes):
                pb = per_bucket[b]
                lo = int(offsets[b])
                kb = keep[lo : lo + Tp].astype(jnp.float32)   # [Tp, N]
                cb = jnp.clip(best_c[lo : lo + Tp], 0, pb["C"] - 1)
                mb = jnp.clip(best_m[lo : lo + Tp], 0, U - 1)
                ab = jnp.clip(best_a[lo : lo + Tp], 0, pb["A"] - 1)
                tix = jnp.arange(Tp)[:, None]
                # [Tp, N, U] gathered per-(type, node) demand at its combo
                cpu_g = jnp.where(
                    smt[None, :, None],
                    pb["cpu_g_smt"][tix, cb],
                    pb["cpu_g_raw"][tix, cb],
                ) + jnp.where(
                    smt[None, :, None],
                    pb["cpu_m_smt"][tix, mb],
                    pb["cpu_m_raw"][tix, mb],
                )
                cpu_delta = cpu_delta + jnp.einsum("tn,tnu->nu", kb, cpu_g)
                gpu_delta = gpu_delta + jnp.einsum(
                    "tn,tnu->nu", kb, pb["gpu_g"][tix, cb])
                ca = cb * pb["A"] + ab
                nic_delta = nic_delta.at[..., 0].add(
                    jnp.einsum("tn,tnuk->nuk", kb, pb["nic_rx"][tix, ca]))
                nic_delta = nic_delta.at[..., 1].add(
                    jnp.einsum("tn,tnuk->nuk", kb, pb["nic_tx"][tix, ca]))
                hp_delta = hp_delta + jnp.einsum(
                    "tn,t->n", kb, pb["hp"].astype(jnp.float32)
                ).astype(jnp.int32)
                if respect_busy:
                    busy_new = busy_new | jnp.any(
                        keep[lo : lo + Tp] & pb["needs_gpu"][:, None], axis=0)
            new_mut["cpu_free"] = (
                mutable["cpu_free"].astype(jnp.float32) - cpu_delta
            ).astype(mutable["cpu_free"].dtype)
            new_mut["gpu_free"] = (
                mutable["gpu_free"].astype(jnp.float32) - gpu_delta
            ).astype(mutable["gpu_free"].dtype)
            new_mut["nic_free"] = mutable["nic_free"] - nic_delta
            new_mut["hp_free"] = mutable["hp_free"] - hp_delta
            new_mut["busy"] = busy_new

            # --- record the iteration's claims (one packed word/node) ---
            word = (
                tsel.astype(jnp.int32) * (1 << _T_SHIFT)
                + (c_n * U + m_n) * a_mult_dev[tsel]
                + a_n
            )
            enc = jnp.where(taken_any, word, -1)
            claims = jax.lax.dynamic_update_slice(
                claims, enc[None, :], (it, 0))

            need = need - jnp.sum(keep, axis=1).astype(need.dtype)
            return (it + 1, need, new_mut, claims, jnp.any(taken_any))

        def cond(state):
            it, need, _mut, _c, progress = state
            return (it < iters) & (jnp.sum(need) > 0) & progress

        init = (
            jnp.asarray(0, jnp.int32),
            need,
            mutable,
            jnp.full((iters, N), -1, jnp.int32),
            jnp.asarray(True),
        )
        it, need, mutable, claims, _ = jax.lax.while_loop(cond, body, init)
        return mutable, claims, need

    kwargs = {"donate_argnums": (0,)} if donate else {}
    if out_shardings_key is not None:
        node_sharding, replicated = out_shardings_key
        kwargs["out_shardings"] = (
            {name: node_sharding for name in _MUTABLE},
            replicated,
            replicated,
        )
    return jax.jit(fn, **kwargs)


def decode_claims(
    claims: np.ndarray,       # [iters, N] int32 packed words, -1 = none
    bucket_shapes: Sequence[Tuple[int, int]],
    bucket_keys: Sequence[int],
    U: int,
    K: int,
) -> Dict[int, Dict[int, List[Tuple[int, int, int, int]]]]:
    """Unpack the device claim tensor into
    {bucket key: {local type: [(node, c, m, a), ...]}} with list order =
    (iteration, node index) — the order speculative copies were made."""
    offsets = np.cumsum([0] + [tp for _, tp in bucket_shapes])
    a_width = np.concatenate([
        np.full(tp, get_tables(G, U, K).A, np.int64)
        for G, tp in bucket_shapes
    ])
    out: Dict[int, Dict[int, List[Tuple[int, int, int, int]]]] = {
        gk: {} for gk in bucket_keys
    }
    its, nodes = np.nonzero(claims >= 0)
    word = claims[its, nodes].astype(np.int64)
    tg = word >> _T_SHIFT
    rest = word & ((1 << _T_SHIFT) - 1)
    aw = a_width[tg]
    a = rest % aw
    cm = rest // aw
    c = cm // U
    m = cm % U
    b_of = np.searchsorted(offsets, tg, side="right") - 1
    for i in range(len(its)):
        b = int(b_of[i])
        t_local = int(tg[i] - offsets[b])
        out[bucket_keys[b]].setdefault(t_local, []).append(
            (int(nodes[i]), int(c[i]), int(m[i]), int(a[i]))
        )
    return out
