"""The batched feasibility solve: one jitted function per bucket shape.

This is the TPU replacement for the reference's per-pod Python walk
(Matcher.py:86-391): every predicate becomes a broadcasted boolean tensor
over [T types, N nodes, C numa-combos, A nic-picks], reduced with any/all.
XLA fuses the comparison lattices into the reductions, so the big
intermediates never materialize; the combo tables ride as constants.

Outputs are the *decisions* the host needs, already reduced to [T, N]:
candidacy, the selection preference, and the argmax-encoded best combo /
misc-NUMA / NIC-pick — tie-breaking matches the oracle because combo axes
are in itertools.product order (see combos.py) and jnp.argmax returns the
first maximum.
"""

from __future__ import annotations

import os
from functools import lru_cache
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from nhd_tpu.obs.jitstats import JIT_STATS
from nhd_tpu.solver.combos import get_tables


class SolveOut(NamedTuple):
    cand: jax.Array      # [T, N] bool — node feasible for type
    pref: jax.Array      # [T, N] int32 — 0 invalid / 1 candidate / 2 preferred
    best_c: jax.Array    # [T, N] int32 — skew-maximal feasible combo
    best_m: jax.Array    # [T, N] int32 — first feasible misc NUMA for best_c
    best_a: jax.Array    # [T, N] int32 — first feasible NIC pick for best_c
    n_combos: jax.Array  # [T, N] int32 — feasible combo count (introspection)
    n_picks: jax.Array   # [T, N] int32 — feasible NIC picks at best_c (a
    #                      capacity hint for multi-claim rounds)


def _solve(
    tables,
    # node arrays
    numa_nodes, smt, active, maintenance, busy, gpuless, node_gmask,
    hp_free, cpu_free, gpu_free, nic_count, nic_free, nic_sw, gpu_free_sw,
    node_class,
    # pod-type arrays
    cpu_dem_smt, cpu_dem_raw, gpu_dem, rx, tx, hp, needs_gpu, map_pci,
    pod_gmask, class_score,
) -> SolveOut:
    # node_class/class_score are the policy engine's score-term inputs
    # (nhd_tpu/policy/): feasibility never reads them — the fused ranked
    # programs fold them into the selection value via _policy_pref, and
    # the plain solve (this function's SolveOut) stays the pure
    # feasibility surface.
    C, A, U, K = tables.C, tables.A, tables.U, tables.K
    combo_onehot = jnp.asarray(tables.combo_onehot)          # [C,G,U]
    need_max = jnp.asarray(tables.need_max)                  # [C,A,U]
    maxdig = jnp.asarray(tables.combo_maxdig)                # [C]
    skew = jnp.asarray(tables.skew)                          # [C]

    # ---- node-level predicate (reference: Matcher.py:65-84,103-111 +
    # NHDScheduler.py:235-247 group/active filter) ----
    node_ok = (
        active
        & ~maintenance
        & (hp[:, None] <= hp_free[None, :])
        & ((pod_gmask[:, None] & node_gmask[None, :]) != 0)
        & (~needs_gpu[:, None] | ~busy[None, :])
    )  # [T, N]

    # combos using NUMA nodes the node doesn't have are invalid
    combo_valid = maxdig[None, :] < numa_nodes[:, None]  # [N, C]

    # ---- GPU predicate (reference: Matcher.py:97-141) ----
    gpu_need = jnp.einsum("tg,cgu->tcu", gpu_dem.astype(jnp.float32), combo_onehot)
    gpu_ok = jnp.all(
        gpu_need[:, None, :, :] <= gpu_free[None, :, None, :], axis=-1
    )  # [T, N, C]

    # ---- CPU predicate incl. trailing misc slot (reference: Matcher.py:152-222) ----
    def cpu_fit(dem):  # dem [T, G+1]
        group_need = jnp.einsum(
            "tg,cgu->tcu", dem[:, :-1].astype(jnp.float32), combo_onehot
        )  # [T, C, U]
        misc_need = (
            dem[:, -1].astype(jnp.float32)[:, None, None]
            * jnp.asarray(tables.misc_onehot)[None, :, :]
        )  # [T, M=U, U]
        total = group_need[:, :, None, :] + misc_need[:, None, :, :]  # [T,C,M,U]
        return jnp.all(
            total[:, None] <= cpu_free[None, :, None, None, :], axis=-1
        )  # [T, N, C, M]

    cpu_ok = jnp.where(
        smt[None, :, None, None], cpu_fit(cpu_dem_smt), cpu_fit(cpu_dem_raw)
    )  # [T, N, C, M]
    cpu_any = jnp.any(cpu_ok, axis=-1)  # [T, N, C]

    # ---- NIC predicate (reference: Matcher.py:224-276) ----
    # Group-indexed form (r8): each group chooses exactly ONE (numa, nic)
    # slot per (combo, pick) — slot_u = combo[c, g], slot_k = pick[a, g]
    # — so the feasibility 'all' over the dense [U, K] slot grid reduces
    # to an 'all' over the G chosen slots. Unchosen slots only ever
    # contributed True rows, and groups of one pod sharing a NIC compare
    # the same joint demand against the same slot twice (idempotent
    # under 'all'), so every verdict is bit-identical to the dense form
    # while the dominant lattice shrinks from [T, N, C, A, U, K] to
    # [T, N, C, A, G] (7x fewer element ops at the headline K=7 shape —
    # the fusion-aware-mapper move: never build state the reduction
    # doesn't need). combo/pick index tables are static constants the
    # compiler folds; all derived one-hots fold with them.
    combo_idx = jnp.asarray(tables.combo, jnp.int32)  # [C, G]
    pick_idx = jnp.asarray(tables.pick, jnp.int32)    # [A, G]
    # joint demand per group's slot: groups g, h share bandwidth iff they
    # chose the same (numa, nic) — the reference's in-pod sharing
    # semantics (Matcher.py:253-262)
    same_u = (
        combo_idx[:, :, None] == combo_idx[:, None, :]
    ).astype(jnp.float32)  # [C, G, G]
    same_k = (
        pick_idx[:, :, None] == pick_idx[:, None, :]
    ).astype(jnp.float32)  # [A, G, G]
    dem_rx_g = jnp.einsum("th,cgh,agh->tcag", rx, same_u, same_k)
    dem_tx_g = jnp.einsum("th,cgh,agh->tcag", tx, same_u, same_k)
    u_idx = combo_idx[:, None, :]  # [C, 1, G] — broadcast against...
    k_idx = pick_idx[None, :, :]   # [1, A, G]
    free_at = nic_free[:, u_idx, k_idx, :]  # [N, C, A, G, 2]
    fit = jnp.all(
        (dem_rx_g[:, None] <= free_at[None, ..., 0])
        & (dem_tx_g[:, None] <= free_at[None, ..., 1]),
        axis=-1,
    )  # [T, N, C, A]

    # every chosen ordinal must exist on the node
    pick_valid = jnp.all(
        need_max[None, :, :, :] <= nic_count[:, None, None, :], axis=-1
    )  # [N, C, A]

    # PCI map mode: chosen NICs need matching free GPUs on their PCIe switch
    # (reference: Matcher.py:295-335 — counts NICs per switch, see oracle.py
    # module docstring for the kept quirk). Group-indexed like the fit:
    # sw_need is nonzero only at the <= G switches the chosen slots sit
    # on, so "all switches satisfy need <= free" splits into (a) every
    # group's switch has free >= the count of groups sharing it and
    # (b) every OTHER switch has free >= 0 — term (b) is one per-node
    # reduction instead of the [N, C, A, S] one-hot einsum (S = 14 at
    # the headline shape made that einsum the second-hottest op).
    sw_at = nic_sw[:, u_idx, k_idx]  # [N, C, A, G] — switch per group slot
    share_sw = jnp.sum(
        (sw_at[..., :, None] == sw_at[..., None, :]).astype(jnp.float32),
        axis=-1,
    )  # [N, C, A, G] — groups whose slot sits on this group's switch
    free_sw_at = jnp.take_along_axis(
        gpu_free_sw, sw_at.reshape(sw_at.shape[0], -1), axis=1
    ).reshape(sw_at.shape)
    sw_nonneg = jnp.all(gpu_free_sw >= 0, axis=-1)  # [N]
    pci_ok = (
        jnp.all(share_sw <= free_sw_at, axis=-1) & sw_nonneg[:, None, None]
    )  # [N, C, A]

    # the [T, N, C, A] lattice fuses into these reductions (XLA never
    # materializes it in HBM). A Pallas VMEM-streaming variant of this
    # nest was retired 2026-07-29 after four rounds of unresolvable
    # on-chip Mosaic compile hangs; the artifact lives in
    # attic/nic_pallas.py and the decision record in docs/DESIGN.md.
    nic_ok = (
        fit
        & pick_valid[None]
        & (pci_ok[None] | ~map_pci[:, None, None, None])
    )  # [T, N, C, A]
    nic_any = jnp.any(nic_ok, axis=-1)  # [T, N, C]
    first_a = jnp.argmax(nic_ok, axis=-1).astype(jnp.int32)  # [T, N, C]
    nic_pick_count = jnp.sum(nic_ok, axis=-1).astype(jnp.int32)

    # ---- intersection on the group prefix (reference: Matcher.py:337-390) ----
    feasible = (
        node_ok[:, :, None] & combo_valid[None] & gpu_ok & cpu_any & nic_any
    )  # [T, N, C]
    cand = jnp.any(feasible, axis=-1)
    n_combos = jnp.sum(feasible, axis=-1).astype(jnp.int32)

    # ---- combo choice: max skew, first wins (reference: Matcher.py:423-452) ----
    combo_val = jnp.where(
        feasible,
        skew[None, None, :] * (C + 1) + (C - jnp.arange(C))[None, None, :],
        -1,
    )
    best_c = jnp.argmax(combo_val, axis=-1).astype(jnp.int32)  # [T, N]

    take = lambda x: jnp.take_along_axis(x, best_c[:, :, None], axis=-1)[:, :, 0]
    best_m = jnp.argmax(
        jnp.take_along_axis(cpu_ok, best_c[:, :, None, None], axis=2)[:, :, 0, :],
        axis=-1,
    ).astype(jnp.int32)  # [T, N] first feasible misc NUMA
    best_a = take(first_a)  # [T, N]
    n_picks = take(nic_pick_count)  # [T, N]

    # ---- selection preference (reference: Matcher.py:393-421) ----
    pref = jnp.where(
        cand, 1 + (~needs_gpu[:, None] & gpuless[None, :]).astype(jnp.int32), 0
    )

    return SolveOut(cand, pref, best_c, best_m, best_a, n_combos, n_picks)


# The single node-array argument-order contract every solve entry shares:
# kernel dispatches, device-resident state (solver/device_state.py), the
# speculative megaround (solver/speculate.py) and the AOT export/prewarm
# layer (solver/aot.py) all build their argument lists from these tuples,
# so the 25-array positional signature (15 node + 10 pod-type, grown from
# 23 by the policy engine's node_class/class_score score-term inputs)
# cannot drift between them.
_MUTABLE = ("busy", "hp_free", "cpu_free", "gpu_free", "nic_free", "gpu_free_sw")
_STATIC = (
    "numa_nodes", "smt", "active", "maintenance", "gpuless", "group_mask",
    "nic_count", "nic_sw", "node_class",
)
_ARG_ORDER = (
    "numa_nodes", "smt", "active", "maintenance", "busy", "gpuless",
    "group_mask", "hp_free", "cpu_free", "gpu_free", "nic_count",
    "nic_free", "nic_sw", "gpu_free_sw", "node_class",
)
_POD_ARG_ORDER = (
    "cpu_dem_smt", "cpu_dem_raw", "gpu_dem", "rx", "tx", "hp", "needs_gpu",
    "map_pci", "group_mask", "class_score",
)

# combo-lattice ceiling: (U^G) * (K^G) above this routes the bucket to the
# serial oracle instead of enumerating a huge static axis (a 6-group pod on
# a 4-NUMA/8-NIC cluster would otherwise demand a 2^30-wide tensor)
MAX_LATTICE = int(os.environ.get("NHD_TPU_MAX_LATTICE", str(1 << 16)))


def bucket_tractable(n_groups: int, n_numa: int, max_nic: int) -> bool:
    """Whether a (G, U, K) bucket fits the dense-enumeration budget."""
    return (n_numa ** n_groups) * (max(max_nic, 1) ** n_groups) <= MAX_LATTICE


@lru_cache(maxsize=None)
def get_solver(n_groups: int, n_numa: int, max_nic: int):
    """A jitted solver specialized to one bucket shape; tables are closure
    constants so XLA folds them."""
    tables = get_tables(n_groups, n_numa, max_nic)

    def fn(*args):
        return _solve(tables, *args)

    return jax.jit(fn)


def _pad_pow2(n: int, floor: int = 8) -> int:
    p = floor
    while p < n:
        p *= 2
    return p


def pad_nodes(n: int, n_dev: int = 1, floor: int = 8) -> int:
    """Padded node-axis length: a power-of-two bucket (jit-cache reuse)
    that is also a multiple of the mesh size (even shards). The single
    place this rule lives — device_state and parallel/sharding share it."""
    p = _pad_pow2(max(n, 1), floor=max(floor, n_dev))
    if p % n_dev:
        p += n_dev - (p % n_dev)
    return p


class RankOut(NamedTuple):
    """Field order of the PACKED per-type top-R ranking tensor.

    The ranking leaves the device as ONE [9, T, R] int32 array whose
    leading-axis rows are these fields, in this order — nine separate
    output arrays cost nine device→host transfers, and on the
    tunnel-attached TPU each transfer pays ~84 ms of relay latency
    regardless of size (measured: 9 separate [8,512] pulls 756 ms vs one
    packed pull 77 ms, docs/TPU_STATUS.md). The host slices zero-copy
    row views back out (solver/batch.py RankHost). R >= the round's
    largest per-type pod count, so a capacity>=1 candidate list is never
    cut short — selection semantics match the old host argsort exactly
    (sel value encodes pref then low-node-index tiebreak; lax.top_k
    breaks value ties toward lower index like a stable argsort)."""

    val: jax.Array       # [T, R] int32 — ranking value, 0 = not a candidate
    idx: jax.Array       # [T, R] int32 — node index, descending val
    best_c: jax.Array    # [T, R] int32 — gathered SolveOut fields at idx
    best_m: jax.Array
    best_a: jax.Array
    n_picks: jax.Array
    free_gpu: jax.Array  # [T, R] int32 — node free-GPU totals at idx (the
    #                      host capacity estimate's ingredients, gathered
    #                      so the host never touches an [N] array)
    free_cpu: jax.Array
    free_hp: jax.Array


def _rank_body(R, cand, pref, best_c, best_m, best_a, n_picks,
               gpu_free, cpu_free, hp_free) -> jax.Array:
    """The top-R ranking math, traceable inside any jitted program — the
    standalone ranker below and the fused scatter+solve+rank dispatch
    (solver/device_state.py) share it so their selection semantics cannot
    drift. Returns the packed [9, T, R] int32 tensor (RankOut order)."""
    N = cand.shape[1]
    sel = jnp.where(
        cand,
        pref * (N + 1) + (N - jnp.arange(N, dtype=jnp.int32))[None, :],
        0,
    )
    val, idx = jax.lax.top_k(sel, R)
    gat = lambda a: jnp.take_along_axis(a, idx, axis=1)
    return jnp.stack([
        val, idx.astype(jnp.int32),
        gat(best_c), gat(best_m), gat(best_a), gat(n_picks),
        gpu_free.sum(axis=1).astype(jnp.int32)[idx],
        cpu_free.sum(axis=1).astype(jnp.int32)[idx],
        hp_free.astype(jnp.int32)[idx],
    ])


def _policy_pref(pref, node_class, class_score):
    """Fold the heterogeneity score term into the selection preference
    (nhd_tpu/policy/): the fused ranking value becomes

        sel = (score * 3 + pref) * (N + 1) + (N - node_index)

    i.e. throughput class is the primary key, the gpuless preference the
    tiebreak, low node index last — Gavel's throughput-matrix scoring as
    one extra vmapped gather inside the existing megaround. With the
    policy off, class_score is all-zero and sel reduces bit-exactly to
    the pre-policy ``pref * (N + 1) + (N - idx)`` (the pinned
    NHD_POLICY=0 control). int32 headroom: score <= 255 (SCORE_QUANT),
    pref <= 2, so sel stays in-range past a 2M-row node axis — far
    beyond the streaming tiler's per-solve tile bound."""
    idx = jnp.clip(
        node_class.astype(jnp.int32), 0, class_score.shape[1] - 1
    )
    score = jnp.take(class_score, idx, axis=1)  # [T, N]
    return pref + 3 * score


def rank_cap(accelerator: bool) -> int:
    """Ceiling for the top-R rank width.

    CPU backend: 1024 — pulls are free (zero-copy), so prefer fewer
    rounds; the cap only guards top_k from degenerating into a full sort
    at federation scale. Accelerator backend: 512 — on the tunnel-attached
    TPU each ROUND costs ~1.2 s of fixed dispatch latency, which swamps
    the [T, R] pull-size savings of a tighter cap: measured at cfg4
    (10k×1k), R=128 needs 7 greedy rounds and R=256 needs 5, while R=512
    matches the uncapped 3 (the capacity-repeat select runs out of ranked
    candidates below that and pays whole extra rounds; BENCH_r02's
    R=128 TPU run was 8.7 s vs 3.6 s uncapped for exactly this reason).
    A type that exhausts R candidates while pods remain simply stays
    pending and the next round re-ranks against advanced state — the cap
    is never a correctness cut. NHD_TPU_RANK_CAP overrides both."""
    env = os.environ.get("NHD_TPU_RANK_CAP")
    if env:
        return int(env)
    return 512 if accelerator else 1024


def rank_budget(max_need: int, n_padded: int, *, accelerator: bool = False) -> int:
    """The R for a batch, bucketed for jit-cache reuse under the
    platform cap (see rank_cap).

    CPU backend: R is a pure function of CLUSTER size — min(nodes, cap)
    — never of batch composition. Pulls are zero-copy there, so a
    need-proportional R only bought a smaller top_k; but with the solve
    and rank fused into ONE program (r8) R became a specializing dim of
    the whole megaround, and a max-need change re-traced the entire
    fused solve (measured: the cfg5 streaming run recompiled every
    bucket x tile program mid-measurement because its warmup batch had
    a different largest-type count). A fixed R per cluster also makes
    the zero-recompile invariant hold by construction on the serving
    path. Accelerator backend: the need-proportional budget stands —
    the [T, R] pull crosses the relay, so covering the largest per-type
    pod count (every candidate carries capacity >= 1, so R >= need
    never costs extra rounds) at minimal width still wins."""
    cap = rank_cap(accelerator)
    if not accelerator:
        # pow2-bucket the node bound exactly like the Np padding
        # (floor 8): callers pass the RAW node count, and an unbucketed
        # min would move R — re-tracing every fused program and missing
        # every AOT artifact — each time a node joins or leaves
        return min(_pad_pow2(max(n_padded, 1), floor=8), cap)
    return min(n_padded, _pad_pow2(min(max(max_need, 1), cap), floor=64))


@lru_cache(maxsize=None)
def get_ranked_solver(G: int, U: int, K: int, R: int):
    """ONE jitted program: the bucket solve FUSED with the top-R ranking
    (r8 megaround fusion). The [T, N] feasibility/score/choice tensors
    never leave the program — XLA fuses the solve reductions straight
    into the rank's top_k/gather inputs and dead-code-eliminates outputs
    the rank never reads (n_combos), where the old two-program pipeline
    materialized all seven SolveOut tensors between dispatches. Takes
    the 15 node arrays (``_ARG_ORDER``) followed by the 10 pod-type
    arrays (``_POD_ARG_ORDER``); returns the packed [9, T, R] int32 rank
    tensor (RankOut order). This is THE production program — the AOT
    layer (solver/aot.py) exports and prewarm-loads exactly this
    signature, and tools/export_tpu.py pins it as the TPU artifact."""
    tables = get_tables(G, U, K)
    i_hp = _ARG_ORDER.index("hp_free")
    i_cpu = _ARG_ORDER.index("cpu_free")
    i_gpu = _ARG_ORDER.index("gpu_free")
    i_nc = _ARG_ORDER.index("node_class")
    i_cs = len(_ARG_ORDER) + _POD_ARG_ORDER.index("class_score")

    def fn(*args):
        out = _solve(tables, *args)
        pref = _policy_pref(out.pref, args[i_nc], args[i_cs])
        return _rank_body(
            R, out.cand, pref, out.best_c, out.best_m, out.best_a,
            out.n_picks, args[i_gpu], args[i_cpu], args[i_hp],
        )

    return jax.jit(fn)


def mesh_desc(mesh) -> str:
    """Canonical descriptor of a 1-D scheduler mesh ("nodes8" = a
    ``nodes`` axis over 8 devices) — the string form every layer that
    must name a sharded program shares: jit-stats shape keys, AOT cache
    keys/artifact names (solver/aot.py reconstructs the mesh from it at
    prewarm), and the NHD_MESH operator knob's log lines."""
    if mesh is None:
        return ""
    (axis,) = mesh.axis_names
    return f"{axis}{mesh.devices.size}"


def parse_mesh_desc(desc: str):
    """(axis, n_devices) from a mesh_desc string, or None for ""."""
    if not desc:
        return None
    axis = desc.rstrip("0123456789")
    return axis, int(desc[len(axis):])


def mesh_shardings(mesh):
    """(node_sharding, replicated) for *mesh* — the one place the
    solver's GSPMD layout lives: every node array shards along axis 0
    of the ``nodes`` mesh axis, everything else (pod-type arrays, the
    packed rank output) replicates."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    (axis,) = mesh.axis_names
    return NamedSharding(mesh, P(axis)), NamedSharding(mesh, P())


@lru_cache(maxsize=None)
def get_ranked_solver_mesh(G: int, U: int, K: int, R: int, mesh):
    """The fused solve+rank megaround (get_ranked_solver) lowered onto a
    device mesh: the 15 node arrays shard along the ``nodes`` axis, the
    10 pod-type arrays replicate, and the packed [9, T, R] rank tensor
    comes back replicated — the top-k over the sharded node axis is the
    one collective GSPMD inserts. SAME program text as the single-device
    megaround, so mesh results are bit-exact with it by construction
    (pinned in tests/test_spmd.py); this replaced the legacy unfused
    ``parallel.sharding.get_sharded_solver`` + separate ranker split,
    whose intermediate [T, N] SolveOut tensors materialized (and
    re-sharded) between two dispatches."""
    node_spec, repl_spec = mesh_shardings(mesh)
    in_shardings = (node_spec,) * len(_ARG_ORDER) + (
        repl_spec,
    ) * len(_POD_ARG_ORDER)
    tables = get_tables(G, U, K)
    i_hp = _ARG_ORDER.index("hp_free")
    i_cpu = _ARG_ORDER.index("cpu_free")
    i_gpu = _ARG_ORDER.index("gpu_free")
    i_nc = _ARG_ORDER.index("node_class")
    i_cs = len(_ARG_ORDER) + _POD_ARG_ORDER.index("class_score")

    def fn(*args):
        out = _solve(tables, *args)
        pref = _policy_pref(out.pref, args[i_nc], args[i_cs])
        return _rank_body(
            R, out.cand, pref, out.best_c, out.best_m, out.best_a,
            out.n_picks, args[i_gpu], args[i_cpu], args[i_hp],
        )

    return jax.jit(fn, in_shardings=in_shardings, out_shardings=repl_spec)


def ranked_shape_key(G, U, K, R, Tp, Np, mesh: str = "") -> str:
    """The jit-stats shape key of one fused solve+rank program — every
    dim the compiled program specializes on (``mesh``: the mesh_desc of
    a sharded variant — a mesh program is a DIFFERENT compilation).
    Shared by the dispatch sites and the AOT prewarm loader so a
    prewarmed program's first real use counts as a cache hit, never a
    compile."""
    key = f"G{G}_U{U}_K{K}_R{R}_T{Tp}_N{Np}"
    return key + (f"_M{mesh}" if mesh else "")


def parse_ranked_shape_key(key: str):
    """(G, U, K, R, Tp, Np, mesh_desc) back out of a ranked_shape_key
    string, or None when it doesn't parse — the guard's quarantine
    bookkeeping (solver/guard.py) maps a faulting shape key back to its
    AOT ShapeKey to retire the cached artifact."""
    import re

    m = re.fullmatch(
        r"G(\d+)_U(\d+)_K(\d+)_R(\d+)_T(\d+)_N(\d+)(?:_M(.+))?", key
    )
    if m is None:
        return None
    dims = tuple(int(x) for x in m.groups()[:6])
    return dims + (m.group(7) or "",)


def dispatch_ranked(G, U, K, R, Tp, Np, args, mesh=None) -> jax.Array:
    """Resolve + invoke the fused solve+rank program for one padded
    shape: the AOT prewarm cache first (zero-cold-start — the program
    was deserialized from StableHLO and compiled at daemon start), else
    the live jit, which is exported back to the AOT artifact cache when
    saving is on (solver/aot.py). ``args`` is the full 25-array
    positional list; host and device-resident callers share this single
    entry so their programs (and AOT artifacts) are one and the same.
    With ``mesh`` the SAME fused program runs SPMD over the node axis
    (get_ranked_solver_mesh) — one seam serves single-chip and
    multi-chip dispatch, and sharded programs export/prewarm through
    the same AOT cache under a mesh-qualified key."""
    # recompile accounting (obs/jitstats.py): a first-seen key IS a
    # fresh trace+compile (or a prewarm load), the silent stall the
    # nhd_jit_* metrics make scrapeable
    desc = mesh_desc(mesh)
    key_str = ranked_shape_key(G, U, K, R, Tp, Np, desc)
    JIT_STATS.record_use("solve_ranked", key_str)
    from nhd_tpu.solver import aot, guard

    # chaos fault-injection seam (solver/guard.py): no-op in production
    guard.maybe_inject("dispatch", key_str)
    key = aot.ShapeKey("ranked", G, U, K, R, Tp, Np, desc)
    quarantined = guard.GUARD.shape_quarantined(key_str)
    if not quarantined:
        prog = aot.lookup(key)
        if prog is not None:
            return prog(*args)
    fn = (
        get_ranked_solver_mesh(G, U, K, R, mesh) if mesh is not None
        else get_ranked_solver(G, U, K, R)
    )
    if not quarantined:
        # a quarantined shape must not re-seed the cache it was just
        # evicted from — its dispatches stay live-traced
        aot.maybe_export(key, fn, args)
    return fn(*args)


def _pad_rows_to(a: np.ndarray, size: int) -> np.ndarray:
    if a.shape[0] == size:
        return a
    return np.concatenate(
        [a, np.zeros((size - a.shape[0], *a.shape[1:]), a.dtype)], axis=0
    )


def padded_args(cluster, pods, Tp: int, Np: int) -> list:
    """The 25 padded solver arguments (node arrays in ``_ARG_ORDER``,
    then pod arrays in ``_POD_ARG_ORDER``) — the one place the host
    padding rule lives."""
    return [
        _pad_rows_to(getattr(cluster, name), Np) for name in _ARG_ORDER
    ] + [
        _pad_rows_to(getattr(pods, name), Tp) for name in _POD_ARG_ORDER
    ]


def solve_bucket_ranked(cluster, pods, R: int) -> jax.Array:
    """Solve + top-R ranking in ONE fused dispatch (get_ranked_solver):
    feasibility masks, scores and the ranked gathers never materialize
    between programs, on host or in HBM. Returns the packed [9, Tp, R]
    tensor — callers slice [:, :T]."""
    T, N = pods.n_types, cluster.n_nodes
    Tp, Np = _pad_pow2(T), _pad_pow2(N, floor=8)
    return dispatch_ranked(
        pods.G, cluster.U, cluster.K, min(R, Np), Tp, Np,
        padded_args(cluster, pods, Tp, Np),
    )


def _solve_padded(cluster, pods) -> SolveOut:
    """The padded plain-solve call (full [Tp, Np] SolveOut, no host
    slicing) — the parity/debug surface; production rounds go through
    the fused ``solve_bucket_ranked``."""
    Tp = _pad_pow2(pods.n_types)
    Np = _pad_pow2(cluster.n_nodes, floor=8)
    JIT_STATS.record_use(
        "solve", f"G{pods.G}_U{cluster.U}_K{cluster.K}_T{Tp}_N{Np}"
    )
    solver = get_solver(pods.G, cluster.U, cluster.K)
    return solver(*padded_args(cluster, pods, Tp, Np))


def solve_bucket(cluster, pods, *, device=None) -> SolveOut:
    """Run the bucket solve for (ClusterArrays, PodTypeArrays) → SolveOut.

    Node and type axes are padded to power-of-two buckets so repeated solves
    against growing/shrinking batches reuse the jit cache (SURVEY §7 hard
    part 3: fixed-shape padding without recompiles). Padded node rows are
    inactive (never candidates); padded type rows are garbage the callers
    must slice off (outputs are [T, N] with the original sizes restored).
    """
    T, N = pods.n_types, cluster.n_nodes
    if device is not None:
        with jax.default_device(device):
            out = _solve_padded(cluster, pods)
    else:
        out = _solve_padded(cluster, pods)
    return SolveOut(*(x[:T, :N] if x.ndim == 2 else x for x in out))
