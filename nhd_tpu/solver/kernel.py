"""The batched feasibility solve: one jitted function per bucket shape.

This is the TPU replacement for the reference's per-pod Python walk
(Matcher.py:86-391): every predicate becomes a broadcasted boolean tensor
over [T types, N nodes, C numa-combos, A nic-picks], reduced with any/all.
XLA fuses the comparison lattices into the reductions, so the big
intermediates never materialize; the combo tables ride as constants.

Outputs are the *decisions* the host needs, already reduced to [T, N]:
candidacy, the selection preference, and the argmax-encoded best combo /
misc-NUMA / NIC-pick — tie-breaking matches the oracle because combo axes
are in itertools.product order (see combos.py) and jnp.argmax returns the
first maximum.
"""

from __future__ import annotations

import os
from functools import lru_cache
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from nhd_tpu.obs.jitstats import JIT_STATS
from nhd_tpu.solver.combos import get_tables


class SolveOut(NamedTuple):
    cand: jax.Array      # [T, N] bool — node feasible for type
    pref: jax.Array      # [T, N] int32 — 0 invalid / 1 candidate / 2 preferred
    best_c: jax.Array    # [T, N] int32 — skew-maximal feasible combo
    best_m: jax.Array    # [T, N] int32 — first feasible misc NUMA for best_c
    best_a: jax.Array    # [T, N] int32 — first feasible NIC pick for best_c
    n_combos: jax.Array  # [T, N] int32 — feasible combo count (introspection)
    n_picks: jax.Array   # [T, N] int32 — feasible NIC picks at best_c (a
    #                      capacity hint for multi-claim rounds)


def _solve(
    tables,
    # node arrays
    numa_nodes, smt, active, maintenance, busy, gpuless, node_gmask,
    hp_free, cpu_free, gpu_free, nic_count, nic_free, nic_sw, gpu_free_sw,
    # pod-type arrays
    cpu_dem_smt, cpu_dem_raw, gpu_dem, rx, tx, hp, needs_gpu, map_pci,
    pod_gmask,
) -> SolveOut:
    C, A, U, K = tables.C, tables.A, tables.U, tables.K
    combo_onehot = jnp.asarray(tables.combo_onehot)          # [C,G,U]
    choose_onehot = jnp.asarray(tables.choose_onehot)        # [C,A,G,U,K]
    need_max = jnp.asarray(tables.need_max)                  # [C,A,U]
    chosen_cnt = jnp.asarray(tables.chosen_cnt)              # [C,A,U,K]
    maxdig = jnp.asarray(tables.combo_maxdig)                # [C]
    skew = jnp.asarray(tables.skew)                          # [C]

    # ---- node-level predicate (reference: Matcher.py:65-84,103-111 +
    # NHDScheduler.py:235-247 group/active filter) ----
    node_ok = (
        active
        & ~maintenance
        & (hp[:, None] <= hp_free[None, :])
        & ((pod_gmask[:, None] & node_gmask[None, :]) != 0)
        & (~needs_gpu[:, None] | ~busy[None, :])
    )  # [T, N]

    # combos using NUMA nodes the node doesn't have are invalid
    combo_valid = maxdig[None, :] < numa_nodes[:, None]  # [N, C]

    # ---- GPU predicate (reference: Matcher.py:97-141) ----
    gpu_need = jnp.einsum("tg,cgu->tcu", gpu_dem.astype(jnp.float32), combo_onehot)
    gpu_ok = jnp.all(
        gpu_need[:, None, :, :] <= gpu_free[None, :, None, :], axis=-1
    )  # [T, N, C]

    # ---- CPU predicate incl. trailing misc slot (reference: Matcher.py:152-222) ----
    def cpu_fit(dem):  # dem [T, G+1]
        group_need = jnp.einsum(
            "tg,cgu->tcu", dem[:, :-1].astype(jnp.float32), combo_onehot
        )  # [T, C, U]
        misc_need = (
            dem[:, -1].astype(jnp.float32)[:, None, None]
            * jnp.asarray(tables.misc_onehot)[None, :, :]
        )  # [T, M=U, U]
        total = group_need[:, :, None, :] + misc_need[:, None, :, :]  # [T,C,M,U]
        return jnp.all(
            total[:, None] <= cpu_free[None, :, None, None, :], axis=-1
        )  # [T, N, C, M]

    cpu_ok = jnp.where(
        smt[None, :, None, None], cpu_fit(cpu_dem_smt), cpu_fit(cpu_dem_raw)
    )  # [T, N, C, M]
    cpu_any = jnp.any(cpu_ok, axis=-1)  # [T, N, C]

    # ---- NIC predicate (reference: Matcher.py:224-276) ----
    # demand each (numa, nic) accumulates under combo c / pick a — groups
    # sharing a NIC sum jointly, the reference's in-pod sharing semantics
    dem_rx = jnp.einsum("tg,caguk->tcauk", rx, choose_onehot)
    dem_tx = jnp.einsum("tg,caguk->tcauk", tx, choose_onehot)
    # only (numa, nic) slots some group actually chose constrain the fit —
    # unchosen slots are padded with free = -1 and must not veto
    unchosen = (chosen_cnt == 0)[None, None]  # [1, 1, C, A, U, K]
    fit = jnp.all(
        unchosen
        | (
            (dem_rx[:, None] <= nic_free[None, :, None, None, :, :, 0])
            & (dem_tx[:, None] <= nic_free[None, :, None, None, :, :, 1])
        ),
        axis=(-2, -1),
    )  # [T, N, C, A]

    # every chosen ordinal must exist on the node
    pick_valid = jnp.all(
        need_max[None, :, :, :] <= nic_count[:, None, None, :], axis=-1
    )  # [N, C, A]

    # PCI map mode: chosen NICs need matching free GPUs on their PCIe switch
    # (reference: Matcher.py:295-335 — counts NICs per switch, see oracle.py
    # module docstring for the kept quirk)
    S = gpu_free_sw.shape[-1]
    sw_onehot = (
        nic_sw[:, :, :, None] == jnp.arange(S)[None, None, None, :]
    ).astype(jnp.float32)  # [N, U, K, S]
    sw_need = jnp.einsum("cauk,nuks->ncas", chosen_cnt, sw_onehot)
    pci_ok = jnp.all(sw_need <= gpu_free_sw[:, None, None, :], axis=-1)  # [N,C,A]

    # the [T, N, C, A] lattice fuses into these reductions (XLA never
    # materializes it in HBM). A Pallas VMEM-streaming variant of this
    # nest was retired 2026-07-29 after four rounds of unresolvable
    # on-chip Mosaic compile hangs; the artifact lives in
    # attic/nic_pallas.py and the decision record in docs/DESIGN.md.
    nic_ok = (
        fit
        & pick_valid[None]
        & (pci_ok[None] | ~map_pci[:, None, None, None])
    )  # [T, N, C, A]
    nic_any = jnp.any(nic_ok, axis=-1)  # [T, N, C]
    first_a = jnp.argmax(nic_ok, axis=-1).astype(jnp.int32)  # [T, N, C]
    nic_pick_count = jnp.sum(nic_ok, axis=-1).astype(jnp.int32)

    # ---- intersection on the group prefix (reference: Matcher.py:337-390) ----
    feasible = (
        node_ok[:, :, None] & combo_valid[None] & gpu_ok & cpu_any & nic_any
    )  # [T, N, C]
    cand = jnp.any(feasible, axis=-1)
    n_combos = jnp.sum(feasible, axis=-1).astype(jnp.int32)

    # ---- combo choice: max skew, first wins (reference: Matcher.py:423-452) ----
    combo_val = jnp.where(
        feasible,
        skew[None, None, :] * (C + 1) + (C - jnp.arange(C))[None, None, :],
        -1,
    )
    best_c = jnp.argmax(combo_val, axis=-1).astype(jnp.int32)  # [T, N]

    take = lambda x: jnp.take_along_axis(x, best_c[:, :, None], axis=-1)[:, :, 0]
    best_m = jnp.argmax(
        jnp.take_along_axis(cpu_ok, best_c[:, :, None, None], axis=2)[:, :, 0, :],
        axis=-1,
    ).astype(jnp.int32)  # [T, N] first feasible misc NUMA
    best_a = take(first_a)  # [T, N]
    n_picks = take(nic_pick_count)  # [T, N]

    # ---- selection preference (reference: Matcher.py:393-421) ----
    pref = jnp.where(
        cand, 1 + (~needs_gpu[:, None] & gpuless[None, :]).astype(jnp.int32), 0
    )

    return SolveOut(cand, pref, best_c, best_m, best_a, n_combos, n_picks)


# combo-lattice ceiling: (U^G) * (K^G) above this routes the bucket to the
# serial oracle instead of enumerating a huge static axis (a 6-group pod on
# a 4-NUMA/8-NIC cluster would otherwise demand a 2^30-wide tensor)
MAX_LATTICE = int(os.environ.get("NHD_TPU_MAX_LATTICE", str(1 << 16)))


def bucket_tractable(n_groups: int, n_numa: int, max_nic: int) -> bool:
    """Whether a (G, U, K) bucket fits the dense-enumeration budget."""
    return (n_numa ** n_groups) * (max(max_nic, 1) ** n_groups) <= MAX_LATTICE


@lru_cache(maxsize=None)
def get_solver(n_groups: int, n_numa: int, max_nic: int):
    """A jitted solver specialized to one bucket shape; tables are closure
    constants so XLA folds them."""
    tables = get_tables(n_groups, n_numa, max_nic)

    def fn(*args):
        return _solve(tables, *args)

    return jax.jit(fn)


def _pad_pow2(n: int, floor: int = 8) -> int:
    p = floor
    while p < n:
        p *= 2
    return p


def pad_nodes(n: int, n_dev: int = 1, floor: int = 8) -> int:
    """Padded node-axis length: a power-of-two bucket (jit-cache reuse)
    that is also a multiple of the mesh size (even shards). The single
    place this rule lives — device_state and parallel/sharding share it."""
    p = _pad_pow2(max(n, 1), floor=max(floor, n_dev))
    if p % n_dev:
        p += n_dev - (p % n_dev)
    return p


class RankOut(NamedTuple):
    """Field order of the PACKED per-type top-R ranking tensor.

    The ranking leaves the device as ONE [9, T, R] int32 array whose
    leading-axis rows are these fields, in this order — nine separate
    output arrays cost nine device→host transfers, and on the
    tunnel-attached TPU each transfer pays ~84 ms of relay latency
    regardless of size (measured: 9 separate [8,512] pulls 756 ms vs one
    packed pull 77 ms, docs/TPU_STATUS.md). The host slices zero-copy
    row views back out (solver/batch.py RankHost). R >= the round's
    largest per-type pod count, so a capacity>=1 candidate list is never
    cut short — selection semantics match the old host argsort exactly
    (sel value encodes pref then low-node-index tiebreak; lax.top_k
    breaks value ties toward lower index like a stable argsort)."""

    val: jax.Array       # [T, R] int32 — ranking value, 0 = not a candidate
    idx: jax.Array       # [T, R] int32 — node index, descending val
    best_c: jax.Array    # [T, R] int32 — gathered SolveOut fields at idx
    best_m: jax.Array
    best_a: jax.Array
    n_picks: jax.Array
    free_gpu: jax.Array  # [T, R] int32 — node free-GPU totals at idx (the
    #                      host capacity estimate's ingredients, gathered
    #                      so the host never touches an [N] array)
    free_cpu: jax.Array
    free_hp: jax.Array


def _rank_body(R, cand, pref, best_c, best_m, best_a, n_picks,
               gpu_free, cpu_free, hp_free) -> jax.Array:
    """The top-R ranking math, traceable inside any jitted program — the
    standalone ranker below and the fused scatter+solve+rank dispatch
    (solver/device_state.py) share it so their selection semantics cannot
    drift. Returns the packed [9, T, R] int32 tensor (RankOut order)."""
    N = cand.shape[1]
    sel = jnp.where(
        cand,
        pref * (N + 1) + (N - jnp.arange(N, dtype=jnp.int32))[None, :],
        0,
    )
    val, idx = jax.lax.top_k(sel, R)
    gat = lambda a: jnp.take_along_axis(a, idx, axis=1)
    return jnp.stack([
        val, idx.astype(jnp.int32),
        gat(best_c), gat(best_m), gat(best_a), gat(n_picks),
        gpu_free.sum(axis=1).astype(jnp.int32)[idx],
        cpu_free.sum(axis=1).astype(jnp.int32)[idx],
        hp_free.astype(jnp.int32)[idx],
    ])


@lru_cache(maxsize=None)
def _get_ranker(R: int, out_sharding_key=None):
    """Jitted top-R ranking over a solve's [T, N] outputs, returning the
    packed [9, T, R] tensor. Cached per R (R is a pow-2 bucket, so a
    handful of programs total); on a mesh the caller passes a replicated
    out-sharding via ``out_sharding_key``."""

    def rank(cand, pref, best_c, best_m, best_a, n_picks,
             gpu_free, cpu_free, hp_free):
        return _rank_body(
            R, cand, pref, best_c, best_m, best_a, n_picks,
            gpu_free, cpu_free, hp_free,
        )

    if out_sharding_key is not None:
        return jax.jit(rank, out_shardings=out_sharding_key)
    return jax.jit(rank)


def rank_cap(accelerator: bool) -> int:
    """Ceiling for the top-R rank width.

    CPU backend: 1024 — pulls are free (zero-copy), so prefer fewer
    rounds; the cap only guards top_k from degenerating into a full sort
    at federation scale. Accelerator backend: 512 — on the tunnel-attached
    TPU each ROUND costs ~1.2 s of fixed dispatch latency, which swamps
    the [T, R] pull-size savings of a tighter cap: measured at cfg4
    (10k×1k), R=128 needs 7 greedy rounds and R=256 needs 5, while R=512
    matches the uncapped 3 (the capacity-repeat select runs out of ranked
    candidates below that and pays whole extra rounds; BENCH_r02's
    R=128 TPU run was 8.7 s vs 3.6 s uncapped for exactly this reason).
    A type that exhausts R candidates while pods remain simply stays
    pending and the next round re-ranks against advanced state — the cap
    is never a correctness cut. NHD_TPU_RANK_CAP overrides both."""
    env = os.environ.get("NHD_TPU_RANK_CAP")
    if env:
        return int(env)
    return 512 if accelerator else 1024


def rank_budget(max_need: int, n_padded: int, *, accelerator: bool = False) -> int:
    """The R for a batch: covers the largest per-type pod count (every
    candidate carries capacity >= 1, so R >= need never costs extra
    rounds), bucketed to a power of two for jit-cache reuse, under the
    platform cap (see rank_cap)."""
    cap = rank_cap(accelerator)
    return min(n_padded, _pad_pow2(min(max(max_need, 1), cap), floor=64))


def solve_bucket_ranked(cluster, pods, R: int) -> jax.Array:
    """solve_bucket + on-device top-R ranking, without materializing the
    [T, N] outputs on host. Returns the packed [9, Tp, R] tensor —
    callers slice [:, :T]."""
    N = cluster.n_nodes
    Np = _pad_pow2(N, floor=8)

    def pad_n(a):
        if a.shape[0] == Np:
            return a
        return np.concatenate(
            [a, np.zeros((Np - a.shape[0], *a.shape[1:]), a.dtype)], axis=0
        )

    out = _solve_padded(cluster, pods)
    # recompile accounting: the ranker specializes on (R, padded T)
    JIT_STATS.record_use(
        "rank", f"R{min(R, Np)}_T{_pad_pow2(pods.n_types)}_N{Np}"
    )
    ranker = _get_ranker(min(R, Np))
    return ranker(
        out.cand, out.pref, out.best_c, out.best_m, out.best_a, out.n_picks,
        pad_n(cluster.gpu_free), pad_n(cluster.cpu_free),
        pad_n(cluster.hp_free),
    )


def _solve_padded(cluster, pods) -> SolveOut:
    """The padded solver call (full [Tp, Np] outputs, no host slicing)."""
    T, N = pods.n_types, cluster.n_nodes
    Tp, Np = _pad_pow2(T), _pad_pow2(N, floor=8)

    def pad_n(a):
        if a.shape[0] == Np:
            return a
        return np.concatenate(
            [a, np.zeros((Np - a.shape[0], *a.shape[1:]), a.dtype)], axis=0
        )

    def pad_t(a):
        if a.shape[0] == Tp:
            return a
        return np.concatenate(
            [a, np.zeros((Tp - a.shape[0], *a.shape[1:]), a.dtype)], axis=0
        )

    # recompile accounting (obs/jitstats.py): the compiled program is
    # keyed by the bucket (G, U, K) plus the padded axes XLA specializes
    # on — a first-seen key here IS a fresh trace+compile, the silent
    # stall the nhd_jit_* metrics make scrapeable
    JIT_STATS.record_use(
        "solve", f"G{pods.G}_U{cluster.U}_K{cluster.K}_T{Tp}_N{Np}"
    )
    solver = get_solver(pods.G, cluster.U, cluster.K)
    return solver(
        pad_n(cluster.numa_nodes), pad_n(cluster.smt), pad_n(cluster.active),
        pad_n(cluster.maintenance), pad_n(cluster.busy), pad_n(cluster.gpuless),
        pad_n(cluster.group_mask), pad_n(cluster.hp_free), pad_n(cluster.cpu_free),
        pad_n(cluster.gpu_free), pad_n(cluster.nic_count), pad_n(cluster.nic_free),
        pad_n(cluster.nic_sw), pad_n(cluster.gpu_free_sw),
        pad_t(pods.cpu_dem_smt), pad_t(pods.cpu_dem_raw), pad_t(pods.gpu_dem),
        pad_t(pods.rx), pad_t(pods.tx), pad_t(pods.hp), pad_t(pods.needs_gpu),
        pad_t(pods.map_pci), pad_t(pods.group_mask),
    )


def solve_bucket(cluster, pods, *, device=None) -> SolveOut:
    """Run the bucket solve for (ClusterArrays, PodTypeArrays) → SolveOut.

    Node and type axes are padded to power-of-two buckets so repeated solves
    against growing/shrinking batches reuse the jit cache (SURVEY §7 hard
    part 3: fixed-shape padding without recompiles). Padded node rows are
    inactive (never candidates); padded type rows are garbage the callers
    must slice off (outputs are [T, N] with the original sizes restored).
    """
    T, N = pods.n_types, cluster.n_nodes
    if device is not None:
        with jax.default_device(device):
            out = _solve_padded(cluster, pods)
    else:
        out = _solve_padded(cluster, pods)
    return SolveOut(*(x[:T, :N] if x.ndim == 2 else x for x in out))
