"""Streaming solver for federation-scale problems (BASELINE config 5).

The 100k-pod × 10k-node federation config must not materialize one giant
solve: this module tiles the *node axis* into fixed-size tiles (each a
region/cluster of the federation) and streams *pod chunks* through them —
the scheduler-domain analog of blockwise/ring long-axis techniques
(SURVEY §5.7: "block the node axis across devices, stream pod batches
through").

Memory is bounded by (tile_nodes × encode width) + (chunk_pods ×
bookkeeping): each tile owns a persistent ScheduleContext (packed arrays +
FastCluster + device-resident, mesh-sharded state), so a chunk visiting a
tile pays only for the rows it claims, never a re-encode. Within one
device, tiles stream sequentially; on a multi-device mesh each tile's
solve is itself sharded over the mesh (solver/batch.py auto-mesh), so the
two axes compose: tiles over time, nodes-within-tile over devices.

Placement semantics: pods visit tiles in name order and fill earlier
tiles first — the same first-fit shape the reference's sequential walk
produces over one big node list (Matcher.py:393-421 picks the first
candidate), realized tile-by-tile. Every claim is re-verified against
live state exactly as in BatchScheduler; serializability per node is
unchanged. One documented deviation: the gpuless-node selection
preference (Matcher.py:404-416) applies *within* a tile, not globally —
a CPU-only pod takes a feasible GPU node in an early tile rather than a
gpuless node in a later one. That is the federation-locality trade-off
(earlier tiles = nearer regions); on homogeneous clusters placement is
identical to the untiled scheduler (tests/test_streaming.py). Combo-
oversized pods (bucket_tractable=False) take the serial oracle pre-pass
against the full cluster, mirroring BatchScheduler's documented
oversized-first exception.
"""

from __future__ import annotations

import time
from typing import Dict, List, Optional, Sequence, Tuple

from nhd_tpu.core.node import HostNode
from nhd_tpu.core.topology import MapMode
from nhd_tpu.solver.batch import (
    BatchAssignment,
    BatchItem,
    BatchScheduler,
    BatchStats,
    ScheduleContext,
)
from nhd_tpu.solver.encode import cluster_dims
from nhd_tpu.solver.kernel import bucket_tractable
from nhd_tpu.utils import get_logger


class StreamingScheduler:
    """Tile the node axis, stream pod chunks through the tiles.

    ``tile_nodes`` bounds the per-solve node count (encode + solve memory);
    ``chunk_pods`` bounds the per-call pod bookkeeping. Remaining keyword
    arguments configure the underlying BatchScheduler (respect_busy,
    use_fast, mesh, ...).
    """

    def __init__(
        self,
        *,
        tile_nodes: int = 2048,
        chunk_pods: int = 16384,
        **batch_kwargs,
    ):
        if tile_nodes < 1 or chunk_pods < 1:
            raise ValueError("tile_nodes and chunk_pods must be >= 1")
        self.logger = get_logger(__name__)
        self.tile_nodes = tile_nodes
        self.chunk_pods = chunk_pods
        self.batch = BatchScheduler(**batch_kwargs)

    def schedule(
        self,
        nodes: Dict[str, HostNode],
        items: Sequence[BatchItem],
        *,
        now: Optional[float] = None,
    ) -> Tuple[List[BatchAssignment], BatchStats]:
        """Place every item it can; mutates ``nodes``. Same contract as
        BatchScheduler.schedule (apply semantics only)."""
        if now is None:
            now = time.monotonic()
        t_stream = time.perf_counter()

        stats = BatchStats()
        results: List[BatchAssignment] = [
            BatchAssignment(it.key, None) for it in items
        ]
        schedulable = [
            i for i, it in enumerate(items)
            if it.request.map_mode in (MapMode.NUMA, MapMode.PCI)
        ]

        # node tiles in name-insertion order (the reference's iteration
        # order): tile boundaries never split the first-fit preference,
        # because earlier tiles are exhausted before later ones are offered
        names = list(nodes.keys())
        tiles: List[Dict[str, HostNode]] = [
            {n: nodes[n] for n in names[i : i + self.tile_nodes]}
            for i in range(0, len(names), self.tile_nodes)
        ]

        # oversized pre-pass against the FULL cluster (tiles would hide
        # feasible nodes from the serial oracle) — BatchScheduler's
        # oversized-first exception, applied before any tile context exists
        # so serial claims are visible in every tile's encode below.
        # Tractability is judged at the worst-case (globally maximal) U/K —
        # the same rule every tile's encode uses (encode.cluster_dims), so
        # nothing deemed tractable here can be oversized inside a tile.
        U, K, _ = cluster_dims(nodes)
        oversized = [
            i for i in schedulable
            if not bucket_tractable(items[i].request.n_groups, U, K)
        ]
        if oversized:
            self.batch._schedule_serial(
                nodes, items, oversized, results, stats, now, True
            )
            ov = set(oversized)
            schedulable = [i for i in schedulable if i not in ov]
            stats.round_end_seconds.append(time.perf_counter() - t_stream)
            for i in oversized:
                if results[i].node is not None:
                    results[i].round_no = len(stats.round_end_seconds) - 1

        contexts: List[Optional[ScheduleContext]] = [None] * len(tiles)
        # per-tile saturation certificates: a request type that came back
        # unschedulable from a tile stays unschedulable there for the rest
        # of this call (resources only shrink within one schedule()), so
        # later chunks skip the futile solve. Terminal assignment failures
        # (r.failed) are NOT certified — they had a candidate.
        exhausted: List[set] = [set() for _ in tiles]

        for lo in range(0, len(schedulable), self.chunk_pods):
            chunk = schedulable[lo : lo + self.chunk_pods]
            pending = list(chunk)
            for ti, tile in enumerate(tiles):
                if not pending:
                    break
                offer = []
                for i in pending:
                    if items[i].request in exhausted[ti]:
                        # the certificate stands in for the tile's verdict
                        # ("no candidate", not a hard failure) so a stale
                        # failed=True from an earlier tile can't leak into
                        # the final stats
                        results[i] = BatchAssignment(items[i].key, None)
                    else:
                        offer.append(i)
                if not offer:
                    continue
                if contexts[ti] is None:
                    contexts[ti] = self.batch.make_context(tile, now=now)
                sub_items = [items[i] for i in offer]
                t_sub = time.perf_counter()
                sub_results, sub_stats = self.batch.schedule(
                    tile, sub_items, now=now, context=contexts[ti]
                )
                # merge: remap round numbers into the streaming timeline
                offset = len(stats.round_end_seconds)
                shift = t_sub - t_stream
                stats.round_end_seconds.extend(
                    t + shift for t in sub_stats.round_end_seconds
                )
                stats.rounds += sub_stats.rounds
                stats.solve_seconds += sub_stats.solve_seconds
                stats.select_seconds += sub_stats.select_seconds
                stats.assign_seconds += sub_stats.assign_seconds
                stats.scheduled += sub_stats.scheduled
                # NOT sub_stats.failed: a pod failing its first-on-node
                # claim in one tile is re-offered to later tiles, so
                # per-tile failure counts would double-book; terminal
                # failures are recounted from result flags at the end

                # a no-candidate verdict is only a saturation certificate
                # when the batch loop ended by exhausting candidates, not
                # by hitting the round cap (a capped run can leave feasible
                # pods unplaced mid-retry)
                certify = sub_stats.rounds < self.batch.max_rounds
                placed_here: set = set()
                for pod_i, r in zip(offer, sub_results):
                    if r.node is None:
                        # carry the latest tile's verdict (failed flag) so
                        # the final stats can distinguish assignment
                        # failure from plain unschedulability
                        results[pod_i] = r
                        if certify and not r.failed:
                            exhausted[ti].add(items[pod_i].request)
                        continue
                    if r.round_no >= 0:
                        r = BatchAssignment(
                            r.key, r.node, r.mapping, r.nic_list,
                            r.round_no + offset,
                        )
                    results[pod_i] = r
                    placed_here.add(pod_i)
                pending = [i for i in pending if i not in placed_here]
            if pending:
                self.logger.info(
                    f"streaming: {len(pending)} pods of chunk "
                    f"{lo // self.chunk_pods} unschedulable after "
                    f"{len(tiles)} tiles"
                )
        # stats.failed so far counts only the serial pre-pass (never
        # retried); add pods whose final tile verdict was a hard failure
        stats.failed += sum(
            1 for i in schedulable
            if results[i].node is None and results[i].failed
        )
        return results, stats
