"""Streaming solver for federation-scale problems (BASELINE config 5).

The 100k-pod × 10k-node federation config must not materialize one giant
solve: this module tiles the *node axis* into fixed-size tiles (each a
region/cluster of the federation) and streams *pod chunks* through them —
the scheduler-domain analog of blockwise/ring long-axis techniques
(SURVEY §5.7: "block the node axis across devices, stream pod batches
through").

Memory is bounded by (tile_nodes × encode width) + (chunk_pods ×
bookkeeping): each tile owns a persistent ScheduleContext (packed arrays +
FastCluster + device-resident, mesh-sharded state), so a chunk visiting a
tile pays only for the rows it claims, never a re-encode. On a
multi-device mesh each tile's solve is itself sharded over the mesh
(solver/batch.py auto-mesh), so the two axes compose: tiles over time,
nodes-within-tile over devices.

Tiles PIPELINE (VERDICT r2 item 3 — the p99 cut): each tile is a pipeline
stage with its own FIFO of chunks; a chunk's leftover forwards to the
next tile's FIFO the moment the sub-call returns, so tile t works chunk c
while tile t+1 works chunk c-1's spill. Because one worker serves each
tile, a tile processes chunks strictly in arrival order over disjoint
node state — every per-tile claim stream is IDENTICAL to the serial
sweep's, so placement semantics are bit-for-bit unchanged; only the
wall-clock interleaving across tiles differs. Worker threads are capped
by NHD_STREAM_WORKERS (jax dispatch is thread-safe; the native assign
calls release the GIL).

Placement semantics: pods visit tiles in name order and fill earlier
tiles first — the same first-fit shape the reference's sequential walk
produces over one big node list (Matcher.py:393-421 picks the first
candidate), realized tile-by-tile. Every claim is re-verified against
live state exactly as in BatchScheduler; serializability per node is
unchanged. One documented deviation: the gpuless-node selection
preference (Matcher.py:404-416) applies *within* a tile, not globally —
a CPU-only pod takes a feasible GPU node in an early tile rather than a
gpuless node in a later one. That is the federation-locality trade-off
(earlier tiles = nearer regions); on homogeneous clusters placement is
identical to the untiled scheduler (tests/test_streaming.py). Combo-
oversized pods (bucket_tractable=False) take the serial oracle pre-pass
against the full cluster, mirroring BatchScheduler's documented
oversized-first exception.
"""

from __future__ import annotations

import contextlib
import os
import threading
import time
from collections import deque
from concurrent.futures import ThreadPoolExecutor
from typing import Dict, List, Optional, Sequence, Tuple

from nhd_tpu.core.node import HostNode
from nhd_tpu.core.topology import MapMode
from nhd_tpu.solver.batch import (
    BatchAssignment,
    BatchItem,
    BatchScheduler,
    BatchStats,
    ScheduleContext,
)
from nhd_tpu.solver.encode import cluster_dims
from nhd_tpu.solver.kernel import bucket_tractable
from nhd_tpu.utils import get_logger

# Serializes tile-worker mesh solves when the mesh is CPU-backed: on the
# host backend all "devices" are one process's threads, and two
# concurrent pjit SPMD solves interleave their per-device host
# collectives — on a low-core box neither solve's participants all get
# scheduled, so both rendezvous barriers wait forever (the tier-1
# streaming-mesh deadlock, ROADMAP open item; the cycle shape is kept as
# an nhdsan regression in tests/test_sanitizer.py). One solve in flight
# at a time always completes; real accelerator backends rendezvous in
# hardware and skip this lock entirely.
_CPU_MESH_SOLVE_LOCK = threading.Lock()


class StreamingScheduler:
    """Tile the node axis, stream pod chunks through the tiles.

    ``tile_nodes`` bounds the per-solve node count (encode + solve memory);
    ``chunk_pods`` bounds the per-call pod bookkeeping. Remaining keyword
    arguments configure the underlying BatchScheduler (respect_busy,
    use_fast, mesh, ...).
    """

    def __init__(
        self,
        *,
        tile_nodes: int = 2048,
        chunk_pods: int = 16384,
        placement: str = "first-fit",
        persistent: bool = False,
        **batch_kwargs,
    ):
        if tile_nodes < 1 or chunk_pods < 1:
            raise ValueError("tile_nodes and chunk_pods must be >= 1")
        if placement not in ("first-fit", "routed"):
            raise ValueError(
                f"placement must be 'first-fit' or 'routed', got {placement!r}"
            )
        self.logger = get_logger(__name__)
        self.tile_nodes = tile_nodes
        self.chunk_pods = chunk_pods
        # ``persistent``: keep every tile's ScheduleContext (packed
        # arrays + FastCluster + device-resident state) alive ACROSS
        # schedule() calls, maintained incrementally by a per-tile
        # ClusterDelta — the scheduler routes inter-call churn in via
        # note_nodes(), and each tile's first offer of a call folds its
        # noted rows in as patches + device row scatters instead of a
        # fresh make_context (O(tile) encode per tile per call → O(
        # changed rows)). Membership or interner-budget changes drop the
        # whole state (counted as delta rebuilds). Single-caller
        # contract: note_nodes/schedule run on the scheduler thread.
        # Solver-guard posture (solver/guard.py): each persistent tile
        # context reposturues at its first offer of a call — a
        # degradation condemns its resident plane down the mesh →
        # single-device → host ladder, a re-promotion rebuilds it from
        # host truth at the faster rung — via the same
        # make_context/refresh_context chokepoints the solo path uses;
        # a tile whose solve trips the guard terminally fails only its
        # own call (the errored call never banks its state).
        self.persistent = persistent
        self._pstate: Optional[dict] = None
        self._pstale: set = set()
        # 'first-fit': every chunk enters at tile 0 and spills forward —
        # placement identical to the serial sweep (and, on homogeneous
        # clusters, to the untiled scheduler). 'routed': pods are
        # pre-partitioned across tiles by estimated residual capacity and
        # the tiles run CONCURRENTLY (spill still cascades to the next
        # tile) — the federation posture (a pod has no inherent preference
        # for region 0) that turns the pipeline into real parallelism;
        # placement can differ from the serial sweep when estimates err,
        # conservation is unaffected (claims are re-verified as always).
        self.placement = placement
        self.batch = BatchScheduler(**batch_kwargs)

    def note_nodes(self, names) -> None:
        """An event touched these nodes: their tiles' persistent
        contexts patch the rows in at the next schedule() call."""
        if self.persistent:
            self._pstale.update(names)

    def reset_state(self) -> None:
        """Drop the persistent tile contexts (restart-grade mirror
        events: promotion replay, drift repair)."""
        self._pstate = None
        self._pstale.clear()

    def route_notes(self) -> None:
        """Fold pending inter-call churn notes into their owning tiles'
        deltas. schedule() calls this before refreshing contexts; the
        chaos parity invariant calls it so tile state is judged net of
        the note trail, not mid-flight. Notes naming nodes outside the
        persisted membership stay pending (that membership change
        condemns the whole state at the next schedule)."""
        ps = self._pstate
        if ps is None or not self._pstale:
            return
        tile_of = ps["tile_of"]
        keep = set()
        stale, self._pstale = self._pstale, set()
        for name in stale:
            ti = tile_of.get(name)
            if ti is None:
                keep.add(name)
            elif ps["deltas"][ti] is not None:
                ps["deltas"][ti].note(name)
        self._pstale |= keep

    @staticmethod
    def _batch_demand(items, indices) -> Tuple[float, float, float]:
        """Average per-pod (cores, gpus, hugepages) demand of the batch —
        computed ONCE per schedule() (walking 100k pods per tile showed
        up at ~0.7 s in the federation profile)."""
        n = len(indices)
        if n == 0:
            # sentinel read by _tile_capacity as "no demand → no capacity"
            return (0.0, 0.0, 0.0)
        cores = gpus = hp = 0
        for i in indices:
            req = items[i].request
            cores += req.misc.count
            for g in req.groups:
                cores += g.proc.count + g.misc.count
                gpus += g.gpus
            hp += req.hugepages_gb
        return (max(cores / n, 1e-6), gpus / n, hp / n)

    @staticmethod
    def _tile_capacity(
        tile: Dict[str, HostNode], demand: Tuple[float, float, float]
    ) -> int:
        """Estimated pod count *tile* can absorb for this batch: per-
        resource free totals over the batch's average per-pod demand,
        minimized across resources. Only balance matters — errors spill
        to the next tile."""
        avg_cores, avg_gpus, avg_hp = demand
        if avg_cores <= 0:
            return 0  # empty batch: no demand, report no capacity
        free_cores = free_gpus = free_hp = 0
        for node in tile.values():
            free_cores += node.free_cpu_core_count()
            free_gpus += node.free_gpu_count()
            free_hp += node.mem.free_hugepages_gb
        cap = free_cores / avg_cores
        if avg_gpus > 1e-6:
            cap = min(cap, free_gpus / avg_gpus)
        if avg_hp > 1e-6:
            cap = min(cap, free_hp / avg_hp)
        return max(int(cap), 0)

    def schedule(
        self,
        nodes: Dict[str, HostNode],
        items: Sequence[BatchItem],
        *,
        now: Optional[float] = None,
    ) -> Tuple[List[BatchAssignment], BatchStats]:
        """Place every item it can; mutates ``nodes``. Same contract as
        BatchScheduler.schedule (apply semantics only)."""
        if now is None:
            now = time.monotonic()
        t_stream = time.perf_counter()

        # pin the heap for the sweep: a federation-scale node mirror is
        # ~10M objects, and a major gc pass mid-run traverses all of them
        # (measured as multi-second stalls inside otherwise-tiny spill
        # sub-calls). GcPin gc.freeze()s the pre-existing heap AND
        # disables automatic collection for the sweep (young-gen
        # re-scans of the sweep's own result objects were ~50% of the
        # federation materialize phase); the next natural collection
        # after release reclaims the sweep's bounded garbage. GcPin
        # holds across every per-tile sub-call (their own acquire sees
        # it active and leaves gc alone). Small sweeps skip the pin —
        # see batch._gc_pinned for why per-call pinning of small
        # batches would starve generational collection.
        from nhd_tpu.solver.batch import _GC_PIN_MIN_ITEMS, GcPin

        held = (
            GcPin.acquire() if len(items) >= _GC_PIN_MIN_ITEMS else False
        )
        try:
            return self._schedule_inner(nodes, items, now, t_stream)
        finally:
            GcPin.release(held)

    def _schedule_inner(
        self,
        nodes: Dict[str, HostNode],
        items: Sequence[BatchItem],
        now: float,
        t_stream: float,
    ) -> Tuple[List[BatchAssignment], BatchStats]:
        stats = BatchStats()
        # results materialize lazily (sub-calls fill placed/verdict slots;
        # the rest back-fill before return) — building 100k placeholder
        # objects up front was measurable federation preamble
        results: List[Optional[BatchAssignment]] = [None] * len(items)
        schedulable = [
            i for i, it in enumerate(items)
            if it.request.map_mode in (MapMode.NUMA, MapMode.PCI)
        ]

        # node tiles in name-insertion order (the reference's iteration
        # order): tile boundaries never split the first-fit preference,
        # because earlier tiles are exhausted before later ones are offered.
        # (Group-sorting tiles to align with regions was tried and measured
        # WORSE on interleaved-group clusters: each pod then has exactly
        # one compatible tile of exactly-matching capacity, and the lost
        # spill alternatives cost contention-retry rounds.)
        names = list(nodes.keys())
        ps = self._pstate if self.persistent else None
        if ps is not None and (
            ps["names"] != names
            or any(
                nodes[n] is not node
                for tile in ps["tiles"]
                for n, node in tile.items()
            )
        ):
            # membership (or the node objects behind it) changed: the
            # persistent tile contexts have nothing stable to patch
            ps = self._pstate = None
            self._pstale.clear()
        if ps is not None:
            tiles: List[Dict[str, HostNode]] = ps["tiles"]
        else:
            tiles = [
                {n: nodes[n] for n in names[i : i + self.tile_nodes]}
                for i in range(0, len(names), self.tile_nodes)
            ]
        if not tiles:
            # empty node set (e.g. a multihost rank whose region slice is
            # empty): everything stays unschedulable, like the serial
            # sweep that simply had no tiles to visit
            return (
                [BatchAssignment(it.key, None) for it in items], stats
            )
        # per-tile union of node groups: a pod with no group overlap can
        # skip the tile without a solve (same predicate the solver's
        # group_mask lattice applies, hoisted to the offer). No-op on
        # interleaved-group clusters; wins on naturally region-partitioned
        # federations.
        tile_groups: List[frozenset] = [
            frozenset().union(*(set(n.groups) for n in tile.values()))
            for tile in tiles
        ]

        # oversized pre-pass against the FULL cluster (tiles would hide
        # feasible nodes from the serial oracle) — BatchScheduler's
        # oversized-first exception, applied before any tile context exists
        # so serial claims are visible in every tile's encode below.
        # Tractability is judged at the worst-case (globally maximal) U/K —
        # the same rule every tile's encode uses (encode.cluster_dims), so
        # nothing deemed tractable here can be oversized inside a tile.
        U, K, _ = cluster_dims(nodes)
        # tractability memoized per group count (one bucket verdict
        # covers a whole gang): the per-pod power computation was 0.26 s
        # of serial preamble at the 100k federation scale
        _tract: Dict[int, bool] = {}
        oversized = []
        for i in schedulable:
            G = items[i].request.n_groups
            v = _tract.get(G)
            if v is None:
                v = _tract[G] = bucket_tractable(G, U, K)
            if not v:
                oversized.append(i)
        if oversized:
            touched = self.batch._schedule_serial(
                nodes, items, oversized, results, stats, now, True
            )
            ov = set(oversized)
            schedulable = [i for i in schedulable if i not in ov]
            # persistent tile contexts may already exist (prior calls):
            # their touched rows (winners + busy-stamped failures) fold
            # in as deltas at the context refresh below, exactly like
            # any other inter-batch churn
            self.note_nodes(touched)
            stats.round_end_seconds.append(time.perf_counter() - t_stream)
            for i in oversized:
                if results[i] is not None and results[i].node is not None:
                    results[i] = results[i]._replace(
                        round_no=len(stats.round_end_seconds) - 1
                    )

        # one interner shared by every tile context so a chunk's pod
        # encode (group_mask bit positions) is valid against all of them
        # — each chunk is encoded ONCE and re-offered to successive tiles
        # via schedule(encoded=..., offer=...) instead of re-encoding
        # (and re-hashing) the leftovers per tile. Sharing turns the
        # 63-bit group-mask budget federation-wide, so it only engages
        # when the whole batch's distinct groups fit with margin;
        # otherwise every sub-call encodes per tile exactly as before.
        # Eligible groups are pre-interned here, SORTED, so worker-side
        # encodes never mutate the interner (no lock; deterministic bits).
        from nhd_tpu.solver.encode import GroupInterner, encode_pods

        all_groups = set().union(frozenset(), *tile_groups)
        for i in schedulable:
            all_groups |= items[i].request.node_groups
        share_enc = len(all_groups) <= 48
        interner = None
        if ps is not None and (
            ps["share_enc"] != share_enc
            or (
                share_enc
                and not ps["interner"].known(all_groups)
                and ps["interner"].n_bits + len(all_groups) > 56
            )
        ):
            # encode-sharing mode flipped, or the persisted interner
            # would overflow its bit budget absorbing this batch's new
            # groups — rebuild the tile state from scratch
            ps = self._pstate = None
            self._pstale.clear()
        if share_enc and ps is not None:
            # reuse the persisted interner (tile arrays bake its bit
            # positions); new groups intern HERE, sorted, on the main
            # thread — workers still never mutate it
            interner = ps["interner"]
            interner.mask(sorted(all_groups))
        elif share_enc:
            interner = GroupInterner()
            interner.mask(sorted(all_groups))
        # per-chunk encode cache: cid -> (items, buckets, global->local);
        # a chunk lives in exactly one tile queue at a time, so per-cid
        # calls never race
        chunk_enc: Dict[int, tuple] = {}

        def chunk_encoded(cid: int, global_ids: List[int]):
            """First call (the chunk's first tile offer) encodes the full
            chunk; later offers are shrinking subsets of the same ids and
            hit the cache."""
            got = chunk_enc.get(cid)
            if got is None:
                sub_items = [items[g] for g in global_ids]
                buckets = encode_pods(
                    [it.request for it in sub_items], interner
                )
                got = chunk_enc[cid] = (
                    sub_items,
                    buckets,
                    {g: j for j, g in enumerate(global_ids)},
                )
            return got

        # CPU-backed mesh: one per-tile schedule() sub-call in flight at
        # a time (module docstring + _CPU_MESH_SOLVE_LOCK). The gate is
        # deliberately coarse — it wraps the whole sub-call, host-side
        # select/assign included, because only the batch internals know
        # where the collective-bearing solves sit; chunk encode, the
        # group-overlap offer filter and spill forwarding still overlap
        # across tiles. Real accelerators skip the gate entirely.
        serialize_mesh = False
        try:
            mesh = self.batch._resolve_mesh()
            serialize_mesh = mesh is not None and all(
                getattr(d, "platform", None) == "cpu"
                for d in mesh.devices.flat
            )
        except Exception:
            serialize_mesh = False
        solve_gate = (
            _CPU_MESH_SOLVE_LOCK if serialize_mesh
            else contextlib.nullcontext()
        )

        if ps is not None:
            contexts: List[Optional[ScheduleContext]] = ps["ctxs"]
            deltas = ps["deltas"]
            # route inter-call churn notes to their owning tiles' deltas
            # (a tile with no built context yet has nothing to patch —
            # its eventual make_context reads live nodes)
            self.route_notes()
        else:
            contexts = [None] * len(tiles)
            deltas = [None] * len(tiles)
            self._pstale.clear()
        # persistent contexts refresh ONCE per call, at their first
        # offer (busy decay + noted rows fold in); within-call reuse
        # needs none — claims maintain the arrays as they apply. Each
        # slot is only touched by its tile's single worker.
        refreshed = [False] * len(tiles)
        # per-tile saturation certificates: a request type that came back
        # unschedulable from a tile stays unschedulable there for the rest
        # of this call (resources only shrink within one schedule()), so
        # later chunks skip the futile solve. Terminal assignment failures
        # (r.failed) are NOT certified — they had a candidate.
        exhausted: List[set] = [set() for _ in tiles]

        # ---- tile pipeline ----
        # Each tile is a stage with a FIFO of (chunk id, pending pods);
        # one worker serves a tile at a time, so per-tile claim streams
        # are identical to the serial sweep's (see module docstring).
        lock = threading.Lock()
        done = threading.Condition(lock)
        tile_q: List[deque] = [deque() for _ in tiles]
        tile_busy = [False] * len(tiles)
        outstanding = 0          # queued + running work items
        errors: List[BaseException] = []

        def process(ti: int, chunk_id: int, pending: List[int]) -> List[int]:
            """One (tile, chunk) sub-call; returns the leftover pods."""
            offer = []
            tg = tile_groups[ti]
            for i in pending:
                req = items[i].request
                if not (req.node_groups & tg):
                    # no node in this tile shares a group with the pod:
                    # skip the solve entirely (stays pending, forwards on)
                    continue
                if req in exhausted[ti]:
                    # the certificate stands in for the tile's verdict
                    # ("no candidate", not a hard failure) so a stale
                    # failed=True from an earlier tile can't leak into
                    # the final stats
                    results[i] = BatchAssignment(items[i].key, None)
                else:
                    offer.append(i)
            if not offer:
                return pending
            if contexts[ti] is None:
                with solve_gate:
                    if self.persistent:
                        from nhd_tpu.solver.encode import ClusterDelta

                        deltas[ti] = ClusterDelta(
                            tiles[ti], now=now, interner=interner,
                            respect_busy=self.batch.respect_busy,
                        )
                        contexts[ti] = self.batch.make_context(
                            tiles[ti], now=now, delta=deltas[ti]
                        )
                    else:
                        contexts[ti] = self.batch.make_context(
                            tiles[ti], now=now, interner=interner
                        )
                refreshed[ti] = True
            elif not refreshed[ti]:
                # a persistent context from an earlier call: fold the
                # inter-call churn in (row patches + device scatters)
                with solve_gate:
                    self.batch.refresh_context(contexts[ti], now=now)
                refreshed[ti] = True
            # delta-built contexts solve over their row-aligned view
            # dict; plain contexts' nodes IS tiles[ti]
            sub_nodes = contexts[ti].nodes
            t_sub = time.perf_counter()
            if share_enc:
                sub_items, encoded, local_of = chunk_encoded(
                    chunk_id, pending
                )
                # the chunk's FIRST full offer has identity locals
                # (local_of maps the same global_ids in order) — skip the
                # two 100k-element remap comprehensions for it
                identity = len(offer) == len(sub_items)
                with solve_gate:
                    sub_results, sub_stats = self.batch.schedule(
                        sub_nodes, sub_items, now=now, context=contexts[ti],
                        encoded=encoded,
                        offer=(
                            None if identity
                            else [local_of[i] for i in offer]
                        ),
                    )
                if not identity:
                    sub_results = [sub_results[local_of[i]] for i in offer]
            else:
                # >48 distinct groups: per-tile interners, per-offer
                # encode (the pre-sharing behavior)
                sub_items = [items[i] for i in offer]
                with solve_gate:
                    sub_results, sub_stats = self.batch.schedule(
                        sub_nodes, sub_items, now=now, context=contexts[ti]
                    )
            # merge: remap round numbers into the streaming timeline
            with lock:
                offset = len(stats.round_end_seconds)
                shift = t_sub - t_stream
                stats.round_end_seconds.extend(
                    t + shift for t in sub_stats.round_end_seconds
                )
                stats.rounds += sub_stats.rounds
                stats.solve_seconds += sub_stats.solve_seconds
                stats.select_seconds += sub_stats.select_seconds
                stats.assign_seconds += sub_stats.assign_seconds
                stats.scheduled += sub_stats.scheduled
                for name, dt in sub_stats.phases.items():
                    stats.phase_add(name, dt)
                for name, k in sub_stats.counters.items():
                    stats.count_add(name, k)
                # NOT sub_stats.failed: a pod failing its first-on-node
                # claim in one tile is re-offered to later tiles, so
                # per-tile failure counts would double-book; terminal
                # failures are recounted from result flags at the end

            # a no-candidate verdict is only a saturation certificate
            # when the batch loop ended by exhausting candidates, not
            # by hitting the round cap (a capped run can leave feasible
            # pods unplaced mid-retry)
            certify = sub_stats.rounds < self.batch.max_rounds
            placed_here: set = set()
            for pod_i, r in zip(offer, sub_results):
                if r.node is None:
                    # carry the latest tile's verdict (failed flag) so
                    # the final stats can distinguish assignment
                    # failure from plain unschedulability
                    results[pod_i] = r
                    if certify and not r.failed:
                        exhausted[ti].add(items[pod_i].request)
                    continue
                if r.round_no >= 0 and offset:
                    # remap the sub-call round into the streaming timeline;
                    # the first sub-call (offset 0) needs no remap, and at
                    # federation scale 100k reconstructions are real wall
                    r = BatchAssignment(
                        r.key, r.node, r.mapping, r.nic_list,
                        r.round_no + offset,
                    )
                results[pod_i] = r
                placed_here.add(pod_i)
            if len(placed_here) == len(pending):
                return []  # common case: whole chunk landed in this tile
            return [i for i in pending if i not in placed_here]

        def run_tile(ti: int) -> None:
            nonlocal outstanding
            while True:
                with lock:
                    if errors or not tile_q[ti]:
                        tile_busy[ti] = False
                        if errors:
                            outstanding -= len(tile_q[ti])
                            tile_q[ti].clear()
                        done.notify_all()
                        return
                    chunk_id, pending, hops = tile_q[ti].popleft()
                try:
                    leftover = process(ti, chunk_id, pending)
                except BaseException as exc:
                    with lock:
                        errors.append(exc)
                        outstanding -= 1
                        tile_busy[ti] = False
                        done.notify_all()
                    return
                submit_next = False
                with lock:
                    outstanding -= 1
                    # spill forwarding: first-fit stops at the last tile;
                    # routed wraps so a mis-routed pod still visits every
                    # tile exactly once (hops counts tiles seen)
                    nxt = ti + 1
                    if self.placement == "routed":
                        nxt = (ti + 1) % len(tiles)
                    if leftover and hops + 1 < len(tiles) and nxt < len(tiles):
                        outstanding += 1
                        tile_q[nxt].append((chunk_id, leftover, hops + 1))
                        if not tile_busy[nxt]:
                            # reserve the wake-up under the lock, submit
                            # outside it: Executor.submit can block in
                            # Thread.start() while spinning up a worker,
                            # and holding the pipeline lock across that
                            # wait stalls every other stage (nhdsan
                            # hold-while-blocking witness)
                            tile_busy[nxt] = True
                            submit_next = True
                    elif leftover:
                        self.logger.info(
                            f"streaming: {len(leftover)} pods of chunk "
                            f"{chunk_id} unschedulable after "
                            f"{len(tiles)} tiles"
                        )
                    if outstanding == 0:
                        done.notify_all()
                if submit_next:
                    pool.submit(run_tile, nxt)

        # default workers: on an accelerator, 4 regardless of core count —
        # tile stages spend much of their wall blocked on relay flushes
        # (GIL released), so concurrent stages overlap those waits even
        # on a 1-core host (measured cfg5 6.1→5.7 s r4). On the CPU
        # backend the host-side spans keep shrinking (r8 fused solve;
        # r9 memoized winner materialization) while XLA's own thread
        # pool already spreads each solve across the cores — so extra
        # pipeline workers now buy GIL contention, not overlap: measured
        # cfg5 on a 2-core box, r8: 4 workers 4.87 s vs 2 workers
        # 4.47 s; r9: 2 workers 4.37 s vs ONE worker 3.75 s with every
        # host phase halving (no interleave inflation). Default to one
        # worker per two cores, floor 1.
        import jax

        try:
            accel = jax.default_backend() != "cpu"
        except Exception:
            accel = False
        default_workers = (
            4 if accel else min(4, max(1, (os.cpu_count() or 2) // 2))
        )
        n_workers = max(
            1,
            min(
                len(tiles),
                int(os.environ.get("NHD_STREAM_WORKERS", default_workers)),
            ),
        )
        # initial work distribution: first-fit feeds every chunk to tile 0
        # (strict spill order); routed pre-partitions pods across tiles in
        # proportion to estimated residual capacity so the tiles run
        # concurrently from t=0
        start_blocks: List[Tuple[int, List[int]]] = []  # (tile, pod indices)
        if self.placement == "routed" and len(tiles) > 1:
            demand = self._batch_demand(items, schedulable)
            caps = [
                self._tile_capacity(tile, demand) for tile in tiles
            ]
            # group-aware routing: each pod only goes to tiles whose node
            # groups intersect its own, split by capacity share within
            # those; mis-splits spill through the wrap-around cascade
            from collections import defaultdict

            by_gkey: Dict[frozenset, List[int]] = defaultdict(list)
            for i in schedulable:
                by_gkey[items[i].request.node_groups].append(i)
            blocks: List[List[int]] = [[] for _ in tiles]
            for gkey, idxs in by_gkey.items():
                comp = [
                    t for t in range(len(tiles)) if gkey & tile_groups[t]
                ] or list(range(len(tiles)))
                w = [max(caps[t], 1) for t in comp]
                total = sum(w)
                acc = 0
                lo = 0
                for pos, t in enumerate(comp):
                    acc += w[pos]
                    hi = (
                        len(idxs) if pos == len(comp) - 1
                        else min(len(idxs), round(len(idxs) * acc / total))
                    )
                    blocks[t].extend(idxs[lo:hi])
                    lo = hi
            for ti, block in enumerate(blocks):
                if block:
                    block.sort()  # keep pod-index claim order per tile
                    start_blocks.append((ti, block))
        else:
            start_blocks.append((0, schedulable))

        with ThreadPoolExecutor(
            max_workers=n_workers, thread_name_prefix="nhd-stream"
        ) as pool:
            to_start: List[int] = []
            with lock:
                cid = 0
                for ti, block in start_blocks:
                    for lo in range(0, len(block), self.chunk_pods):
                        tile_q[ti].append(
                            (cid, list(block[lo : lo + self.chunk_pods]), 0)
                        )
                        outstanding += 1
                        cid += 1
                    if tile_q[ti] and not tile_busy[ti]:
                        tile_busy[ti] = True
                        to_start.append(ti)
            # submit outside the lock (same reasoning as run_tile's spill
            # forwarding): tile_busy reserved the wake-ups, so no other
            # thread can double-submit these tiles
            for ti in to_start:
                pool.submit(run_tile, ti)
            with lock:
                while outstanding > 0 and not errors:
                    done.wait()
        if errors:
            raise errors[0]
        if self.persistent and self._pstate is None:
            # bank this call's tile contexts for the next one (an errored
            # call never saves — it rebuilds from the live mirror)
            self._pstate = {
                "names": names,
                "tiles": tiles,
                "tile_of": {
                    n: ti for ti, tile in enumerate(tiles) for n in tile
                },
                "ctxs": contexts,
                "deltas": deltas,
                "share_enc": share_enc,
                "interner": interner,
            }
        # back-fill the lazy result slots (never-offered / unplaced pods)
        for i, it in enumerate(items):
            if results[i] is None:
                results[i] = BatchAssignment(it.key, None)
        # stats.failed so far counts only the serial pre-pass (never
        # retried); add pods whose final tile verdict was a hard failure
        stats.failed += sum(
            1 for i in schedulable
            if results[i].node is None and results[i].failed
        )
        return results, stats
