"""Dense tensor encodings of cluster state and pod batches.

Host-side HostNode objects stay the source of truth (SURVEY §5.4 stance:
device state must always be re-derivable from host state); this module
projects them into packed numpy arrays the jitted solver consumes, and
dedupes a pod batch into *types* — identical PodRequests share one solver
row, which is what makes gang batches (a TriadSet scaling to thousands of
replicas, BASELINE config 4) cheap: feasibility is O(types × nodes), not
O(pods × nodes).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from nhd_tpu.core.node import HostNode
from nhd_tpu.core.request import PodRequest
from nhd_tpu.core.topology import MapMode

MAX_GROUP_BITS = 63  # node-group bitmask width (int64, sign bit unused)


class GroupInterner:
    """Node-group names → bit positions, shared across cluster and pods."""

    def __init__(self) -> None:
        self._bits: Dict[str, int] = {}

    def mask(self, names) -> int:
        m = 0
        for name in names:
            bit = self._bits.get(name)
            if bit is None:
                bit = len(self._bits)
                if bit >= MAX_GROUP_BITS:
                    raise ValueError(
                        f"more than {MAX_GROUP_BITS} distinct node groups"
                    )
                self._bits[name] = bit
            m |= 1 << bit
        return m


@dataclass
class ClusterArrays:
    """Packed per-node state. Shapes: N nodes, U NUMA (padded), K NICs/NUMA
    (padded), S PCIe switches per node (padded)."""

    names: List[str]
    U: int
    K: int
    S: int
    numa_nodes: np.ndarray     # [N] int8
    smt: np.ndarray            # [N] bool
    active: np.ndarray         # [N] bool
    maintenance: np.ndarray    # [N] bool
    busy: np.ndarray           # [N] bool (pre-resolved against `now`)
    gpuless: np.ndarray        # [N] bool — node has zero GPUs total
    group_mask: np.ndarray     # [N] int64
    hp_free: np.ndarray        # [N] int32
    cpu_free: np.ndarray       # [N, U] int32 — fully-free physical cores
    gpu_free: np.ndarray       # [N, U] int32
    nic_count: np.ndarray      # [N, U] int32
    nic_free: np.ndarray       # [N, U, K, 2] float32 — rx/tx headroom Gbps
    nic_sw: np.ndarray         # [N, U, K] int32 — dense per-node switch id, -1 none
    gpu_free_sw: np.ndarray    # [N, S] int32 — free GPUs per dense switch id
    interner: GroupInterner = field(default_factory=GroupInterner)

    @property
    def n_nodes(self) -> int:
        return len(self.names)


def cluster_dims(nodes) -> Tuple[int, int, int]:
    """(U, K, S) padding dims for a node collection: max NUMA nodes, max
    NICs per NUMA, max PCIe switches per node. The single source of the
    rule — streaming's oversized routing (solver/streaming.py) must judge
    tractability with exactly the dims the tile encodes will use."""
    nl = list(nodes.values()) if isinstance(nodes, dict) else list(nodes)
    U = max((n.numa_nodes for n in nl), default=1) or 1
    K = 1
    S = 1
    for node in nl:
        per_numa = [0] * node.numa_nodes
        for nic in node.nics:
            if nic.numa_node < node.numa_nodes:
                per_numa[nic.numa_node] += 1
        K = max(K, max(per_numa, default=0))
        switches = {g.pciesw for g in node.gpus} | {n.pciesw for n in node.nics}
        S = max(S, len(switches))
    return U, K, S


def encode_cluster(
    nodes: Dict[str, HostNode],
    *,
    now: Optional[float] = None,
    interner: Optional[GroupInterner] = None,
) -> ClusterArrays:
    """Project HostNodes into dense arrays (one row per node, name order =
    dict insertion order = the reference's node iteration order)."""
    names = list(nodes.keys())
    nl = [nodes[n] for n in names]
    N = len(nl)
    U, K, S = cluster_dims(nl)

    interner = interner or GroupInterner()
    arr = ClusterArrays(
        names=names, U=U, K=K, S=S,
        numa_nodes=np.zeros(N, np.int8),
        smt=np.zeros(N, bool),
        active=np.zeros(N, bool),
        maintenance=np.zeros(N, bool),
        busy=np.zeros(N, bool),
        gpuless=np.zeros(N, bool),
        group_mask=np.zeros(N, np.int64),
        hp_free=np.zeros(N, np.int32),
        cpu_free=np.zeros((N, U), np.int32),
        gpu_free=np.zeros((N, U), np.int32),
        nic_count=np.zeros((N, U), np.int32),
        nic_free=np.full((N, U, K, 2), -1.0, np.float32),
        nic_sw=np.full((N, U, K), -1, np.int32),
        gpu_free_sw=np.zeros((N, S), np.int32),
        interner=interner,
    )
    for i, node in enumerate(nl):
        refresh_node_row(arr, i, node, now=now)
    return arr


def refresh_node_row(
    arr: ClusterArrays, i: int, node: HostNode, *, now: Optional[float] = None
) -> None:
    """Re-project one node into row *i* (incremental update path)."""
    U, K, S = arr.U, arr.K, arr.S
    arr.numa_nodes[i] = node.numa_nodes
    arr.smt[i] = node.smt_enabled
    arr.active[i] = node.active
    arr.maintenance[i] = node.maintenance
    arr.busy[i] = node.is_busy(now)
    arr.gpuless[i] = node.total_gpus() == 0
    arr.group_mask[i] = arr.interner.mask(node.groups)
    arr.hp_free[i] = node.mem.free_hugepages_gb

    arr.cpu_free[i] = 0
    cpu = node.free_cpu_cores_per_numa()
    arr.cpu_free[i, : len(cpu)] = cpu

    arr.gpu_free[i] = 0
    gpu = node.free_gpus_per_numa()
    arr.gpu_free[i, : len(gpu)] = gpu

    arr.nic_count[i] = 0
    arr.nic_free[i] = -1.0
    arr.nic_sw[i] = -1

    # dense per-node PCIe switch ids, in sorted order for determinism
    switches = sorted({g.pciesw for g in node.gpus} | {n.pciesw for n in node.nics})
    sw_id = {sw: j for j, sw in enumerate(switches)}

    for nic in node.nics:
        u, k = nic.numa_node, nic.idx
        if u >= U or k >= K:
            continue
        rx, tx = nic.free_bw()
        arr.nic_free[i, u, k, 0] = rx
        arr.nic_free[i, u, k, 1] = tx
        arr.nic_sw[i, u, k] = sw_id[nic.pciesw]
        arr.nic_count[i, u] = max(arr.nic_count[i, u], k + 1)

    arr.gpu_free_sw[i] = 0
    for g in node.gpus:
        if not g.used and sw_id.get(g.pciesw, S) < S:
            arr.gpu_free_sw[i, sw_id[g.pciesw]] += 1


@dataclass
class PodTypeArrays:
    """Deduped pod-type tensors for one group-count bucket (G groups)."""

    G: int
    requests: List[PodRequest]      # one exemplar per type, type order
    pod_type: np.ndarray            # [P] int32 — type index of each input pod
    pod_index: np.ndarray           # [P] int64 — original batch positions
    cpu_dem_smt: np.ndarray         # [T, G+1] int32 (node-SMT-enabled demand)
    cpu_dem_raw: np.ndarray         # [T, G+1] int32
    gpu_dem: np.ndarray             # [T, G] int32
    rx: np.ndarray                  # [T, G] float32
    tx: np.ndarray                  # [T, G] float32
    hp: np.ndarray                  # [T] int32
    needs_gpu: np.ndarray           # [T] bool
    map_pci: np.ndarray             # [T] bool
    group_mask: np.ndarray          # [T] int64

    @property
    def n_types(self) -> int:
        return len(self.requests)


def encode_pods(
    pods: Sequence[PodRequest],
    interner: GroupInterner,
    indices: Optional[Sequence[int]] = None,
) -> Dict[int, PodTypeArrays]:
    """Bucket a pod batch by group count and dedupe identical requests into
    types. Returns {n_groups: PodTypeArrays}."""
    if indices is None:
        indices = range(len(pods))
    buckets: Dict[int, Tuple[List[PodRequest], List[int], List[int], Dict[PodRequest, int]]] = {}
    for pod, idx in zip(pods, indices):
        G = pod.n_groups
        reqs, types, positions, seen = buckets.setdefault(G, ([], [], [], {}))
        t = seen.get(pod)
        if t is None:
            t = len(reqs)
            seen[pod] = t
            reqs.append(pod)
        types.append(t)
        positions.append(idx)

    out: Dict[int, PodTypeArrays] = {}
    for G, (reqs, types, positions, _) in buckets.items():
        T = len(reqs)
        arr = PodTypeArrays(
            G=G,
            requests=reqs,
            pod_type=np.asarray(types, np.int32),
            pod_index=np.asarray(positions, np.int64),
            cpu_dem_smt=np.zeros((T, G + 1), np.int32),
            cpu_dem_raw=np.zeros((T, G + 1), np.int32),
            gpu_dem=np.zeros((T, G), np.int32),
            rx=np.zeros((T, G), np.float32),
            tx=np.zeros((T, G), np.float32),
            hp=np.zeros(T, np.int32),
            needs_gpu=np.zeros(T, bool),
            map_pci=np.zeros(T, bool),
            group_mask=np.zeros(T, np.int64),
        )
        for t, r in enumerate(reqs):
            arr.cpu_dem_smt[t] = r.cpu_slot_counts(node_smt=True)
            arr.cpu_dem_raw[t] = r.cpu_slot_counts(node_smt=False)
            arr.gpu_dem[t] = r.gpu_counts()
            for g, (rx, tx) in enumerate(r.nic_bw()):
                arr.rx[t, g] = rx
                arr.tx[t, g] = tx
            arr.hp[t] = r.hugepages_gb
            arr.needs_gpu[t] = r.needs_gpu
            arr.map_pci[t] = r.map_mode == MapMode.PCI
            arr.group_mask[t] = interner.mask(r.node_groups)
        out[G] = arr
    return out
