"""Dense tensor encodings of cluster state and pod batches.

Host-side HostNode objects stay the source of truth (SURVEY §5.4 stance:
device state must always be re-derivable from host state); this module
projects them into packed numpy arrays the jitted solver consumes, and
dedupes a pod batch into *types* — identical PodRequests share one solver
row, which is what makes gang batches (a TriadSet scaling to thousands of
replicas, BASELINE config 4) cheap: feasibility is O(types × nodes), not
O(pods × nodes).
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from nhd_tpu.core.node import HostNode
from nhd_tpu.core.request import PodRequest
from nhd_tpu.core.topology import MapMode

MAX_GROUP_BITS = 63  # node-group bitmask width (int64, sign bit unused)


class GroupInterner:
    """Node-group names → bit positions, shared across cluster and pods."""

    def __init__(self) -> None:
        self._bits: Dict[str, int] = {}

    def mask(self, names) -> int:
        m = 0
        for name in names:
            bit = self._bits.get(name)
            if bit is None:
                bit = len(self._bits)
                if bit >= MAX_GROUP_BITS:
                    raise ValueError(
                        f"more than {MAX_GROUP_BITS} distinct node groups"
                    )
                self._bits[name] = bit
            m |= 1 << bit
        return m


@dataclass
class ClusterArrays:
    """Packed per-node state. Shapes: N nodes, U NUMA (padded), K NICs/NUMA
    (padded), S PCIe switches per node (padded)."""

    names: List[str]
    U: int
    K: int
    S: int
    numa_nodes: np.ndarray     # [N] int8
    smt: np.ndarray            # [N] bool
    active: np.ndarray         # [N] bool
    maintenance: np.ndarray    # [N] bool
    busy: np.ndarray           # [N] bool (pre-resolved against `now`)
    gpuless: np.ndarray        # [N] bool — node has zero GPUs total
    group_mask: np.ndarray     # [N] int64
    hp_free: np.ndarray        # [N] int32
    cpu_free: np.ndarray       # [N, U] int32 — fully-free physical cores
    gpu_free: np.ndarray       # [N, U] int32
    nic_count: np.ndarray      # [N, U] int32
    nic_free: np.ndarray       # [N, U, K, 2] float32 — rx/tx headroom Gbps
    nic_sw: np.ndarray         # [N, U, K] int32 — dense per-node switch id, -1 none
    gpu_free_sw: np.ndarray    # [N, S] int32 — free GPUs per dense switch id
    interner: GroupInterner = field(default_factory=GroupInterner)
    # every node's NICs share one capacity (speed): with NIC sharing off,
    # candidacy then depends only on free-NIC COUNTS per NUMA, which the
    # speculative loop tracks exactly — the precondition for its
    # saturation certificate (solver/speculate.py)
    uniform_nic_caps: bool = False

    @property
    def n_nodes(self) -> int:
        return len(self.names)


def cluster_dims(nodes) -> Tuple[int, int, int]:
    """(U, K, S) padding dims for a node collection: max NUMA nodes, max
    NICs per NUMA, max PCIe switches per node. The single source of the
    rule — streaming's oversized routing (solver/streaming.py) must judge
    tractability with exactly the dims the tile encodes will use."""
    nl = list(nodes.values()) if isinstance(nodes, dict) else list(nodes)
    U = max((n.numa_nodes for n in nl), default=1) or 1
    K = 1
    S = 1
    for node in nl:
        per_numa = [0] * node.numa_nodes
        for nic in node.nics:
            if nic.numa_node < node.numa_nodes:
                per_numa[nic.numa_node] += 1
        K = max(K, max(per_numa, default=0))
        switches = {g.pciesw for g in node.gpus} | {n.pciesw for n in node.nics}
        S = max(S, len(switches))
    return U, K, S


class EncodeStatic:
    """Cross-node index vectors for the batched cluster encode.

    Everything here depends only on hardware topology (packed by
    core/node.py _pack_state), not on allocation state, so one instance
    serves every encode over the same node set; the per-encode work
    reduces to a few concatenates, bincounts and scatters over flat
    vectors instead of ~10 small numpy calls per node."""

    def __init__(self, nl: List[HostNode], U: int, K: int, S: int):
        import numpy as np

        self.node_objs = nl  # pins the nodes (id-keyed cache safety)
        N = len(nl)
        self.U, self.K, self.S = U, K, S

        # --- cores: flat positions of every physical core + its sibling ---
        offs = np.cumsum([0] + [len(n.cores) for n in nl])
        self.core_off = offs
        phys_idx, sib_idx, cpu_code = [], [], []
        for i, n in enumerate(nl):
            phys = n.cores_per_proc * n.sockets
            base = offs[i]
            p = np.arange(phys, dtype=np.int64) + base
            phys_idx.append(p)
            # SMT sibling of physical core c is c + phys (identity layout,
            # checked by _pack_state); without SMT the "sibling" is the
            # core itself, making the pair test a no-op
            sib_idx.append(p + phys if n.smt_enabled else p)
            cpu_code.append(
                i * U + n._core_socket[:phys].astype(np.int64)
            )
        self.phys_idx = np.concatenate(phys_idx) if phys_idx else np.zeros(0, np.int64)
        self.sib_idx = np.concatenate(sib_idx) if sib_idx else np.zeros(0, np.int64)
        self.cpu_code = np.concatenate(cpu_code) if cpu_code else np.zeros(0, np.int64)

        # --- gpus ---
        self.gpu_numa_code = np.concatenate(
            [i * U + n._gpu_numa.astype(np.int64) for i, n in enumerate(nl)]
        ) if N else np.zeros(0, np.int64)
        gpu_sw_code = []
        for i, n in enumerate(nl):
            d = n._gpu_sw_dense
            # out-of-range dense ids (> S-1) are dropped from the
            # free-per-switch count, as the per-node path did
            gpu_sw_code.append(np.where(d < S, i * S + d, -1))
        self.gpu_sw_code = np.concatenate(gpu_sw_code) if gpu_sw_code else np.zeros(0, np.int64)
        self.gpuless = np.array([len(n.gpus) == 0 for n in nl], bool)

        # --- nics (pre-filtered to u < U and k < K) ---
        nic_node, nic_u, nic_k, nic_cap, nic_swd, nic_sel = [], [], [], [], [], []
        for i, n in enumerate(nl):
            nb = len(n.nics)
            if not nb:
                continue
            valid = (n._nic_u < U) & (n._nic_k < K)
            nic_sel.append((i, valid))
            nic_node.append(np.full(int(valid.sum()), i, np.int64))
            nic_u.append(n._nic_u[valid].astype(np.int64))
            nic_k.append(n._nic_k[valid].astype(np.int64))
            nic_cap.append(n._nic_cap[valid])
            nic_swd.append(n._nic_sw_dense[valid])
        z = np.zeros(0, np.int64)
        self.nic_node = np.concatenate(nic_node) if nic_node else z
        self.nic_u = np.concatenate(nic_u) if nic_u else z
        self.nic_k = np.concatenate(nic_k) if nic_k else z
        self.nic_cap = np.concatenate(nic_cap) if nic_cap else np.zeros(0)
        self.nic_sw_dense = np.concatenate(nic_swd) if nic_swd else z
        self.nic_sel = nic_sel  # (node index, valid mask) per NIC-bearing node

        # fully static matrices, copied into each ClusterArrays
        self.numa_nodes = np.array([n.numa_nodes for n in nl], np.int8)
        self.smt = np.array([n.smt_enabled for n in nl], bool)
        self.nic_count_mat = np.zeros((N, U), np.int32)
        for i, n in enumerate(nl):
            if len(n.nics):
                cnt = n._nic_cnt[:U]
                self.nic_count_mat[i, : len(cnt)] = np.minimum(cnt, K)
        self.nic_sw_mat = np.full((N, U, K), -1, np.int32)
        self.nic_sw_mat[self.nic_node, self.nic_u, self.nic_k] = self.nic_sw_dense


# id-keyed EncodeStatic cache. The entries pin their node lists, keeping
# the id() keys valid (same pattern as FastCluster._bucket_arrays — an
# unpinned id key can be reused by CPython and serve wrong data)
_ENC_STATIC: Dict[tuple, EncodeStatic] = {}


def _encode_static(nl: List[HostNode], U: int, K: int, S: int) -> EncodeStatic:
    from nhd_tpu.core.node import pack_generation_key

    key = pack_generation_key(nl, U, K, S)
    st = _ENC_STATIC.get(key)
    if st is None:
        if len(_ENC_STATIC) >= 8:
            _ENC_STATIC.clear()
        st = EncodeStatic(nl, U, K, S)
        _ENC_STATIC[key] = st
    return st


def encode_cluster(
    nodes: Dict[str, HostNode],
    *,
    now: Optional[float] = None,
    interner: Optional[GroupInterner] = None,
) -> ClusterArrays:
    """Project HostNodes into dense arrays (one row per node, name order =
    dict insertion order = the reference's node iteration order).

    Batched across nodes: allocation state is concatenated from the
    packed per-node arrays and every output matrix is computed with a
    few global vector ops (EncodeStatic caches the index vectors). Falls
    back to the per-node refresh loop when any node lacks the identity
    core layout the packed path needs."""
    names = list(nodes.keys())
    nl = [nodes[n] for n in names]
    N = len(nl)
    U, K, S = cluster_dims(nl)

    interner = interner or GroupInterner()
    arr = ClusterArrays(
        names=names, U=U, K=K, S=S,
        numa_nodes=np.zeros(N, np.int8),
        smt=np.zeros(N, bool),
        active=np.zeros(N, bool),
        maintenance=np.zeros(N, bool),
        busy=np.zeros(N, bool),
        gpuless=np.zeros(N, bool),
        group_mask=np.zeros(N, np.int64),
        hp_free=np.zeros(N, np.int32),
        cpu_free=np.zeros((N, U), np.int32),
        gpu_free=np.zeros((N, U), np.int32),
        nic_count=np.zeros((N, U), np.int32),
        nic_free=np.full((N, U, K, 2), -1.0, np.float32),
        nic_sw=np.full((N, U, K), -1, np.int32),
        gpu_free_sw=np.zeros((N, S), np.int32),
        interner=interner,
    )
    arr.uniform_nic_caps = all(
        len({nic.speed_gbps for nic in n.nics}) <= 1 for n in nl
    )
    for node in nl:
        node._ensure_packed()
    if N == 0:
        return arr
    if any(n._core_used is None for n in nl):
        for i, node in enumerate(nl):
            refresh_node_row(arr, i, node, now=now)
        return arr

    from nhd_tpu.core.node import ENABLE_NIC_SHARING, MIN_BUSY_SECS

    st = _encode_static(nl, U, K, S)

    arr.numa_nodes[:] = st.numa_nodes
    arr.smt[:] = st.smt
    arr.gpuless[:] = st.gpuless
    arr.nic_count[:] = st.nic_count_mat
    arr.nic_sw[:] = st.nic_sw_mat
    arr.active[:] = [n.active for n in nl]
    arr.maintenance[:] = [n.maintenance for n in nl]
    t = time.monotonic() if now is None else now
    arr.busy[:] = (
        np.array([n._busy_time for n in nl]) > t - MIN_BUSY_SECS
    )
    arr.group_mask[:] = [interner.mask(n.groups) for n in nl]
    arr.hp_free[:] = [n.mem.free_hugepages_gb for n in nl]

    # cores: one flat concat + one masked bincount for the whole cluster
    used_flat = np.concatenate([n._core_used for n in nl])
    free_phys = ~used_flat[st.phys_idx] & ~used_flat[st.sib_idx]
    arr.cpu_free[:] = np.bincount(
        st.cpu_code[free_phys], minlength=N * U
    ).reshape(N, U)

    # gpus
    gpu_used_flat = (
        np.concatenate([n._gpu_used for n in nl])
        if st.gpu_numa_code.size
        else np.zeros(0, bool)
    )
    if st.gpu_numa_code.size:
        free_g = ~gpu_used_flat
        arr.gpu_free[:] = np.bincount(
            st.gpu_numa_code[free_g], minlength=N * U
        ).reshape(N, U)
        code = st.gpu_sw_code[free_g]
        code = code[code >= 0]
        arr.gpu_free_sw[:] = np.bincount(
            code, minlength=N * S
        ).reshape(N, S)

    # nics
    if st.nic_node.size:
        bw = np.concatenate(
            [nl[i]._nic_bw[valid] for (i, valid) in st.nic_sel]
        )
        pods = np.concatenate(
            [nl[i]._nic_pods[valid] for (i, valid) in st.nic_sel]
        )
        if ENABLE_NIC_SHARING:
            free = st.nic_cap[:, None] - bw
        else:
            cap = np.where(pods > 0, 0.0, st.nic_cap)
            free = np.stack([cap, cap], axis=1)
        arr.nic_free[st.nic_node, st.nic_u, st.nic_k] = free
    return arr


def refresh_node_row(
    arr: ClusterArrays, i: int, node: HostNode, *, now: Optional[float] = None
) -> None:
    """Re-project one node into row *i* (incremental update path).

    Vector ops over the node's packed state (core/node.py _pack_state) —
    this runs once per node per batch (encode_cluster), so per-component
    Python loops here used to dominate the whole non-solve budget at
    1000-node scale. ``free_bw`` semantics are inlined vectorized
    (reference: Node.py:283-296)."""
    from nhd_tpu.core.node import ENABLE_NIC_SHARING

    node._ensure_packed()
    U, K, S = arr.U, arr.K, arr.S
    arr.numa_nodes[i] = node.numa_nodes
    arr.smt[i] = node.smt_enabled
    arr.active[i] = node.active
    arr.maintenance[i] = node.maintenance
    arr.busy[i] = node.is_busy(now)
    arr.gpuless[i] = len(node.gpus) == 0
    arr.group_mask[i] = arr.interner.mask(node.groups)
    arr.hp_free[i] = node.mem.free_hugepages_gb

    arr.cpu_free[i] = 0
    cpu = node.free_cpu_cores_per_numa()
    arr.cpu_free[i, : len(cpu)] = cpu

    arr.gpu_free[i] = 0
    gpu = node.free_gpus_per_numa()
    arr.gpu_free[i, : len(gpu)] = gpu

    arr.nic_count[i] = 0
    arr.nic_free[i] = -1.0
    arr.nic_sw[i] = -1

    nb = len(node.nics)
    if nb:
        cnt = node._nic_cnt[:U]
        # per-NUMA ordinals are dense (0..count-1) so every k < K for
        # dims from cluster_dims; the clip only guards foreign dims
        arr.nic_count[i, : len(cnt)] = np.minimum(cnt, K)
        u, k = node._nic_u, node._nic_k
        valid = (u < U) & (k < K)
        uu, kk = u[valid], k[valid]
        if ENABLE_NIC_SHARING:
            free = node._nic_cap[valid, None] - node._nic_bw[valid]
        else:
            cap = np.where(node._nic_pods[valid] > 0, 0.0, node._nic_cap[valid])
            free = np.stack([cap, cap], axis=1)
        arr.nic_free[i, uu, kk, 0] = free[:, 0]
        arr.nic_free[i, uu, kk, 1] = free[:, 1]
        arr.nic_sw[i, uu, kk] = node._nic_sw_dense[valid]

    arr.gpu_free_sw[i] = 0
    if len(node.gpus):
        d = node._gpu_sw_dense[~node._gpu_used]
        d = d[d < S]
        arr.gpu_free_sw[i] = np.bincount(d, minlength=S)[:S]


@dataclass
class PodTypeArrays:
    """Deduped pod-type tensors for one group-count bucket (G groups)."""

    G: int
    requests: List[PodRequest]      # one exemplar per type, type order
    pod_type: np.ndarray            # [P] int32 — type index of each input pod
    pod_index: np.ndarray           # [P] int64 — original batch positions
    cpu_dem_smt: np.ndarray         # [T, G+1] int32 (node-SMT-enabled demand)
    cpu_dem_raw: np.ndarray         # [T, G+1] int32
    gpu_dem: np.ndarray             # [T, G] int32
    rx: np.ndarray                  # [T, G] float32
    tx: np.ndarray                  # [T, G] float32
    hp: np.ndarray                  # [T] int32
    needs_gpu: np.ndarray           # [T] bool
    map_pci: np.ndarray             # [T] bool
    group_mask: np.ndarray          # [T] int64

    @property
    def n_types(self) -> int:
        return len(self.requests)


def encode_pods(
    pods: Sequence[PodRequest],
    interner: GroupInterner,
    indices: Optional[Sequence[int]] = None,
) -> Dict[int, PodTypeArrays]:
    """Bucket a pod batch by group count and dedupe identical requests into
    types. Returns {n_groups: PodTypeArrays}."""
    if indices is None:
        indices = range(len(pods))
    buckets: Dict[int, Tuple[List[PodRequest], List[int], List[int], Dict[PodRequest, int]]] = {}
    # gang batches arrive bucket-coherent, so the per-pod loop caches the
    # last bucket's bindings — this loop runs once per pod of a 10k gang
    # and is most of the encode phase's wall (r5)
    last_g = -1
    reqs: List[PodRequest] = []
    seen: Dict[PodRequest, int] = {}
    types_append = positions_append = None
    for pod, idx in zip(pods, indices):
        G = len(pod.groups)
        if G != last_g:
            b = buckets.get(G)
            if b is None:
                b = buckets[G] = ([], [], [], {})
            reqs, types, positions, seen = b
            types_append = types.append
            positions_append = positions.append
            last_g = G
        t = seen.get(pod)
        if t is None:
            t = len(reqs)
            seen[pod] = t
            reqs.append(pod)
        types_append(t)
        positions_append(idx)

    out: Dict[int, PodTypeArrays] = {}
    for G, (reqs, types, positions, _) in buckets.items():
        T = len(reqs)
        arr = PodTypeArrays(
            G=G,
            requests=reqs,
            pod_type=np.asarray(types, np.int32),
            pod_index=np.asarray(positions, np.int64),
            cpu_dem_smt=np.zeros((T, G + 1), np.int32),
            cpu_dem_raw=np.zeros((T, G + 1), np.int32),
            gpu_dem=np.zeros((T, G), np.int32),
            rx=np.zeros((T, G), np.float32),
            tx=np.zeros((T, G), np.float32),
            hp=np.zeros(T, np.int32),
            needs_gpu=np.zeros(T, bool),
            map_pci=np.zeros(T, bool),
            group_mask=np.zeros(T, np.int64),
        )
        for t, r in enumerate(reqs):
            arr.cpu_dem_smt[t] = r.cpu_slot_counts(node_smt=True)
            arr.cpu_dem_raw[t] = r.cpu_slot_counts(node_smt=False)
            arr.gpu_dem[t] = r.gpu_counts()
            for g, (rx, tx) in enumerate(r.nic_bw()):
                arr.rx[t, g] = rx
                arr.tx[t, g] = tx
            arr.hp[t] = r.hugepages_gb
            arr.needs_gpu[t] = r.needs_gpu
            arr.map_pci[t] = r.map_mode == MapMode.PCI
            arr.group_mask[t] = interner.mask(r.node_groups)
        out[G] = arr
    return out
