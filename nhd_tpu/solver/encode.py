"""Dense tensor encodings of cluster state and pod batches.

Host-side HostNode objects stay the source of truth (SURVEY §5.4 stance:
device state must always be re-derivable from host state); this module
projects them into packed numpy arrays the jitted solver consumes, and
dedupes a pod batch into *types* — identical PodRequests share one solver
row, which is what makes gang batches (a TriadSet scaling to thousands of
replicas, BASELINE config 4) cheap: feasibility is O(types × nodes), not
O(pods × nodes).
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

import numpy as np

from nhd_tpu.core.node import HostNode
from nhd_tpu.core.request import PodRequest
from nhd_tpu.core.topology import MapMode

# policy score-term inputs (node_class rows, per-type score rows):
# imported at module load, NOT lazily inside the encode functions — the
# first encode sits inside the timed first-bind window, and the lazy
# import showed up as a ~3 ms first_bind_prewarmed regression on the
# bench-smoke gate. No cycle: nhd_tpu.policy never imports the solver.
from nhd_tpu.policy.classes import MAX_CLASSES, node_class_index
from nhd_tpu.policy.scoring import score_row

MAX_GROUP_BITS = 63  # node-group bitmask width (int64, sign bit unused)


class GroupInterner:
    """Node-group names → bit positions, shared across cluster and pods."""

    def __init__(self) -> None:
        self._bits: Dict[str, int] = {}

    def mask(self, names) -> int:
        m = 0
        for name in names:
            bit = self._bits.get(name)
            if bit is None:
                bit = len(self._bits)
                if bit >= MAX_GROUP_BITS:
                    raise ValueError(
                        f"more than {MAX_GROUP_BITS} distinct node groups"
                    )
                self._bits[name] = bit
            m |= 1 << bit
        return m

    def known(self, names) -> bool:
        """Whether every name already has a bit — ``mask(names)`` would
        not grow the interner. The delta layer's new-group-bit fallback
        trigger (ClusterDelta) asks this before patching a row: bit
        positions depend on interning ORDER, so a bit minted by event
        order instead of node-iteration order would break the
        re-derivability contract."""
        bits = self._bits
        return all(n in bits for n in names)

    @property
    def n_bits(self) -> int:
        return len(self._bits)


@dataclass
class ClusterArrays:
    """Packed per-node state. Shapes: N nodes, U NUMA (padded), K NICs/NUMA
    (padded), S PCIe switches per node (padded)."""

    names: List[str]
    U: int
    K: int
    S: int
    numa_nodes: np.ndarray     # [N] int8
    smt: np.ndarray            # [N] bool
    active: np.ndarray         # [N] bool
    maintenance: np.ndarray    # [N] bool
    busy: np.ndarray           # [N] bool (pre-resolved against `now`)
    gpuless: np.ndarray        # [N] bool — node has zero GPUs total
    group_mask: np.ndarray     # [N] int64
    hp_free: np.ndarray        # [N] int32
    cpu_free: np.ndarray       # [N, U] int32 — fully-free physical cores
    gpu_free: np.ndarray       # [N, U] int32
    nic_count: np.ndarray      # [N, U] int32
    nic_free: np.ndarray       # [N, U, K, 2] float32 — rx/tx headroom Gbps
    nic_sw: np.ndarray         # [N, U, K] int32 — dense per-node switch id, -1 none
    gpu_free_sw: np.ndarray    # [N, S] int32 — free GPUs per dense switch id
    node_class: np.ndarray     # [N] int32 — hardware-generation class index
    #                            (policy/classes.py process-global interner;
    #                            0 = default class). Scored against the
    #                            per-type class_score rows in the fused
    #                            megaround; all-zero scoring leaves
    #                            placements bit-exact with the pre-policy
    #                            ranking.
    interner: GroupInterner = field(default_factory=GroupInterner)
    # every node's NICs share one capacity (speed): with NIC sharing off,
    # candidacy then depends only on free-NIC COUNTS per NUMA, which the
    # speculative loop tracks exactly — the precondition for its
    # saturation certificate (solver/speculate.py)
    uniform_nic_caps: bool = False

    @property
    def n_nodes(self) -> int:
        return len(self.names)


def cluster_dims(nodes) -> Tuple[int, int, int]:
    """(U, K, S) padding dims for a node collection: max NUMA nodes, max
    NICs per NUMA, max PCIe switches per node. The single source of the
    rule — streaming's oversized routing (solver/streaming.py) must judge
    tractability with exactly the dims the tile encodes will use."""
    nl = list(nodes.values()) if isinstance(nodes, dict) else list(nodes)
    U = max((n.numa_nodes for n in nl), default=1) or 1
    K = 1
    S = 1
    for node in nl:
        per_numa = [0] * node.numa_nodes
        for nic in node.nics:
            if nic.numa_node < node.numa_nodes:
                per_numa[nic.numa_node] += 1
        K = max(K, max(per_numa, default=0))
        switches = {g.pciesw for g in node.gpus} | {n.pciesw for n in node.nics}
        S = max(S, len(switches))
    return U, K, S


class EncodeStatic:
    """Cross-node index vectors for the batched cluster encode.

    Everything here depends only on hardware topology (packed by
    core/node.py _pack_state), not on allocation state, so one instance
    serves every encode over the same node set; the per-encode work
    reduces to a few concatenates, bincounts and scatters over flat
    vectors instead of ~10 small numpy calls per node."""

    def __init__(self, nl: List[HostNode], U: int, K: int, S: int):
        import numpy as np

        self.node_objs = nl  # pins the nodes (id-keyed cache safety)
        N = len(nl)
        self.U, self.K, self.S = U, K, S

        # --- cores: flat positions of every physical core + its sibling ---
        offs = np.cumsum([0] + [len(n.cores) for n in nl])
        self.core_off = offs
        phys_idx, sib_idx, cpu_code = [], [], []
        for i, n in enumerate(nl):
            phys = n.cores_per_proc * n.sockets
            base = offs[i]
            p = np.arange(phys, dtype=np.int64) + base
            phys_idx.append(p)
            # SMT sibling of physical core c is c + phys (identity layout,
            # checked by _pack_state); without SMT the "sibling" is the
            # core itself, making the pair test a no-op
            sib_idx.append(p + phys if n.smt_enabled else p)
            cpu_code.append(
                i * U + n._core_socket[:phys].astype(np.int64)
            )
        self.phys_idx = np.concatenate(phys_idx) if phys_idx else np.zeros(0, np.int64)
        self.sib_idx = np.concatenate(sib_idx) if sib_idx else np.zeros(0, np.int64)
        self.cpu_code = np.concatenate(cpu_code) if cpu_code else np.zeros(0, np.int64)

        # --- gpus ---
        self.gpu_numa_code = np.concatenate(
            [i * U + n._gpu_numa.astype(np.int64) for i, n in enumerate(nl)]
        ) if N else np.zeros(0, np.int64)
        gpu_sw_code = []
        for i, n in enumerate(nl):
            d = n._gpu_sw_dense
            # out-of-range dense ids (> S-1) are dropped from the
            # free-per-switch count, as the per-node path did
            gpu_sw_code.append(np.where(d < S, i * S + d, -1))
        self.gpu_sw_code = np.concatenate(gpu_sw_code) if gpu_sw_code else np.zeros(0, np.int64)
        self.gpuless = np.array([len(n.gpus) == 0 for n in nl], bool)

        # --- nics (pre-filtered to u < U and k < K) ---
        nic_node, nic_u, nic_k, nic_cap, nic_swd, nic_sel = [], [], [], [], [], []
        for i, n in enumerate(nl):
            nb = len(n.nics)
            if not nb:
                continue
            valid = (n._nic_u < U) & (n._nic_k < K)
            nic_sel.append((i, valid))
            nic_node.append(np.full(int(valid.sum()), i, np.int64))
            nic_u.append(n._nic_u[valid].astype(np.int64))
            nic_k.append(n._nic_k[valid].astype(np.int64))
            nic_cap.append(n._nic_cap[valid])
            nic_swd.append(n._nic_sw_dense[valid])
        z = np.zeros(0, np.int64)
        self.nic_node = np.concatenate(nic_node) if nic_node else z
        self.nic_u = np.concatenate(nic_u) if nic_u else z
        self.nic_k = np.concatenate(nic_k) if nic_k else z
        self.nic_cap = np.concatenate(nic_cap) if nic_cap else np.zeros(0)
        self.nic_sw_dense = np.concatenate(nic_swd) if nic_swd else z
        self.nic_sel = nic_sel  # (node index, valid mask) per NIC-bearing node

        # fully static matrices, copied into each ClusterArrays
        self.numa_nodes = np.array([n.numa_nodes for n in nl], np.int8)
        self.smt = np.array([n.smt_enabled for n in nl], bool)
        self.nic_count_mat = np.zeros((N, U), np.int32)
        for i, n in enumerate(nl):
            if len(n.nics):
                cnt = n._nic_cnt[:U]
                self.nic_count_mat[i, : len(cnt)] = np.minimum(cnt, K)
        self.nic_sw_mat = np.full((N, U, K), -1, np.int32)
        self.nic_sw_mat[self.nic_node, self.nic_u, self.nic_k] = self.nic_sw_dense

        # hardware-generation class indices (policy/classes.py): static
        # per pack generation (node_class only changes on a label
        # reparse, which bumps _pack_gen and misses this cache), and the
        # process-global interner never re-maps a name, so the resolved
        # indices are safe to cache
        self.node_class = np.array(
            [node_class_index(n) for n in nl], np.int32
        )


# id-keyed EncodeStatic cache. The entries pin their node lists, keeping
# the id() keys valid (same pattern as FastCluster._bucket_arrays — an
# unpinned id key can be reused by CPython and serve wrong data)
_ENC_STATIC: Dict[tuple, EncodeStatic] = {}


def _encode_static(nl: List[HostNode], U: int, K: int, S: int) -> EncodeStatic:
    from nhd_tpu.core.node import pack_generation_key

    key = pack_generation_key(nl, U, K, S)
    st = _ENC_STATIC.get(key)
    if st is None:
        if len(_ENC_STATIC) >= 8:
            _ENC_STATIC.clear()
        st = EncodeStatic(nl, U, K, S)
        _ENC_STATIC[key] = st
    return st


def encode_cluster(
    nodes: Dict[str, HostNode],
    *,
    now: Optional[float] = None,
    interner: Optional[GroupInterner] = None,
    dims: Optional[Tuple[int, int, int]] = None,
) -> ClusterArrays:
    """Project HostNodes into dense arrays (one row per node, name order =
    dict insertion order = the reference's node iteration order).

    Batched across nodes: allocation state is concatenated from the
    packed per-node arrays and every output matrix is computed with a
    few global vector ops (EncodeStatic caches the index vectors). Falls
    back to the per-node refresh loop when any node lacks the identity
    core layout the packed path needs.

    ``dims``: force the (U, K, S) padding instead of deriving it from
    the node set. Must cover the nodes' natural dims (smaller would
    silently drop NICs/switches — refused loudly). The delta layer's
    parity check uses this to compare against incrementally-maintained
    arrays whose padding outlived the node that demanded it."""
    names = list(nodes.keys())
    nl = [nodes[n] for n in names]
    N = len(nl)
    nat_U, nat_K, nat_S = cluster_dims(nl)
    if dims is None:
        U, K, S = nat_U, nat_K, nat_S
    else:
        U, K, S = dims
        if U < nat_U or K < nat_K or S < nat_S:
            raise ValueError(
                f"forced dims {dims} below the node set's natural "
                f"({nat_U}, {nat_K}, {nat_S}) — NICs/switches would be "
                "silently dropped"
            )

    interner = interner or GroupInterner()
    arr = ClusterArrays(
        names=names, U=U, K=K, S=S,
        numa_nodes=np.zeros(N, np.int8),
        smt=np.zeros(N, bool),
        active=np.zeros(N, bool),
        maintenance=np.zeros(N, bool),
        busy=np.zeros(N, bool),
        gpuless=np.zeros(N, bool),
        group_mask=np.zeros(N, np.int64),
        hp_free=np.zeros(N, np.int32),
        cpu_free=np.zeros((N, U), np.int32),
        gpu_free=np.zeros((N, U), np.int32),
        nic_count=np.zeros((N, U), np.int32),
        nic_free=np.full((N, U, K, 2), -1.0, np.float32),
        nic_sw=np.full((N, U, K), -1, np.int32),
        gpu_free_sw=np.zeros((N, S), np.int32),
        node_class=np.zeros(N, np.int32),
        interner=interner,
    )
    arr.uniform_nic_caps = all(
        len({nic.speed_gbps for nic in n.nics}) <= 1 for n in nl
    )
    for node in nl:
        node._ensure_packed()
    if N == 0:
        return arr
    if any(n._core_used is None for n in nl):
        for i, node in enumerate(nl):
            refresh_node_row(arr, i, node, now=now)
        return arr

    from nhd_tpu.core.node import ENABLE_NIC_SHARING, MIN_BUSY_SECS

    st = _encode_static(nl, U, K, S)

    arr.numa_nodes[:] = st.numa_nodes
    arr.smt[:] = st.smt
    arr.gpuless[:] = st.gpuless
    arr.node_class[:] = st.node_class
    arr.nic_count[:] = st.nic_count_mat
    arr.nic_sw[:] = st.nic_sw_mat
    arr.active[:] = [n.active for n in nl]
    arr.maintenance[:] = [n.maintenance for n in nl]
    t = time.monotonic() if now is None else now
    arr.busy[:] = (
        np.array([n._busy_time for n in nl]) > t - MIN_BUSY_SECS
    )
    arr.group_mask[:] = [interner.mask(n.groups) for n in nl]
    arr.hp_free[:] = [n.mem.free_hugepages_gb for n in nl]

    # cores: one flat concat + one masked bincount for the whole cluster
    used_flat = np.concatenate([n._core_used for n in nl])
    free_phys = ~used_flat[st.phys_idx] & ~used_flat[st.sib_idx]
    arr.cpu_free[:] = np.bincount(
        st.cpu_code[free_phys], minlength=N * U
    ).reshape(N, U)

    # gpus
    gpu_used_flat = (
        np.concatenate([n._gpu_used for n in nl])
        if st.gpu_numa_code.size
        else np.zeros(0, bool)
    )
    if st.gpu_numa_code.size:
        free_g = ~gpu_used_flat
        arr.gpu_free[:] = np.bincount(
            st.gpu_numa_code[free_g], minlength=N * U
        ).reshape(N, U)
        code = st.gpu_sw_code[free_g]
        code = code[code >= 0]
        arr.gpu_free_sw[:] = np.bincount(
            code, minlength=N * S
        ).reshape(N, S)

    # nics
    if st.nic_node.size:
        bw = np.concatenate(
            [nl[i]._nic_bw[valid] for (i, valid) in st.nic_sel]
        )
        pods = np.concatenate(
            [nl[i]._nic_pods[valid] for (i, valid) in st.nic_sel]
        )
        if ENABLE_NIC_SHARING:
            free = st.nic_cap[:, None] - bw
        else:
            cap = np.where(pods > 0, 0.0, st.nic_cap)
            free = np.stack([cap, cap], axis=1)
        arr.nic_free[st.nic_node, st.nic_u, st.nic_k] = free
    return arr


def refresh_node_row(
    arr: ClusterArrays, i: int, node: HostNode, *, now: Optional[float] = None
) -> None:
    """Re-project one node into row *i* (incremental update path).

    Vector ops over the node's packed state (core/node.py _pack_state) —
    this runs once per node per batch (encode_cluster), so per-component
    Python loops here used to dominate the whole non-solve budget at
    1000-node scale. ``free_bw`` semantics are inlined vectorized
    (reference: Node.py:283-296)."""
    from nhd_tpu.core.node import ENABLE_NIC_SHARING

    node._ensure_packed()
    U, K, S = arr.U, arr.K, arr.S
    arr.numa_nodes[i] = node.numa_nodes
    arr.smt[i] = node.smt_enabled
    arr.active[i] = node.active
    arr.maintenance[i] = node.maintenance
    arr.busy[i] = node.is_busy(now)
    arr.gpuless[i] = len(node.gpus) == 0
    arr.group_mask[i] = arr.interner.mask(node.groups)
    arr.hp_free[i] = node.mem.free_hugepages_gb
    arr.node_class[i] = node_class_index(node)

    arr.cpu_free[i] = 0
    cpu = node.free_cpu_cores_per_numa()
    arr.cpu_free[i, : len(cpu)] = cpu

    arr.gpu_free[i] = 0
    gpu = node.free_gpus_per_numa()
    arr.gpu_free[i, : len(gpu)] = gpu

    arr.nic_count[i] = 0
    arr.nic_free[i] = -1.0
    arr.nic_sw[i] = -1

    nb = len(node.nics)
    if nb:
        cnt = node._nic_cnt[:U]
        # per-NUMA ordinals are dense (0..count-1) so every k < K for
        # dims from cluster_dims; the clip only guards foreign dims
        arr.nic_count[i, : len(cnt)] = np.minimum(cnt, K)
        u, k = node._nic_u, node._nic_k
        valid = (u < U) & (k < K)
        uu, kk = u[valid], k[valid]
        if ENABLE_NIC_SHARING:
            free = node._nic_cap[valid, None] - node._nic_bw[valid]
        else:
            cap = np.where(node._nic_pods[valid] > 0, 0.0, node._nic_cap[valid])
            free = np.stack([cap, cap], axis=1)
        arr.nic_free[i, uu, kk, 0] = free[:, 0]
        arr.nic_free[i, uu, kk, 1] = free[:, 1]
        arr.nic_sw[i, uu, kk] = node._nic_sw_dense[valid]

    arr.gpu_free_sw[i] = 0
    if len(node.gpus):
        d = node._gpu_sw_dense[~node._gpu_used]
        d = d[d < S]
        arr.gpu_free_sw[i] = np.bincount(d, minlength=S)[:S]


# ---------------------------------------------------------------------------
# Incremental cluster state — the delta layer (docs/PERFORMANCE.md
# "Incremental device-resident state")
# ---------------------------------------------------------------------------
#
# encode_cluster re-projects all N nodes per call; at event rates the
# scheduler re-pays O(N) host work per round for a stream that touches
# O(changed) nodes. ClusterDelta keeps ONE ClusterArrays alive and patches
# it row-by-row as events arrive: watch events (cordon/maintenance/group),
# claim/release churn, and structural node add/remove — the latter through
# padded-capacity row slots (adds append inside the power-of-two capacity
# bucket; removals tombstone their row in place) with periodic compaction.
# Anything a row patch cannot express detects itself and falls back to a
# LOGGED full rebuild through encode_cluster — the one sanctioned rebuild
# chokepoint (nhdlint NHD108): host HostNode objects stay the source of
# truth and the resident arrays stay re-derivable (SURVEY §5.4), verified
# continuously by ``parity_errors``.

#: every per-row array of ClusterArrays, in _ARG_ORDER (kernel.py) order —
#: the delta layer's row patches and the device row scatter share it
DELTA_FIELDS = (
    "numa_nodes", "smt", "active", "maintenance", "busy", "gpuless",
    "group_mask", "hp_free", "cpu_free", "gpu_free", "nic_count",
    "nic_free", "nic_sw", "gpu_free_sw", "node_class",
)

#: the bounded rebuild-reason vocabulary (NHD603: the metrics label set
#: must be finite — anything novel folds into "other")
REBUILD_REASONS = (
    "init", "dims-overflow", "capacity", "new-group", "tombstone-readd",
    "compaction", "generation", "drift", "manual",
)

_REBUILD_LOCK = threading.Lock()
_REBUILD_COUNTS: Dict[str, int] = {}

# live deltas, for the resident-age gauge: one process can hold several
# (the streaming tiler keeps one per tile), and a per-instance write
# would make the gauge last-writer-wins — the operator question is "how
# stale is the OLDEST resident state", so the gauge reports the max age
# over live instances. WeakSet: a dropped context must not pin its delta
# (or hold the age forever).
import weakref

_LIVE_DELTAS: "weakref.WeakSet" = weakref.WeakSet()


def resident_age_seconds() -> float:
    """Max seconds since the last full rebuild over every live
    ClusterDelta (0.0 when none exist)."""
    now = time.monotonic()
    with _REBUILD_LOCK:
        return max(
            (now - d.last_rebuild_monotonic for d in _LIVE_DELTAS),
            default=0.0,
        )


def _count_rebuild(reason: str) -> None:
    if reason not in REBUILD_REASONS:
        reason = "other"
    with _REBUILD_LOCK:
        _REBUILD_COUNTS[reason] = _REBUILD_COUNTS.get(reason, 0) + 1


def rebuild_reasons_snapshot() -> Dict[str, int]:
    """{reason: count} of full rebuilds this process ran (rendered as
    nhd_device_state_rebuilds_total{reason=...} by rpc/metrics.py)."""
    with _REBUILD_LOCK:
        return dict(_REBUILD_COUNTS)


def reset_delta_metrics() -> None:
    """Test isolation: zero the rebuild-reason registry."""
    with _REBUILD_LOCK:
        _REBUILD_COUNTS.clear()


def _counters():
    from nhd_tpu.k8s.retry import API_COUNTERS

    return API_COUNTERS


def _pad_cap(n: int, floor: int = 8) -> int:
    """Row capacity for *n* live nodes: the power-of-two bucket (same
    rule as kernel.pad_nodes on one device, duplicated here to keep
    encode free of kernel/jax imports). Capacity == the device padding,
    so adds inside the bucket are pure row scatters and crossing it is
    a rebuild — which retraces the jitted programs anyway (the node
    axis is a specializing dim)."""
    p = floor
    while p < n:
        p *= 2
    return p


class ClusterDelta:
    """Incrementally-maintained ClusterArrays over a live HostNode dict.

    ``nodes`` is the LIVE dict (the scheduler's mirror, or a streaming
    tile's slice) — callers mutate it as usual and tell the delta which
    names an event touched via ``note``; ``refresh`` folds the noted
    names into the packed arrays as row patches and returns control with
    ``drain_dirty`` carrying exactly the changed row indices (the device
    layer scatters those rows, solver/device_state.py).

    Row order: the delta's view preserves the live dict's insertion
    order (removals tombstone in place — Python dicts preserve relative
    order on deletion — and adds append), so live rows read in physical
    order are bit-exact with a from-scratch ``encode_cluster`` at the
    delta's padding dims. ``parity_errors`` checks exactly that.

    Fallbacks — events a row patch cannot express trigger a logged full
    rebuild (counted per reason, bounded vocabulary):

    * ``dims-overflow``   — a node demands more U/K/S padding
    * ``capacity``        — adds exhausted the power-of-two row bucket
    * ``new-group``       — a node brings an uninterned group name (bit
                            positions depend on interning order)
    * ``tombstone-readd`` — a removed node's name re-added while its
                            tombstone row still holds its old slot
    * ``compaction``      — tombstones crossed the occupancy threshold
    * ``generation``      — a node's packed topology was rebuilt (label
                            reparse): every static cache over it is stale
    * ``drift``           — the live dict changed shape without notes
    """

    #: tombstone fraction (of total rows) that triggers compaction
    TOMBSTONE_FRAC = 8  # 1/8

    def __init__(
        self,
        nodes: Dict[str, HostNode],
        *,
        now: Optional[float] = None,
        interner: Optional[GroupInterner] = None,
        respect_busy: bool = True,
    ):
        self.nodes = nodes
        self.interner = interner or GroupInterner()
        self.respect_busy = respect_busy
        self.logger = None  # lazy (utils.get_logger imports logging config)
        #: row-aligned view: live dict order plus in-place tombstones.
        #: Object identity is STABLE across rebuilds (cleared + refilled)
        #: so ScheduleContexts holding it stay valid.
        self.view: Dict[str, HostNode] = {}
        self._names: List[str] = []        # arrays.names IS this list
        self._index: Dict[str, int] = {}
        self._tombstones: Set[str] = set()
        self._stale: Set[str] = set()      # names awaiting a row patch
        self._dirty: Set[int] = set()      # rows changed since drain
        self._pack_gens: Dict[str, int] = {}
        self._buf: Dict[str, np.ndarray] = {}
        self.arrays: Optional[ClusterArrays] = None
        self.capacity = 0
        self.now = time.monotonic() if now is None else now
        self.rebuilds = 0
        self.last_rebuild_monotonic = time.monotonic()
        self._full = True
        with _REBUILD_LOCK:
            _LIVE_DELTAS.add(self)
        self._rebuild("init")

    # -- bookkeeping -----------------------------------------------------

    def _log(self):
        if self.logger is None:
            from nhd_tpu.utils import get_logger

            self.logger = get_logger(__name__)
        return self.logger

    @property
    def n_rows(self) -> int:
        """Physical rows (live + tombstones) the arrays expose."""
        return len(self._names)

    @property
    def dims(self) -> Tuple[int, int, int]:
        a = self.arrays
        return (a.U, a.K, a.S)

    # -- the sanctioned rebuild chokepoint -------------------------------

    def _rebuild(self, reason: str) -> None:
        """Full re-encode from the live dict — the ONE place the delta
        layer pays O(N) host work, entered only by fallback triggers.
        Everything downstream re-derives: capacity buffers reallocate at
        the new power-of-two bucket, tombstones drop, and ``_full`` tells
        the device layer to re-upload wholesale (or rebuild, if the
        capacity bucket changed)."""
        nodes = self.nodes
        fresh = encode_cluster(nodes, now=self.now, interner=self.interner)
        if not self.respect_busy:
            fresh.busy[:] = False
        N = fresh.n_nodes
        cap = _pad_cap(max(N, 1))
        self._buf = {}
        for name in DELTA_FIELDS:
            src = getattr(fresh, name)
            buf = np.zeros((cap, *src.shape[1:]), src.dtype)
            if name == "nic_free":
                buf[...] = -1.0
            elif name == "nic_sw":
                buf[...] = -1
            buf[:N] = src
            self._buf[name] = buf
        self.view.clear()
        self.view.update(nodes)
        self._names[:] = fresh.names
        self._index = {n: i for i, n in enumerate(self._names)}
        self._tombstones.clear()
        self._stale.clear()
        self._dirty.clear()
        self._pack_gens = {n: nodes[n]._pack_gen for n in self._names}
        self.capacity = cap
        if self.arrays is None:
            self.arrays = ClusterArrays(
                names=self._names, U=fresh.U, K=fresh.K, S=fresh.S,
                interner=self.interner,
                **{name: self._buf[name][:N] for name in DELTA_FIELDS},
            )
        else:
            arr = self.arrays
            arr.U, arr.K, arr.S = fresh.U, fresh.K, fresh.S
            for name in DELTA_FIELDS:
                setattr(arr, name, self._buf[name][:N])
        self.arrays.uniform_nic_caps = fresh.uniform_nic_caps
        self._full = True
        self.rebuilds += 1
        self.last_rebuild_monotonic = time.monotonic()
        _count_rebuild(reason)
        c = _counters()
        if reason != "init":
            # the first build is a build, not a fallback: the counter
            # answers "how often did the delta path give up", and a
            # per-tile init storm would drown that signal
            c.inc("device_state_full_rebuilds_total")
        c.set("device_state_resident_age_seconds", resident_age_seconds())
        if reason != "init":
            self._log().warning(
                f"cluster delta: full rebuild ({reason}); {N} nodes at "
                f"capacity {cap}, dims U={fresh.U} K={fresh.K} S={fresh.S}"
            )

    def _reslice(self) -> None:
        """Re-point the ClusterArrays fields at the first n_rows rows of
        the capacity buffers (O(1) views; the object identity callers
        hold never changes)."""
        R = len(self._names)
        arr = self.arrays
        for name in DELTA_FIELDS:
            setattr(arr, name, self._buf[name][:R])

    def rebuild(self, reason: str = "manual") -> None:
        """Force the sanctioned full rebuild (drift repair, claim
        replays: every row changed, so one re-encode beats N patches)."""
        self._rebuild(reason if reason in REBUILD_REASONS else "manual")

    # -- event intake ----------------------------------------------------

    def note(self, name: str) -> None:
        """An event touched node *name* (update, claim/release churn,
        add, or remove — flush() discovers which by diffing against the
        live dict). Cheap and idempotent; safe to over-call."""
        self._stale.add(name)
        _counters().inc("device_state_events_total")

    def note_all(self, names: Iterable[str]) -> None:
        for n in names:
            self.note(n)

    # -- folding notes into the arrays -----------------------------------

    def refresh(self, now: Optional[float] = None) -> None:
        """Bring the arrays current: re-resolve busy against *now*, then
        fold every noted name in as a row patch (or fallback-rebuild).
        Called once per scheduling batch, before the arrays are solved
        against."""
        if now is not None:
            self._refresh_busy(now)
        self.flush()
        _counters().set(
            "device_state_resident_age_seconds", resident_age_seconds()
        )

    def _refresh_busy(self, now: float) -> None:
        """Busy-stamp decay, O(busy rows): only rows currently marked
        busy can decay by time passage (rows BECOME busy through claim
        paths the delta already sees), so the scan walks the busy set,
        not the cluster."""
        self.now = now
        if not self.respect_busy:
            return
        busy = self.arrays.busy
        for i in np.nonzero(busy)[0].tolist():
            name = self._names[i]
            if name in self._tombstones:
                busy[i] = False
                self._dirty.add(i)
                continue
            node = self.view[name]
            if not node.is_busy(now):
                busy[i] = False
                self._dirty.add(i)

    #: dirty-update count above which one BATCHED re-projection of the
    #: live rows beats per-row patches: refresh_node_row costs ~20 small
    #: numpy calls per row, while the EncodeStatic vector path projects
    #: the whole cluster in a handful of global ops — measured
    #: break-even ~N/4 at bench shapes. The bulk path writes the SAME
    #: values (non-noted rows re-project to themselves bit-exactly), so
    #: only the noted rows are marked device-dirty either way.
    BULK_PATCH_DIV = 4

    def flush(self) -> None:
        """Apply every noted name: row patches for updates, padded-slot
        appends for adds, in-place tombstones for removals; fallback
        rebuild for anything else. Clears the note set."""
        if not self._stale:
            return
        stale, self._stale = self._stale, set()
        nodes = self.nodes
        updates: List[str] = []
        adds: Set[str] = set()
        for name in stale:
            live = name in nodes
            idx = self._index.get(name)
            if live and idx is not None and name not in self._tombstones:
                updates.append(name)
            elif live:
                adds.add(name)
            elif idx is not None and name not in self._tombstones:
                self._remove_node(name, idx)
            # else: unknown/already-tombstoned name — nothing to express
        if adds:
            # append in LIVE-DICT order, not note order: several adds in
            # one flush must land in the same relative order a fresh
            # encode would give them (row order == dict order is the
            # parity contract)
            for name in nodes:
                if name in adds and not self._add_node(name, nodes[name]):
                    return  # fell back to a rebuild: notes are subsumed
        if updates:
            live_rows = len(self._names) - len(self._tombstones)
            if len(updates) > max(512, live_rows // self.BULK_PATCH_DIV):
                if not self._bulk_patch(updates):
                    return
            else:
                for name in updates:
                    if not self._patch_row(self._index[name], nodes[name]):
                        return
        if len(self._tombstones) > max(
            4, len(self._names) // self.TOMBSTONE_FRAC
        ):
            self._rebuild("compaction")
            return
        if len(self.view) - len(self._tombstones) != len(nodes):
            # the live dict changed shape without notes — a plumbing gap;
            # rebuild rather than solve against a silently-wrong mirror
            self._rebuild("drift")

    def _bulk_patch(self, updates: List[str]) -> bool:
        """The batched form of _patch_row for storm-sized update sets:
        ONE vectorized re-projection of every live row (EncodeStatic
        path — a handful of global numpy ops) written through the live-
        row index. Values are bit-identical to per-row patches (unpatched
        rows re-project to themselves), so only the noted rows go device-
        dirty. Fallback triggers are checked per noted node first, same
        as the per-row path."""
        nodes = self.nodes
        arr = self.arrays
        for name in updates:
            node = nodes[name]
            if node._pack_gen != self._pack_gens.get(name):
                self._rebuild("generation")
                return False
            if not self.interner.known(node.groups):
                self._rebuild("new-group")
                return False
            nU, nK, nS = cluster_dims([node])
            if nU > arr.U or nK > arr.K or nS > arr.S:
                self._rebuild("dims-overflow")
                return False
        fresh = encode_cluster(
            nodes, now=self.now, interner=self.interner, dims=self.dims
        )
        if not self.respect_busy:
            fresh.busy[:] = False
        live = np.fromiter(
            (
                i for i, n in enumerate(self._names)
                if n not in self._tombstones
            ),
            np.int64,
        )
        if len(live) != fresh.n_nodes:
            self._rebuild("drift")
            return False
        for name in DELTA_FIELDS:
            getattr(arr, name)[live] = getattr(fresh, name)
        index = self._index
        self._dirty.update(index[n] for n in updates)
        _counters().inc("device_state_deltas_total", len(updates))
        return True

    def _patch_row(self, i: int, node: HostNode) -> bool:
        """Re-project one live node into its row. Returns False when the
        event could not be expressed as a patch (rebuild ran)."""
        if node._pack_gen != self._pack_gens.get(node.name):
            # label reparse rebuilt the packed topology: dims may have
            # moved and every id-keyed static cache over this node set
            # (EncodeStatic, FastCluster._build_static) is stale
            self._rebuild("generation")
            return False
        if not self.interner.known(node.groups):
            self._rebuild("new-group")
            return False
        arr = self.arrays
        nU, nK, nS = cluster_dims([node])
        if nU > arr.U or nK > arr.K or nS > arr.S:
            self._rebuild("dims-overflow")
            return False
        refresh_node_row(arr, i, node, now=self.now)
        if not self.respect_busy:
            arr.busy[i] = False
        self._dirty.add(i)
        _counters().inc("device_state_deltas_total")
        return True

    def _add_node(self, name: str, node: HostNode) -> bool:
        """Structural add into a padded-capacity slot (append keeps row
        order == dict order: the live dict appended it too)."""
        if name in self._tombstones:
            # the old incarnation's row still holds a mid-array slot; a
            # patched resurrection there would break row order vs the
            # live dict (which re-inserted at the END)
            self._rebuild("tombstone-readd")
            return False
        if len(self._names) >= self.capacity:
            self._rebuild("capacity")
            return False
        node._ensure_packed()
        arr = self.arrays
        nU, nK, nS = cluster_dims([node])
        if nU > arr.U or nK > arr.K or nS > arr.S:
            self._rebuild("dims-overflow")
            return False
        if not self.interner.known(node.groups):
            self._rebuild("new-group")
            return False
        i = len(self._names)
        self.view[name] = node
        self._names.append(name)
        self._index[name] = i
        self._pack_gens[name] = node._pack_gen
        self._reslice()
        refresh_node_row(arr, i, node, now=self.now)
        if not self.respect_busy:
            arr.busy[i] = False
        # uniformity can only be broken by an add (recheck the newcomer),
        # never restored by one — restoration waits for the next rebuild
        if arr.uniform_nic_caps and len(
            {nic.speed_gbps for nic in node.nics}
        ) > 1:
            arr.uniform_nic_caps = False
        self._dirty.add(i)
        _counters().inc("device_state_deltas_total")
        return True

    def _remove_node(self, name: str, i: int) -> None:
        """Structural remove: tombstone the row in place. The HostNode
        object is retained (deactivated) so row-aligned consumers —
        FastCluster, the serial oracle pre-pass — keep a coherent object
        per row until compaction reclaims the slot."""
        node = self.view[name]
        node.active = False  # the delta owns the lingering object now
        self._tombstones.add(name)
        arr = self.arrays
        arr.active[i] = False
        arr.busy[i] = False
        self._dirty.add(i)
        _counters().inc("device_state_deltas_total")

    # -- device-sync handshake -------------------------------------------

    def consume_full(self) -> bool:
        """True once after a rebuild: the consumer must re-derive its
        resident state wholesale (row scatters cannot express a
        reallocation)."""
        full, self._full = self._full, False
        return full

    def drain_dirty(self) -> np.ndarray:
        """Row indices changed since the last drain (sorted int64),
        clearing the set — the device scatter's worklist."""
        if not self._dirty:
            return np.zeros(0, np.int64)
        rows = np.fromiter(sorted(self._dirty), np.int64, len(self._dirty))
        self._dirty.clear()
        return rows

    # -- re-derivability (SURVEY §5.4) -----------------------------------

    def snapshot(self) -> ClusterArrays:
        """Live rows gathered in order (tombstones dropped) — the
        projection ``parity_errors`` compares against a from-scratch
        encode. O(N); never on the hot path."""
        arr = self.arrays
        live = np.fromiter(
            (
                i for i, n in enumerate(self._names)
                if n not in self._tombstones
            ),
            np.int64,
        )
        names = [self._names[int(i)] for i in live]
        snap = ClusterArrays(
            names=names, U=arr.U, K=arr.K, S=arr.S,
            interner=self.interner,
            **{
                name: getattr(arr, name)[live].copy()
                for name in DELTA_FIELDS
            },
        )
        snap.uniform_nic_caps = arr.uniform_nic_caps
        return snap

    def parity_errors(self, now: Optional[float] = None) -> List[str]:
        """Defects between the incremental arrays and a from-scratch
        ``encode_cluster`` of the live dict at the delta's dims ([] =
        bit-exact). The continuous re-derivability check: chaos wires it
        as a sim invariant, the property test asserts it per batch."""
        self.flush()
        errs: List[str] = []
        snap = self.snapshot()
        ref = encode_cluster(
            self.nodes, now=self.now if now is None else now,
            interner=self.interner, dims=self.dims,
        )
        if not self.respect_busy:
            ref.busy[:] = False
        if snap.names != ref.names:
            errs.append(
                f"row order diverged: {snap.names[:8]}... != "
                f"{ref.names[:8]}..."
            )
            return errs
        if snap.uniform_nic_caps and not ref.uniform_nic_caps:
            # the delta may conservatively UNDER-report uniformity until
            # the next rebuild (a removal can restore it); claiming a
            # uniformity the live set lacks is the defect direction —
            # the speculative certificate would trust it
            errs.append("uniform_nic_caps claimed but the live set mixes")
        for name in DELTA_FIELDS:
            a, b = getattr(snap, name), getattr(ref, name)
            if a.shape != b.shape:
                errs.append(f"{name}: shape {a.shape} != {b.shape}")
            elif not np.array_equal(a, b):
                bad = np.nonzero(a != b)[0]
                rows = sorted({int(r) for r in np.atleast_1d(bad)[:8]})
                errs.append(
                    f"{name} diverged at rows {rows} "
                    f"(nodes {[snap.names[r] for r in rows[:4]]})"
                )
        return errs


@dataclass
class PodTypeArrays:
    """Deduped pod-type tensors for one group-count bucket (G groups)."""

    G: int
    requests: List[PodRequest]      # one exemplar per type, type order
    pod_type: np.ndarray            # [P] int32 — type index of each input pod
    pod_index: np.ndarray           # [P] int64 — original batch positions
    cpu_dem_smt: np.ndarray         # [T, G+1] int32 (node-SMT-enabled demand)
    cpu_dem_raw: np.ndarray         # [T, G+1] int32
    gpu_dem: np.ndarray             # [T, G] int32
    rx: np.ndarray                  # [T, G] float32
    tx: np.ndarray                  # [T, G] float32
    hp: np.ndarray                  # [T] int32
    needs_gpu: np.ndarray           # [T] bool
    map_pci: np.ndarray             # [T] bool
    group_mask: np.ndarray          # [T] int64
    class_score: np.ndarray         # [T, policy.classes.MAX_CLASSES] int32 —
    #                                 quantized per-node-class throughput
    #                                 scores (policy/scoring.py), gathered
    #                                 against node_class in the fused
    #                                 megaround. All-zero with NHD_POLICY=0
    #                                 (the bit-exact placement control).

    @property
    def n_types(self) -> int:
        return len(self.requests)


def encode_pods(
    pods: Sequence[PodRequest],
    interner: GroupInterner,
    indices: Optional[Sequence[int]] = None,
) -> Dict[int, PodTypeArrays]:
    """Bucket a pod batch by group count and dedupe identical requests into
    types. Returns {n_groups: PodTypeArrays}."""
    if indices is None:
        indices = range(len(pods))
    buckets: Dict[int, Tuple[List[PodRequest], List[int], List[int], Dict[PodRequest, int]]] = {}
    # gang batches arrive bucket-coherent, so the per-pod loop caches the
    # last bucket's bindings — this loop runs once per pod of a 10k gang
    # and is most of the encode phase's wall (r5)
    last_g = -1
    reqs: List[PodRequest] = []
    seen: Dict[PodRequest, int] = {}
    types_append = positions_append = None
    for pod, idx in zip(pods, indices):
        G = len(pod.groups)
        if G != last_g:
            b = buckets.get(G)
            if b is None:
                b = buckets[G] = ([], [], [], {})
            reqs, types, positions, seen = b
            types_append = types.append
            positions_append = positions.append
            last_g = G
        t = seen.get(pod)
        if t is None:
            t = len(reqs)
            seen[pod] = t
            reqs.append(pod)
        types_append(t)
        positions_append(idx)

    out: Dict[int, PodTypeArrays] = {}
    for G, (reqs, types, positions, _) in buckets.items():
        T = len(reqs)
        arr = PodTypeArrays(
            G=G,
            requests=reqs,
            pod_type=np.asarray(types, np.int32),
            pod_index=np.asarray(positions, np.int64),
            cpu_dem_smt=np.zeros((T, G + 1), np.int32),
            cpu_dem_raw=np.zeros((T, G + 1), np.int32),
            gpu_dem=np.zeros((T, G), np.int32),
            rx=np.zeros((T, G), np.float32),
            tx=np.zeros((T, G), np.float32),
            hp=np.zeros(T, np.int32),
            needs_gpu=np.zeros(T, bool),
            map_pci=np.zeros(T, bool),
            group_mask=np.zeros(T, np.int64),
            class_score=np.zeros((T, MAX_CLASSES), np.int32),
        )
        for t, r in enumerate(reqs):
            arr.cpu_dem_smt[t] = r.cpu_slot_counts(node_smt=True)
            arr.cpu_dem_raw[t] = r.cpu_slot_counts(node_smt=False)
            arr.gpu_dem[t] = r.gpu_counts()
            for g, (rx, tx) in enumerate(r.nic_bw()):
                arr.rx[t, g] = rx
                arr.tx[t, g] = tx
            arr.hp[t] = r.hugepages_gb
            arr.needs_gpu[t] = r.needs_gpu
            arr.map_pci[t] = r.map_mode == MapMode.PCI
            arr.group_mask[t] = interner.mask(r.node_groups)
            arr.class_score[t] = score_row(r)  # one cached row per kind
        out[G] = arr
    return out
