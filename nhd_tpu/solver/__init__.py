"""Matchers and batch scheduling.

The serial oracle is pure Python; the batched solver pulls in jax. Jax-
dependent symbols are exported lazily so `from nhd_tpu.solver import
OracleMatcher` (the baseline path) neither requires jax nor pays its
import cost.
"""

from nhd_tpu.solver.oracle import MatchResult, OracleMatcher, find_node

__all__ = [
    "BatchAssignment",
    "BatchItem",
    "BatchScheduler",
    "BatchStats",
    "JaxMatcher",
    "MatchResult",
    "OracleMatcher",
    "ScheduleContext",
    "StreamingScheduler",
    "find_node",
]

_LAZY = {
    "BatchAssignment": "nhd_tpu.solver.batch",
    "BatchItem": "nhd_tpu.solver.batch",
    "BatchScheduler": "nhd_tpu.solver.batch",
    "BatchStats": "nhd_tpu.solver.batch",
    "JaxMatcher": "nhd_tpu.solver.jax_matcher",
    "ScheduleContext": "nhd_tpu.solver.batch",
    "StreamingScheduler": "nhd_tpu.solver.streaming",
}


def __getattr__(name: str):
    module = _LAZY.get(name)
    if module is None:
        raise AttributeError(name)
    import importlib

    return getattr(importlib.import_module(module), name)
