from nhd_tpu.solver.oracle import MatchResult, OracleMatcher, find_node

__all__ = ["MatchResult", "OracleMatcher", "find_node"]
