"""Prometheus-format metrics endpoint.

The reference's only metrics plane is its gRPC service (SURVEY §5.5 — "No
Prometheus"). This adds a stdlib-only HTTP exporter: GET /metrics renders
the same scheduler-owned stats (via the single-writer RPC queue, like the
gRPC plane) in Prometheus text exposition format, so standard scrapers work
without a sidecar. Opt-in via ``nhd-tpu --metrics-port``.
"""

from __future__ import annotations

import queue
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import List

from nhd_tpu.k8s.retry import API_COUNTERS, ApiCounters
from nhd_tpu.rpc import ask_scheduler
from nhd_tpu.scheduler.core import RpcMsgType
from nhd_tpu.utils import get_logger


def render_metrics(
    nodes: List[dict], failed_count: int, perf: dict | None = None,
    api_stats: dict | None = None,
) -> str:
    """Scheduler stats → Prometheus text exposition format."""
    lines = [
        "# HELP nhd_failed_schedule_total Pods that failed to schedule",
        "# TYPE nhd_failed_schedule_total counter",
        f"nhd_failed_schedule_total {failed_count}",
    ]
    if api_stats is None:
        api_stats = API_COUNTERS.snapshot()
    # fault-tolerance layer: ApiCounters.KNOWN is the single name → (kind,
    # help) table, so a counter added there surfaces here with no edit
    for name, (kind, help_text) in ApiCounters.KNOWN.items():
        if name not in api_stats:
            continue
        # exact rendering (no :g): large monotonic counters must not lose
        # precision or rate() reads zero-then-spike past ~1e6
        lines += [
            f"# HELP nhd_{name} {help_text}",
            f"# TYPE nhd_{name} {kind}",
            f"nhd_{name} {api_stats[name]}",
        ]
    for name, kind, help_text in (
        ("batches_total", "counter", "Scheduling batches run"),
        ("scheduled_total", "counter", "Pods scheduled"),
        ("rounds_total", "counter", "Greedy solver rounds run"),
        ("solve_seconds_total", "counter",
         "Seconds in the batched feasibility solve"),
        ("select_seconds_total", "counter",
         "Seconds in candidate selection/packing"),
        ("assign_seconds_total", "counter",
         "Seconds in physical ID assignment"),
        ("last_batch_pods", "gauge", "Pod count of the last batch"),
        ("last_batch_seconds", "gauge", "Wall seconds of the last batch"),
        ("last_bind_p99_seconds", "gauge",
         "p99 bind latency within the last batch"),
    ):
        if perf is None or name not in perf:
            continue
        lines += [
            f"# HELP nhd_{name} {help_text}",
            f"# TYPE nhd_{name} {kind}",
            f"nhd_{name} {perf[name]}",
        ]
    lines += [
        "# HELP nhd_node_free_cpus Free logical CPU cores per node",
        "# TYPE nhd_node_free_cpus gauge",
        "# HELP nhd_node_free_gpus Free GPUs per node",
        "# TYPE nhd_node_free_gpus gauge",
        "# HELP nhd_node_free_hugepages_gb Free 1Gi hugepages per node",
        "# TYPE nhd_node_free_hugepages_gb gauge",
        "# HELP nhd_node_pods Scheduled pods per node",
        "# TYPE nhd_node_pods gauge",
        "# HELP nhd_node_active Node schedulable by NHD",
        "# TYPE nhd_node_active gauge",
        "# HELP nhd_nic_used_gbps NIC bandwidth booked per node/nic/direction",
        "# TYPE nhd_nic_used_gbps gauge",
    ]
    for n in nodes:
        label = f'node="{n["name"]}"'
        lines.append(f'nhd_node_free_cpus{{{label}}} {n["freecpu"]}')
        lines.append(f'nhd_node_free_gpus{{{label}}} {n["freegpu"]}')
        lines.append(
            f'nhd_node_free_hugepages_gb{{{label}}} {max(n["freehuge_gb"], 0)}'
        )
        lines.append(f'nhd_node_pods{{{label}}} {n["totalpods"]}')
        lines.append(f'nhd_node_active{{{label}}} {int(n["active"])}')
        for i, (rx, tx) in enumerate(n["nicstats"]):
            lines.append(
                f'nhd_nic_used_gbps{{{label},nic="{i}",dir="rx"}} {rx}'
            )
            lines.append(
                f'nhd_nic_used_gbps{{{label},nic="{i}",dir="tx"}} {tx}'
            )
    return "\n".join(lines) + "\n"


class MetricsServer(threading.Thread):
    """HTTP thread serving /metrics off the scheduler's RPC queue."""

    def __init__(self, sched_queue: queue.Queue, *, port: int = 9464):
        super().__init__(name="nhd-metrics", daemon=True)
        self.logger = get_logger(__name__)
        self.mainq = sched_queue
        outer = self

        class Handler(BaseHTTPRequestHandler):
            def do_GET(self) -> None:  # noqa: N802 (http.server API)
                path = self.path.split("?", 1)[0].rstrip("/")
                if path not in ("", "/metrics"):
                    self.send_error(404)
                    return
                try:
                    body = outer._collect().encode()
                except Exception as exc:  # scheduler unavailable
                    self.send_error(503, str(exc))
                    return
                self.send_response(200)
                self.send_header(
                    "Content-Type", "text/plain; version=0.0.4"
                )
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *args) -> None:
                pass  # keep scrapes out of the logs

        self.server = ThreadingHTTPServer(("", port), Handler)
        self.port = self.server.server_address[1]
        # _started gates stop(): HTTPServer.shutdown() blocks forever if
        # serve_forever never entered its loop, and the old plain-bool
        # handshake raced a stop() issued right after start()
        self._started = threading.Event()
        self._stop_lock = threading.Lock()
        self._stopped = False

    def _collect(self) -> str:
        nodes = ask_scheduler(self.mainq, RpcMsgType.NODE_INFO)
        failed = ask_scheduler(self.mainq, RpcMsgType.SCHEDULER_INFO)
        perf = ask_scheduler(self.mainq, RpcMsgType.PERF_INFO)
        return render_metrics(nodes, failed, perf)

    def run(self) -> None:
        self._started.set()
        self.logger.warning(f"metrics endpoint on :{self.port}/metrics")
        # short poll: shutdown() waits out one poll interval, and the
        # 0.5 s default is pure teardown latency for every embedder
        self.server.serve_forever(poll_interval=0.05)

    def stop(self) -> None:
        """Idempotent, and safe on a never-started server (shutdown() would
        otherwise block forever waiting for the serve loop)."""
        with self._stop_lock:
            if self._stopped:
                return
            self._stopped = True
        if self.is_alive() or self._started.is_set():
            # the thread exists: wait for run() to reach serve_forever so
            # shutdown() has a loop to stop (a stop() racing start() used
            # to skip shutdown and leave the serve loop running forever)
            self._started.wait(timeout=2.0)
            if self._started.is_set():
                self.server.shutdown()
        self.server.server_close()  # release the listening socket
